"""ProviderFactory: per-NodeClass VPC vs IKS actuation selection.

Capability parity with ``pkg/providers/factory.go``: shared providers built
once (:49), instance provider selected per NodeClass by
``determineProviderMode`` (:124-158): explicit bootstrapMode=iks-api ->
IKS; spec.iksClusterID -> IKS; ``IKS_CLUSTER_ID`` env -> IKS; default VPC.
"""

from __future__ import annotations

import os

from karpenter_tpu.apis.nodeclass import NodeClass
from karpenter_tpu.core.actuator import Actuator
from karpenter_tpu.core.workerpool import WorkerPoolActuator
from karpenter_tpu.utils.logging import get_logger

log = get_logger("core.factory")

MODE_VPC = "vpc"
MODE_IKS = "iks"


def determine_mode(nodeclass: NodeClass, env=os.environ) -> str:
    """(ref factory.go:124-158)"""
    if nodeclass.spec.bootstrap_mode == "iks-api":
        return MODE_IKS
    if nodeclass.spec.iks_cluster_id:
        return MODE_IKS
    if env.get("IKS_CLUSTER_ID"):
        return MODE_IKS
    return MODE_VPC


class ProviderFactory:
    def __init__(self, vpc_actuator: Actuator,
                 iks_actuator: WorkerPoolActuator | None = None,
                 env=os.environ):
        self.vpc = vpc_actuator
        self.iks = iks_actuator
        self.env = env

    def get_actuator(self, nodeclass: NodeClass):
        mode = determine_mode(nodeclass, self.env)
        if mode == MODE_IKS:
            if self.iks is None:
                log.warning("IKS mode requested but no IKS actuator wired; "
                            "falling back to VPC", nodeclass=nodeclass.name)
                return self.vpc
            return self.iks
        return self.vpc

    def get_actuator_for_claim(self, claim):
        """Delete-path routing: a claim created through the worker-pool path
        carries the pool annotations, which outlive its NodeClass — deleting
        an IKS worker via the VPC path would strand the pool's bookkeeping
        (worker record + size) and keep the empty-pool reaper from firing."""
        from karpenter_tpu.core.workerpool import ANNOTATION_POOL_ID
        if claim.annotations.get(ANNOTATION_POOL_ID) and self.iks is not None:
            return self.iks
        return self.vpc
