"""AOT executable cache: persist compiled-shape knowledge across restarts.

Two layers (docs/design/resident.md "AOT cache keying"):

1. **XLA's on-disk compilation cache** (solver/warmup.py
   ``enable_persistent_compile_cache``): a restart recompiles nothing it
   compiled before — but only once something ASKS for each executable.
2. **The signature manifest** (this module): devtel already tracks every
   dispatch's static-shape signature (the jit cache key — kernel path +
   bucket-padded G/O/U/N + output layout).  The cache records each NEW
   signature into ``aot_manifest.json`` next to the disk cache, and
   :meth:`AOTExecutableCache.prewarm` replays the manifest through the
   REAL jit entry points at boot — so a restarted process pre-compiles
   exactly the executables production dispatched before, each served
   from the disk cache instead of a cold XLA compile.  That is what
   cuts ``encode_cold`` / first-solve overhead to a disk read
   (tools/warm_restart_check.py is the CI gate on ``warmup_restart_s``).

The manifest is advisory: unknown kernels, stale shapes (an O_pad
smaller than the current catalog) and failed replays are skipped, never
fatal — cold compilation always remains the fallback.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from karpenter_tpu.utils.logging import get_logger

log = get_logger("resident.aot")

MANIFEST_NAME = "aot_manifest.json"
MAX_ENTRIES = 512

# kernels the prewarm replayer knows how to reconstruct dummy inputs
# for; others are recorded anyway (future replayers) but skipped
_PALLAS_KERNELS = {"pallas", "pallas-batch"}
_SUPPORTED = {"scan", "scan-batch", "resident"} | _PALLAS_KERNELS


class AOTExecutableCache:
    """Signature manifest + persistent compile cache in one directory."""

    def __init__(self, directory: str):
        self.dir = directory
        self.path = os.path.join(directory, MANIFEST_NAME)
        self._entries: dict[tuple, None] = {}
        self._enabled = False
        self._load()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            for e in doc.get("entries", []):
                sig = tuple(e["signature"])
                self._entries[(e["kernel"], sig)] = None
        except FileNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 — a corrupt manifest is a cold start
            log.warning("aot manifest unreadable; starting cold",
                        error=str(e)[:200])
            self._entries = {}

    def _flush(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        doc = {"version": 1,
               "entries": [{"kernel": k, "signature": list(sig)}
                           for (k, sig) in self._entries]}
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)

    # -- recording ---------------------------------------------------------

    def enable(self) -> "AOTExecutableCache":
        """Point JAX's persistent compile cache at the directory and
        start recording new dispatch signatures from devtel."""
        from karpenter_tpu.obs.devtel import get_devtel
        from karpenter_tpu.solver.warmup import enable_persistent_compile_cache

        enable_persistent_compile_cache(self.dir)
        get_devtel().signature_sink = self.record
        self._enabled = True
        return self

    def record(self, kernel: str, signature: tuple) -> None:
        """One new static-shape signature (devtel sink).  Only flat
        int/bool signatures round-trip through JSON; anything else is
        left to the disk cache alone."""
        if not all(isinstance(v, (int, bool)) for v in signature):
            return
        key = (kernel, tuple(signature))
        if key in self._entries:
            return
        # FIFO eviction at the cap: a long-lived cache dir whose
        # workload shapes drift must keep recording what production
        # dispatches NOW, not freeze on the first 512 shapes ever seen
        while len(self._entries) >= MAX_ENTRIES:
            self._entries.pop(next(iter(self._entries)))
        self._entries[key] = None
        try:
            self._flush()
        except OSError as e:
            log.warning("aot manifest write failed", error=str(e)[:200])

    def entries(self) -> list[tuple]:
        return list(self._entries)

    # -- replay ------------------------------------------------------------

    def prewarm(self, solver, catalog, *, block: bool = True) -> dict:
        """Replay every manifest signature through the real jit entry
        points (zero-filled problems: the solve is trivial, the compile
        — served from the disk cache — is the point).  Returns
        ``{"warmed", "skipped", "seconds"}``."""
        import jax

        t0 = time.perf_counter()
        on_tpu = jax.default_backend() not in ("cpu", "gpu")
        warmed = skipped = 0
        pending = []
        for kernel, sig in list(self._entries):
            if kernel not in _SUPPORTED or \
                    (kernel in _PALLAS_KERNELS and not on_tpu):
                skipped += 1
                continue
            try:
                dev = self._replay_one(solver, catalog, kernel, sig)
            except Exception as e:  # noqa: BLE001 — prewarm is best-effort
                log.warning("aot prewarm entry failed", kernel=kernel,
                            signature=sig, error=str(e)[:200])
                skipped += 1
                continue
            if dev is None:
                skipped += 1
            else:
                pending.append(dev)
                warmed += 1
        if block:
            for dev in pending:
                try:
                    jax.block_until_ready(dev)
                except Exception:  # noqa: BLE001
                    pass
        out = {"warmed": warmed, "skipped": skipped,
               "seconds": round(time.perf_counter() - t0, 3)}
        log.info("aot prewarm done", **out)
        return out

    def _replay_one(self, solver, catalog, kernel: str, sig: tuple):
        from karpenter_tpu.solver.jax_backend import pack_input

        if kernel == "resident":
            G, O, U, N, D, K, d16, c16, rs = sig
        elif kernel in ("scan-batch", "pallas-batch"):
            G, O, U, N, C, K, d16, c16, rs = sig
        else:
            G, O, U, N, K, d16, c16, rs = sig
        if O % 32 or O < catalog.num_offerings:
            return None   # stale shape: this catalog no longer fits it
        packed = pack_input(np.zeros((G, 4), np.int32),
                            np.zeros(G, np.int32), np.zeros(G, np.int32),
                            np.zeros(G, np.int32), np.zeros((U, O), bool))
        if kernel == "scan":
            from karpenter_tpu.solver.jax_backend import solve_packed

            off_alloc, off_price, off_rank = solver._device_offerings(
                catalog, O)
            return solve_packed(packed, off_alloc, off_price, off_rank,
                                G=G, O=O, U=U, N=N, right_size=rs,
                                compact=K, dense16=d16, coo16=c16)
        if kernel == "scan-batch":
            from karpenter_tpu.solver.jax_backend import solve_packed_batch

            off_alloc, off_price, off_rank = solver._device_offerings(
                catalog, O)
            return solve_packed_batch(
                np.stack([packed] * C), off_alloc, off_price, off_rank,
                G=G, O=O, U=U, N=N, right_size=rs, compact=K,
                dense16=d16, coo16=c16)
        if kernel == "resident":
            import jax

            from karpenter_tpu.resident.kernels import solve_resident

            off_alloc, off_price, off_rank = solver._device_offerings(
                catalog, O)
            didx = np.full(D, packed.size, np.int32)
            dval = np.zeros(D, np.int32)
            _, out = solve_resident(
                jax.device_put(packed), didx, dval,
                off_alloc, off_price, off_rank,
                G=G, O=O, U=U, N=N, right_size=rs, compact=K,
                dense16=d16, coo16=c16)
            return out
        if kernel == "pallas":
            from karpenter_tpu.solver.jax_backend import solve_packed_pallas

            alloc8, rank_row, price = solver._device_offerings_pallas(
                catalog, O)
            return solve_packed_pallas(packed, alloc8, rank_row, price,
                                       G=G, O=O, U=U, N=N, right_size=rs,
                                       compact=K, dense16=d16, coo16=c16)
        if kernel == "pallas-batch":
            from karpenter_tpu.solver.jax_backend import (
                solve_packed_pallas_batch,
            )

            alloc8, rank_row, price = solver._device_offerings_pallas(
                catalog, O)
            return solve_packed_pallas_batch(
                np.stack([packed] * C), alloc8, rank_row, price,
                C=C, G=G, O=O, U=U, N=N, right_size=rs, compact=K,
                dense16=d16, coo16=c16)
        return None
