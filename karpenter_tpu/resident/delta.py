"""Delta encoder: window -> compact update tensors vs the resident state.

The packed solve buffer (jax_backend pack_input: [G,8] meta rows + the
factored label-row bitset) is a CONTENT-ADDRESSED lowering of a window:
pod arrivals and departures change a handful of meta rows (count /
request columns of their groups), claim transitions change nothing (the
solve input is the pending set), and constraint changes flip label-row
words.  So the minimal correct delta between two windows is exactly the
set of int32 words that differ — computed here as one vectorized
``np.nonzero`` over the mirror, then padded to a small bucket ladder so
the donated update kernel compiles once per rung, not per window.

Parity contract: applying ``(idx, val)`` on device must reproduce the
full host packed buffer bit-for-bit (the chaos invariant and the
differential tests rebuild from ClusterState and compare) — which makes
the incremental solve bit-identical to a from-scratch encode by
construction: the solve kernel's input IS the full buffer.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# delta-size rungs: the (didx, dval) pair is padded up to one of these
# so XLA compiles the update/solve executable once per rung.  Padding
# entries carry an out-of-range index and are dropped on device
# (.at[].set(mode="drop")).
DELTA_BUCKETS = (64, 256, 1024, 4096, 16384, 65536)

# a delta larger than this fraction of the buffer loses to a plain
# re-upload (diff + scatter overhead for most of the buffer's words);
# the window rebuilds instead
REBUILD_FRACTION = 0.5


@dataclass(frozen=True)
class WindowDelta:
    """What one window cost against the resident state."""

    mode: str              # "hit" (no change) | "delta" | "rebuild"
    words: int             # changed int32 words (0 for hit; buffer size
                           # for rebuild)
    h2d_bytes: int         # bytes this window actually moved host->device
    reason: str = ""       # rebuild reason ("" unless mode == "rebuild")
    arrivals: int = 0      # semantic churn, when the caller tracked pod
    departures: int = 0    # keys across windows (telemetry only)


def diff_words(mirror: np.ndarray, packed: np.ndarray) -> np.ndarray:
    """int64 indices of words differing between the resident mirror and
    the new window's packed buffer (both flat int32, same length)."""
    return np.nonzero(mirror != packed)[0]


def pad_delta(idx: np.ndarray, val: np.ndarray, drop_index: int,
              buckets=DELTA_BUCKETS) -> tuple[np.ndarray, np.ndarray]:
    """Pad ``(idx, val)`` up to the smallest bucket: padding rows carry
    ``drop_index`` (one past the buffer end) so the device-side
    ``.at[].set(mode="drop")`` ignores them."""
    from karpenter_tpu.solver.types import bucket

    d_pad = bucket(max(int(idx.size), 1), buckets)
    didx = np.full(d_pad, drop_index, dtype=np.int32)
    dval = np.zeros(d_pad, dtype=np.int32)
    didx[:idx.size] = idx
    dval[:idx.size] = val
    return didx, dval


def pod_churn(prev_keys: frozenset, pods) -> tuple[int, int, frozenset]:
    """(arrivals, departures, current key set) between two windows —
    the semantic delta size reported alongside the word-level one."""
    from karpenter_tpu.apis.pod import pod_key

    cur = frozenset(pod_key(p) for p in pods)
    return (len(cur - prev_keys), len(prev_keys - cur), cur)


def pack_window(problem) -> tuple[np.ndarray, tuple[int, int, int]]:
    """Lower an EncodedProblem to its bucket-padded packed buffer +
    shape key — the SAME padding and packing ``JaxSolver._prepare``
    applies (shared code path: pack_input / dedup_rows / the bucket
    ladders), so a host-side tracker (chaos harness, invariant rebuild)
    and the solver agree on the buffer layout word for word."""
    from karpenter_tpu.solver.jax_backend import (
        _pad1, _pad2, dedup_rows, pack_input,
    )
    from karpenter_tpu.solver.types import (
        GROUP_BUCKETS, LABELROW_BUCKETS, OFFERING_BUCKETS, bucket,
    )

    G = problem.num_groups
    O = problem.catalog.num_offerings
    G_pad = bucket(G, GROUP_BUCKETS)
    O_pad = bucket(O, OFFERING_BUCKETS)
    if problem.label_rows is not None and problem.label_idx is not None:
        rows, label_idx = problem.label_rows, problem.label_idx
    else:
        label_idx, rows = dedup_rows(problem.compat)
    U_pad = bucket(max(rows.shape[0], 1), LABELROW_BUCKETS)
    packed = pack_input(_pad2(problem.group_req, G_pad),
                        _pad1(problem.group_count, G_pad),
                        _pad1(problem.group_cap, G_pad),
                        _pad1(label_idx, G_pad),
                        _pad2(rows, U_pad, O_pad),
                        group_prio=_pad1(problem.group_prio, G_pad))
    return packed, (G_pad, O_pad, U_pad)
