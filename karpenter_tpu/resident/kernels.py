"""Donated device kernels for the resident state store.

Two entry points, both with the old state buffer DONATED (graftlint
GL006: the transient state input must alias the output, never double
the device footprint):

- :func:`update_resident` — apply one delta in place; the standalone
  form non-solver consumers ride (the fleet path's input buffer, the
  chaos harness's tracked window state).
- :func:`solve_resident` — delta-apply FUSED with the packed solve in
  ONE dispatch: the per-window H2D collapses to the (idx, val) pair and
  the new resident state rides back as an aliased output next to the
  packed result buffer.  Traces the same ``_unpack_problem`` +
  ``solve_core`` body as ``solve_packed``, so a resident incremental
  solve on a bit-identical buffer is bit-identical to the from-scratch
  path (the parity contract docs/design/resident.md pins).

The catalog tensors (off_alloc / off_price / off_rank) are the
device-RESIDENT cache JaxSolver keys by generation — they are never
donated (GL006's explicit carve-out).
"""

from __future__ import annotations

import functools

import jax

from karpenter_tpu.solver.jax_backend import (
    _pack_result_telemetry, _unpack_problem, solve_core,
)


@functools.partial(jax.jit, donate_argnames=("state",))
def update_resident(state, didx, dval):
    """Scatter a padded word delta into the resident buffer: padding
    entries carry an out-of-range index and drop.  The old buffer is
    donated — the update aliases in place on device."""
    flat = state.reshape(-1).at[didx].set(dval, mode="drop")
    return flat.reshape(state.shape)


@functools.partial(jax.jit,
                   static_argnames=("G", "O", "U", "N", "right_size",
                                    "compact", "dense16", "coo16"),
                   donate_argnames=("state",))
def solve_resident(state, didx, dval, off_alloc, off_price, off_rank, *,
                   G: int, O: int, U: int, N: int,
                   right_size: bool = True, compact: int = 0,
                   dense16: bool = False, coo16: bool = False):
    """Delta-apply + packed solve in one dispatch.

    Args: ``state`` int32 [L] resident packed buffer (donated);
    ``didx``/``dval`` int32 [D] padded word delta; catalog tensors as in
    ``solve_packed``.  Returns ``(new_state, packed_result)`` — the new
    state stays on device for the next window's delta.
    """
    state = state.at[didx].set(dval, mode="drop")
    meta, compat_i, rows_g = _unpack_problem(state, off_alloc, G, O, U)
    node_off, assign, unplaced, cost = solve_core(
        meta[:, :4], meta[:, 4], meta[:, 5], compat_i > 0,
        off_alloc, off_price, off_rank, num_nodes=N,
        right_size=right_size)
    return state, _pack_result_telemetry(meta, rows_g, compat_i, node_off,
                                         assign, unplaced, cost, off_alloc,
                                         compact, dense16, coo16)
