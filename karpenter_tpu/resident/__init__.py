"""Device-resident cluster state: delta-encoded incremental solves.

ROADMAP item 1 (the substrate items 2-5 build on): BENCH_r05 shows the
solver is >98% transfer/dispatch overhead on the single-shot path —
compute ~1.2 ms against an exec_fetch of ~70 ms with an rtt_floor of
~68 ms, plus encode_cold of ~105-117 ms and ~19 ms of first-solve
overhead.  The scheduler loop has exactly the shape CvxCluster
(PAPERS.md) amortizes 100-1000x: each window differs from the last by a
handful of pod arrivals/departures and claim transitions, yet the
pre-resident path re-encoded and re-uploaded the whole world every
window.

This package keeps the per-window problem state RESIDENT on device as
donated buffers and moves only what changed:

- :mod:`karpenter_tpu.resident.delta` — the delta encoder: lowers a
  window to compact ``(word index, word value)`` update tensors against
  the previous window's device-resident packed buffer (pod arrivals,
  departures and occupancy changes all manifest as changed meta rows /
  label-row words of the packed layout).  Full re-encode remains the
  cold/recovery path and the parity oracle: a resident incremental
  solve must be bit-identical to a from-scratch encode.
- :mod:`karpenter_tpu.resident.kernels` — the donated device kernels:
  ``update_resident`` (apply a delta in place, old buffer donated) and
  ``solve_resident`` (fused delta-apply + packed solve in ONE dispatch,
  returning the new resident state alongside the result buffer).
- :mod:`karpenter_tpu.resident.store` — generation-tracked state:
  ``ResidentStore`` (the solver-side store JaxSolver dispatches
  through), ``ResidentBuffer`` (the generic buffer parallel/fleet
  rides), and ``OccupancySnapshot`` (the one-per-tick occupancy view
  the disruption/repack plane shares instead of per-claim pod scans).
  Catalog updates, NodePool edits and degraded-mode fallbacks force a
  clean rebuild — never a silent solve against stale device state.
- :mod:`karpenter_tpu.resident.aot` — the AOT executable cache: the
  static-shape signatures devtel tracks are persisted in a manifest
  next to JAX's on-disk compilation cache, so a restarted process
  pre-compiles exactly the executables production dispatched before
  (cuts encode_cold / first-solve overhead; tools/warm_restart_check.py
  is the CI gate).

Opt-in via ``KARPENTER_ENABLE_RESIDENT`` (the preempt/gang convention)
or ``SolverOptions.resident="on"``.  Design: docs/design/resident.md.
"""

from __future__ import annotations

import os


def resident_enabled(options=None, env=None) -> bool:
    """The one gate every wiring point shares: SolverOptions.resident
    "on"/"off" wins; "auto" defers to KARPENTER_ENABLE_RESIDENT."""
    mode = getattr(options, "resident", "auto") if options is not None \
        else "auto"
    if mode == "on":
        return True
    if mode == "off":
        return False
    raw = (os.environ if env is None else env).get(
        "KARPENTER_ENABLE_RESIDENT", "")
    return raw.lower() in ("1", "true", "yes", "on")


__all__ = ["resident_enabled"]
