"""Generation-tracked device-resident state: the store itself.

Three consumers share this module (docs/design/resident.md):

- :class:`ResidentStore` — the solver-side store.  ``JaxSolver``
  dispatches warm windows through :meth:`dispatch_solve` (fused
  delta-apply + solve, ONE device input of delta size); host-side
  trackers (the chaos harness, the greedy parity leg of the
  differential tests) ride :meth:`track_window`, which maintains the
  same mirror + device buffer through the standalone update kernel.
- :class:`ResidentBuffer` — one generic donated buffer + host mirror;
  the fleet path keeps its stacked [C, Li] input resident with it.
- :class:`OccupancySnapshot` — the one-per-tick occupancy view the
  disruption/repack plane reads instead of re-scanning every pod per
  claim (O(claims x pods) host rebuilds were the repack tick's tail).

Invalidation rules (the "generation-tracked" part): a state's device
tensors are only ever consulted when its recorded generation equals the
catalog's ``(generation, availability_generation)`` AND the window's
padded shape matches — anything else is a clean rebuild with the reason
recorded.  Degraded-mode fallbacks call :meth:`ResidentStore.invalidate`
so the next window never solves against device state a failed dispatch
may have poisoned.  The full re-encode path remains both the recovery
path and the parity oracle: between chaos sync rounds the
``resident-state-fresh`` invariant rebuilds the packed buffer from
ClusterState and compares it word-for-word against the mirror AND the
fetched device tensors.
"""

from __future__ import annotations

import threading

import numpy as np

from karpenter_tpu.resident.delta import (
    DELTA_BUCKETS, REBUILD_FRACTION, WindowDelta, diff_words, pack_window,
    pad_delta, pod_churn,
)
from karpenter_tpu import obs
from karpenter_tpu.faulttol import DeviceFaultError, device_guard
from karpenter_tpu.obs.devtel import get_devtel
from karpenter_tpu.obs.prof import get_profiler
from karpenter_tpu.utils.logging import get_logger

log = get_logger("resident.store")


def plan_update(buf, flat: np.ndarray, generation: tuple | None):
    """THE one cold/generation/shape/oversized-delta decision ladder,
    shared by every resident consumer (``ResidentBuffer.update`` and the
    solver's fused dispatch must never drift apart on invalidation
    semantics).  ``buf`` is anything exposing mirror/dev/generation/
    pending_reason.  Returns ``(reason, idx)``: a non-empty reason means
    rebuild (a pending invalidation reason wins over the generic
    "cold"); otherwise ``idx`` holds the changed-word indices (possibly
    empty = hit)."""
    if buf.dev is None or buf.mirror is None:
        return buf.pending_reason or "cold", None
    if buf.generation != generation:
        return "generation", None
    if buf.mirror.shape != flat.shape:
        return "shape", None
    idx = diff_words(buf.mirror, flat)
    if idx.size > max(64, flat.size * REBUILD_FRACTION):
        return "delta_too_large", None
    return "", idx


class ResidentBuffer:
    """One device-resident int32 buffer + its host mirror.

    ``update(host)`` returns the device buffer to dispatch: a no-change
    window reuses it outright (zero H2D), a small diff rides the donated
    ``update_resident`` kernel as a padded (idx, val) pair, and a shape/
    generation change or an oversized diff rebuilds from host.  The
    mirror always equals the device content — that equality IS the
    parity contract the invariants check.
    """

    __slots__ = ("name", "mirror", "dev", "generation", "stats",
                 "pending_reason")

    def __init__(self, name: str = "buffer"):
        self.name = name
        self.mirror: np.ndarray | None = None
        self.dev = None
        self.generation: tuple | None = None
        self.stats = {"hit": 0, "delta": 0, "rebuild": 0}
        # an explicit invalidation's reason, reported by the NEXT
        # rebuild instead of the generic "cold" (one logical rebuild =
        # one counted rebuild, carrying the cause)
        self.pending_reason = ""

    def invalidate(self, reason: str = "") -> None:
        self.mirror = None
        self.dev = None
        self.generation = None
        self.pending_reason = reason

    def update(self, host: np.ndarray, generation: tuple | None = None,
               kernel: str = "resident-update"):
        """-> (device buffer, WindowDelta).  ``host`` must be int32."""
        import jax

        from karpenter_tpu.resident.kernels import update_resident

        flat = host.reshape(-1)
        reason, idx = plan_update(self, flat, generation)
        if not reason:
            if idx.size == 0:
                self.stats["hit"] += 1
                delta = WindowDelta(mode="hit", words=0, h2d_bytes=0)
                self._note(kernel, host, delta, generation)
                return self.dev, delta
            didx, dval = pad_delta(idx, flat[idx], flat.size,
                                   DELTA_BUCKETS)
            try:
                with device_guard(kernel):
                    with get_profiler().sampled(kernel) as probe:
                        self.dev = update_resident(self.dev, didx, dval)
                        # fetch=False: the updated buffer stays device-
                        # resident by design — fetching the WHOLE state
                        # would measure a full-buffer D2H the production
                        # path never performs
                        probe.dispatched(self.dev, fetch=False)
            except DeviceFaultError as e:
                # the donated update faulted mid-flight: the device
                # buffer can no longer be trusted.  Fall through to the
                # full host rebuild below — the window is never lost.
                self.invalidate(f"device_fault:{e.kind}")
                reason = self.pending_reason
            else:
                self.mirror[idx] = flat[idx]
                self.stats["delta"] += 1
                delta = WindowDelta(
                    mode="delta", words=int(idx.size),
                    h2d_bytes=int(didx.nbytes + dval.nbytes))
                self._note(kernel, host, delta, generation)
                return self.dev, delta
        self.dev = jax.device_put(host)
        self.mirror = flat.copy()
        self.generation = generation
        self.pending_reason = ""
        self.stats["rebuild"] += 1
        delta = WindowDelta(mode="rebuild", words=int(flat.size),
                            h2d_bytes=int(host.nbytes), reason=reason)
        self._note(kernel, host, delta, generation)
        return self.dev, delta

    def _note(self, kernel: str, host: np.ndarray, delta: WindowDelta,
              generation) -> None:
        get_devtel().note_resident_window(
            delta.mode, h2d_bytes=delta.h2d_bytes, words=delta.words,
            reason=delta.reason, resident_bytes=int(host.nbytes),
            generation=generation)
        if delta.mode != "hit":
            get_devtel().note_dispatch(
                kernel, (host.size, delta.mode == "rebuild"),
                h2d_bytes=delta.h2d_bytes,
                donated=delta.mode == "delta")


class _SolveState:
    """Per-(catalog uid, padded shape) resident solve state: the buffer
    plus the tracked window's pod-key set (semantic churn telemetry)."""

    __slots__ = ("buf", "pod_keys")

    def __init__(self):
        self.buf = ResidentBuffer(name="solve")
        self.pod_keys: frozenset = frozenset()


class ResidentStore:
    """The solver-side store: keyed resident solve states + counters."""

    MAX_STATES = 8   # distinct (catalog uid, shape) combos kept resident

    def __init__(self):
        self._lock = threading.Lock()
        self._states: dict[tuple, _SolveState] = {}
        self.windows = 0
        self.rebuilds = 0
        self.invalidations = 0
        self.last_delta: WindowDelta | None = None
        self.last_rebuild_reason = ""
        self.last_key: tuple | None = None

    # -- state bookkeeping -------------------------------------------------

    def _state_for(self, key: tuple) -> _SolveState:
        with self._lock:
            st = self._states.get(key)
            if st is None:
                while len(self._states) >= self.MAX_STATES:
                    self._states.pop(next(iter(self._states)))
                st = self._states[key] = _SolveState()
            return st

    def invalidate(self, reason: str = "invalidated") -> None:
        """Drop EVERY resident state (degraded-mode fallback, NodePool
        edit, operator request): the next window of each key rebuilds
        from host instead of trusting possibly-stale device tensors.
        The reason rides to that rebuild (ONE logical rebuild, counted
        once, carrying its cause) rather than being double-counted
        here."""
        with self._lock:
            for st in self._states.values():
                st.buf.invalidate(reason)
            occ = getattr(self, "_occ_buf", None)
            if occ is not None:
                occ.invalidate(reason)
            self.invalidations += 1
        get_devtel().note_resident_invalidation(reason)

    def _account(self, key: tuple, delta: WindowDelta) -> None:
        with self._lock:
            self.windows += 1
            if delta.mode == "rebuild":
                self.rebuilds += 1
                self.last_rebuild_reason = delta.reason
            self.last_delta = delta
            self.last_key = key

    # -- the solver dispatch path ------------------------------------------

    def dispatch_solve(self, prep, packed: np.ndarray, catalog_tensors,
                       right_size: bool):
        """Fused delta-apply + solve for one prepared window; returns
        the device result buffer (same wire layout as ``solve_packed``).
        The caller (JaxSolver._dispatch) owns routing, escalation and
        fallback — an exception here must invalidate + fall back there.
        """
        from karpenter_tpu.resident.kernels import solve_resident

        catalog = prep.catalog
        key = (catalog.uid, prep.G_pad, prep.O_pad, prep.U_pad)
        gen = (catalog.generation, catalog.availability_generation)
        buf = self._state_for(key).buf
        flat = packed.reshape(-1)
        t0 = obs.now()
        reason, idx = plan_update(buf, flat, gen)
        if reason:
            import jax

            buf.dev = jax.device_put(flat)
            buf.mirror = flat.copy()
            buf.generation = gen
            buf.pending_reason = ""
            buf.stats["rebuild"] += 1
            didx, dval = pad_delta(np.empty(0, np.int64),
                                   np.empty(0, np.int32), flat.size)
            delta = WindowDelta(mode="rebuild", words=int(flat.size),
                                h2d_bytes=int(flat.nbytes), reason=reason)
        else:
            didx, dval = pad_delta(idx, flat[idx], flat.size)
            if idx.size:
                buf.mirror[idx] = flat[idx]
                buf.stats["delta"] += 1
                delta = WindowDelta(
                    mode="delta", words=int(idx.size),
                    h2d_bytes=int(didx.nbytes + dval.nbytes))
            else:
                # unchanged window: the delta pair still rides along
                # (smallest bucket, all padding) so the dispatch shape
                # stays uniform, but it IS a resident hit
                buf.stats["hit"] += 1
                delta = WindowDelta(mode="hit", words=0,
                                    h2d_bytes=int(didx.nbytes + dval.nbytes))
        off_alloc, off_price, off_rank = catalog_tensors
        sig = (prep.G_pad, prep.O_pad, prep.U_pad, prep.N, didx.size,
               prep.K, prep.dense16, prep.coo16, right_size)
        get_devtel().note_dispatch(
            "resident", sig, h2d_bytes=delta.h2d_bytes,
            donated=delta.mode != "rebuild")
        get_devtel().note_resident_window(
            delta.mode, h2d_bytes=delta.h2d_bytes, words=delta.words,
            reason=delta.reason, resident_bytes=int(flat.nbytes),
            generation=(catalog.uid,) + gen)
        with device_guard("resident"):
            with get_profiler().sampled("resident") as probe:
                buf.dev, out = solve_resident(
                    buf.dev, didx, dval, off_alloc, off_price, off_rank,
                    G=prep.G_pad, O=prep.O_pad, U=prep.U_pad, N=prep.N,
                    right_size=right_size, compact=prep.K,
                    dense16=prep.dense16, coo16=prep.coo16)
                probe.dispatched(out)
        self._account(key, delta)
        obs.record("resident.window", t0, obs.now(), mode=delta.mode,
                   words=delta.words, h2d_bytes=delta.h2d_bytes,
                   reason=delta.reason)
        return out

    # -- host-side window tracking (chaos harness, parity legs) ------------

    def track_window(self, pods, catalog, nodepool=None) -> WindowDelta:
        """Maintain the resident state for a window WITHOUT solving on
        it: encode (memoized), pack, and apply the delta through the
        standalone donated update kernel.  Non-jax backends (the chaos
        harness runs greedy) exercise the exact store machinery the
        solver path relies on — and the invariant checks it against a
        fresh ClusterState rebuild between sync rounds."""
        from karpenter_tpu.solver.encode import encode

        problem = encode(pods, catalog, nodepool)
        packed, (G_pad, O_pad, U_pad) = pack_window(problem)
        key = (catalog.uid, G_pad, O_pad, U_pad)
        gen = (catalog.generation, catalog.availability_generation)
        st = self._state_for(key)
        arrivals, departures, cur = pod_churn(st.pod_keys, pods)
        st.pod_keys = cur
        _, delta = st.buf.update(packed, gen)
        delta = WindowDelta(mode=delta.mode, words=delta.words,
                            h2d_bytes=delta.h2d_bytes, reason=delta.reason,
                            arrivals=arrivals, departures=departures)
        self._account(key, delta)
        return delta

    # -- device-resident claim occupancy -----------------------------------

    def occupancy_tensors(self, cluster, catalog):
        """Device-resident claim/occupancy tensors: one int32 row
        ``[offering, pod_count, resid_cpu, resid_mem, resid_gpu,
        resid_pods]`` per live launched claim (cluster insertion order,
        padded to a node bucket), maintained through the same donated
        delta path as the solve state.  Claim churn (register/delete)
        and pod binds change a handful of rows per tick — this is the
        residual-capacity substrate the repack-on-TPU item (ROADMAP 2)
        solves against without a per-tick host rebuild + full upload.

        Returns ``(claim_names, device [Nn_pad, 6] int32, WindowDelta)``.
        """
        from karpenter_tpu.apis.pod import NUM_RESOURCES
        from karpenter_tpu.preempt.encode import (
            _pod_req_vec, claim_pods, occupancy_index,
        )
        from karpenter_tpu.solver.types import NODE_BUCKETS, bucket

        idx = occupancy_index(cluster)
        alloc = catalog.offering_alloc().astype(np.int64)
        names: list[str] = []
        rows: list[tuple] = []
        for c in cluster.nodeclaims():
            if c.deleted or not c.launched:
                continue
            off = catalog.find_offering(c.instance_type, c.zone,
                                        c.capacity_type)
            if off is None:
                continue
            resid = alloc[off].copy()
            count = 0
            for p in claim_pods(cluster, c, index=idx):
                resid -= _pod_req_vec(p.spec)
                count += 1
            names.append(c.name)
            rows.append((off, count) + tuple(int(v) for v in resid))
        width = 2 + NUM_RESOURCES
        n_pad = bucket(max(len(rows), 1), NODE_BUCKETS)
        arr = np.zeros((n_pad, width), dtype=np.int32)
        if rows:
            arr[:len(rows)] = np.asarray(rows, dtype=np.int64).clip(
                np.iinfo(np.int32).min, np.iinfo(np.int32).max)
        with self._lock:
            buf = getattr(self, "_occ_buf", None)
            if buf is None:
                buf = self._occ_buf = ResidentBuffer(name="occupancy")
        gen = (catalog.uid, catalog.generation,
               catalog.availability_generation)
        dev, delta = buf.update(arr, generation=gen,
                                kernel="resident-occupancy")
        return names, dev, delta

    def occupancy_rows(self) -> np.ndarray | None:
        """Host mirror of the occupancy rows as ``[Nn_pad, 6] int32``
        (None before any :meth:`occupancy_tensors` call) — the repack
        encoder's host-side view of the device-resident rows; by the
        parity contract it equals the device tensor word-for-word."""
        from karpenter_tpu.apis.pod import NUM_RESOURCES

        with self._lock:
            buf = getattr(self, "_occ_buf", None)
        if buf is None or buf.mirror is None:
            return None
        return buf.mirror.reshape(-1, 2 + NUM_RESOURCES)

    def snapshot_state(self, catalog=None) -> dict | None:
        """The most recent state's (mirror, device fetch, generation) for
        invariant checks / debug — None before any window."""
        with self._lock:
            key = self.last_key
            st = self._states.get(key) if key is not None else None
        if st is None or st.buf.mirror is None or st.buf.dev is None:
            return None
        return {"key": key, "generation": st.buf.generation,
                "mirror": st.buf.mirror, "device": np.asarray(st.buf.dev)}

    def stats(self) -> dict:
        with self._lock:
            last = self.last_delta
            return {
                "states": len(self._states),
                "windows": self.windows,
                "rebuilds": self.rebuilds,
                "invalidations": self.invalidations,
                "last_mode": last.mode if last else "",
                "last_delta_words": last.words if last else 0,
                "last_delta_h2d_bytes": last.h2d_bytes if last else 0,
                "last_rebuild_reason": self.last_rebuild_reason,
            }


def resident_store_of(solver):
    """The ResidentStore behind any solver-shaped object (ResilientSolver
    delegates unknown attributes to its primary), or None."""
    return getattr(solver, "resident", None)


class OccupancySnapshot:
    """One shared occupancy view per disruption tick.

    Reproduces ``DisruptionController._bound_pods`` EXACTLY — pods whose
    ``bound_node`` or ``nominated_node`` equals the queried name, in pod
    collection order — from ONE pass over the pod collection instead of
    one full scan per claim (the O(claims x pods) host rebuild the
    resident store removes from the repack tick).  In-pass mutations
    (consolidation rebinds, evictions) go through :meth:`rebind` /
    :meth:`unbind`, which preserve each pod's original collection order
    so results stay bit-identical to the per-call rescan path (pinned
    by tests/test_resident.py).
    """

    def __init__(self, cluster):
        from karpenter_tpu.apis.pod import pod_key

        self._order: dict[str, int] = {}
        self._by_name: dict[str, dict[str, None]] = {}
        self._homes: dict[str, tuple[str, ...]] = {}
        for i, p in enumerate(cluster.list("pods")):
            key = pod_key(p.spec)
            self._order[key] = i
            names = []
            if p.bound_node:
                names.append(p.bound_node)
            if p.nominated_node and p.nominated_node != p.bound_node:
                names.append(p.nominated_node)
            for n in names:
                self._by_name.setdefault(n, {})[key] = None
            self._homes[key] = tuple(names)

    def pods_on(self, name: str) -> list[str]:
        if not name:
            return []
        keys = self._by_name.get(name)
        if not keys:
            return []
        return sorted(keys, key=self._order.__getitem__)

    def _drop(self, key: str) -> None:
        for n in self._homes.get(key, ()):
            bucket = self._by_name.get(n)
            if bucket is not None:
                bucket.pop(key, None)
        self._homes[key] = ()

    def rebind(self, key: str, bound_node: str,
               nominated_node: str = "") -> None:
        """A consolidation move changed ``key``'s binding: re-home it
        under its CURRENT (bound, nominated) pair — the same pair the
        per-call rescan would see — at its original collection order."""
        self._drop(key)
        names = []
        if bound_node:
            names.append(bound_node)
        if nominated_node and nominated_node != bound_node:
            names.append(nominated_node)
        for n in names:
            self._by_name.setdefault(n, {})[key] = None
        self._homes[key] = tuple(names)
        self._order.setdefault(key, len(self._order))

    def unbind(self, key: str) -> None:
        """An eviction returned ``key`` to pending (no node)."""
        self._drop(key)
