"""Cross-cutting constants (reference: pkg/constants/constants.go —
the API group + client cache TTLs the provider shares across packages).

This module is the INDEX of values that already have an owner — it
re-exports the canonical definitions instead of minting second copies
(two same-named constants with different values is how label-selector
bugs are born).  Only values used by more than one subsystem appear;
subsystem-local tunables stay next to their owner.
"""

from __future__ import annotations

# karpenter-core-owned label keys (the scheduler contract, shared with
# upstream karpenter tooling) — canonical home: apis/requirements.py
from karpenter_tpu.apis.requirements import LABEL_NODEPOOL  # noqa: F401

# provider-owned API group of the CRDs (reference Group =
# "karpenter-ibm.sh"; deploy/crds/tpunodeclass.yaml anchors this value)
GROUP = "karpenter-tpu.sh"

# CRD kind names (apis/nodeclass.py + charts render from these)
NODECLASS_KIND = "TPUNodeClass"
NODECLAIM_KIND = "TPUNodeClaim"

# the tag/label marking instances this operator owns (core/actuator.py
# KARPENTER_TAGS stamps it on every create; orphan sweeps select by it)
LABEL_MANAGED = "karpenter.sh/managed"

# the finalizer the claim lifecycle controller owns (consumed by the
# nodeclaim controller, the actuator, and the IKS worker-pool actuator)
CLAIM_FINALIZER = f"{GROUP}/termination"

# default client-cache TTL for cloud API clients (reference
# DefaultVPCClientCacheTTL = 30 min; cloud/client_manager.py default)
DEFAULT_CLIENT_CACHE_TTL_SECONDS = 30 * 60
