"""Controller runtime: watch-driven reconcilers + singleton pollers.

The reference's two controller shapes (SURVEY.md §2.5):

- **watch-driven** — controller-runtime ``Reconcile(ctx, req)`` fed by
  informer events; here a work queue with per-key dedup fed by
  ClusterState watch callbacks (the informer analogue), drained by worker
  threads.
- **singleton pollers** — ``Reconcile(ctx)`` + ``RequeueAfter``; here a
  poll loop whose interval the reconcile can adapt per-cycle (the GC
  controller's 10s/2m adaptive requeue, garbagecollection/controller.go:201).

``ControllerManager.sync()`` is the deterministic test entry: enqueue every
existing object, drain all queues, run every poller once — no threads.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from collections.abc import Callable, Sequence

from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.runtime")


@dataclass
class Result:
    """Reconcile outcome (controller-runtime's ctrl.Result analogue)."""

    requeue_after: float = 0.0     # >0: re-reconcile this key/poller later


class WatchController:
    """Base for watch-driven controllers.

    Subclasses set ``name`` and ``watch_kinds`` and implement
    ``reconcile(name) -> Result``; ``map_event`` can redirect an event on a
    watched object to a different reconcile key (the reference's event
    handlers mapping node events -> claims, startuptaint/nodehandler.go).
    """

    name = "watch"
    watch_kinds: Sequence[str] = ()

    def map_event(self, kind: str, event_type: str, obj) -> str | None:
        return getattr(obj, "name", None)

    def reconcile(self, key: str) -> Result:  # pragma: no cover - abstract
        raise NotImplementedError


class PollController:
    """Base for singleton pollers: ``reconcile() -> Result`` decides its own
    next interval via requeue_after (else ``interval``)."""

    name = "poll"
    interval = 60.0

    def reconcile(self) -> Result:  # pragma: no cover - abstract
        raise NotImplementedError


class _Queue:
    """Per-controller keyed work queue with dedup + delayed requeue."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: list[str] = []
        self._in_queue: set = set()
        self._delayed: dict[str, float] = {}   # key -> not-before monotonic
        self._closed = False

    def add(self, key: str, after: float = 0.0) -> None:
        with self._cv:
            if after > 0:
                due = time.monotonic() + after
                # keep the EARLIER due time if already delayed
                prev = self._delayed.get(key)
                self._delayed[key] = due if prev is None else min(prev, due)
            elif key not in self._in_queue:
                self._pending.append(key)
                self._in_queue.add(key)
            self._cv.notify()

    def _promote_due_locked(self, now: float) -> None:
        # caller holds self._cv (the _locked contract, docs/development.md)
        due = [k for k, t in self._delayed.items() if t <= now]
        for k in due:
            del self._delayed[k]
            if k not in self._in_queue:
                self._pending.append(k)
                self._in_queue.add(k)

    def get(self, timeout: float = 0.2) -> str | None:
        with self._cv:
            self._promote_due_locked(time.monotonic())
            if not self._pending and not self._closed:
                self._cv.wait(timeout)
                self._promote_due_locked(time.monotonic())
            if not self._pending:
                return None
            key = self._pending.pop(0)
            self._in_queue.discard(key)
            return key

    def drain(self) -> list[str]:
        """Take everything currently due (test/sync path)."""
        with self._cv:
            self._promote_due_locked(time.monotonic())
            keys, self._pending = self._pending, []
            self._in_queue.clear()
            return keys

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class ControllerManager:
    # crash backoff: a reconcile key whose handler keeps throwing backs
    # off 5s -> 10s -> ... -> 5min instead of hot-looping every 5s
    # forever (a poisoned object would otherwise burn a worker + error
    # log line every 5s for its whole life); any successful reconcile
    # of the key resets the schedule
    crash_backoff_initial = 5.0
    crash_backoff_cap = 300.0

    def __init__(self, cluster: ClusterState, leader=None):
        self.cluster = cluster
        # leader gate (core/leaderelection.py): non-leader replicas keep
        # watching (queues accumulate, caches stay warm) but do not
        # reconcile — controller-runtime's leader-election semantics.
        # Queued keys drain on failover; pollers just skip their tick.
        self.leader = leader if leader is not None else (lambda: True)
        self._watch: list[WatchController] = []
        self._poll: list[PollController] = []
        self._queues: dict[str, _Queue] = {}
        self._unsubs: list[Callable[[], None]] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._crash_lock = threading.Lock()
        self._crash_counts: dict[tuple[str, str], int] = {}

    # -- registration ------------------------------------------------------

    def register(self, controller) -> None:
        if isinstance(controller, WatchController):
            self._watch.append(controller)
            self._queues[controller.name] = _Queue()
        elif isinstance(controller, PollController):
            self._poll.append(controller)
        else:
            raise TypeError(f"not a controller: {controller!r}")

    def controllers(self) -> list[str]:
        return [c.name for c in self._watch] + [c.name for c in self._poll]

    # -- live operation ----------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        for ctrl in self._watch:
            queue = self._queues[ctrl.name]
            for kind in ctrl.watch_kinds:
                self._unsubs.append(self.cluster.watch(
                    kind, self._make_handler(ctrl, kind, queue)))
            t = threading.Thread(target=self._watch_loop, args=(ctrl, queue),
                                 name=f"ctrl-{ctrl.name}", daemon=True)
            t.start()
            self._threads.append(t)
        for poller in self._poll:
            t = threading.Thread(target=self._poll_loop, args=(poller,),
                                 name=f"ctrl-{poller.name}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        for unsub in self._unsubs:
            unsub()
        self._unsubs.clear()
        for q in self._queues.values():
            q.close()
        for t in self._threads:
            t.join(timeout)
        self._threads.clear()
        self._queues = {c.name: _Queue() for c in self._watch}
        with self._crash_lock:
            self._crash_counts.clear()

    def _make_handler(self, ctrl: WatchController, kind: str, queue: _Queue):
        def handler(event_type: str, obj):
            key = ctrl.map_event(kind, event_type, obj)
            if key:
                queue.add(key)
        return handler

    def _watch_loop(self, ctrl: WatchController, queue: _Queue) -> None:
        while not self._stop.is_set():
            key = queue.get()
            if key is None:
                continue
            if not self.leader():
                # keep the key queued for the leader-to-be (small delay
                # so a follower doesn't spin on one hot key)
                queue.add(key, after=1.0)
                continue
            result = self._reconcile_one(ctrl, key)
            if result.requeue_after > 0:
                queue.add(key, after=result.requeue_after)

    def _poll_loop(self, poller: PollController) -> None:
        wait = 0.0   # first cycle immediately
        while not self._stop.wait(wait):
            if not self.leader():
                wait = min(poller.interval, 1.0)
                continue
            result = self._run_poller(poller)
            wait = result.requeue_after or poller.interval

    def _reconcile_one(self, ctrl: WatchController, key: str) -> Result:
        t0 = time.perf_counter()
        ck = (ctrl.name, key)
        try:
            result = ctrl.reconcile(key) or Result()
        except Exception as e:  # noqa: BLE001 — controllers must not die
            with self._crash_lock:
                crashes = self._crash_counts.get(ck, 0) + 1
                self._crash_counts[ck] = crashes
            # exponent clamp: 2**(crashes-1) overflows float conversion
            # after ~1024 consecutive crashes of one key — the cap is
            # reached long before, so bound the exponent, not the product
            delay = min(self.crash_backoff_cap,
                        self.crash_backoff_initial * (2 ** min(crashes - 1, 30)))
            log.error("reconcile failed", controller=ctrl.name, key=key,
                      error=str(e), crashes=crashes, requeue_after=delay)
            metrics.ERRORS.labels(f"controller.{ctrl.name}", "reconcile").inc()
            result = Result(requeue_after=delay)
        else:
            with self._crash_lock:
                self._crash_counts.pop(ck, None)
        metrics.RECONCILE_DURATION.labels(ctrl.name).observe(
            time.perf_counter() - t0)
        return result

    def _run_poller(self, poller: PollController) -> Result:
        t0 = time.perf_counter()
        try:
            result = poller.reconcile() or Result()
        except Exception as e:  # noqa: BLE001
            log.error("poll reconcile failed", controller=poller.name,
                      error=str(e))
            metrics.ERRORS.labels(f"controller.{poller.name}", "reconcile").inc()
            result = Result()
        metrics.RECONCILE_DURATION.labels(poller.name).observe(
            time.perf_counter() - t0)
        return result

    # -- deterministic sync (tests; also the resync on operator start) -----

    def sync(self, rounds: int = 3) -> None:
        """Reconcile every existing object through every watch controller
        and run every poller once, repeated ``rounds`` times so cascades
        (status -> autoplacement -> ...) settle.  No threads."""
        if not self.leader():
            return   # a follower's resync would actuate (GC deletes etc.)
        for _ in range(rounds):
            for ctrl in self._watch:
                keys: list[str] = []
                for kind in ctrl.watch_kinds:
                    for obj in self.cluster.list(kind):
                        key = ctrl.map_event(kind, "SYNC", obj)
                        if key and key not in keys:
                            keys.append(key)
                # plus anything queued by watch events since the last drain
                queue = self._queues.get(ctrl.name)
                if queue is not None:
                    for key in queue.drain():
                        if key not in keys:
                            keys.append(key)
                for key in keys:
                    self._reconcile_one(ctrl, key)
            for poller in self._poll:
                self._run_poller(poller)
