"""Controller plane: the reference's 16 controllers rebuilt host-side.

The reference registers its controllers with controller-runtime
(``pkg/controllers/controllers.go:117-259``); here a small native runtime
(`runtime.py`) provides the same two shapes — watch-driven reconcilers and
singleton pollers with requeue — over the in-memory ClusterState, feeding
the TPU solve loop instead of the K8s API server.
"""

from karpenter_tpu.controllers.runtime import (  # noqa: F401
    ControllerManager, PollController, Result, WatchController,
)
