"""NodeClaim lifecycle controllers: registration, startup taints,
termination, garbage collection, tagging.

Reference: ``pkg/controllers/nodeclaim/{registration,startuptaint,
garbagecollection,tagging}`` plus the karpenter-core claim-termination
lifecycle the reference delegates to (standalone framework implements both
halves — SURVEY.md §0).
"""

from __future__ import annotations

import time

from karpenter_tpu.apis.nodeclaim import Node, NodeClaim, parse_provider_id
from karpenter_tpu.apis.pod import Taint, pod_key
from karpenter_tpu.cloud.errors import CloudError, NodeClaimNotFoundError, is_not_found
from karpenter_tpu.controllers.runtime import PollController, Result, WatchController
from karpenter_tpu.core.actuator import KARPENTER_TAGS, Actuator
from karpenter_tpu.core.bootstrap import TAINT_UNREGISTERED
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.nodeclaim")

LABEL_INITIALIZED = "karpenter.sh/initialized"
from karpenter_tpu.constants import CLAIM_FINALIZER

# Taint-key prefixes that mean "CNI/cloud init not finished" — startup
# taints are held until these clear (ref startuptaint/controller.go:322-433).
CNI_NOT_READY_PREFIXES = (
    "node.cilium.io", "node.cloudprovider.kubernetes.io",
    "node.kubernetes.io/not-ready", "node.kubernetes.io/network-unavailable",
)


def _claim_for_node(cluster: ClusterState, node: Node) -> NodeClaim | None:
    for claim in cluster.nodeclaims():
        if claim.provider_id and claim.provider_id == node.provider_id:
            return claim
    return None


class RegistrationController(WatchController):
    """Post-join node<->claim sync (ref registration/controller.go:67):
    find the node by providerID (:192), copy labels/annotations/taints from
    the claim (:238-391), set Registered, then Initialized + the
    initialized label once the node reports Ready (:393-463)."""

    name = "nodeclaim.registration"
    watch_kinds = ("nodes", "nodeclaims")

    def __init__(self, cluster: ClusterState):
        self.cluster = cluster

    def map_event(self, kind: str, event_type: str, obj) -> str | None:
        if kind == "nodes":
            claim = _claim_for_node(self.cluster, obj)
            return claim.name if claim else None
        return getattr(obj, "name", None)

    def reconcile(self, key: str) -> Result:
        claim = self.cluster.get_nodeclaim(key)
        if claim is None or claim.deleted or not claim.launched:
            return Result()
        node = self._find_node(claim)
        if node is None:
            return Result()   # not joined yet; node ADDED will re-trigger
        changed = False
        if not claim.registered:
            self._sync_metadata(claim, node)
            claim.registered = True
            claim.registered_at = time.time()
            claim.node_name = node.name
            self.cluster.update("nodeclaims", key, claim)
            self.cluster.record_event("NodeClaim", claim.name, "Normal",
                                      "Registered", f"node {node.name}")
            # SLO ledger: every pod nominated onto this claim now has a
            # full first-seen -> registered latency (the true end-to-end
            # leg including cloud create + boot + join)
            ledger = obs.get_ledger()
            for pending in self.cluster.pending_pods():
                if pending.nominated_node == claim.name:
                    ledger.registered(pod_key(pending.spec))
            changed = True
        if claim.registered and not claim.initialized and node.ready:
            claim.initialized = True
            self.cluster.update("nodeclaims", key, claim)
            node.labels[LABEL_INITIALIZED] = "true"
            changed = True
        if changed:
            self.cluster.update("nodes", node.name, node)
        return Result()

    def _find_node(self, claim: NodeClaim) -> Node | None:
        for node in self.cluster.nodes():
            if node.provider_id == claim.provider_id and not node.deleted:
                return node
        return None

    def _sync_metadata(self, claim: NodeClaim, node: Node) -> None:
        for k, v in claim.labels.items():
            node.labels.setdefault(k, v)
        for k, v in claim.annotations.items():
            node.annotations.setdefault(k, v)
        have = {(t.key, t.effect) for t in node.taints}
        for t in list(claim.taints) + list(claim.startup_taints):
            if (t.key, t.effect) not in have:
                node.taints.append(t)
        # registration releases the unregistered NoExecute taint the
        # bootstrap applied (registration/controller.go:238-391)
        node.taints = [t for t in node.taints
                       if t.key != TAINT_UNREGISTERED.key]


class StartupTaintController(WatchController):
    """Removes the claim's startup taints once the node is Ready and no
    CNI/init taints remain (ref startuptaint/controller.go:322-433;
    node events map to claims via nodehandler.go)."""

    name = "nodeclaim.startuptaint"
    watch_kinds = ("nodes", "nodeclaims")

    def __init__(self, cluster: ClusterState):
        self.cluster = cluster

    def map_event(self, kind: str, event_type: str, obj) -> str | None:
        if kind == "nodes":
            claim = _claim_for_node(self.cluster, obj)
            return claim.name if claim else None
        return getattr(obj, "name", None)

    def reconcile(self, key: str) -> Result:
        claim = self.cluster.get_nodeclaim(key)
        if claim is None or not claim.registered or not claim.startup_taints:
            return Result()
        node = self.cluster.get_node(claim.node_name) if claim.node_name else None
        if node is None or not node.ready:
            return Result()
        if any(t.key.startswith(CNI_NOT_READY_PREFIXES) for t in node.taints):
            return Result(requeue_after=5.0)   # CNI still settling
        startup = {(t.key, t.effect) for t in claim.startup_taints}
        before = len(node.taints)
        node.taints = [t for t in node.taints
                       if (t.key, t.effect) not in startup]
        if len(node.taints) != before:
            self.cluster.update("nodes", node.name, node)
            self.cluster.record_event(
                "Node", node.name, "Normal", "StartupTaintsRemoved",
                f"removed {before - len(node.taints)} startup taints")
        return Result()


class NodeClaimTerminationController(WatchController):
    """Claim deletion lifecycle (the karpenter-core half): deleted claim ->
    cloud delete -> finalizer release on NodeClaimNotFoundError (the
    contract from vpc/instance/provider.go:1041-1046) -> node removed."""

    name = "nodeclaim.termination"
    watch_kinds = ("nodeclaims",)

    def __init__(self, cluster: ClusterState, actuator: Actuator, factory=None):
        self.cluster = cluster
        self.actuator = actuator
        # optional ProviderFactory: deletes route to the actuator that
        # created the claim (IKS pool decrement vs VPC instance delete)
        self.factory = factory

    def _actuator_for(self, claim):
        if self.factory is not None:
            return self.factory.get_actuator_for_claim(claim)
        return self.actuator

    def reconcile(self, key: str) -> Result:
        claim = self.cluster.get_nodeclaim(key)
        if claim is None or not claim.deleted:
            return Result()
        try:
            self._actuator_for(claim).delete_node(claim)
        except NodeClaimNotFoundError:
            pass   # instance verifiably gone -> release finalizer
        except CloudError as e:
            log.warning("claim delete retrying", claim=key, error=str(e))
            return Result(requeue_after=5.0)
        else:
            # delete_node returning without the not-found signal means the
            # instance may still be draining; verify next pass
            return Result(requeue_after=5.0)
        if CLAIM_FINALIZER in claim.finalizers:
            claim.finalizers.remove(CLAIM_FINALIZER)
        if claim.node_name:
            # node-lifecycle eviction: pods bound to the dying node
            # re-pend (the retry ticker re-windows them)
            self.cluster.evict_node_pods(claim.node_name)
            self.cluster.delete("nodes", claim.node_name)
        self.cluster.delete("nodeclaims", key)
        self.cluster.record_event("NodeClaim", key, "Normal", "Terminated", "")
        return Result()


class GarbageCollectionController(PollController):
    """Cloud<->cluster reconciliation sweep (ref garbagecollection/
    controller.go): instances with no claim -> delete (:106); claims whose
    instance is gone -> finalize; claims never registered past the timeout
    -> replace (:343); orphaned nodes -> delete (:242).  Adaptive interval:
    10s while dirty, 2m when a full sweep finds nothing (:201)."""

    name = "nodeclaim.garbagecollection"
    interval = 120.0
    fast_interval = 10.0
    registration_timeout = 900.0   # 15 min (ref registration TTL)
    min_instance_age = 120.0       # create_instance -> add_nodeclaim race grace

    def __init__(self, cluster: ClusterState, cloud, journal=None):
        from karpenter_tpu.recovery.journal import NULL_JOURNAL

        self.cluster = cluster
        self.cloud = cloud
        self.journal = journal if journal is not None else NULL_JOURNAL

    def reconcile(self) -> Result:
        dirty = 0
        dirty += self._orphan_instances()
        dirty += self._dead_claims()
        dirty += self._unregistered_claims()
        dirty += self._orphan_nodes()
        return Result(requeue_after=self.fast_interval if dirty else self.interval)

    def _claimed_ids(self) -> set:
        ids = set()
        for claim in self.cluster.nodeclaims():
            parsed = parse_provider_id(claim.provider_id)
            if parsed:
                ids.add(parsed[1])
        return ids

    def _orphan_instances(self) -> int:
        """Karpenter-tagged instances with no NodeClaim tracking them."""
        claimed = self._claimed_ids()
        now = time.time()
        n = 0
        for inst in self.cloud.list_instances():
            if inst.id in claimed:
                continue
            if not all(inst.tags.get(k) == v for k, v in KARPENTER_TAGS.items()):
                continue   # not ours — never touch unmanaged instances
            # grace: the actuator creates the instance BEFORE registering
            # the claim; a sweep in that gap must not reap the newborn
            if now - inst.created_at < self.min_instance_age:
                continue
            try:
                with self.journal.intent("orphan_delete", instance=inst.id,
                                         reason="gc_sweep"):
                    self.cloud.delete_instance(inst.id)
                n += 1
                metrics.INSTANCE_LIFECYCLE.labels(
                    "gc_orphan_instance", inst.profile, inst.zone).inc()
                log.info("GC: deleted orphan instance", instance=inst.id)
            except CloudError as e:
                if not is_not_found(e):
                    log.warning("GC: orphan delete failed", instance=inst.id,
                                error=str(e))
        return n

    def _dead_claims(self) -> int:
        """Claims whose backing instance no longer exists -> mark deleted so
        the termination controller finalizes them."""
        n = 0
        for claim in self.cluster.nodeclaims():
            if claim.deleted or not claim.launched:
                continue
            parsed = parse_provider_id(claim.provider_id)
            if parsed is None:
                continue
            try:
                self.cloud.get_instance(parsed[1])
            except CloudError as e:
                if is_not_found(e):
                    claim.deleted = True
                    self.cluster.update("nodeclaims", claim.name, claim)
                    self.cluster.record_event(
                        "NodeClaim", claim.name, "Warning", "InstanceGone",
                        "backing instance disappeared; finalizing claim")
                    n += 1
        return n

    def _unregistered_claims(self) -> int:
        """Launched but never registered past the timeout -> give up and
        delete (pods re-pend, next solve replaces the capacity)."""
        now = time.time()
        n = 0
        for claim in self.cluster.nodeclaims():
            if claim.deleted or claim.registered or not claim.launched:
                continue
            if now - claim.created_at > self.registration_timeout:
                claim.deleted = True
                self.cluster.update("nodeclaims", claim.name, claim)
                self.cluster.record_event(
                    "NodeClaim", claim.name, "Warning", "RegistrationTimeout",
                    f"not registered after {self.registration_timeout:.0f}s")
                n += 1
        return n

    def _orphan_nodes(self) -> int:
        """Nodes with a karpenter providerID but no claim and no instance."""
        claimed_pids = {c.provider_id for c in self.cluster.nodeclaims()
                        if c.provider_id}
        n = 0
        for node in self.cluster.nodes():
            parsed = parse_provider_id(node.provider_id)
            if parsed is None or node.provider_id in claimed_pids:
                continue
            try:
                self.cloud.get_instance(parsed[1])
            except CloudError as e:
                if is_not_found(e):
                    self.cluster.evict_node_pods(node.name)
                    self.cluster.delete("nodes", node.name)
                    log.info("GC: deleted orphan node", node=node.name)
                    n += 1
        return n


class TaggingController(PollController):
    """Ensures Karpenter tags on every claimed instance (ref tagging/
    controller.go:62; VPC mode only :130 — the IKS pool path owns its
    workers' tags)."""

    name = "nodeclaim.tagging"
    interval = 300.0

    def __init__(self, cluster: ClusterState, cloud):
        self.cluster = cluster
        self.cloud = cloud

    def reconcile(self) -> Result:
        for claim in self.cluster.nodeclaims():
            if claim.deleted:
                continue
            parsed = parse_provider_id(claim.provider_id)
            if parsed is None:
                continue
            try:
                inst = self.cloud.get_instance(parsed[1])
            except CloudError:
                continue
            want = {**KARPENTER_TAGS,
                    "karpenter.sh/nodepool": claim.nodepool_name,
                    "karpenter-tpu.sh/nodeclass": claim.nodeclass_name}
            missing = {k: v for k, v in want.items() if inst.tags.get(k) != v}
            if missing:
                try:
                    self.cloud.update_tags(parsed[1], {**inst.tags, **missing})
                except CloudError as e:
                    log.warning("tagging failed", instance=parsed[1],
                                error=str(e))
        return Result()
