"""Bootstrap-token controller.

Parity with the reference's ``pkg/controllers/bootstrap/token_controller.go``:

- ensures the RBAC objects that let TLS-bootstrapping kubelets join exist
  (csr auto-approval bindings, token-authentication group binding —
  token_controller.go:91),
- sweeps expired bootstrap tokens (:190),
- pre-mints a fresh token when none has useful life left (:228), so node
  creation never stalls on token creation in the hot provisioning path.

The reference watches kube-system Secrets; here tokens live in the
in-memory :class:`~karpenter_tpu.core.bootstrap.TokenStore` and RBAC is
modeled as ClusterState objects (kind ``rbac``), which the fake admission
layer and tests can assert on.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.core.bootstrap import TokenStore
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.bootstrap")

# (ref token_controller.go:91-160 — the bindings created on boot)
REQUIRED_BINDINGS = (
    ("karpenter:bootstrap:auto-approve-csr",
     "system:bootstrappers:karpenter:default-node-token",
     "system:certificates.k8s.io:certificatesigningrequests:nodeclient"),
    ("karpenter:bootstrap:auto-approve-renewals",
     "system:nodes",
     "system:certificates.k8s.io:certificatesigningrequests:selfnodeclient"),
    ("karpenter:bootstrap:node-bootstrapper",
     "system:bootstrappers:karpenter:default-node-token",
     "system:node-bootstrapper"),
)


@dataclass
class RBACBinding:
    name: str
    subject_group: str
    role: str
    labels: dict[str, str] = field(default_factory=dict)


class BootstrapTokenController(PollController):
    """Singleton poller (the reference is secret-watch-driven; the token
    set here is process-local, so a 5-minute sweep gives the same
    guarantees)."""

    name = "bootstrap.token"
    interval = 300.0

    # mint a replacement when the freshest token has < this much life —
    # matches TokenStore.find_or_create's reuse threshold so provisioning
    # never needs to mint inline (token.go:85 find-unexpired contract)
    MIN_TOKEN_LIFE = 6 * 3600.0

    def __init__(self, cluster: ClusterState, tokens: TokenStore):
        self.cluster = cluster
        self.tokens = tokens

    def reconcile(self) -> Result:
        self._ensure_rbac()
        removed = self.tokens.cleanup_expired()
        if removed:
            log.info("expired bootstrap tokens removed", count=removed)
        live = self.tokens.live_tokens()
        now = self.tokens._clock()
        if not any(t.expires_at - now > self.MIN_TOKEN_LIFE for t in live):
            t = self.tokens.find_or_create()
            log.info("bootstrap token minted", token_id=t.token_id)
        return Result()

    def _ensure_rbac(self) -> None:
        for name, group, role in REQUIRED_BINDINGS:
            if self.cluster.get("rbac", name) is None:
                self.cluster.add("rbac", name, RBACBinding(
                    name=name, subject_group=group, role=role,
                    labels={"app.kubernetes.io/managed-by": "karpenter-tpu"}))
                log.info("rbac binding ensured", name=name, role=role)

    def missing_bindings(self) -> list[str]:
        return [n for n, _, _ in REQUIRED_BINDINGS
                if self.cluster.get("rbac", n) is None]
