"""Preemption controller: executes priority-aware eviction plans.

The planning half lives in ``karpenter_tpu/preempt`` (pure functions);
this controller owns the messy parts:

- **trigger**: pending pods still unnominated after the provisioning
  plane had its chance (``min_pending_age``) — the solve could not
  create capacity for them (blackouts, quota, pool budgets);
- **budgets**: plans run per NodePool with ``pool.preemption_budget``
  as the eviction cap per reconcile round (0 disables the pool; the
  karpenter spec.disruption.budgets analogue);
- **execution**: victims are stamped back into the pending queue
  (unbound, un-nominated, immediate re-window — the provisioner
  re-places them when capacity returns), beneficiaries are nominated
  onto the freed claims for the scheduler/kubelet bind;
- **safety**: the planner structurally cannot evict equal-or-higher
  priority (``preempt/planner.py``), the ResilientPlanner degrades a
  broken batched path to the greedy host loop, and the independent
  ``validate_preemption_plan`` oracle gates every execution — an
  invalid plan is dropped with an ERRORS breadcrumb, never actuated;
- **evidence**: ``preempt.plan`` / ``preempt.evict`` spans, Preempted
  events, ``karpenter_tpu_preemptions_total{reason}`` + candidate
  metrics, and an ``eviction_log`` the chaos invariants drain
  (no-priority-inversion, preempted-pods-resolve).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass

from karpenter_tpu.apis.nodeclaim import NodePool
from karpenter_tpu.apis.pod import pod_key
from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.preempt.degraded import ResilientPlanner
from karpenter_tpu.preempt.encode import encode_victims, occupancy_index
from karpenter_tpu.preempt.types import PlannerOptions
from karpenter_tpu.recovery import crashpoints
from karpenter_tpu.recovery.journal import NULL_JOURNAL
from karpenter_tpu.solver.encode import encode
from karpenter_tpu.solver.validate import validate_preemption_plan
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.preemption")


@dataclass(frozen=True)
class PreemptionRecord:
    """One executed eviction — the chaos invariants' ground-truth row."""

    pod_key: str
    victim_priority: int
    beneficiary_priority: int
    beneficiary: str
    claim_name: str


class PreemptionController(PollController):
    """Singleton poller: plan + execute priority preemption per pool."""

    name = "preemption"
    interval = 15.0

    def __init__(self, cluster: ClusterState, provisioner,
                 options: PlannerOptions | None = None, clock=time.time,
                 min_pending_age: float = 5.0, journal=None):
        self.cluster = cluster
        self.provisioner = provisioner
        # write-ahead journal: evictions record an intent before the
        # first victim moves, and every victim a durable preempted/
        # state row — a restart rebuilds preempted_keys from it
        self.journal = journal if journal is not None else NULL_JOURNAL
        self.options = options or PlannerOptions()
        self.planner = ResilientPlanner(options=self.options)
        self.clock = clock
        # a pod must have sat unnominated this long before preemption is
        # considered — the provisioning plane (window + retry ticker)
        # gets first shot at CREATING capacity for it.  0 = immediate
        # (the chaos harness, whose pump provisions before every sync).
        # Age is tracked by OUR first-seen stamps, not enqueued_at: the
        # provisioner's retry ticker restamps enqueued_at on every
        # re-window, and both loops run at the same fixed period — an
        # unlucky phase offset would make every stranded pod look
        # permanently "too young" and starve the plane forever.
        self.min_pending_age = min_pending_age
        self._first_pending: dict[str, float] = {}
        # executed-eviction evidence: `eviction_log` is drained per
        # chaos round (no-priority-inversion) and bounded for the
        # operator path, where nothing drains it; `preempted_keys`
        # backs preempted-pods-resolve and is pruned as evicted pods
        # bind again, so neither grows without bound under sustained
        # overload
        self.eviction_log: deque[PreemptionRecord] = deque(maxlen=4096)
        self.preempted_keys: set[str] = set()

    def seed_recovered(self, preempted_keys) -> None:
        """Adopt the restart reconciler's rebuilt ``preempted_keys`` —
        the preempted-pods-resolve contract survives the crash only if
        the new process keeps watching the old process's victims."""
        self.preempted_keys.update(preempted_keys)

    # -- reconcile ---------------------------------------------------------

    def reconcile(self) -> Result:
        if self.provisioner is None:
            return Result()
        now = self.clock()
        for key in list(self.preempted_keys):
            p = self.cluster.get("pods", key)
            if p is None or p.bound_node:
                self.preempted_keys.discard(key)   # resolved (or gone)
                self.journal.state(f"preempted/{key}", None)
        pending = {pod_key(p.spec): p for p in self.cluster.pending_pods()
                   if not p.nominated_node}
        self._first_pending = {k: self._first_pending.get(k, now)
                               for k in pending}
        cutoff = now - self.min_pending_age
        stranded = [p for k, p in pending.items()
                    if self._first_pending[k] <= cutoff]
        if not stranded:
            return Result()
        executed = 0
        budget_blocked = False
        attempted = False
        for pool in self._pools():
            if pool.preemption_budget == 0:
                budget_blocked = True
                continue
            # placements from an earlier pool consume their pods
            stranded = [p for p in stranded if not p.nominated_node]
            if not stranded:
                break
            attempted = True
            executed += self._preempt_pool(pool, stranded)
        if executed:
            log.info("preemption pass", evictions=executed)
        # explain verdict for pods the plane could not help.  A plan
        # that RAN and left them stranded is the most specific truth —
        # no strictly-lower-priority victim worth evicting
        # (priority_starved) — regardless of some OTHER pool being
        # budget-gated; only when no budgeted pool attempted at all is
        # the budget the blocker.
        still = [p for p in stranded if not p.nominated_node]
        if still and (budget_blocked or attempted):
            self._stamp_unhelped(
                still, "priority_starved" if attempted
                else "preemption_budget")
        return Result()

    def _stamp_unhelped(self, stranded: list, reason: str) -> None:
        """Layer the preemption plane's verdict onto the explain
        registry (karpenter_tpu/explain).  Pods whose standing reason is
        STATIC (nothing could ever host them) are skipped — preemption
        was never going to help, and blaming it would contradict the
        consistency oracle."""
        from karpenter_tpu.explain import get_registry
        from karpenter_tpu.explain.validate import STATIC_REASONS

        registry = get_registry()
        for p in stranded:
            key = pod_key(p.spec)
            entry = registry.get(key)
            if entry is not None and entry.reason in STATIC_REASONS:
                continue
            if registry.stamp(key, reason, detail="preemption plane"):
                self.cluster.record_event(
                    "Pod", key, "Warning", "Unplaced",
                    f"cannot place: {reason}")
        registry.update_unplaced_gauge()

    def _pools(self) -> list[NodePool]:
        # the provisioner's resolution, not a reimplementation: it knows
        # the configured default_nodepool name — a hardcoded "default"
        # here would silently dead-end the plane on customized setups
        return self.provisioner._pools()

    def _pool_claims(self, pool: NodePool) -> list:
        # a synthesized pool (no cluster object) also owns claims with
        # no nodepool_name stamp (manually adopted capacity)
        synthesized = self.cluster.get("nodepools", pool.name) is None
        return [c for c in self.cluster.nodeclaims()
                if not c.deleted and c.launched
                and (c.nodepool_name == pool.name
                     or (synthesized and not c.nodepool_name))]

    def _preempt_pool(self, pool: NodePool, stranded: list) -> int:
        claims = self._pool_claims(pool)
        if not claims:
            return 0
        nodeclass = self.cluster.get_nodeclass(pool.nodeclass_name) \
            or self.cluster.get_nodeclass("default")
        if nodeclass is None:
            return 0
        catalog = self.provisioner._catalog_for(nodeclass)
        if catalog is None:
            return 0
        # plan + execute under the solve lock: a concurrent window
        # nominating one of these pods (or onto one of these claims)
        # would race the capacity accounting
        with self.provisioner._solve_lock:
            pods = [p.spec for p in stranded
                    if not p.nominated_node and not p.bound_node]
            if not pods:
                return 0
            t0 = time.perf_counter()
            with obs.span("preempt.plan", pool=pool.name,
                          pending=len(pods)) as sp:
                problem = encode(pods, catalog, pool)
                # one pod-collection scan shared by the victim encoder
                # and the validation oracle (both on this lock-holding
                # path; nothing mutates occupancy between them)
                occupancy = occupancy_index(self.cluster)
                victims = encode_victims(self.cluster, catalog,
                                         claims=claims,
                                         occupancy=occupancy)
                if victims.num_nodes == 0:
                    return 0
                budget = pool.preemption_budget
                self.planner.options.max_evictions = \
                    budget if budget >= 0 else -1
                plan = self.planner.plan(problem, victims)
                sp.set("backend", plan.backend)
                sp.set("candidates", plan.candidate_count)
                sp.set("evictions", plan.eviction_count)
                sp.set("placed", plan.placed_count)
                metrics.PREEMPTION_CANDIDATES.observe(plan.candidate_count)
                metrics.PREEMPTION_PLAN_DURATION.labels(
                    plan.backend).observe(time.perf_counter() - t0)
                if plan.empty:
                    return 0
                # independent oracle gate: never actuate an invalid plan
                errors = validate_preemption_plan(plan, pods, self.cluster,
                                                  catalog, pool,
                                                  occupancy=occupancy)
                if errors:
                    metrics.ERRORS.labels("preempt", "invalid_plan").inc()
                    sp.set("invalid", len(errors))
                    log.error("preemption plan failed validation; dropped",
                              pool=pool.name, errors=errors[:3])
                    return 0
                return self._execute(plan, pool)

    def _execute(self, plan, pool: NodePool) -> int:
        """Evict victims, then nominate beneficiaries (that order: a bind
        racing the eviction must see the capacity already released).
        The whole eviction batch runs under one write-ahead intent: a
        crash mid-batch leaves the intent open, and the restart
        reconciler re-pends exactly the victims the notes say moved."""
        executed = 0
        with self.journal.intent(
                "eviction", pool=pool.name,
                pods=[ev.pod_key for ev in plan.evictions]) as intent:
            for ev in plan.evictions:
                pending = self.cluster.get("pods", ev.pod_key)
                if pending is None:
                    continue
                crashpoints.hit("preempt.mid_evict")
                with obs.span("preempt.evict", pod=ev.pod_key,
                              claim=ev.claim_name,
                              victim_priority=ev.victim_priority,
                              beneficiary_priority=ev.beneficiary_priority):
                    pending.bound_node = ""
                    pending.nominated_node = ""
                    pending.enqueued_at = 0.0   # immediate re-window
                    # SLO ledger: the victim's placement clock restarts —
                    # its re-placement resolves as outcome "replaced"
                    obs.get_ledger().reopen(ev.pod_key, "preempted")
                    executed += 1
                intent.note(f"evicted:{ev.pod_key}", pod=ev.pod_key)
                self.journal.state(f"preempted/{ev.pod_key}", 1)
                metrics.PREEMPTIONS.labels("priority").inc()
                self.cluster.record_event(
                    "Pod", ev.pod_key, "Warning", "Preempted",
                    f"evicted from {ev.claim_name} (priority "
                    f"{ev.victim_priority}) for a priority "
                    f"{ev.beneficiary_priority} pod")
                rec = PreemptionRecord(
                    pod_key=ev.pod_key, victim_priority=ev.victim_priority,
                    beneficiary_priority=ev.beneficiary_priority,
                    beneficiary=ev.beneficiary, claim_name=ev.claim_name)
                self.eviction_log.append(rec)
                self.preempted_keys.add(ev.pod_key)
        placed = 0
        for pn, claim_name in plan.placements.items():
            pending = self.cluster.get("pods", pn)
            if pending is None or pending.bound_node \
                    or pending.nominated_node:
                continue
            pending.nominated_node = claim_name
            self.journal.state(f"nom/{pn}", claim_name)
            obs.get_ledger().resolve(pn, "placed")
            from karpenter_tpu.explain import get_registry

            get_registry().resolve(pn)
            placed += 1
            self.cluster.record_event(
                "Pod", pn, "Normal", "PreemptionPlaced",
                f"nominated onto existing node {claim_name} by the "
                f"preemption planner")
        if executed or placed:
            obs.instant("preempt.executed", pool=pool.name,
                        evictions=executed, placed=placed)
        return executed
