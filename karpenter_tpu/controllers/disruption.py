"""Disruption controller: drift replacement + consolidation.

In the reference these live in karpenter-core (the drift and disruption
controllers call ``CloudProvider.IsDrifted`` — SURVEY.md §3.4 — and run
empty/underutilized consolidation against the cluster state).  The
standalone framework owns both:

- **Drift replacement**: claims whose NodeClass has moved under them
  (hash/hash-version/image/subnet/security-group drift, core/drift.py)
  are replaced — pods are unbound back to pending, the claim is deleted
  (the termination controller finalizes the instance), and the
  provisioning window re-places the pods against the *current* spec.
- **Empty consolidation**: nodes with no bound pods past the pool's
  ``consolidate_after_seconds`` are removed (policy gate:
  WhenEmpty / WhenEmptyOrUnderutilized).
- **Underutilized consolidation**: karpenter's single-node move — if every
  pod on a node provably fits in the residual capacity of other live
  nodes, bind them there directly (this framework owns the scheduler
  role, so the rebind is ours to do, not a kube-scheduler's) and delete
  the node.  Savings-first order: cheapest-to-remove nodes go first.

The full cost-optimal *repack* (BASELINE config #4) reuses the solver:
``propose_repack`` returns the fresh-solve plan and its cost delta vs the
live fleet; the poll loop only *executes* the safe single-node moves, so
actuation stays idempotent while the repack remains observable (and is
what bench_fleet exercises on TPU).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from karpenter_tpu.apis.nodeclaim import NodeClaim, NodePool
from karpenter_tpu.apis.pod import NUM_RESOURCES
from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.core.cloudprovider import CloudProvider
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.disruption")


@dataclass(frozen=True)
class RepackRecord:
    """Ground-truth evidence of one EXECUTED migration plan — the chaos
    harness's ``repack-plan-valid`` invariant re-derives plan validity
    (no pod dropped, capacity respected, claimed slices actually
    reopened) from these records + the live cluster, the same drained-
    log discipline the preemption/gang invariants use."""

    migrations: tuple = ()       # (pod_key, src_claim, dst_claim) triples
    drained: tuple = ()          # claim names deleted by the plan
    # (claim_name, offering, shape, pre_mask, post_mask) per reopened
    # slice — geometry evidence the invariant re-enumerates from scratch
    reopened: tuple = ()
    backend: str = ""
    savings_fraction: float = 0.0


@dataclass
class RepackProposal:
    """Observable outcome of a fresh fleet solve vs the live fleet."""

    current_cost: float
    proposed_cost: float
    plan: object = None            # solver Plan
    savings: float = 0.0
    nodeclass: object = None       # resolved once; apply reuses it
    catalog: object = None
    pool: object = None


@dataclass
class _PendingRepack:
    """Phase-2 state: new fleet created, waiting for it to become Ready
    before any pod moves or old capacity drains."""

    new_claims: list
    old_claim_names: list
    pod_map: dict                  # pod key -> new claim name
    deadline: float
    current_cost: float
    proposed_cost: float


class DisruptionController(PollController):
    """Singleton poller (10s — the repack cadence of BASELINE config #4)."""

    name = "disruption"
    interval = 10.0

    def __init__(self, cluster: ClusterState, cloudprovider: CloudProvider,
                 provisioner=None, clock=time.time,
                 repack_enabled: bool = False,
                 repack_min_savings_fraction: float = 0.15,
                 repack_cooldown: float = 600.0,
                 resident_occupancy: bool = False,
                 repack_migrate: bool = True,
                 repack_rebuild: bool = True,
                 repack_options=None, journal=None):
        from karpenter_tpu.recovery.journal import NULL_JOURNAL

        self.cluster = cluster
        self.cloudprovider = cloudprovider
        self.provisioner = provisioner
        self.clock = clock
        # write-ahead journal: an executed migration plan records an
        # intent before the first rebind, so a crash mid-plan re-pends
        # the interrupted pods on restart (docs/design/recovery.md)
        self.journal = journal if journal is not None else NULL_JOURNAL
        # KARPENTER_ENABLE_RESIDENT: the consolidation passes read node
        # occupancy through ONE shared per-tick snapshot
        # (resident/store.OccupancySnapshot) instead of one full pod
        # scan per claim — results pinned bit-identical to the rescan
        # path (tests/test_resident.py)
        self.resident_occupancy = resident_occupancy
        self._occ = None
        # cost-optimal repack (BASELINE config #4 actuated): OFF by
        # default — blue/green churn is a policy decision, gated like the
        # reference's consolidation policies.  Hysteresis: a minimum
        # savings fraction plus a cooldown between applications.
        self.repack_enabled = repack_enabled
        self.repack_min_savings_fraction = repack_min_savings_fraction
        self.repack_cooldown = repack_cooldown
        self.repack_ready_timeout = 900.0   # new-fleet Ready deadline
        self._last_repack = 0.0             # stamped on EVERY attempt —
        # a converged fleet must not pay a full fresh solve per 10s poll
        self._pending_repack: _PendingRepack | None = None
        # migration-first repack (karpenter_tpu/repack): one batched
        # LP-relaxed plan over EXISTING capacity — drains + defrag moves
        # actuated directly (no create burst, no blue/green wait) when
        # the plan clears the same savings-fraction hysteresis, or
        # reopens a parked gang slice.  The blue/green fresh-solve
        # transition below remains the fallback for savings only a
        # re-typed fleet can reach.
        self.repack_migrate = repack_migrate
        # the blue/green fresh-solve rebuild (phase-1 create burst +
        # Ready-gated cutover).  Off = migration-only repack: no create
        # bursts, no transition state — what the chaos harness runs,
        # where a rollback's re-pended pods would race the round clock.
        self.repack_rebuild = repack_rebuild
        self._repacker = None
        self._repack_options = repack_options
        # ground truth for the chaos repack-plan-valid invariant:
        # executed plans (drained per check) + choke-point validator
        # errors (an invalid plan is never actuated, but the harness
        # must still see that it was produced)
        self.repack_log: list[RepackRecord] = []
        self.repack_violations: list[str] = []

    # -- reconcile ---------------------------------------------------------

    def reconcile(self) -> Result:
        drifted = self._replace_drifted()
        # consolidation pauses while a repack transition is in flight:
        # the new fleet is intentionally empty until cutover, so empty
        # consolidation would reap it (and underutilized moves would use
        # unproven nodes as targets / drain old capacity early)
        transitioning = self._pending_repack is not None
        # the occupancy snapshot is built AFTER drift replacement (which
        # unbinds pods) and stays live through the migration repack (its
        # moves ride rebind(), same as consolidation); it is torn down
        # before the blue/green fallback can renominate pending pods the
        # snapshot does not track (nothing reads it after that point)
        if self.resident_occupancy and not transitioning:
            from karpenter_tpu.resident.store import OccupancySnapshot

            self._occ = OccupancySnapshot(self.cluster)
        try:
            emptied = 0 if transitioning else self._consolidate_empty()
            moved = 0 if transitioning else self._consolidate_underutilized()
            repacked = self._repack_if_profitable() \
                if self.repack_enabled else 0
        finally:
            self._occ = None
        if drifted or emptied or moved or repacked:
            log.info("disruption pass", drifted=drifted, empty=emptied,
                     consolidated=moved, repacked=repacked)
        return Result()

    # -- drift (SURVEY.md §3.4) -------------------------------------------

    def _replace_drifted(self) -> int:
        n = 0
        for claim in self.cluster.nodeclaims():
            if claim.deleted or not claim.registered:
                continue
            reason = self.cloudprovider.is_drifted(claim)
            if not reason:
                continue
            # (is_drifted already counted the detection metric)
            log.info("drifted claim replaced", claim=claim.name,
                     reason=reason)
            self._evict_and_delete(claim)
            n += 1
        return n

    # -- consolidation -----------------------------------------------------

    def _pool_for(self, claim: NodeClaim) -> NodePool:
        pool = self.cluster.get("nodepools", claim.nodepool_name)
        return pool if pool is not None else NodePool(name="default")

    EMPTY_SINCE_ANNOTATION = "karpenter-tpu.sh/empty-since"

    def _consolidate_empty(self) -> int:
        now = self.clock()
        n = 0
        for claim in self.cluster.nodeclaims():
            if claim.deleted or not claim.initialized or not claim.node_name:
                continue
            pool = self._pool_for(claim)
            if pool.consolidation_policy not in (
                    "WhenEmpty", "WhenEmptyOrUnderutilized"):
                continue
            if self._claim_pods(claim):
                # node busy again: reset the emptiness clock so a later
                # drain restarts the consolidateAfter damping window
                if claim.annotations.pop(self.EMPTY_SINCE_ANNOTATION, None):
                    self.cluster.update("nodeclaims", claim.name, claim)
                continue
            # consolidateAfter measures from when the node *became* empty
            # (karpenter semantics), not from node creation — a long-lived
            # node must still wait out the window after its last pod exits
            empty_since = claim.annotations.get(self.EMPTY_SINCE_ANNOTATION)
            if empty_since is None:
                claim.annotations[self.EMPTY_SINCE_ANNOTATION] = repr(now)
                self.cluster.update("nodeclaims", claim.name, claim)
                empty_since = repr(now)
            if now - float(empty_since) < pool.consolidate_after_seconds:
                continue
            log.info("empty node consolidated", claim=claim.name)
            self._evict_and_delete(claim)
            n += 1
        return n

    def _consolidate_underutilized(self) -> int:
        """Single-node move: cheapest removable node whose pods all fit in
        the other nodes' residuals; pods are rebound directly."""
        claims = [c for c in self.cluster.nodeclaims()
                  if not c.deleted and c.initialized and c.node_name
                  and self._pool_for(c).consolidation_policy
                  == "WhenEmptyOrUnderutilized"]
        if len(claims) < 2:
            return 0
        resid = {c.name: self._residual(c) for c in claims}
        moved = 0
        # cheapest first: removing a low-price node frees least value, but
        # is likeliest to fit elsewhere; karpenter sorts by disruption cost
        for claim in sorted(claims, key=lambda c: c.hourly_price):
            pods = self._claim_pods(claim)
            if not pods:
                continue
            if any((p := self.cluster.get("pods", pk)) is not None
                   and (not p.bound_node or p.spec.gang is not None)
                   for pk in pods):
                # in-flight nominations: the node is about to RECEIVE
                # pods — rebinding an unbound nomination here would
                # bypass the kubelet bind.  Gang members are immovable
                # outright: a single-node move scatters an atomically
                # co-located gang (and voids its slice geometry) — the
                # same movability rule the repack plane enforces.
                continue
            placement = self._fit_elsewhere(claim, pods, claims, resid)
            if placement is None:
                continue
            for pod, target in placement:
                p = self.cluster.get("pods", pod)
                if p is not None:
                    # clear the stale nomination: leaving it pointing at
                    # the OLD claim lets that claim's finalizer
                    # (evict_node_pods matches nominated too) rip the
                    # pod off its new home later
                    p.nominated_node = ""
                self.cluster.bind_pod(pod, target.node_name)
                if self._occ is not None:
                    self._occ.rebind(pod, target.node_name, "")
                resid[target.name] = resid[target.name] - \
                    self._pod_req(pod)
            log.info("underutilized node consolidated", claim=claim.name,
                     pods_moved=len(placement))
            self._delete_claim(claim)
            claims.remove(claim)
            moved += 1
        return moved

    # -- repack (observable; BASELINE config #4) --------------------------

    def propose_repack(self) -> RepackProposal | None:
        """Fresh solve of the entire workload vs the live fleet cost.
        Single-pool scope: with multiple NodePools (or pool taints the
        solve can't reproduce without pool context) the repack proposal
        declines rather than produce a fleet stripped of pool policy."""
        if self.provisioner is None:
            return None
        from karpenter_tpu.solver.types import SolveRequest

        pools = self.cluster.list("nodepools")
        if len(pools) > 1:
            return None
        pool = pools[0] if pools else None
        claims = [c for c in self.cluster.nodeclaims() if not c.deleted]
        if not claims:
            return None
        current = sum(c.hourly_price for c in claims)
        wanted = pool.nodeclass_name if pool and pool.nodeclass_name \
            else "default"
        nodeclass = self.cluster.get_nodeclass(wanted)
        if nodeclass is None:
            # decline rather than silently rebuilding the fleet from a
            # DIFFERENT nodeclass (drift would immediately fight it)
            return None
        catalog = self.provisioner._catalog_for(nodeclass)
        if catalog is None:
            return None
        if pool is not None and (pool.cpu_limit_milli
                                 or pool.memory_limit_mib):
            # blue/green repack doubles the pool's footprint during the
            # overlap, which a resource-limited pool cannot absorb;
            # rather than transiently violate spec.limits (or apply a
            # trimmed fleet that strands pods mid-replacement), repack
            # defers to the consolidation paths for capped pools
            log.info("repack skipped: pool has resource limits",
                     pool=pool.name)
            return None
        pods = [p.spec for p in self.cluster.list("pods")]
        if not pods:
            return None
        plan = self.provisioner.solver.solve(
            SolveRequest(pods, catalog, pool))
        return RepackProposal(
            current_cost=current, proposed_cost=plan.total_cost_per_hour,
            plan=plan, savings=current - plan.total_cost_per_hour,
            nodeclass=nodeclass, catalog=catalog, pool=pool)

    def _repack_if_profitable(self) -> int:
        """Two-phase blue/green repack, serialized behind the
        provisioner's solve lock (a concurrent solve window and a repack
        solving the same pods would double-provision).

        Phase 1: fresh solve; when it places everything and saves at
        least the threshold, CREATE the new fleet — and stop.  No pod
        moves, no old capacity drained: the plan is unproven until its
        nodes are Ready.  Phase 2 (subsequent polls): once every new
        claim is initialized, renominate the pods onto their planned
        nodes and drain the old fleet; if the new fleet misses the Ready
        deadline, roll IT back and keep the old fleet serving."""
        if self.provisioner is None:
            return 0
        with self.provisioner._solve_lock:
            if self._pending_repack is not None:
                return self._advance_pending_repack_locked()
            now = self.clock()
            if now - self._last_repack < self.repack_cooldown:
                return 0
            self._last_repack = now   # stamp EVERY attempt (poll damping)
            if self.repack_migrate:
                migrated = self._repack_migrate_locked()
                if migrated:
                    return migrated
            if not self.repack_rebuild:
                return 0
            proposal = self.propose_repack()
            if proposal is None or proposal.current_cost <= 0:
                return 0
            if proposal.plan.unplaced_pods:
                return 0   # the fresh solve can't host the full workload
            if proposal.savings < \
                    self.repack_min_savings_fraction * proposal.current_cost:
                return 0
            old_names = [c.name for c in self.cluster.nodeclaims()
                         if not c.deleted]
            actuator = self.provisioner.actuator_for(proposal.nodeclass)
            # repack creates its fleet in one burst and cannot make
            # incremental progress on partial creates — defer when the
            # plan exceeds the breaker's per-minute budget instead of
            # churning create/rollback every cooldown
            breaker = getattr(actuator, "breaker", None)
            if breaker is not None and getattr(breaker, "config", None) \
                    is not None and breaker.config.enabled and \
                    len(proposal.plan.nodes) > \
                    breaker.config.rate_limit_per_minute:
                log.warning(
                    "repack deferred: plan exceeds the circuit breaker's "
                    "provision rate budget",
                    plan_nodes=len(proposal.plan.nodes),
                    rate_limit=breaker.config.rate_limit_per_minute)
                return 0
            pool_name = proposal.pool.name if proposal.pool is not None \
                else "default"
        # the create burst runs OUTSIDE the solve lock — per-node cloud
        # calls must not stall unrelated solve windows; the old_names
        # snapshot was taken under the lock, so claims a concurrent
        # window creates are never drained at cutover
        new_claims, errors = actuator.execute_plan(
            proposal.plan, proposal.nodeclass, proposal.catalog,
            nodepool_name=pool_name)
        if errors or any(c is None for c in new_claims):
            # roll back: the old fleet keeps serving.  Stamp the cooldown
            # so the failure backs off instead of retrying next poll.
            for c in new_claims:
                if c is not None:
                    self._delete_claim(c)
            # single-writer: only this controller's keyed reconcile
            # thread touches the cooldown stamp, and the create burst
            # deliberately runs outside the solve lock (see above)
            self._last_repack = self.clock()  # graftlint: disable=GL103
            log.warning("repack aborted on partial create",
                        errors=errors[:3])
            return 0
        with self.provisioner._solve_lock:
            pod_map = {pk: claim.name
                       for node, claim in zip(proposal.plan.nodes, new_claims)
                       for pk in node.pod_names}
            # pods that are still PENDING (unnominated, unbound) nominate
            # onto the new fleet immediately — exactly what a provisioner
            # window would do — so no concurrent window double-provisions
            # them during the Ready wait.  Pods bound to old nodes move
            # only at cutover.
            for pk, claim_name in pod_map.items():
                p = self.cluster.get("pods", pk)
                if p is not None and not p.bound_node \
                        and not p.nominated_node:
                    p.nominated_node = claim_name
            self._pending_repack = _PendingRepack(
                new_claims=new_claims, old_claim_names=old_names,
                pod_map=pod_map,
                deadline=self.clock() + self.repack_ready_timeout,
                current_cost=proposal.current_cost,
                proposed_cost=proposal.proposed_cost)
        log.info("repack phase 1: new fleet created, awaiting Ready",
                 new_nodes=len(new_claims), old_nodes=len(old_names))
        return 0   # nothing moved yet

    def _repack_migrate_locked(self) -> int:
        """Migration-first repack: plan one batched LP-relaxed
        consolidation + defrag pass over EXISTING capacity (the
        karpenter_tpu/repack plane, fed from the resident occupancy
        substrate), validate it with the independent
        ``validate_repack_plan`` oracle, then actuate — pods rebound
        directly (this framework owns the scheduler role), emptied
        nodes drained.  Same single-pool scope and savings-fraction
        hysteresis as the blue/green path; a plan that reopens a parked
        gang slice actuates regardless of savings (a starving gang
        outranks cost hysteresis)."""
        if self.provisioner is None:
            return 0
        pools = self.cluster.list("nodepools")
        if len(pools) > 1:
            return 0
        pool = pools[0] if pools else None
        wanted = pool.nodeclass_name if pool and pool.nodeclass_name \
            else "default"
        nodeclass = self.cluster.get_nodeclass(wanted)
        if nodeclass is None:
            return 0
        catalog = self.provisioner._catalog_for(nodeclass)
        if catalog is None:
            return 0
        from karpenter_tpu.repack import (
            ResilientRepacker, RepackOptions, encode_repack,
        )
        from karpenter_tpu.resident.store import resident_store_of
        from karpenter_tpu.solver.validate import validate_repack_plan

        if self._repacker is None:
            self._repacker = ResilientRepacker(
                options=self._repack_options or RepackOptions())
        store = resident_store_of(self.provisioner.solver) \
            if self.resident_occupancy else None
        t0 = time.perf_counter()
        with obs.span("repack.plan", pool=pool.name if pool else "") as sp:
            problem = encode_repack(self.cluster, catalog, pool,
                                    snapshot=self._occ, store=store)
            plan = self._repacker.plan(problem)
            sp.set("backend", plan.backend)
            sp.set("nodes", problem.num_nodes)
            sp.set("migrations", plan.migration_count)
            sp.set("drained", len(plan.drained))
            sp.set("slices_reopened", plan.slices_reopened)
        metrics.REPACK_PLAN_DURATION.labels(plan.backend).observe(
            time.perf_counter() - t0)
        if plan.empty:
            return 0
        profitable = plan.current_cost > 0 and plan.savings >= \
            self.repack_min_savings_fraction * plan.current_cost
        if not profitable and plan.slices_reopened == 0:
            return 0
        # independent oracle gate: never actuate an invalid plan (same
        # choke-point discipline as preempt/gang execution)
        errors = validate_repack_plan(plan, self.cluster, catalog, pool)
        if errors:
            metrics.ERRORS.labels("repack", "invalid_plan").inc()
            self.repack_violations.extend(errors[:10])
            log.error("repack migration plan failed validation; dropped",
                      errors=errors[:3])
            return 0
        return self._actuate_repack_plan(plan)

    def _actuate_repack_plan(self, plan) -> int:
        from karpenter_tpu.repack.types import KIND_DRAIN

        claims = {c.name: c for c in self.cluster.nodeclaims()
                  if not c.deleted}
        moved = 0
        with self.journal.intent(
                "repack_migration",
                migrations=[(m.pod_key, m.src_claim, m.dst_claim)
                            for m in plan.migrations],
                drained=list(plan.drained)) as intent:
            for m in plan.migrations:
                dst = claims.get(m.dst_claim)
                if dst is None:
                    continue
                p = self.cluster.get("pods", m.pod_key)
                if p is not None:
                    # re-home fully: a nomination left dangling on the
                    # source claim would keep counting against its chips
                    p.nominated_node = ""
                self.cluster.bind_pod(m.pod_key, dst.node_name)
                if self._occ is not None:
                    self._occ.rebind(m.pod_key, dst.node_name, "")
                intent.note(f"moved:{m.pod_key}", dst=m.dst_claim)
                metrics.REPACK_MIGRATIONS.labels(
                    "consolidate" if m.kind == KIND_DRAIN else "defrag").inc()
                moved += 1
            drained = 0
            for name in plan.drained:
                claim = self.cluster.get_nodeclaim(name)
                if claim is not None and not claim.deleted:
                    # occupants were all migrated above; eviction only
                    # re-pends stragglers that raced onto the node
                    self._evict_and_delete(claim)
                    drained += 1
        if plan.slices_reopened:
            metrics.REPACK_SLICES_REOPENED.inc(plan.slices_reopened)
        metrics.REPACK_SAVINGS_FRACTION.set(plan.savings_fraction)
        self.repack_log.append(RepackRecord(
            migrations=tuple((m.pod_key, m.src_claim, m.dst_claim)
                             for m in plan.migrations),
            drained=tuple(plan.drained),
            reopened=tuple((r.claim_name, r.offering, tuple(r.shape),
                            r.pre_mask, r.post_mask)
                           for r in plan.reopened),
            backend=plan.backend,
            savings_fraction=plan.savings_fraction))
        self.cluster.record_event(
            "NodeClaim", "fleet", "Normal", "RepackMigrated",
            f"${plan.current_cost:.2f}/h -> ${plan.proposed_cost:.2f}/h "
            f"({moved} pods moved, {drained} nodes drained, "
            f"{plan.slices_reopened} slices reopened)")
        log.info("repack migration plan actuated", migrations=moved,
                 drained=drained, slices_reopened=plan.slices_reopened,
                 backend=plan.backend,
                 savings_fraction=round(plan.savings_fraction, 4))
        defrag_sources = {r.claim_name for r in plan.reopened}
        return drained + len(defrag_sources)

    def _advance_pending_repack_locked(self) -> int:
        pending = self._pending_repack
        fresh = [self.cluster.get_nodeclaim(c.name)
                 for c in pending.new_claims]
        if any(c is None or c.deleted for c in fresh):
            # GC/interruption took a new node out before cutover: abandon
            self._rollback_pending_locked("new fleet lost a node before Ready")
            return 0
        if not all(c.initialized for c in fresh):
            if self.clock() > pending.deadline:
                self._rollback_pending_locked("new fleet missed the Ready deadline")
            return 0
        # cutover: every new node proved Ready — move pods, drain old
        for pk, claim_name in pending.pod_map.items():
            p = self.cluster.get("pods", pk)
            if p is not None:
                p.bound_node = ""
                p.nominated_node = claim_name
        drained = 0
        for name in pending.old_claim_names:
            old = self.cluster.get_nodeclaim(name)
            if old is not None and not old.deleted:
                # pods in pod_map were just renominated (bound_node
                # cleared), so eviction only re-pends stragglers that
                # landed on the old node after the phase-1 snapshot
                self._evict_and_delete(old)
                drained += 1
        self.cluster.record_event(
            "NodeClaim", "fleet", "Normal", "Repacked",
            f"${pending.current_cost:.2f}/h -> "
            f"${pending.proposed_cost:.2f}/h "
            f"({drained} -> {len(pending.new_claims)} nodes)")
        log.info("repack phase 2: cutover complete", drained=drained,
                 new_nodes=len(pending.new_claims))
        self._pending_repack = None
        self._last_repack = self.clock()
        return 1

    def _rollback_pending_locked(self, why: str) -> None:
        for c in self._pending_repack.new_claims:
            live = self.cluster.get_nodeclaim(c.name)
            if live is not None and not live.deleted:
                # eviction, not bare delete: anything that bound onto a
                # new node during the wait must re-pend, not strand
                self._evict_and_delete(live)
        log.warning("repack rolled back", reason=why)
        self._pending_repack = None
        # a failed transition backs off a full cooldown before retrying
        self._last_repack = self.clock()

    # -- helpers -----------------------------------------------------------

    def _claim_pods(self, claim: NodeClaim) -> list[str]:
        """Pods homed on ``claim`` under EITHER name — bound/nominated to
        its node, or nominated onto the claim itself (the provisioner
        nominates by CLAIM name; a node-name-only scan would call a node
        with in-flight nominations 'empty' and strand them on delete).
        Same two-name union as ``preempt.encode.claim_pods``."""
        seen: dict[str, None] = {}
        for name in (claim.node_name, claim.name):
            for pk in self._bound_pods(name):
                seen.setdefault(pk, None)
        return list(seen)

    def _bound_pods(self, node_name: str) -> list[str]:
        from karpenter_tpu.apis.pod import pod_key

        if not node_name:
            # a never-joined claim has node_name "" — matching it against
            # pods would claim every un-nominated pod in the cluster
            return []
        if self._occ is not None:
            # one shared snapshot per tick (KARPENTER_ENABLE_RESIDENT):
            # same pods, same order as the rescan below — in-pass moves
            # and evictions keep it current via rebind()/unbind()
            return self._occ.pods_on(node_name)
        return [pod_key(p.spec) for p in self.cluster.list("pods")
                if p.bound_node == node_name
                or p.nominated_node == node_name]

    def _pod_req(self, pod_key_str: str) -> np.ndarray:
        pending = self.cluster.get("pods", pod_key_str)
        if pending is None:
            return np.zeros(NUM_RESOURCES, dtype=np.int64)
        req = pending.spec.requests.as_tuple()
        return np.array((req[0], req[1], req[2], max(req[3], 1)),
                        dtype=np.int64)

    def _alloc(self, claim: NodeClaim) -> np.ndarray:
        it = self.cloudprovider.instance_types.get(claim.instance_type)
        if it is None:
            return np.zeros(NUM_RESOURCES, dtype=np.int64)
        return np.array((it.allocatable_cpu_milli, it.allocatable_memory_mib,
                         it.gpu, it.pods), dtype=np.int64)

    def _residual(self, claim: NodeClaim) -> np.ndarray:
        resid = self._alloc(claim)
        for pk in self._claim_pods(claim):
            resid = resid - self._pod_req(pk)
        return resid

    def _target_labels(self, claim: NodeClaim) -> dict[str, str]:
        """Effective scheduling labels of the node backing ``claim``: claim
        labels + pool static labels + well-known placement labels (mirrors
        what the actuator/registration stamp on the real node)."""
        from karpenter_tpu.apis.requirements import (
            LABEL_CAPACITY_TYPE, LABEL_HOSTNAME, LABEL_INSTANCE_TYPE,
            LABEL_NODEPOOL, LABEL_ZONE)

        labels = dict(self._pool_for(claim).labels)
        labels.update(claim.labels)
        labels.setdefault(LABEL_INSTANCE_TYPE, claim.instance_type)
        labels.setdefault(LABEL_ZONE, claim.zone)
        labels.setdefault(LABEL_CAPACITY_TYPE, claim.capacity_type)
        labels.setdefault(LABEL_NODEPOOL, claim.nodepool_name)
        labels.setdefault(LABEL_HOSTNAME, claim.node_name)
        return labels

    def _pod_compatible(self, spec, victim: NodeClaim, target: NodeClaim,
                        target_labels: dict[str, str],
                        planned_on_target: list) -> bool:
        """Full compatibility of a pod move onto ``target`` — the same
        constraints the solver's compat mask enforces at placement time
        (node selectors / required affinity, taints, zone co-location,
        hostname anti-affinity cap).  Reference karpenter simulates full
        scheduling before consolidating; moves that only check resources
        can silently break zone pins and taint gates."""
        from karpenter_tpu.apis.pod import tolerates_all
        from karpenter_tpu.solver.encode import (
            _has_hostname_anti_affinity, _has_zone_affinity,
            _zone_spread_constraints)

        if not spec.scheduling_requirements().matches(target_labels):
            return False
        pool = self._pool_for(target)
        if not tolerates_all(spec.tolerations, target.taints) or \
                not tolerates_all(spec.tolerations, pool.taints):
            return False
        # zone co-schedule affinity and DoNotSchedule zone spread: keep the
        # pod in its current zone so group purity / skew is preserved
        if (_has_zone_affinity(spec) or _zone_spread_constraints(spec)) \
                and target.zone != victim.zone:
            return False
        # hostname anti-affinity (self): at most one matching pod per node
        if _has_hostname_anti_affinity(spec):
            own = spec.labels_dict
            for other in self._pods_on(target, planned_on_target):
                if other is not None and all(
                        other.labels_dict.get(k) == v
                        for k, v in own.items()) and own:
                    return False
        return True

    def _pods_on(self, claim: NodeClaim, planned: list):
        """PodSpecs currently bound to ``claim``'s node plus any planned
        moves onto it within this consolidation pass."""
        out = []
        for pk in self._claim_pods(claim):
            pending = self.cluster.get("pods", pk)
            if pending is not None:
                out.append(pending.spec)
        out.extend(planned)
        return out

    def _fit_elsewhere(self, victim: NodeClaim, pods: list[str],
                       claims: list[NodeClaim],
                       resid: dict[str, np.ndarray]
                       ) -> list[tuple[str, NodeClaim]] | None:
        """First-fit each pod into other nodes' residuals (on a working
        copy), honoring the pod's full scheduling constraints against each
        candidate target; None if any pod does not fit."""
        work = {k: v.copy() for k, v in resid.items()}
        placement: list[tuple[str, NodeClaim]] = []
        planned: dict[str, list] = {}
        others = [c for c in claims if c.name != victim.name]
        labels = {c.name: self._target_labels(c) for c in others}
        for pk in pods:
            req = self._pod_req(pk)
            pending = self.cluster.get("pods", pk)
            spec = pending.spec if pending is not None else None
            target = None
            for c in others:
                if not (work[c.name] >= req).all():
                    continue
                if spec is not None and not self._pod_compatible(
                        spec, victim, c, labels[c.name],
                        planned.get(c.name, [])):
                    continue
                target = c
                break
            if target is None:
                return None
            work[target.name] = work[target.name] - req
            if spec is not None:
                planned.setdefault(target.name, []).append(spec)
            placement.append((pk, target))
        return placement

    def _evict_and_delete(self, claim: NodeClaim) -> None:
        """Unbind the node's pods back to pending, then delete the claim
        (the termination controller finalizes the instance; the window
        re-places the pods)."""
        for pk in self._claim_pods(claim):
            pending = self.cluster.get("pods", pk)
            if pending is not None:
                pending.bound_node = ""
                pending.nominated_node = ""
                pending.enqueued_at = 0.0   # immediate re-window
            if self._occ is not None:
                self._occ.unbind(pk)
        self._delete_claim(claim)

    def _delete_claim(self, claim: NodeClaim) -> None:
        claim.deleted = True
        self.cluster.update("nodeclaims", claim.name, claim)
