"""Disruption controller: drift replacement + consolidation.

In the reference these live in karpenter-core (the drift and disruption
controllers call ``CloudProvider.IsDrifted`` — SURVEY.md §3.4 — and run
empty/underutilized consolidation against the cluster state).  The
standalone framework owns both:

- **Drift replacement**: claims whose NodeClass has moved under them
  (hash/hash-version/image/subnet/security-group drift, core/drift.py)
  are replaced — pods are unbound back to pending, the claim is deleted
  (the termination controller finalizes the instance), and the
  provisioning window re-places the pods against the *current* spec.
- **Empty consolidation**: nodes with no bound pods past the pool's
  ``consolidate_after_seconds`` are removed (policy gate:
  WhenEmpty / WhenEmptyOrUnderutilized).
- **Underutilized consolidation**: karpenter's single-node move — if every
  pod on a node provably fits in the residual capacity of other live
  nodes, bind them there directly (this framework owns the scheduler
  role, so the rebind is ours to do, not a kube-scheduler's) and delete
  the node.  Savings-first order: cheapest-to-remove nodes go first.

The full cost-optimal *repack* (BASELINE config #4) reuses the solver:
``propose_repack`` returns the fresh-solve plan and its cost delta vs the
live fleet; the poll loop only *executes* the safe single-node moves, so
actuation stays idempotent while the repack remains observable (and is
what bench_fleet exercises on TPU).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.apis.nodeclaim import NodeClaim, NodePool
from karpenter_tpu.apis.pod import NUM_RESOURCES
from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.core.cloudprovider import CloudProvider
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.disruption")


@dataclass
class RepackProposal:
    """Observable outcome of a fresh fleet solve vs the live fleet."""

    current_cost: float
    proposed_cost: float
    plan: object = None            # solver Plan
    savings: float = 0.0


class DisruptionController(PollController):
    """Singleton poller (10s — the repack cadence of BASELINE config #4)."""

    name = "disruption"
    interval = 10.0

    def __init__(self, cluster: ClusterState, cloudprovider: CloudProvider,
                 provisioner=None, clock=time.time):
        self.cluster = cluster
        self.cloudprovider = cloudprovider
        self.provisioner = provisioner
        self.clock = clock

    # -- reconcile ---------------------------------------------------------

    def reconcile(self) -> Result:
        drifted = self._replace_drifted()
        emptied = self._consolidate_empty()
        moved = self._consolidate_underutilized()
        if drifted or emptied or moved:
            log.info("disruption pass", drifted=drifted, empty=emptied,
                     consolidated=moved)
        return Result()

    # -- drift (SURVEY.md §3.4) -------------------------------------------

    def _replace_drifted(self) -> int:
        n = 0
        for claim in self.cluster.nodeclaims():
            if claim.deleted or not claim.registered:
                continue
            reason = self.cloudprovider.is_drifted(claim)
            if not reason:
                continue
            # (is_drifted already counted the detection metric)
            log.info("drifted claim replaced", claim=claim.name,
                     reason=reason)
            self._evict_and_delete(claim)
            n += 1
        return n

    # -- consolidation -----------------------------------------------------

    def _pool_for(self, claim: NodeClaim) -> NodePool:
        pool = self.cluster.get("nodepools", claim.nodepool_name)
        return pool if pool is not None else NodePool(name="default")

    EMPTY_SINCE_ANNOTATION = "karpenter-tpu.sh/empty-since"

    def _consolidate_empty(self) -> int:
        now = self.clock()
        n = 0
        for claim in self.cluster.nodeclaims():
            if claim.deleted or not claim.initialized or not claim.node_name:
                continue
            pool = self._pool_for(claim)
            if pool.consolidation_policy not in (
                    "WhenEmpty", "WhenEmptyOrUnderutilized"):
                continue
            if self._bound_pods(claim.node_name):
                # node busy again: reset the emptiness clock so a later
                # drain restarts the consolidateAfter damping window
                if claim.annotations.pop(self.EMPTY_SINCE_ANNOTATION, None):
                    self.cluster.update("nodeclaims", claim.name, claim)
                continue
            # consolidateAfter measures from when the node *became* empty
            # (karpenter semantics), not from node creation — a long-lived
            # node must still wait out the window after its last pod exits
            empty_since = claim.annotations.get(self.EMPTY_SINCE_ANNOTATION)
            if empty_since is None:
                claim.annotations[self.EMPTY_SINCE_ANNOTATION] = repr(now)
                self.cluster.update("nodeclaims", claim.name, claim)
                empty_since = repr(now)
            if now - float(empty_since) < pool.consolidate_after_seconds:
                continue
            log.info("empty node consolidated", claim=claim.name)
            self._evict_and_delete(claim)
            n += 1
        return n

    def _consolidate_underutilized(self) -> int:
        """Single-node move: cheapest removable node whose pods all fit in
        the other nodes' residuals; pods are rebound directly."""
        claims = [c for c in self.cluster.nodeclaims()
                  if not c.deleted and c.initialized and c.node_name
                  and self._pool_for(c).consolidation_policy
                  == "WhenEmptyOrUnderutilized"]
        if len(claims) < 2:
            return 0
        resid = {c.name: self._residual(c) for c in claims}
        moved = 0
        # cheapest first: removing a low-price node frees least value, but
        # is likeliest to fit elsewhere; karpenter sorts by disruption cost
        for claim in sorted(claims, key=lambda c: c.hourly_price):
            pods = self._bound_pods(claim.node_name)
            if not pods:
                continue
            placement = self._fit_elsewhere(claim, pods, claims, resid)
            if placement is None:
                continue
            for pod, target in placement:
                self.cluster.bind_pod(pod, target.node_name)
                resid[target.name] = resid[target.name] - \
                    self._pod_req(pod)
            log.info("underutilized node consolidated", claim=claim.name,
                     pods_moved=len(placement))
            self._delete_claim(claim)
            claims.remove(claim)
            moved += 1
        return moved

    # -- repack (observable; BASELINE config #4) --------------------------

    def propose_repack(self) -> Optional[RepackProposal]:
        """Fresh solve of the entire workload vs the live fleet cost."""
        if self.provisioner is None:
            return None
        from karpenter_tpu.solver.types import SolveRequest

        claims = [c for c in self.cluster.nodeclaims() if not c.deleted]
        if not claims:
            return None
        current = sum(c.hourly_price for c in claims)
        nodeclass = self.cluster.get_nodeclass("default")
        if nodeclass is None:
            return None
        catalog = self.provisioner._catalog_for(nodeclass)
        if catalog is None:
            return None
        pods = [p.spec for p in self.cluster.list("pods")]
        if not pods:
            return None
        plan = self.provisioner.solver.solve(SolveRequest(pods, catalog))
        return RepackProposal(
            current_cost=current, proposed_cost=plan.total_cost_per_hour,
            plan=plan, savings=current - plan.total_cost_per_hour)

    # -- helpers -----------------------------------------------------------

    def _bound_pods(self, node_name: str) -> List[str]:
        from karpenter_tpu.apis.pod import pod_key

        return [pod_key(p.spec) for p in self.cluster.list("pods")
                if p.bound_node == node_name
                or p.nominated_node == node_name]

    def _pod_req(self, pod_key_str: str) -> np.ndarray:
        pending = self.cluster.get("pods", pod_key_str)
        if pending is None:
            return np.zeros(NUM_RESOURCES, dtype=np.int64)
        req = pending.spec.requests.as_tuple()
        return np.array((req[0], req[1], req[2], max(req[3], 1)),
                        dtype=np.int64)

    def _alloc(self, claim: NodeClaim) -> np.ndarray:
        it = self.cloudprovider.instance_types.get(claim.instance_type)
        if it is None:
            return np.zeros(NUM_RESOURCES, dtype=np.int64)
        return np.array((it.allocatable_cpu_milli, it.allocatable_memory_mib,
                         it.gpu, it.pods), dtype=np.int64)

    def _residual(self, claim: NodeClaim) -> np.ndarray:
        resid = self._alloc(claim)
        for pk in self._bound_pods(claim.node_name):
            resid = resid - self._pod_req(pk)
        return resid

    def _target_labels(self, claim: NodeClaim) -> Dict[str, str]:
        """Effective scheduling labels of the node backing ``claim``: claim
        labels + pool static labels + well-known placement labels (mirrors
        what the actuator/registration stamp on the real node)."""
        from karpenter_tpu.apis.requirements import (
            LABEL_CAPACITY_TYPE, LABEL_HOSTNAME, LABEL_INSTANCE_TYPE,
            LABEL_NODEPOOL, LABEL_ZONE)

        labels = dict(self._pool_for(claim).labels)
        labels.update(claim.labels)
        labels.setdefault(LABEL_INSTANCE_TYPE, claim.instance_type)
        labels.setdefault(LABEL_ZONE, claim.zone)
        labels.setdefault(LABEL_CAPACITY_TYPE, claim.capacity_type)
        labels.setdefault(LABEL_NODEPOOL, claim.nodepool_name)
        labels.setdefault(LABEL_HOSTNAME, claim.node_name)
        return labels

    def _pod_compatible(self, spec, victim: NodeClaim, target: NodeClaim,
                        target_labels: Dict[str, str],
                        planned_on_target: List) -> bool:
        """Full compatibility of a pod move onto ``target`` — the same
        constraints the solver's compat mask enforces at placement time
        (node selectors / required affinity, taints, zone co-location,
        hostname anti-affinity cap).  Reference karpenter simulates full
        scheduling before consolidating; moves that only check resources
        can silently break zone pins and taint gates."""
        from karpenter_tpu.apis.pod import tolerates_all
        from karpenter_tpu.solver.encode import (
            _has_hostname_anti_affinity, _has_zone_affinity,
            _zone_spread_constraints)

        if not spec.scheduling_requirements().matches(target_labels):
            return False
        pool = self._pool_for(target)
        if not tolerates_all(spec.tolerations, target.taints) or \
                not tolerates_all(spec.tolerations, pool.taints):
            return False
        # zone co-schedule affinity and DoNotSchedule zone spread: keep the
        # pod in its current zone so group purity / skew is preserved
        if (_has_zone_affinity(spec) or _zone_spread_constraints(spec)) \
                and target.zone != victim.zone:
            return False
        # hostname anti-affinity (self): at most one matching pod per node
        if _has_hostname_anti_affinity(spec):
            own = spec.labels_dict
            for other in self._pods_on(target, planned_on_target):
                if other is not None and all(
                        other.labels_dict.get(k) == v
                        for k, v in own.items()) and own:
                    return False
        return True

    def _pods_on(self, claim: NodeClaim, planned: List):
        """PodSpecs currently bound to ``claim``'s node plus any planned
        moves onto it within this consolidation pass."""
        out = []
        for pk in self._bound_pods(claim.node_name):
            pending = self.cluster.get("pods", pk)
            if pending is not None:
                out.append(pending.spec)
        out.extend(planned)
        return out

    def _fit_elsewhere(self, victim: NodeClaim, pods: List[str],
                       claims: List[NodeClaim],
                       resid: Dict[str, np.ndarray]
                       ) -> Optional[List[Tuple[str, NodeClaim]]]:
        """First-fit each pod into other nodes' residuals (on a working
        copy), honoring the pod's full scheduling constraints against each
        candidate target; None if any pod does not fit."""
        work = {k: v.copy() for k, v in resid.items()}
        placement: List[Tuple[str, NodeClaim]] = []
        planned: Dict[str, List] = {}
        others = [c for c in claims if c.name != victim.name]
        labels = {c.name: self._target_labels(c) for c in others}
        for pk in pods:
            req = self._pod_req(pk)
            pending = self.cluster.get("pods", pk)
            spec = pending.spec if pending is not None else None
            target = None
            for c in others:
                if not (work[c.name] >= req).all():
                    continue
                if spec is not None and not self._pod_compatible(
                        spec, victim, c, labels[c.name],
                        planned.get(c.name, [])):
                    continue
                target = c
                break
            if target is None:
                return None
            work[target.name] = work[target.name] - req
            if spec is not None:
                planned.setdefault(target.name, []).append(spec)
            placement.append((pk, target))
        return placement

    def _evict_and_delete(self, claim: NodeClaim) -> None:
        """Unbind the node's pods back to pending, then delete the claim
        (the termination controller finalizes the instance; the window
        re-places the pods)."""
        for pk in self._bound_pods(claim.node_name):
            pending = self.cluster.get("pods", pk)
            if pending is not None:
                pending.bound_node = ""
                pending.nominated_node = ""
                pending.enqueued_at = 0.0   # immediate re-window
        self._delete_claim(claim)

    def _delete_claim(self, claim: NodeClaim) -> None:
        claim.deleted = True
        self.cluster.update("nodeclaims", claim.name, claim)
