"""NodeClass controllers: hash, status (validation), autoplacement,
termination.

Reference: ``pkg/controllers/nodeclass/{hash,status,autoplacement,
termination}`` (SURVEY.md §2.5).
"""

from __future__ import annotations

import time

from karpenter_tpu.apis.nodeclass import (
    ANNOTATION_NODECLASS_HASH, ANNOTATION_NODECLASS_HASH_VERSION,
    NODECLASS_HASH_VERSION, NodeClass,
)
from karpenter_tpu.catalog.instancetype import InstanceTypeProvider, filter_instance_types
from karpenter_tpu.cloud.errors import CloudError
from karpenter_tpu.cloud.image import ImageResolver
from karpenter_tpu.cloud.subnet import SubnetProvider
from karpenter_tpu.controllers.runtime import Result, WatchController
from karpenter_tpu.core.cluster import ClusterState, ConflictError
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.nodeclass")

TERMINATION_FINALIZER = "karpenter-tpu.sh/nodeclass-termination"


class NodeClassHashController(WatchController):
    """Stamps the spec-hash + hash-version annotations used for drift
    (ref hash/controller.go:62-84)."""

    name = "nodeclass.hash"
    watch_kinds = ("nodeclasses",)

    def __init__(self, cluster: ClusterState):
        self.cluster = cluster

    def reconcile(self, key: str) -> Result:
        nc = self.cluster.get_nodeclass(key)
        if nc is None or nc.deleted:
            return Result()
        want_hash = nc.spec_hash()
        if nc.annotations.get(ANNOTATION_NODECLASS_HASH) == want_hash and \
                nc.annotations.get(ANNOTATION_NODECLASS_HASH_VERSION) == \
                NODECLASS_HASH_VERSION:
            return Result()
        nc.annotations[ANNOTATION_NODECLASS_HASH] = want_hash
        nc.annotations[ANNOTATION_NODECLASS_HASH_VERSION] = NODECLASS_HASH_VERSION
        self.cluster.update("nodeclasses", key, nc)
        return Result()


class NodeClassStatusController(WatchController):
    """Validates the NodeClass against the cloud, resolves defaults into
    status, and sets the Ready condition (ref status/controller.go: field
    checks :200-222, subnet/zone compat :567-660, image :662-733, SGs :735;
    24h revalidation :44)."""

    name = "nodeclass.status"
    watch_kinds = ("nodeclasses",)
    revalidate_after = 24 * 3600.0

    def __init__(self, cluster: ClusterState, cloud,
                 subnet_provider: SubnetProvider | None = None,
                 image_resolver: ImageResolver | None = None):
        self.cluster = cluster
        self.cloud = cloud
        self.subnets = subnet_provider or SubnetProvider(cloud)
        self.images = image_resolver or ImageResolver(cloud)

    def reconcile(self, key: str) -> Result:
        nc = self.cluster.get_nodeclass(key)
        if nc is None or nc.deleted:
            return Result()
        errs = nc.validate()
        if not errs:
            errs += self._validate_cloud(nc)
        # snapshot the material outcome BEFORE mutating: publishing an
        # update on every pass would re-trigger our own watch (MODIFIED ->
        # re-enqueue -> reconcile), a self-feeding hot loop in live mode
        ready_before = nc.status.is_ready()
        before = (nc.status.validation_error,
                  list(nc.status.resolved_security_groups),
                  nc.status.resolved_image_id)
        nc.status.last_validation_time = time.time()
        if errs:
            nc.status.validation_error = "; ".join(errs)
            nc.status.set_condition("Ready", "False", "ValidationFailed",
                                    nc.status.validation_error)
        else:
            nc.status.validation_error = ""
            self._resolve_status(nc)
            nc.status.set_condition("Ready", "True", "Validated", "")
        after = (nc.status.validation_error,
                 list(nc.status.resolved_security_groups),
                 nc.status.resolved_image_id)
        if before == after and ready_before == nc.status.is_ready():
            return Result(requeue_after=self.revalidate_after)
        if errs:
            self.cluster.record_event("NodeClass", nc.name, "Warning",
                                      "ValidationFailed", nc.status.validation_error)
        try:
            self.cluster.update("nodeclasses", key, nc)
        except ConflictError:
            return Result(requeue_after=1.0)
        return Result(requeue_after=self.revalidate_after)

    def _validate_cloud(self, nc: NodeClass) -> list:
        errs = []
        zones = set(self.cloud.list_zones())
        if nc.spec.zone and nc.spec.zone not in zones:
            errs.append(f"zone {nc.spec.zone} not found in region")
        if nc.spec.subnet:
            try:
                sub = self.subnets.get_subnet(nc.spec.subnet)
                if nc.spec.zone and sub.zone != nc.spec.zone:
                    errs.append(f"subnet {nc.spec.subnet} is in zone "
                                f"{sub.zone}, not {nc.spec.zone}")
            except CloudError:
                errs.append(f"subnet {nc.spec.subnet} not found")
        if nc.spec.instance_profile:
            profiles = {p.name for p in self.cloud.list_instance_profiles()}
            if nc.spec.instance_profile not in profiles:
                errs.append(f"instance profile {nc.spec.instance_profile} "
                            "not found")
        errs += self._validate_vpc_resources(nc)
        try:
            self.images.resolve(nc.spec.image, nc.spec.image_selector)
        except CloudError as e:
            errs.append(f"image resolution failed: {e.message}")
        return errs

    def _validate_vpc_resources(self, nc: NodeClass) -> list:
        """VPC-in-region, security-group, and SSH-key existence (ref
        status/controller.go:471 VPC, :735 SGs, :796 keys).  Cloud hiccups
        during these lookups do NOT fail validation — a transient list
        error must not flip a Ready NodeClass to NotReady.  Capability is
        probed explicitly (getattr) so a client lacking the listing
        surface skips the check, while a genuine bug inside a list
        implementation still surfaces."""
        errs = []
        checks = [
            ("list_vpcs", [nc.spec.vpc] if nc.spec.vpc else [],
             lambda ident: f"VPC {ident} not found in region"),
            ("list_security_groups", list(nc.spec.security_groups),
             lambda ident: f"security group {ident} not found"),
            ("list_ssh_keys", list(nc.spec.ssh_keys),
             lambda ident: f"SSH key {ident} not found"),
        ]
        for method, idents, msg in checks:
            if not idents:
                continue
            fn = getattr(self.cloud, method, None)
            if fn is None:
                continue
            try:
                known = set(fn())
            except CloudError:
                continue
            errs.extend(msg(i) for i in idents if i not in known)
        return errs

    def _resolve_status(self, nc: NodeClass) -> None:
        # default security group when none specified (ref resolves the VPC
        # default SG, status/controller.go:735)
        if nc.spec.security_groups:
            nc.status.resolved_security_groups = list(nc.spec.security_groups)
        else:
            nc.status.resolved_security_groups = [
                self.cloud.get_default_security_group()]
        nc.status.resolved_image_id = self.images.resolve(
            nc.spec.image, nc.spec.image_selector)


class AutoplacementController(WatchController):
    """Resolves instanceRequirements -> Status.SelectedInstanceTypes and
    placementStrategy -> Status.SelectedSubnets (ref autoplacement/
    controller.go:104-242, optimistic-lock patch :248)."""

    name = "nodeclass.autoplacement"
    watch_kinds = ("nodeclasses",)

    def __init__(self, cluster: ClusterState,
                 instance_types: InstanceTypeProvider,
                 subnet_provider: SubnetProvider):
        self.cluster = cluster
        self.instance_types = instance_types
        self.subnets = subnet_provider
        # names already warned about an empty selection — a fresh
        # NodeClass starts with selected==[] so the change-check alone
        # would never emit the first warning, and without the memo every
        # revalidation would re-emit it
        self._warned_empty: set = set()

    def reconcile(self, key: str) -> Result:
        nc = self.cluster.get_nodeclass(key)
        if nc is None or nc.deleted:
            # a recreated NodeClass with the same name must warn afresh
            self._warned_empty.discard(key)
            return Result()
        rv = nc.resource_version
        changed = False
        if nc.spec.instance_requirements is not None:
            changed |= self._select_types(nc)
        if nc.spec.placement_strategy is not None and not nc.spec.subnet:
            changed |= self._select_subnets(nc)
        if changed:
            try:
                self.cluster.update("nodeclasses", key, nc, expect_rv=rv)
            except ConflictError:
                return Result(requeue_after=0.5)
        return Result()

    def _select_types(self, nc: NodeClass) -> bool:
        t0 = time.perf_counter()
        types = filter_instance_types(self.instance_types.list(nc),
                                      nc.spec.instance_requirements)
        selected = [t.name for t in types]
        metrics.AUTOPLACEMENT_DURATION.labels("instance_types").observe(
            time.perf_counter() - t0)
        metrics.AUTOPLACEMENT_SELECTIONS.labels(
            "instance_types", "ok" if selected else "empty").inc()
        if not selected and nc.name not in self._warned_empty:
            self._warned_empty.add(nc.name)
            self.cluster.record_event(
                "NodeClass", nc.name, "Warning", "NoMatchingInstanceTypes",
                "instanceRequirements matched no instance types")
        elif selected:
            self._warned_empty.discard(nc.name)
        if selected == nc.status.selected_instance_types:
            return False
        nc.status.selected_instance_types = selected
        return True

    def _select_subnets(self, nc: NodeClass) -> bool:
        t0 = time.perf_counter()
        subnets = self.subnets.select_subnets(nc.spec.placement_strategy)
        selected = [s.id for s in subnets]
        metrics.AUTOPLACEMENT_DURATION.labels("subnets").observe(
            time.perf_counter() - t0)
        metrics.AUTOPLACEMENT_SELECTIONS.labels(
            "subnets", "ok" if selected else "empty").inc()
        if selected == nc.status.selected_subnets:
            return False
        nc.status.selected_subnets = selected
        return True


class NodeClassTerminationController(WatchController):
    """Finalizer-based deletion: a deleted NodeClass is only removed once no
    NodeClaim references it (ref termination/controller.go:63)."""

    name = "nodeclass.termination"
    watch_kinds = ("nodeclasses", "nodeclaims")

    def __init__(self, cluster: ClusterState):
        self.cluster = cluster

    def map_event(self, kind: str, event_type: str, obj) -> str | None:
        if kind == "nodeclaims":
            # a claim going away may unblock its nodeclass's deletion
            return getattr(obj, "nodeclass_name", None) or None
        return getattr(obj, "name", None)

    def reconcile(self, key: str) -> Result:
        nc = self.cluster.get_nodeclass(key)
        if nc is None:
            return Result()
        if not nc.deleted:
            if TERMINATION_FINALIZER not in nc.finalizers:
                nc.finalizers.append(TERMINATION_FINALIZER)
                self.cluster.update("nodeclasses", key, nc)
            return Result()
        holders = [c.name for c in self.cluster.nodeclaims()
                   if c.nodeclass_name == key and not c.deleted]
        if holders:
            self.cluster.record_event(
                "NodeClass", key, "Warning", "TerminationBlocked",
                f"{len(holders)} NodeClaims still reference this class")
            return Result(requeue_after=10.0)
        if TERMINATION_FINALIZER in nc.finalizers:
            nc.finalizers.remove(TERMINATION_FINALIZER)
        if not nc.finalizers:
            self.cluster.delete("nodeclasses", key)
        return Result()
