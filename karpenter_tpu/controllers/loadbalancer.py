"""NodeClaim -> load balancer registration controller.

Reference: ``pkg/controllers/nodeclaim/loadbalancer/controller.go:95`` —
when a NodeClass has ``loadBalancerIntegration.enabled``, registered nodes'
IPs join the configured LB pools; deletion (or claim deletion with
``autoDeregister``) removes them (:201).

Registrations are recorded as durable objects in cluster state (the K8s-API
analogue, SURVEY.md §5.4) so deregistration survives controller restarts and
missed DELETED events, and so the sweep poller only ever touches members
karpenter itself registered — never operator-added backends sharing a pool.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.apis.nodeclass import LoadBalancerTarget
from karpenter_tpu.cloud.errors import CloudError
from karpenter_tpu.cloud.loadbalancer import LoadBalancerProvider
from karpenter_tpu.controllers.runtime import PollController, Result, WatchController
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.loadbalancer")

ANNOTATION_LB_REGISTERED = "karpenter-tpu.sh/lb-registered"


@dataclass
class LBRegistration:
    """Durable record of one claim's LB membership (what karpenter owns)."""

    name: str                                  # claim name
    address: str
    targets: tuple[LoadBalancerTarget, ...]
    auto_deregister: bool = True
    resource_version: int = 0


class LoadBalancerController(WatchController):
    name = "nodeclaim.loadbalancer"
    watch_kinds = ("nodeclaims", "nodes")

    def __init__(self, cluster: ClusterState, provider: LoadBalancerProvider):
        self.cluster = cluster
        self.provider = provider

    def map_event(self, kind: str, event_type: str, obj) -> str | None:
        if kind == "nodes":
            for claim in self.cluster.nodeclaims():
                if claim.provider_id == obj.provider_id:
                    return claim.name
            return None
        return getattr(obj, "name", None)

    def reconcile(self, key: str) -> Result:
        claim = self.cluster.get_nodeclaim(key)
        if claim is None or claim.deleted:
            return self._deregister(key)
        nc = self.cluster.get_nodeclass(claim.nodeclass_name)
        if nc is None or nc.spec.load_balancer_integration is None or \
                not nc.spec.load_balancer_integration.enabled:
            return Result()
        if not claim.registered or not claim.node_name:
            return Result()   # wait for the node join
        if claim.annotations.get(ANNOTATION_LB_REGISTERED) == "true":
            return Result()
        node = self.cluster.get_node(claim.node_name)
        if node is None or not node.addresses:
            return Result()
        address = node.addresses[0]
        integration = nc.spec.load_balancer_integration
        try:
            self.provider.register_instance(integration, address)
        except CloudError as e:
            log.warning("LB registration failed", claim=key, error=str(e))
            self.cluster.record_event("NodeClaim", key, "Warning",
                                      "LBRegistrationFailed", str(e))
            return Result(requeue_after=10.0)
        claim.annotations[ANNOTATION_LB_REGISTERED] = "true"
        self.cluster.update("nodeclaims", key, claim)
        record = LBRegistration(name=key, address=address,
                                targets=tuple(integration.target_groups),
                                auto_deregister=integration.auto_deregister)
        if self.cluster.get("lbregistrations", key) is None:
            self.cluster.add("lbregistrations", key, record)
        else:
            self.cluster.update("lbregistrations", key, record)
        self.cluster.record_event(
            "NodeClaim", key, "Normal", "LBRegistered",
            f"{address} -> {len(integration.target_groups)} pools")
        return Result()

    def _deregister(self, key: str) -> Result:
        record = self.cluster.get("lbregistrations", key)
        if record is None:
            return Result()
        if record.auto_deregister:
            removed, failures = self.provider.remove_targets(record.targets,
                                                             record.address)
            if failures:
                # keep the record: a leaked member keeps routing traffic to
                # a dead backend, and the sweeper can only retry what is
                # still recorded
                return Result(requeue_after=10.0)
            if removed:
                self.cluster.record_event("NodeClaim", key, "Normal",
                                          "LBDeregistered", record.address)
        self.cluster.delete("lbregistrations", key)
        return Result()


class LBMembershipSweeper(PollController):
    """Safety net for missed DELETED events / controller restarts: walks the
    durable registration records and removes membership for claims that no
    longer exist.  Only karpenter-recorded addresses are ever touched —
    operator-added backends sharing a managed pool are invisible to the
    sweep (the reference's eventual-consistency two-way pattern,
    SURVEY.md §5.3, applied to LB membership)."""

    name = "nodeclaim.loadbalancer.sweep"
    interval = 300.0

    def __init__(self, cluster: ClusterState, provider: LoadBalancerProvider):
        self.cluster = cluster
        self.provider = provider

    def reconcile(self) -> Result:
        for record in self.cluster.list("lbregistrations"):
            claim = self.cluster.get_nodeclaim(record.name)
            if claim is not None and not claim.deleted:
                continue
            if record.auto_deregister:
                removed, failures = self.provider.remove_targets(
                    record.targets, record.address)
                if failures:
                    continue   # keep the record; retry next sweep
                if removed:
                    log.info("LB sweep removed stale membership",
                             claim=record.name, address=record.address)
            self.cluster.delete("lbregistrations", record.name)
        return Result()
