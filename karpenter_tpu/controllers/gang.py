"""Gang admission controller: parks, admits, places, and releases gangs.

The planning half lives in ``karpenter_tpu/gang`` (pure functions); this
controller owns the messy parts:

- **parking**: it registers the provisioner's admission gate, so a
  gang's members never enter a solve window until the gang is admitted
  (``min_member`` members pending).  Slice-shaped gangs are NEVER
  released to the ordinary solver — their contiguous-sub-slice contract
  is invisible to it — and are placed here via the topology-aware
  planner instead;
- **first-seen stamps**: gang age is tracked by controller-owned
  stamps, not ``enqueued_at`` — the provisioner's retry ticker restamps
  that field every interval, which would make a parked gang look
  forever-young and never hit its deadline (the same lesson the
  preemption controller learned);
- **admission**: once ``min_member`` members are pending the gang is
  admitted (``gang.admit`` span, event, metrics); non-slice gangs are
  re-windowed immediately and the gang-aware solver places them
  atomically;
- **slice placement**: admitted slice gangs are planned per NodePool
  under the solve lock (``gang.place`` span), validated by the
  independent ``validate_gang_plan`` oracle — an invalid plan is
  dropped with an ERRORS breadcrumb, never actuated — and executed
  through the same actuator path the provisioner uses.  A failed create
  nominates NOBODY (one node per gang), so atomicity survives partial
  actuation;
- **deadline release**: a gang still unplaced past its
  ``deadline_seconds`` is released with a degraded per-pod fallback —
  members lose their gang field and re-enter the queue as ordinary
  pods — plus an ``ERRORS{gang, deadline_release}`` breadcrumb and a
  Warning event (a parked-forever gang is a deadlocked job; per-pod
  capacity at least lets the operator see it running partially);
- **evidence**: ``gang.admit``/``gang.place`` spans,
  ``karpenter_tpu_gang_*`` metric families, and a ``placement_log`` the
  chaos invariants drain (no-partial-gang-placed,
  gangs-resolve-or-release).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from dataclasses import dataclass

from karpenter_tpu.apis.pod import PodSpec, pod_key
from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.explain import get_registry
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.gang.degraded import ResilientGangPlanner
from karpenter_tpu.gang.encode import encode_gangs
from karpenter_tpu.gang.types import GangOptions
from karpenter_tpu.recovery.journal import NULL_JOURNAL
from karpenter_tpu.solver.validate import validate_gang_plan
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.gang")


@dataclass(frozen=True)
class GangPlacementRecord:
    """One executed gang placement — the chaos invariants' ground truth."""

    gang: str
    claim_name: str
    members: tuple[str, ...]
    total_members: int
    min_member: int
    backend: str


class GangAdmissionController(PollController):
    """Singleton poller: admit, place, or release pending gangs."""

    name = "gang"
    interval = 5.0

    def __init__(self, cluster: ClusterState, provisioner,
                 options: GangOptions | None = None, clock=time.time,
                 journal=None):
        self.cluster = cluster
        self.provisioner = provisioner
        # write-ahead journal: gang placements are intents (all-or-
        # nothing on replay), admissions durable state — a restarted
        # operator must not reset parked gangs' deadline clocks
        self.journal = journal if journal is not None else NULL_JOURNAL
        self.options = options or GangOptions()
        self.planner = ResilientGangPlanner(options=self.options)
        self.clock = clock
        # controller-owned first-seen stamps (see module docstring)
        self._first_seen: dict[str, float] = {}
        self.admitted: set[str] = set()
        # gangs released to per-pod scheduling by the deadline fallback
        # — insertion-ordered and FIFO-bounded like placement_log: the
        # release strips members' gang fields, so nothing ever prunes
        # entries by reference and an unbounded set would leak one name
        # per released gang for the process lifetime
        self.released: dict[str, None] = {}
        self._released_max = 4096
        # executed-placement evidence, drained per chaos round and
        # bounded for the operator path where nothing drains it
        self.placement_log: deque[GangPlacementRecord] = deque(maxlen=4096)
        if provisioner is not None:
            provisioner.admission = self.admit

    def seed_recovered(self, admitted: dict[str, float],
                       parked: dict[str, float] | None = None) -> None:
        """Adopt the reconciler's rebuilt gang state: admitted names
        re-enter the admission set, and BOTH admitted and still-parked
        gangs keep the crashed process's first-seen stamps — deadlines
        keep burning across the restart instead of resetting."""
        for name, first in (parked or {}).items():
            self._first_seen.setdefault(name, float(first))
        for name, first in admitted.items():
            self.admitted.add(name)
            self._first_seen.setdefault(name, float(first))

    # -- the provision-queue gate -----------------------------------------

    def admit(self, spec: PodSpec) -> bool:
        """May this pod enter an ordinary solve window?  Non-gang pods
        always; slice gangs never (the topology planner owns them);
        other gangs once admitted."""
        gang = spec.gang
        if gang is None:
            return True
        if gang.slice_shape:
            return False
        return gang.name in self.admitted

    # -- reconcile ---------------------------------------------------------

    def reconcile(self) -> Result:
        if self.provisioner is None:
            return Result()
        now = self.clock()
        groups: dict[str, list] = {}
        for p in self.cluster.pending_pods():
            if p.spec.gang is not None and not p.bound_node:
                groups.setdefault(p.spec.gang.name, []).append(p)
        # prune state for gangs that fully resolved (bound or deleted)
        for name in list(self._first_seen):
            if name not in groups:
                self._first_seen.pop(name, None)
                self.journal.state(f"gang/first_seen/{name}", None)
                if name in self.admitted:
                    self.admitted.discard(name)
                    self.journal.state(f"gang/admitted/{name}", None)
        parked = 0
        to_place: list[tuple[str, list]] = []
        for name, members in groups.items():
            spec = members[0].spec.gang
            if name not in self._first_seen:
                # durable first-seen stamp from the FIRST park
                # observation: a parked gang's deadline clock must keep
                # burning across operator restarts, not reset to zero
                # every time the process rolls
                self.journal.state(f"gang/first_seen/{name}", now)
            first = self._first_seen.setdefault(name, now)
            complete = len(members) >= spec.min_member
            if complete and name not in self.admitted:
                self.admitted.add(name)
                # durable admission + first-seen stamp: a restart must
                # neither re-park an admitted gang nor reset its
                # deadline clock (docs/design/recovery.md)
                self.journal.state(f"gang/admitted/{name}", first)
                metrics.GANG_ADMISSIONS.labels("admitted").inc()
                metrics.GANG_MEMBERS.observe(len(members))
                for p in members:
                    obs.get_ledger().transition(pod_key(p.spec),
                                                "gang.admit")
                    # the park verdict lifted: members now compete as
                    # ordinary solve-window pods
                    get_registry().clear_bits(pod_key(p.spec),
                                              "gang_parked")
                with obs.span("gang.admit", gang=name,
                              members=len(members),
                              min_member=spec.min_member,
                              slice=str(spec.slice_shape or "")):
                    self.cluster.record_event(
                        "PodGroup", name, "Normal", "GangAdmitted",
                        f"{len(members)} members pending "
                        f"(min_member {spec.min_member})")
            if name in self.admitted:
                waiting = [p for p in members if not p.nominated_node]
                if waiting and now - first >= spec.deadline_seconds:
                    # admitted but still (even partially) unplaced by
                    # the deadline: the capacity never fully
                    # materialized — e.g. one of a spanning gang's
                    # creates failed, stranding a sub-min_member
                    # remainder the atomic solver can never place
                    # alone.  Degrade to per-pod rather than park the
                    # job forever (nominated members keep their
                    # nominations; only the gang field is stripped).
                    self._release(name, members, spec)
                elif spec.slice_shape:
                    if waiting:
                        to_place.append((name, members))
                else:
                    # immediate re-window: the admission gate now passes
                    # these pods; waiting out the retry interval would
                    # add a whole tick of latency to every admission
                    for p in waiting:
                        p.enqueued_at = 0.0
            elif now - first >= spec.deadline_seconds:
                self._release(name, members, spec)
            else:
                parked += 1
                # deduped transition: the 5s reconcile loop stamps
                # "gang.park" once per park episode, not once per tick
                for p in members:
                    key = pod_key(p.spec)
                    obs.get_ledger().transition(key, "gang.park")
                    # explain verdict for the parked members: the
                    # registry stamp dedupes, so the 5s loop emits the
                    # Warning event once per park episode
                    if get_registry().stamp(
                            key, "gang_parked",
                            detail=f"gang {name}: {len(members)}/"
                                   f"{spec.min_member} members pending"):
                        self.cluster.record_event(
                            "Pod", key, "Warning", "Unplaced",
                            f"cannot place: gang_parked (gang {name} "
                            f"awaiting min_member {spec.min_member})")
        metrics.GANG_PARKED.set(parked)
        # unconditional: the tick that unparks the LAST gang must zero
        # the gang_parked gauge count, not leave it lingering
        get_registry().update_unplaced_gauge()
        if to_place:
            self._place_slice_gangs(to_place)
        return Result()

    # -- deadline fallback -------------------------------------------------

    def _release(self, name: str, members: list, spec) -> None:
        """Degraded per-pod fallback: strip the gang field so members
        re-enter the queue as ordinary pods."""
        for p in members:
            p.spec = dataclasses.replace(p.spec, gang=None)
            p.enqueued_at = 0.0
            # flags the record: a later nomination resolves as
            # outcome "placed_degraded", feeding the degraded-rate SLO
            obs.get_ledger().transition(pod_key(p.spec), "gang.release")
            # released members are ordinary pods: gang verdicts lift
            get_registry().clear_bits(pod_key(p.spec), "gang_parked",
                                      "gang_geometry")
        while len(self.released) >= self._released_max:
            self.released.pop(next(iter(self.released)))
        self.released[name] = None
        self.admitted.discard(name)
        self.journal.state(f"gang/admitted/{name}", None)
        self._first_seen.pop(name, None)
        self.journal.state(f"gang/first_seen/{name}", None)
        metrics.GANG_ADMISSIONS.labels("released_degraded").inc()
        metrics.ERRORS.labels("gang", "deadline_release").inc()
        obs.instant("gang.release", gang=name, members=len(members),
                    min_member=spec.min_member)
        self.cluster.record_event(
            "PodGroup", name, "Warning", "GangReleased",
            f"deadline {spec.deadline_seconds:.0f}s expired with "
            f"{len(members)}/{spec.min_member} members; released to "
            f"per-pod scheduling (degraded)")
        log.warning("gang released on deadline", gang=name,
                    members=len(members), min_member=spec.min_member)

    # -- slice placement ---------------------------------------------------

    def _place_slice_gangs(self, gangs: list[tuple[str, list]]) -> None:
        placed: set[str] = set()
        for pool in self.provisioner._pools():
            remaining = [(n, m) for n, m in gangs if n not in placed]
            if not remaining:
                break
            placed.update(self._place_pool(pool, remaining))

    def _place_pool(self, pool, gangs: list[tuple[str, list]]) -> set[str]:
        nodeclass = self.cluster.get_nodeclass(pool.nodeclass_name) \
            or self.cluster.get_nodeclass("default")
        if nodeclass is None:
            return set()
        catalog = self.provisioner._catalog_for(nodeclass)
        if catalog is None:
            return set()
        # plan + actuate under the solve lock: a concurrent window
        # nominating one of these pods would race capacity accounting
        with self.provisioner._solve_lock:
            # live-capacity pre-pass: a gang whose slice is already open
            # on an existing accelerator node nominates there directly —
            # free capacity beats a create, and it is the payoff path of
            # the repack plane's slice defragmentation (a parked gang
            # lands on the torus the defrag migrations just vacated,
            # docs/design/repack.md)
            placed_live = self._place_on_live(pool, catalog, gangs)
            gangs = [(n, m) for n, m in gangs if n not in placed_live]
            pods = [p.spec for _, members in gangs for p in members
                    if not p.nominated_node and not p.bound_node]
            if not pods:
                return placed_live
            t0 = time.perf_counter()
            with obs.span("gang.place", pool=pool.name,
                          gangs=len(gangs), pods=len(pods)) as sp:
                problem = encode_gangs(pods, catalog, pool)
                plan = self.planner.plan(problem)
                sp.set("backend", plan.backend)
                sp.set("nodes", len(plan.nodes))
                sp.set("gangs_placed", len(plan.placed_gangs))
                metrics.GANG_PLAN_DURATION.labels(plan.backend).observe(
                    time.perf_counter() - t0)
                # explain: a gang whose compat row is EMPTY has no
                # offering whose torus hosts its slice (or fits its
                # total demand) — the gang_geometry verdict.  A NON-empty
                # row clears the bit: a catalog that recovered (new
                # torus-bearing offering) must not keep blaming geometry
                # for a gang now merely waiting on capacity.
                for idx, g in enumerate(problem.gangs):
                    if not problem.compat[idx].any():
                        for pn in g.pod_names:
                            if get_registry().stamp(
                                    pn, "gang_geometry",
                                    detail=f"gang {g.name}: no offering "
                                           f"hosts the slice"):
                                self.cluster.record_event(
                                    "Pod", pn, "Warning", "Unplaced",
                                    f"cannot place: gang_geometry "
                                    f"(gang {g.name})")
                    else:
                        for pn in g.pod_names:
                            get_registry().clear_bits(pn, "gang_geometry")
                if plan.empty:
                    return placed_live
                # independent oracle gate: never actuate an invalid plan
                errors = validate_gang_plan(plan, pods, catalog, pool)
                if errors:
                    metrics.ERRORS.labels("gang", "invalid_plan").inc()
                    sp.set("invalid", len(errors))
                    log.error("gang plan failed validation; dropped",
                              pool=pool.name, errors=errors[:3])
                    return placed_live
                return placed_live | self._execute(plan, pool, nodeclass,
                                                   catalog, problem)

    def _place_on_live(self, pool, catalog, gangs) -> set[str]:
        """Nominate gangs onto EXISTING initialized nodes of THIS pool
        whose residual capacity covers the gang's total demand and whose
        torus has a free contiguous placement for its slice shape (chip
        occupancy re-derived via the canonical chip model
        repack/encode.py defines).  Oldest claim first, lowest placement
        mask first — deterministic, and by construction atomic (all
        waiting members nominate onto ONE claim or none).

        Eligibility mirrors the repack plane's: initialized, node-backed
        claims only (a launched-but-unready node is unproven capacity —
        a gang parked on a claim the registration-timeout GC later reaps
        would burn its deadline for nothing), and only claims of the
        pool being placed (another pool's labels/taints were never
        matched).  Only torus-bearing types are scanned — slice gangs
        can land nowhere else, and a full-fleet occupancy rebuild per
        reconcile would re-add exactly the host loop the repack plane
        removed."""
        import numpy as np

        from karpenter_tpu.apis.pod import tolerates_all
        from karpenter_tpu.gang.topology import enumerate_placements
        from karpenter_tpu.preempt.encode import (
            _pod_req_vec, claim_pods, occupancy_index,
        )
        from karpenter_tpu.repack.encode import PodRef, chip_layout

        placed: set[str] = set()
        claims = [c for c in self.cluster.nodeclaims()
                  if not c.deleted and c.launched and c.initialized
                  and c.node_name and c.nodepool_name == pool.name]
        if not claims:
            return placed
        idx = None
        alloc = catalog.offering_alloc().astype(np.int64)
        states = []
        for c in claims:
            off = catalog.find_offering(c.instance_type, c.zone,
                                        c.capacity_type)
            if off is None:
                continue
            t = int(catalog.off_type[off])
            torus = tuple(catalog.type_torus[t]) \
                if t < len(catalog.type_torus) else ()
            if not torus:
                continue   # no torus: no slice gang can ever land here
            if idx is None:
                idx = occupancy_index(self.cluster)
            resid = alloc[off].copy()
            refs: list[PodRef] = []
            gang_shapes: list[tuple[str, tuple]] = []
            seen: set[str] = set()
            for p in claim_pods(self.cluster, c, index=idx):
                spec = p.spec
                resid -= _pod_req_vec(spec)
                ref = PodRef(key=pod_key(spec), req=None, sig=0,
                             gpu=int(spec.requests.gpu), movable=False,
                             single=False)
                if spec.gang is not None and spec.gang.slice_shape:
                    if spec.gang.name not in seen:
                        seen.add(spec.gang.name)
                        gang_shapes.append(
                            (spec.gang.name, tuple(spec.gang.slice_shape)))
                    ref.chip_mask = -1
                refs.append(ref)
            occ, _sing = chip_layout(refs, gang_shapes, torus)
            states.append({"claim": c, "off": off, "torus": torus,
                           "resid": resid, "occ": occ})
        if not states:
            return placed
        for name, members in gangs:
            waiting = [p for p in members
                       if not p.nominated_node and not p.bound_node]
            if not waiting:
                continue
            spec = waiting[0].spec.gang
            shape = tuple(spec.slice_shape) if spec.slice_shape else ()
            need = np.zeros(alloc.shape[1], np.int64)
            for p in waiting:
                need += _pod_req_vec(p.spec)
            reqs = waiting[0].spec.scheduling_requirements().merged(
                pool.requirements)
            for st in states:
                c = st["claim"]
                if not (st["resid"] >= need).all():
                    continue
                labels = dict(pool.labels)
                labels.update(catalog.offering_label_values(st["off"]))
                if not reqs.matches(labels):
                    continue
                if (c.taints and any(
                        not tolerates_all(p.spec.tolerations, c.taints)
                        for p in waiting)) or (pool.taints and any(
                        not tolerates_all(p.spec.tolerations, pool.taints)
                        for p in waiting)):
                    continue
                mask = 0
                if shape:
                    for m in enumerate_placements(st["torus"], shape):
                        if (m & st["occ"]) == 0:
                            mask = m
                            break
                    if not mask:
                        continue
                with obs.span("gang.place.live", gang=name, claim=c.name,
                              members=len(waiting)), \
                        self.journal.intent(
                            "gang_placement", gang=name, claim=c.name,
                            pods=[pod_key(p.spec) for p in waiting]):
                    for p in waiting:
                        self.provisioner._nominate(pod_key(p.spec), c.name)
                    self.placement_log.append(GangPlacementRecord(
                        gang=name, claim_name=c.name,
                        members=tuple(pod_key(p.spec) for p in waiting),
                        total_members=len(waiting),
                        min_member=spec.min_member, backend="live"))
                    metrics.GANG_PLACEMENTS.labels("live").inc()
                    self.cluster.record_event(
                        "PodGroup", name, "Normal", "GangPlaced",
                        f"{len(waiting)} members onto live node "
                        f"{c.name}" + (f" (slice "
                                       f"{'x'.join(map(str, shape))})"
                                       if shape else ""))
                st["resid"] = st["resid"] - need
                st["occ"] |= mask
                placed.add(name)
                log.info("gang placed on live capacity", gang=name,
                         claim=c.name, members=len(waiting),
                         slice=str(spec.slice_shape or ""))
                break
        return placed

    def _execute(self, plan, pool, nodeclass, catalog, problem) -> set[str]:
        sizes = {g.name: len(g.pod_names) for g in problem.gangs}
        mins = {g.name: g.min_member for g in problem.gangs}
        actuator = self.provisioner.actuator_for(nodeclass)
        claims, errors = actuator.execute_plan(plan.to_plan(), nodeclass,
                                               catalog, pool.name)
        if errors:
            log.warning("gang plan partially executed", pool=pool.name,
                        errors=errors[:3])
        placed: set[str] = set()
        for node, claim in zip(plan.nodes, claims):
            if claim is None:
                continue   # create failed: the gang stays pending whole
            for a in node.assignments:
                # one intent per (gang, claim): replay is all-or-nothing
                # — a live claim gets the whole membership re-nominated,
                # a dead one releases every member back to pending
                with self.journal.intent("gang_placement", gang=a.gang,
                                         claim=claim.name,
                                         pods=list(a.pod_names)):
                    for pn in a.pod_names:
                        self.provisioner._nominate(pn, claim.name)
                # total_members = the gang's pending membership when
                # planned; the invariant checker compares it against the
                # members the record actually carried (an assignment row
                # holds ALL of them by construction — the checker proves
                # it, never assumes it)
                self.placement_log.append(GangPlacementRecord(
                    gang=a.gang, claim_name=claim.name,
                    members=a.pod_names,
                    total_members=sizes.get(a.gang, len(a.pod_names)),
                    min_member=mins.get(a.gang, 0),
                    backend=plan.backend))
                metrics.GANG_PLACEMENTS.labels(plan.backend).inc()
                placed.add(a.gang)
                self.cluster.record_event(
                    "PodGroup", a.gang, "Normal", "GangPlaced",
                    f"{len(a.pod_names)} members on {claim.name} "
                    f"({node.instance_type}/{node.zone})")
        return placed
