"""IKS pool-cleanup controller: reap empty dynamic worker pools.

Reference: ``pkg/controllers/iks/poolcleanup/controller.go:75-258`` — a
1-minute poller that deletes karpenter-created (dynamic) pools that have
held zero workers past ``emptyPoolTTL``, honoring the cleanup policy
(Delete vs Retain) from the NodeClass's ``iksDynamicPools`` config.
"""

from __future__ import annotations

import time

from karpenter_tpu.cloud.errors import CloudError
from karpenter_tpu.cloud.fake_iks import FakeIKS
from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.iks")


class PoolCleanupController(PollController):
    name = "iks.poolcleanup"
    interval = 60.0

    def __init__(self, cluster: ClusterState, iks: FakeIKS,
                 empty_pool_ttl: float = 600.0, cleanup_policy: str = "Delete"):
        self.cluster = cluster
        self.iks = iks
        self.empty_pool_ttl = empty_pool_ttl
        self.cleanup_policy = cleanup_policy
        self._empty_since: dict[str, float] = {}

    def _policy_for(self, pool) -> tuple:
        """(ttl, policy) from the NodeClass that owns this dynamic pool —
        resolved by the ownership label stamped at creation (immune to name
        sanitization/collision-disambiguation), with a sanitized-prefix
        match as fallback for pools from before the label existed."""
        from karpenter_tpu.core.workerpool import LABEL_OWNER_NODECLASS, sanitize_pool_name
        owner = pool.labels.get(LABEL_OWNER_NODECLASS, "")
        if owner:
            nc = self.cluster.get("nodeclasses", owner)
            if nc is not None and nc.spec.iks_dynamic_pools is not None \
                    and nc.spec.iks_dynamic_pools.enabled:
                dyn = nc.spec.iks_dynamic_pools
                return float(dyn.empty_pool_ttl_seconds), dyn.cleanup_policy
        for nc in self.cluster.list("nodeclasses"):
            dyn = nc.spec.iks_dynamic_pools
            if dyn is not None and dyn.enabled and \
                    pool.name.startswith(sanitize_pool_name(dyn.pool_name_prefix)):
                return float(dyn.empty_pool_ttl_seconds), dyn.cleanup_policy
        return self.empty_pool_ttl, self.cleanup_policy

    def reconcile(self) -> Result:
        now = time.time()
        try:
            pools = self.iks.list_pools()
        except CloudError as e:
            log.warning("pool list failed", error=str(e))
            return Result()
        live_ids = {p.id for p in pools}
        for pid in list(self._empty_since):
            if pid not in live_ids:
                del self._empty_since[pid]
        for pool in pools:
            if not pool.dynamic or pool.state != "normal":
                continue
            workers = self.iks.list_workers(pool.id)
            if workers:
                self._empty_since.pop(pool.id, None)
                continue
            since = self._empty_since.setdefault(pool.id, now)
            ttl, policy = self._policy_for(pool)
            if now - since < ttl:
                continue
            if policy != "Delete":
                continue   # Retain: leave the empty pool alone
            try:
                self.iks.delete_pool(pool.id)
                self._empty_since.pop(pool.id, None)
                self.cluster.record_event(
                    "WorkerPool", pool.name, "Normal", "EmptyPoolDeleted",
                    f"dynamic pool empty past {ttl:.0f}s")
                log.info("deleted empty dynamic pool", pool=pool.name)
            except CloudError as e:
                log.warning("empty pool delete failed", pool=pool.name,
                            error=str(e))
        return Result()
