"""Fault-ring controllers: interruption, spot preemption, orphan cleanup,
catalog/pricing refreshers.

Each is an availability-mask writer (SURVEY.md §7.1 "faults"): their output
feeds ``UnavailableOfferings`` so the next solve window stops picking dead
offerings — the TPU-build shape of the reference's failure-detection loop
(SURVEY.md §5.3).
"""

from __future__ import annotations

import os
import time

from karpenter_tpu.apis.nodeclaim import parse_provider_id
from karpenter_tpu.catalog.instancetype import InstanceTypeProvider
from karpenter_tpu.catalog.unavailable import UnavailableOfferings
from karpenter_tpu.cloud.errors import CloudError, is_not_found
from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.core.actuator import KARPENTER_TAGS
from karpenter_tpu.core.cluster import ClusterState
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("controllers.faults")

ANNOTATION_INTERRUPTED = "karpenter-tpu.sh/interrupted"

# Node-condition heuristics -> interruption classes (ref interruption/
# controller.go:220-255).
_CAPACITY_CONDITIONS = ("OutOfCapacity", "InstanceRetired", "Preempted")
_HEALTH_CONDITIONS = ("KernelDeadlock", "ReadonlyFilesystem",
                      "FrequentKubeletRestart")
_NETWORK_CONDITIONS = ("NetworkUnavailable",)


class InterruptionController(PollController):
    """1-min scan of nodes for interruption signals (ref interruption/
    controller.go:151): condition heuristics with never-ready suppression
    (:259) PLUS the instance metadata-health signal (:304-325 — the
    reference queries the metadata service for health_state
    degraded/faulted; here the cloud API exposes the same field), then
    annotate + event + delete the claim so the replacement cycle runs;
    capacity reasons black out the offering."""

    name = "interruption"
    interval = 60.0
    never_ready_grace = 600.0   # suppress signals on nodes still booting

    def __init__(self, cluster: ClusterState,
                 unavailable: UnavailableOfferings, cloud=None):
        self.cluster = cluster
        self.unavailable = unavailable
        # optional: enables the metadata-health probe (condition
        # heuristics alone otherwise, as when metadata is unreachable —
        # the reference treats that as expected, controller.go:310)
        self.cloud = cloud

    def reconcile(self) -> Result:
        now = time.time()
        health = self._instance_health()
        for node in self.cluster.nodes():
            if node.deleted or ANNOTATION_INTERRUPTED in node.annotations:
                continue
            claim = self._claim_for(node)
            if claim is None or claim.deleted:
                continue
            # never-ready suppression: a node that hasn't become Ready yet
            # is booting, not interrupted (interruption/controller.go:259).
            # Anchored on the CLAIM's registration stamp, never
            # node.created_at alone: a node object recreated by
            # re-adoption would reset the grace window and suppress real
            # interruptions indefinitely.  Before registration stamps it
            # (slow launch, poll ordering) fall back to the LATER of
            # claim/node creation so a freshly joined node still gets
            # its boot grace.
            if not claim.initialized:
                anchor = claim.registered_at \
                    or max(claim.created_at, node.created_at)
                if now - anchor < self.never_ready_grace:
                    continue
            reason = self._interruption_reason(node, health)
            if not reason:
                continue
            self._handle(node, claim, reason)
        return Result()

    def _instance_health(self) -> dict:
        """instance id -> degraded|faulted, from one list call per sweep
        (the per-node metadata probe of the reference, lifted to the
        API so the control plane can see it).  Unreachable cloud ->
        heuristics only, never a failed sweep."""
        if self.cloud is None:
            return {}
        try:
            return {i.id: i.health_state for i in self.cloud.list_instances()
                    if getattr(i, "health_state", "ok")
                    in ("degraded", "faulted")}
        except Exception as e:  # noqa: BLE001 — e.g. a raw socket timeout
            # from the HTTP client; the condition heuristics need no
            # cloud access and must still run this sweep
            log.warning("metadata health probe failed", error=str(e))
            return {}

    def _claim_for(self, node):
        for claim in self.cluster.nodeclaims():
            if claim.provider_id == node.provider_id:
                return claim
        return None

    def _interruption_reason(self, node, health: dict) -> str:
        for cond in _CAPACITY_CONDITIONS:
            if node.conditions.get(cond) == "True":
                return f"capacity:{cond}"
        for cond in _NETWORK_CONDITIONS:
            if node.conditions.get(cond) == "True":
                return f"network:{cond}"
        for cond in _HEALTH_CONDITIONS:
            if node.conditions.get(cond) == "True":
                return f"health:{cond}"
        parsed = parse_provider_id(node.provider_id)
        if parsed and parsed[1] in health:
            # metadata-service health signal (controller.go:316-322)
            return f"health:metadata:{health[parsed[1]]}"
        return ""

    def _handle(self, node, claim, reason: str) -> None:
        node.annotations[ANNOTATION_INTERRUPTED] = reason
        self.cluster.update("nodes", node.name, node)
        self.cluster.record_event("Node", node.name, "Warning", "Interrupted",
                                  reason)
        metrics.INSTANCE_LIFECYCLE.labels("interrupted", claim.instance_type,
                                          claim.zone).inc()
        # capacity interruptions mean the offering is bad right now
        if reason.startswith("capacity:"):
            self.unavailable.mark_unavailable(
                claim.instance_type, claim.zone, claim.capacity_type,
                reason=reason)
        claim.deleted = True   # hand to the termination controller
        self.cluster.update("nodeclaims", claim.name, claim)
        log.info("interrupted node; replacing", node=node.name, reason=reason)


class SpotPreemptionController(PollController):
    """1-min spot scan (ref spot/preemption/controller.go:39-110): stopped
    instances with status_reason stopped_by_preemption -> offering blackout
    for 1h (key type:zone:spot, :97) + delete instance + finalize claim."""

    name = "spot.preemption"
    interval = 60.0
    blackout_ttl = 3600.0

    def __init__(self, cluster: ClusterState, cloud,
                 unavailable: UnavailableOfferings, journal=None):
        from karpenter_tpu.recovery.journal import NULL_JOURNAL

        self.cluster = cluster
        self.cloud = cloud
        self.unavailable = unavailable
        self.journal = journal if journal is not None else NULL_JOURNAL
        # instance ids already counted as interruptions: a stopped
        # instance whose delete keeps failing stays listed for many
        # polls — one real preemption must count ONCE in the risk
        # history, not once per reconcile (pruned against the live
        # list, so the set stays bounded)
        self._counted_interruptions: set[str] = set()

    def reconcile(self) -> Result:
        try:
            spot = self.cloud.list_spot_instances()
        except CloudError as e:
            log.warning("spot list failed", error=str(e))
            return Result()
        preempted = [i for i in spot if i.status == "stopped" and
                     i.status_reason == "stopped_by_preemption"]
        # labeled lifecycle history for the spot risk model
        # (karpenter_tpu/stochastic/risk.py): every live spot instance
        # this round is one exposure, every NEW preemption one
        # interruption — stamped from ground-truth cloud state, so
        # chaos spot storms generate exactly the histories production
        # would
        ledger = obs.get_ledger()
        for inst in spot:
            if inst.status == "running":
                ledger.node_seen(inst.profile, inst.zone)
        self._counted_interruptions &= {i.id for i in preempted}
        for inst in preempted:
            if inst.id not in self._counted_interruptions:
                self._counted_interruptions.add(inst.id)
                ledger.interruption(inst.profile, inst.zone)
            self.unavailable.mark_unavailable(
                inst.profile, inst.zone, "spot",
                ttl=self.blackout_ttl, reason="preempted")
            metrics.INSTANCE_LIFECYCLE.labels("preempted", inst.profile,
                                              inst.zone).inc()
            try:
                with self.journal.intent("orphan_delete", instance=inst.id,
                                         reason="spot_preempted"):
                    self.cloud.delete_instance(inst.id)
            except CloudError as e:
                if not is_not_found(e):
                    log.warning("preempted delete failed", instance=inst.id,
                                error=str(e))
            claim = self._claim_for_instance(inst.id)
            if claim is not None and not claim.deleted:
                claim.deleted = True
                self.cluster.update("nodeclaims", claim.name, claim)
                self.cluster.record_event(
                    "NodeClaim", claim.name, "Warning", "SpotPreempted",
                    f"{inst.profile}/{inst.zone} preempted; offering "
                    f"blacked out {self.blackout_ttl:.0f}s")
        if spot:
            # production learning loop (stochastic/risk.py): re-derive
            # the process risk model from the history this round just
            # extended and persist it through the journal's keyed state
            # records — the provisioner prices every catalog it
            # resolves from this model
            from karpenter_tpu.stochastic.risk import refresh_from_ledger

            refresh_from_ledger(ledger).save(self.journal)
        return Result()

    def _claim_for_instance(self, instance_id: str):
        for claim in self.cluster.nodeclaims():
            parsed = parse_provider_id(claim.provider_id)
            if parsed and parsed[1] == instance_id:
                return claim
        return None


class OrphanCleanupController(PollController):
    """Env-gated two-way orphan sweep (ref orphancleanup/controller.go:117,
    gate KARPENTER_ENABLE_ORPHAN_CLEANUP at controllers.go:238): nodes
    without instances and Karpenter-tagged instances without nodes
    (tag check :350-437)."""

    name = "node.orphancleanup"
    interval = 300.0
    min_instance_age = 600.0   # don't reap instances whose node is booting

    def __init__(self, cluster: ClusterState, cloud, enabled: bool | None = None,
                 journal=None):
        from karpenter_tpu.recovery.journal import NULL_JOURNAL

        self.cluster = cluster
        self.cloud = cloud
        self.journal = journal if journal is not None else NULL_JOURNAL
        self.enabled = (os.environ.get("KARPENTER_ENABLE_ORPHAN_CLEANUP", "")
                        .lower() in ("1", "true", "yes")) if enabled is None \
            else enabled

    def reconcile(self) -> Result:
        if not self.enabled:
            return Result()
        now = time.time()
        # precompute both reference sets once: the sweep must stay
        # O(instances + nodes + claims), not O(instances x (nodes + claims))
        node_ids = set()
        for n in self.cluster.nodes():
            parsed = parse_provider_id(n.provider_id)
            if parsed:
                node_ids.add(parsed[1])
        claim_ids = set()
        for c in self.cluster.nodeclaims():
            parsed = parse_provider_id(c.provider_id)
            if parsed:
                claim_ids.add(parsed[1])
        # instances without nodes (tag-checked — never touch unmanaged)
        for inst in self.cloud.list_instances():
            if not all(inst.tags.get(k) == v for k, v in KARPENTER_TAGS.items()):
                continue
            if now - inst.created_at < self.min_instance_age:
                continue
            if inst.id not in node_ids and inst.id not in claim_ids:
                try:
                    with self.journal.intent("orphan_delete",
                                             instance=inst.id,
                                             reason="orphan_sweep"):
                        self.cloud.delete_instance(inst.id)
                    log.info("orphan cleanup: deleted instance", instance=inst.id)
                except CloudError as e:
                    if not is_not_found(e):
                        log.warning("orphan instance delete failed",
                                    instance=inst.id, error=str(e))
        # nodes without instances
        for node in self.cluster.nodes():
            parsed = parse_provider_id(node.provider_id)
            if parsed is None:
                continue
            try:
                self.cloud.get_instance(parsed[1])
            except CloudError as e:
                if is_not_found(e):
                    self.cluster.delete("nodes", node.name)
                    log.info("orphan cleanup: deleted node", node=node.name)
        return Result()


class InstanceTypeRefreshController(PollController):
    """Hourly catalog refresh + expired-blackout cleanup (ref controllers/
    providers/instancetype/instancetype.go:73)."""

    name = "providers.instancetype"
    interval = 3600.0

    def __init__(self, instance_types: InstanceTypeProvider,
                 unavailable: UnavailableOfferings):
        self.instance_types = instance_types
        self.unavailable = unavailable

    def reconcile(self) -> Result:
        self.instance_types.refresh()
        removed = self.unavailable.cleanup()
        if removed:
            log.info("offering blackouts expired", count=removed)
        return Result()


class PricingRefreshController(PollController):
    """12h pricing refresh (ref controllers/providers/pricing/
    controller.go:73; NoOp fallback :38-50 — a provider without refresh()
    is skipped)."""

    name = "providers.pricing"
    interval = 12 * 3600.0

    def __init__(self, pricing_provider):
        self.pricing = pricing_provider

    def reconcile(self) -> Result:
        refresh = getattr(self.pricing, "refresh", None)
        if callable(refresh):
            refresh()
        return Result()
