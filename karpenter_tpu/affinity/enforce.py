"""The decode choke point: host-side affinity enforcement on COO plans.

Every plan in the system — device kernel, pallas, flat, host greedy,
degraded fallback — decodes through ``solver/encode.decode_plan_entries``,
which routes COO entries through :func:`enforce_affinity` whenever the
problem carries the affinity plane (the gang ``_enforce_gangs`` pattern,
same tuple contract).  Downstream of this line an edge-violating
placement is structurally impossible: violating entries are dropped
(their counts return to the per-group unplaced tally, where the explain
fold assigns the ``affinity_unsatisfied`` / ``spread_bound`` bits),
bound excess is clamped, and nodes emptied by a drop are closed with
their price leaving the plan.

Enforcement is a deterministic fixpoint: dropping a required-edge
target can strand its dependents, so passes repeat until stable
(bounded by the entry count — every pass that changes anything strictly
removes pods).  Order is canonical — nodes ascending, entries by
(group, entry) within a node, zones ascending — so reruns of the same
plan drop the same pods (the chaos digest-determinism contract).

Semantics per scope and kind (kube-faithful on the window):

- anti (both scopes): symmetric — a pod may not share the domain with
  any pod matching its anti selector, nor with a pod whose anti
  selector matches it.  A self-matching zone anti class ("one replica
  per zone") conflicts pods of the same group with each other.
- required (both scopes): each pod needs at least one OTHER matching
  pod co-resident in the domain (pods of the same entry count).
- hostname spread bounds: per node, per bounded class, matching pods
  are clamped to the bound; excess comes off the later entries.
- gang members are EXEMPT: gang atomicity supersedes affinity and
  spread at this choke (docs/design/gang.md).  The gang choke runs
  first in decode_plan_entries, so dropping a gang member here would
  reintroduce the partial gang it just made impossible — gang entries
  still occupy domain census and spread room (non-gang pods yield to
  them), but are never themselves dropped or clamped.
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.affinity import AFF_BIG
from karpenter_tpu.apis.pod import HOSTNAME_TOPOLOGY_KEY
from karpenter_tpu.utils import metrics

# fixpoint guard: each productive pass removes >= 1 pod, so this only
# bounds adversarial plans
_MAX_PASSES = 64


def _anti_mats(aff):
    """(anti_node [G,G], anti_zone [G,G]) symmetric bool conflict
    matrices, diagonal included (self-matching anti classes conflict a
    group with itself)."""
    mem = aff.member.astype(np.int32)
    ah = (aff.anti_host.astype(np.int32) @ mem) > 0
    az = (aff.anti_zone.astype(np.int32) @ mem) > 0
    return ah | ah.T, az | az.T


def enforce_affinity(problem, node_off: np.ndarray, gis: np.ndarray,
                     ns: np.ndarray, cnts: np.ndarray, cost: float):
    """Returns ``(node_off, gis, ns, cnts, dropped_or_None, cost)`` —
    the ``_enforce_gangs`` contract.  ``dropped`` is ``(group indices,
    pod counts)`` ready for the caller's ``np.add.at`` unplaced tally.
    """
    aff = getattr(problem, "aff", None)
    if aff is None or gis.size == 0:
        return node_off, gis, ns, cnts, None, cost
    G = len(problem.groups)
    # gang atomicity supersedes the choke (see module docstring): gang
    # entries count toward census/room but are never dropped or clamped
    gang_g = np.asarray(problem.group_gang[:G]) >= 0
    anti_n, anti_z = _anti_mats(aff)
    member = aff.member                                     # [C, G]
    host_cls = [c for c in range(len(aff.classes))
                if aff.classes[c][1] == HOSTNAME_TOPOLOGY_KEY]
    bounded = [c for c in host_cls if aff.host_bound[c] < AFF_BIG]
    req_h = aff.req_host
    req_z = aff.req_zone
    has_req_h = req_h.any(axis=1)
    has_req_z = req_z.any(axis=1)
    off_zone = problem.catalog.off_zone

    g_l = gis.astype(np.int64).tolist()
    n_l = ns.astype(np.int64).tolist()
    c_l = cnts.astype(np.int64).tolist()
    E = len(g_l)
    alive = [True] * E
    drop_g: list[int] = []
    drop_c: list[int] = []
    spread_clamped = 0

    def _zone_of(n: int) -> int:
        return int(off_zone[int(node_off[n])])

    def _domains(by_zone: bool):
        """{domain key: [entry ids in canonical order]} over live
        entries."""
        doms: dict[int, list[int]] = {}
        for e in range(E):
            if alive[e]:
                doms.setdefault(_zone_of(n_l[e]) if by_zone else n_l[e],
                                []).append(e)
        for es in doms.values():
            es.sort(key=lambda e: (g_l[e], e))
        return dict(sorted(doms.items()))

    def _drop(e: int, pods: int) -> None:
        nonlocal spread_clamped
        drop_g.append(g_l[e])
        drop_c.append(pods)
        c_l[e] -= pods
        if c_l[e] <= 0:
            alive[e] = False

    for _ in range(_MAX_PASSES):
        changed = False
        # ---- anti + hostname spread bounds, per domain ---------------
        for by_zone, anti in ((False, anti_n), (True, anti_z)):
            for _dom, es in _domains(by_zone).items():
                kept: list[int] = []
                for e in es:
                    g = g_l[e]
                    if gang_g[g]:
                        kept.append(e)       # exempt, still in census
                        continue
                    if any(anti[g, g_l[k]] for k in kept):
                        _drop(e, c_l[e])
                        changed = True
                        continue
                    if anti[g, g] and c_l[e] > 1:
                        _drop(e, c_l[e] - 1)     # one survivor per domain
                        changed = True
                    kept.append(e)
                if not by_zone and bounded:
                    room = {c: int(aff.host_bound[c]) for c in bounded}
                    for e in kept:
                        if not alive[e]:
                            continue
                        for c in bounded:
                            if member[c, g_l[e]] and not gang_g[g_l[e]] \
                                    and c_l[e] > room[c]:
                                over = c_l[e] - max(room[c], 0)
                                _drop(e, over)
                                spread_clamped += over
                                changed = True
                            if member[c, g_l[e]]:
                                room[c] -= c_l[e]
        # ---- required edges, per domain ------------------------------
        for by_zone, req, has_req in ((False, req_h, has_req_h),
                                      (True, req_z, has_req_z)):
            if not has_req.any():
                continue
            for _dom, es in _domains(by_zone).items():
                # matching-pod totals per class in this domain
                tot: dict[int, int] = {}
                for e in es:
                    for c in np.nonzero(member[:, g_l[e]])[0].tolist():
                        tot[c] = tot.get(c, 0) + c_l[e]
                for e in es:
                    g = g_l[e]
                    if not has_req[g] or gang_g[g]:
                        continue
                    for c in np.nonzero(req[g])[0].tolist():
                        own = 1 if member[c, g] else 0
                        if tot.get(c, 0) - own < 1:
                            _drop(e, c_l[e])
                            changed = True
                            break
        if not changed:
            break

    if not drop_g:
        return node_off, gis, ns, cnts, None, cost
    if spread_clamped:
        metrics.AFFINITY_SPREAD_AVOIDED.inc(spread_clamped)
    keep = np.array(alive, dtype=bool)
    new_cnts = np.array(c_l, dtype=cnts.dtype)
    dropped = (np.array(drop_g, dtype=np.int64),
               np.array(drop_c, dtype=np.int64))
    dead = np.setdiff1d(np.unique(ns), np.unique(ns[keep]),
                        assume_unique=True)
    if dead.size:
        node_off = np.array(node_off, copy=True)
        cost = float(cost) - float(
            problem.catalog.off_price[node_off[dead]].sum())
        node_off[dead] = -1
    return (node_off, gis[keep], ns[keep], new_cnts[keep], dropped,
            cost)
