"""Independent affinity validator — no shared code with the solver.

The third layer of the plane (kernel gates -> decode choke ->
validator): checks a finished Plan against the RAW pods, re-deriving
every domain from the plan itself (node identity = planned node, zone
identity = the node's zone string).  Nothing here touches the
AffinityIndex, the selector classes, or the enforce pass — a bug in
the lowering cannot hide from this file.

Checks:

- required (anti-)affinity per placed pod, kube semantics: for each
  required term, some OTHER pod matching the selector shares the
  topology domain; for each anti term, NO other matching pod shares it
  — and symmetrically, no co-resident pod's anti term matches this pod
  (anti-affinity disjointness);
- hostname topology spread (DoNotSchedule): per node, pods matching
  the constraint's selector stay within ``max_skew``; an empty
  selector self-selects the carrier's signature group (the documented
  cap lowering).

Gang members are exempt from their OWN terms (gang atomicity
supersedes affinity/spread at the decode choke, docs/design/gang.md),
but still count toward other pods' domains — the same census-only
semantics the choke applies.

Zone-scope spread keeps its legacy validator
(``solver/validate.validate_plan`` section 4 — skew over viable
zones); this file owns everything the affinity plane added.
"""

from __future__ import annotations

from collections import defaultdict
from collections.abc import Sequence

from karpenter_tpu.apis.pod import (
    HOSTNAME_TOPOLOGY_KEY, ZONE_TOPOLOGY_KEY, PodSpec, pod_key,
)
from karpenter_tpu.solver.types import Plan


def _matches(selector, labels_dict) -> bool:
    return bool(selector) \
        and all(labels_dict.get(k) == v for k, v in selector)


def validate_affinity_plan(plan: Plan, pods: Sequence[PodSpec]
                           ) -> list[str]:
    """Returns a list of violations (empty = the plan honors every
    affinity term and hostname spread bound)."""
    errors: list[str] = []
    by_name: dict[str, PodSpec] = {pod_key(p): p for p in pods}

    # domain membership, straight from the plan
    node_pods: list[list[PodSpec]] = []
    zone_pods: dict[str, list[PodSpec]] = defaultdict(list)
    for node in plan.nodes:
        members = [by_name[pn] for pn in node.pod_names if pn in by_name]
        node_pods.append(members)
        zone_pods[node.zone].extend(members)

    def _domain_violations(members: list[PodSpec], scope: str,
                           label: str) -> None:
        labels = [p.labels_dict for p in members]
        for i, p in enumerate(members):
            if p.gang is not None:
                continue        # gang supersedes (census-only)
            others = [labels[j] for j in range(len(members)) if j != i]
            for t in p.affinity:
                if t.topology_key != scope:
                    continue
                hit = any(_matches(t.label_selector, lab)
                          for lab in others)
                if t.anti and hit:
                    errors.append(
                        f"{label}: pod {pod_key(p)} anti-affinity "
                        f"{dict(t.label_selector)} violated by a "
                        f"co-resident matching pod")
                if not t.anti and not hit:
                    errors.append(
                        f"{label}: pod {pod_key(p)} required affinity "
                        f"{dict(t.label_selector)} has no matching "
                        f"co-resident pod")

    for ni, members in enumerate(node_pods):
        _domain_violations(members, HOSTNAME_TOPOLOGY_KEY, f"node{ni}")
    for zone in sorted(zone_pods):
        _domain_violations(zone_pods[zone], ZONE_TOPOLOGY_KEY,
                           f"zone {zone}")

    # hostname spread bounds, re-counted from raw pods per node
    for ni, members in enumerate(node_pods):
        for p in members:
            if p.gang is not None:
                continue        # gang supersedes (census-only)
            for c in p.topology_spread:
                if c.topology_key != HOSTNAME_TOPOLOGY_KEY \
                        or c.when_unsatisfiable != "DoNotSchedule":
                    continue
                if c.label_selector:
                    n = sum(1 for q in members
                            if _matches(c.label_selector, q.labels_dict))
                else:
                    sig = p.constraint_signature()
                    n = sum(1 for q in members
                            if q.constraint_signature() == sig)
                if n > c.max_skew:
                    errors.append(
                        f"node{ni}: hostname spread bound "
                        f"{c.max_skew} exceeded ({n} matching pods, "
                        f"selector {dict(c.label_selector)})")
    return errors
