"""Degraded mode: unconstrained-scan fallback.

The affinity-gated kernel can fail the same ways any device kernel can
(dead tunnel, Mosaic/XLA fault, a poisoned donated buffer).  None of
those may fail a solve window — the ``ResilientSolver`` convention:
the dispatch strips the affinity suffix and re-runs the IDENTICAL
packed buffer through the deterministic scan, with an ``ERRORS``
breadcrumb so dashboards see every degradation.  Correctness survives
the fallback: the decode choke point (``affinity/enforce.py``) runs on
EVERY plan regardless of which kernel produced it, so a degraded
window drops edge-violating placements honestly instead of shipping
them — degraded mode costs packing quality, never constraint fidelity.
"""

from __future__ import annotations

from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("affinity.degraded")


def strip_affinity(prep) -> None:
    """Disarm the affinity route on a prepared dispatch IN PLACE: the
    next ``_dispatch`` of this prep (and of its cached template — a
    broken kernel must not re-break every later window of the same
    shape) runs the deterministic scan on the unchanged base buffer."""
    prep.aff = None
    tmpl = getattr(prep, "tmpl", None)
    if tmpl is not None:
        tmpl.aff = None


def note_degraded(prep, error: Exception) -> None:
    """One degradation breadcrumb: log + metric, then strip."""
    log.warning("affinity kernel failed; unconstrained-scan fallback "
                "engaged (choke-point enforcement still applies)",
                error=str(error)[:300],
                G=prep.G_pad, O=prep.O_pad, N=prep.N)
    metrics.ERRORS.labels("solver", "affinity_fallback").inc()
    strip_affinity(prep)


def unconstrained_problem(problem):
    """Problem-level fallback (host paths): the same window with the
    affinity index dropped — the scan ignores edges, the decode choke
    still enforces them."""
    if getattr(problem, "aff", None) is None:
        return problem
    return problem.replace(aff=None)
