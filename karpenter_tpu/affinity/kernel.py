"""Device kernel: the affinity-gated FFD scan.

Same shape as ``solver/jax_backend.solve_packed`` — one donated packed
problem buffer in, one packed result buffer (node_off / unplaced / cost
/ assign tail / explain words / telemetry) out — plus the small donated
affinity suffix leaf (``affinity/encode.pack_affinity``).  The scan
carries three extra pieces of per-node state:

    node_sel   int32 [N]     bitmask of selector classes PRESENT
    node_anti  int32 [N]     union of anti masks of resident groups
    node_cnt   int32 [N, C]  per-class resident pod counts

and gates every fill with three masked terms (the PR-9
``capacity_higher_prio`` per-reduction reformulation — per-node class
presence instead of the naive O(G²) pairwise grid):

    anti ok    (node_sel & g_anti) == 0  and  (node_anti & g_sel) == 0
               (both directions — kube enforces anti-affinity
               symmetrically at schedule time)
    req ok     (g_req & ~node_sel) == 0  (every required class already
               resident; groups whose own labels don't satisfy their
               required classes can NEVER open a node, so kernel
               placements satisfy required hostname edges BY
               CONSTRUCTION — the encoder's req_depth sort key packs
               targets first)
    spread     fit is clipped to min over the group's bounded member
               classes of (bound_c - node_cnt[n, c])

Zone-scope terms never reach this kernel: the encode prepass co-pins
required zone components and the decode choke point
(``affinity/enforce.py``) drops any residual violation host-side.

Bit-identity with the numpy oracle (affinity/greedy.py) is structural:
every gate is exact int32 arithmetic — no float enters the affinity
terms at all — and the scan body mirrors ``jax_backend._ffd_step``
line for line.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from karpenter_tpu.affinity import AFF_BIG, C_PAD
from karpenter_tpu.solver.types import FIT_BIG as _BIG


def _fit_counts(resid, req):
    """[X,R] // [R] -> [X]; dims with req==0 are unconstrained (mirror
    of jax_backend._fit_counts, local so the kernel module has no
    import-time dependency on the 2k-line backend)."""
    per_dim = jnp.where(req[None, :] > 0,
                        resid // jnp.maximum(req[None, :], 1), _BIG)
    return jnp.min(per_dim, axis=1)


def _spread_allowance(node_cnt, member, bounds):
    """int32 [N]: how many more pods of a group whose member classes are
    ``member`` ([C] 0/1) each node admits under the per-class bounds —
    AFF_BIG when no member class is bounded."""
    live = (member[None, :] > 0) & (bounds[None, :] < AFF_BIG)
    room = jnp.where(live, bounds[None, :] - node_cnt, AFF_BIG)
    return jnp.min(room, axis=1)


def _ffd_step_affinity(off_alloc, off_rank, bounds, state, inputs):
    """One group through the affinity-gated scan.  Mirrors
    jax_backend._ffd_step line for line; the three affinity gates mask
    the open-node fit, the new-node branch honors ``can_open`` and the
    per-class bound, and the class state advances with the placement."""
    node_off, node_resid, node_sel, node_anti, node_cnt, ptr = state
    req, count, cap, compat_g, g_sel_g, g_anti_g, g_req_g = inputs

    C = node_cnt.shape[1]
    member = ((g_sel_g >> jnp.arange(C, dtype=jnp.int32)) & 1) \
        .astype(jnp.int32)                                    # [C] 0/1

    N = node_off.shape[0]
    is_open = node_off >= 0
    node_compat = jnp.where(is_open,
                            compat_g[jnp.clip(node_off, 0, None)], False)

    # ---- fill open nodes, first-fit in age order -------------------------
    fit = _fit_counts(node_resid, req)
    fit = jnp.where(node_compat, fit, 0)
    fit = jnp.minimum(fit, cap)
    ok_anti = ((node_sel & g_anti_g) == 0) & ((node_anti & g_sel_g) == 0)
    ok_req = (g_req_g & ~node_sel) == 0
    fit = jnp.where(ok_anti & ok_req, fit, 0)
    allow = _spread_allowance(node_cnt, member, bounds)
    fit = jnp.minimum(fit, jnp.clip(allow, 0, None))
    cumfit = jnp.cumsum(fit) - fit
    take = jnp.clip(count - cumfit, 0, fit)
    placed = jnp.sum(take)
    node_resid = node_resid - take[:, None] * req[None, :]
    node_cnt = node_cnt + take[:, None] * member[None, :]
    node_sel = jnp.where(take > 0, node_sel | g_sel_g, node_sel)
    node_anti = jnp.where(take > 0, node_anti | g_anti_g, node_anti)
    rem = count - placed

    # ---- open new nodes with the cheapest-per-pod offering ---------------
    # a group whose own labels do not satisfy its required classes can
    # never seed a node: its targets must already be resident
    can_open = (g_req_g & ~g_sel_g) == 0
    bound_new = jnp.min(jnp.where((member > 0) & (bounds < AFF_BIG),
                                  bounds, AFF_BIG))
    fit_empty = _fit_counts(off_alloc, req)
    fit_empty = jnp.where(compat_g, fit_empty, 0)
    fit_empty = jnp.minimum(fit_empty, cap)
    fit_empty = jnp.minimum(fit_empty, rem)
    fit_empty = jnp.where(can_open, fit_empty, 0)
    fit_empty = jnp.minimum(fit_empty, bound_new)
    cpp = jnp.where(fit_empty > 0, off_rank / fit_empty.astype(jnp.float32),
                    jnp.inf)
    best = jnp.argmin(cpp).astype(jnp.int32)
    bf = fit_empty[best]

    n_new = jnp.where(bf > 0, -(-rem // jnp.maximum(bf, 1)), 0)
    n_new = jnp.minimum(n_new, N - ptr)
    idx = jnp.arange(N, dtype=jnp.int32)
    new_pos = idx - ptr
    is_new = (new_pos >= 0) & (new_pos < n_new)
    pods_new = jnp.where(is_new, jnp.clip(rem - new_pos * bf, 0, bf), 0)
    opened = is_new & (pods_new > 0)
    node_off = jnp.where(opened, best, node_off)
    node_resid = jnp.where(
        opened[:, None],
        off_alloc[best][None, :] - pods_new[:, None] * req[None, :],
        node_resid)
    node_cnt = jnp.where(opened[:, None], pods_new[:, None] * member[None, :],
                         node_cnt)
    node_sel = jnp.where(opened, g_sel_g, node_sel)
    node_anti = jnp.where(opened, g_anti_g, node_anti)
    ptr = ptr + jnp.sum(opened.astype(jnp.int32))
    placed_new = jnp.sum(pods_new)
    unplaced_g = rem - placed_new
    assign_g = take + pods_new
    return ((node_off, node_resid, node_sel, node_anti, node_cnt, ptr),
            (assign_g, unplaced_g))


def _right_size_affinity(node_off, load, assign, compat, off_alloc,
                         off_rank):
    """Per-node cheapest compatible offering that fits the final load —
    the base ``jax_backend._right_size`` body (no soft preferences).
    Offering swaps never move a pod between nodes, so every affinity
    gate the scan enforced still holds afterwards."""
    N = node_off.shape[0]
    is_open = node_off >= 0
    safe_off = jnp.clip(node_off, 0, None)
    present = (assign > 0).astype(jnp.float32)               # [G, N]
    incompat = (~compat).astype(jnp.float32)                 # [G, O]
    incompat_count = jnp.einsum("gn,go->no", present, incompat,
                                preferred_element_type=jnp.float32)
    all_compat = incompat_count < 0.5                        # [N, O]
    fits = jnp.all(off_alloc[None, :, :] >= load[:, None, :], axis=2)
    candidate = all_compat & fits & is_open[:, None]
    rank_eff = jnp.broadcast_to(off_rank[None, :], (N, off_rank.shape[0]))
    cand_price = jnp.where(candidate, rank_eff, jnp.inf)
    best = jnp.argmin(cand_price, axis=1).astype(jnp.int32)
    best_price = jnp.min(cand_price, axis=1)
    cur_price = jnp.take_along_axis(rank_eff, safe_off[:, None],
                                    axis=1)[:, 0]
    improve = is_open & (best_price < cur_price - 1e-9)
    return jnp.where(improve, best, node_off)


def _affinity_words(aff_flag, spread_flag, count, unplaced):
    """int32 [G] with the two affinity reason bits: set for a live
    unplaced group that carries (or is targeted by) an armed edge /
    a bounded spread class.  Mirrored in
    explain/greedy.affinity_words_np (the parity contract)."""
    from karpenter_tpu.explain import BIT

    live_un = (count > 0) & (unplaced > 0)
    bits = jnp.where(live_un & (aff_flag > 0),
                     jnp.int32(1 << BIT["affinity_unsatisfied"]), 0)
    bits = bits | jnp.where(live_un & (spread_flag > 0),
                            jnp.int32(1 << BIT["spread_bound"]), 0)
    return bits.astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("G", "O", "U", "N", "right_size",
                                    "compact", "dense16", "coo16"),
                   donate_argnames=("packed", "aff"))
def solve_packed_affinity(packed, aff, off_alloc, off_price, off_rank, *,
                          G: int, O: int, U: int, N: int,
                          right_size: bool = True, compact: int = 0,
                          dense16: bool = False, coo16: bool = False):
    """Packed-I/O affinity-gated solve.  Buffer contract identical to
    ``solve_packed`` (the unconstrained fallback re-dispatches the same
    ``packed`` buffer; the decode choke point still enforces every
    edge), plus the donated affinity suffix ``aff`` — O(G) class
    bitmasks and the C_PAD bound row (affinity/encode.pack_affinity),
    never a (G×G) matrix."""
    from karpenter_tpu.solver.jax_backend import (
        _explain_words, _pack_result, _telemetry_words, _unpack_problem,
    )
    from karpenter_tpu.apis.pod import NUM_RESOURCES

    meta, compat_i, rows_g = _unpack_problem(packed, off_alloc, G, O, U)
    g_sel = aff[:G]
    g_anti = aff[G:2 * G]
    g_req = aff[2 * G:3 * G]
    aff_flag = aff[3 * G:4 * G]
    spread_flag = aff[4 * G:5 * G]
    bounds = aff[5 * G:5 * G + C_PAD]
    compat = compat_i > 0
    count, cap = meta[:, 4], meta[:, 5]

    node_off0 = jnp.full((N,), -1, dtype=jnp.int32)
    node_resid0 = jnp.zeros((N, NUM_RESOURCES), dtype=jnp.int32)
    node_sel0 = jnp.zeros((N,), dtype=jnp.int32)
    node_anti0 = jnp.zeros((N,), dtype=jnp.int32)
    node_cnt0 = jnp.zeros((N, C_PAD), dtype=jnp.int32)
    step = functools.partial(_ffd_step_affinity, off_alloc, off_rank,
                             bounds)
    ((node_off, node_resid, _sel, _anti, _cnt, _ptr),
     (assign, unplaced)) = lax.scan(
        step,
        (node_off0, node_resid0, node_sel0, node_anti0, node_cnt0,
         jnp.int32(0)),
        (meta[:, :4], count, cap, compat, g_sel, g_anti, g_req))
    if right_size:
        load = off_alloc[jnp.clip(node_off, 0, None)] - node_resid
        node_off = _right_size_affinity(node_off, load, assign, compat,
                                        off_alloc, off_rank)
    is_open = node_off >= 0
    # cost word: excluded from bit-parity up to reduction order (see
    # docs/design/parity.md) — the one sanctioned float reduction
    cost = jnp.sum(  # graftlint: disable=GL202 (cost word)
        jnp.where(is_open, off_price[jnp.clip(node_off, 0, None)], 0.0))
    out = _pack_result(node_off, assign, unplaced, cost, compact, dense16,
                       coo16)
    words = _explain_words(meta, rows_g, compat_i,
                           unplaced.astype(jnp.int32), off_alloc)
    words = words | _affinity_words(aff_flag, spread_flag, count,
                                    unplaced.astype(jnp.int32))
    # telemetry binding mask: constrained groups — any armed edge or
    # bounded class membership (the oracle twin passes the identical
    # flags to telemetry_words_np)
    binding = (aff_flag | spread_flag) > 0
    tele = _telemetry_words(meta, node_off, assign, unplaced, off_alloc,
                            binding=binding)
    return jnp.concatenate([out, words, tele])
