"""Pod-to-pod (anti-)affinity and topology spread — the sixth solver plane.

Per "Affinity-Aware Resource Provisioning for Long-Running Applications"
(PAPERS.md), inter-pod placement constraints dominate real long-running
workloads: required affinity ("run with my cache"), anti-affinity
("never two replicas on one host"), and per-topology-key spread bounds.
``apis/pod.PodAffinityTerm`` / ``TopologySpreadConstraint`` carry them
(parse_-style hard-reject validation; both already split constraint
signatures, so an edge-carrying pod never shares a group row with a
lookalike).

The lowering is DENSE, never pairwise-per-pod: the encoder maps each
distinct label selector to a small *selector class* and each group to
int32 class BITMASKS (``g_sel`` — classes the group's labels match,
``g_anti``/``g_req`` — classes its terms target), plus one per-class
spread-bound row.  The device kernel then evaluates every pairwise
constraint through per-node class presence masks — the PR-9
``capacity_higher_prio`` reformulation generalized: O(G·N·C) masked
reductions instead of the naive O(G²·N) pairwise grid — fused into the
one solve dispatch (zero extra dispatches; the class tensors ride a
small packed suffix leaf exactly like the stochastic plane's mean/var
rows, never a (G×G) H2D).

Plane layout (the established encode/kernel/greedy-parity/degraded/
validate pattern of preempt/, gang/, repack/, and stochastic/):

- ``affinity/encode.py``   — selector classes, group bitmasks, spread
  bounds, connected components, required-edge depth ranks, the packed
  suffix leaf, and the zone-scope co-pin prepass;
- ``affinity/kernel.py``   — the affinity-gated FFD scan (jitted,
  donated per GL006, prof-sampled), same packed result wire;
- ``affinity/greedy.py``   — the bit-identical numpy parity oracle;
- ``affinity/degraded.py`` — unconstrained-scan fallback when the
  affinity kernel fails (the choke point below still enforces edges,
  so a degraded window never ships a violating plan);
- ``affinity/enforce.py``  — the decode choke point: every plan (device
  OR host, healthy OR degraded) passes the same host-side edge/bound
  enforcement in ``decode_plan_entries`` (the gang pattern);
- ``affinity/validate.py`` — the independent validator: edge
  satisfaction, spread counts re-derived from raw pods, anti-affinity
  disjointness — shares no code with the solver.

Topology scopes: ``kubernetes.io/hostname`` constraints are enforced
IN-KERNEL (per-node class masks); ``topology.kubernetes.io/zone``
constraints are resolved host-side (the encode zone-pin prepass
co-pins required components, the choke drops violators) — the kernel
stays a pure per-node scan either way.

Every numeric constant the device kernel and the host oracle share
lives HERE — change one side, change both is prevented by having only
one side to change.
"""

from __future__ import annotations

# Selector-class budget of the device lane: class masks are int32
# bitmasks, and bit 31 is the sign bit while bit 30 guards the
# ``~mask`` complement arithmetic — 30 distinct hostname selector
# classes per window is far above real manifests (clusters reuse a
# handful of app/tier selectors).  A window exceeding the budget
# disarms the DEVICE lane only (logged breadcrumb); the decode choke
# and the validator still enforce every edge host-side.
MAX_SELECTOR_CLASSES = 30

# Padded class-axis width of the packed suffix leaf and the kernel's
# per-node count grid — one power-of-two bucket, so the executable
# cache never fragments on class count.
C_PAD = 32

# "Unbounded" sentinel for spread-bound rows: large enough that
# ``bound - node_count`` never binds, small enough that int32
# arithmetic on it can never overflow.
AFF_BIG = 1 << 20


def affinity_enabled(problem) -> bool:
    """Does this encoded problem carry the affinity plane?  True when
    the encoder attached an :class:`~karpenter_tpu.affinity.encode.
    AffinityIndex` (at least one live inter-group edge or bounded
    spread class).  The strict-superset gate: every dispatch path
    checks this before routing to the affinity kernel, and an
    edge-free window is byte-identical to a build without this plane.
    """
    return getattr(problem, "aff", None) is not None
