"""Affinity lowering: pod terms -> selector classes -> dense tensors.

The host half of the affinity plane.  ``solver/encode.py`` calls
:func:`build_affinity_index` over the final group list (after the FFD
sort key is computed — the index rides the SAME group order the other
columns do), and :func:`zone_pin_prepass` before per-signature lowering
so required zone-scope components land in one zone.

The dense trick: instead of a per-pod pairwise test, every DISTINCT
label selector among the window's armed terms becomes one *selector
class*.  Group membership of a class is a [C, G] bool matrix; each
group's constraints collapse to three int32 class bitmasks

    g_sel   classes whose selector matches the group's labels
    g_anti  classes the group's hostname anti-affinity terms target
    g_req   classes the group's hostname required-affinity terms target

plus one per-class spread-bound row.  The kernel then answers "may
group g join node n" from the node's accumulated class-presence mask —
O(G·N·C) masked reductions, the PR-9 ``capacity_higher_prio``
per-offering-reduction reformulation generalized to per-node class
presence (naive pairwise would be O(G²·N) and need a (G×G) H2D).
The dense (G×G) required/anti matrices are still DERIVABLE
(``req_mat``/``anti_mat`` properties, used by the validator and the
router tests) but never shipped to the device.

Arming is strictly-superset: a window whose terms produce no live
inter-group edge and no bounded class gets ``None`` — encode attaches
nothing, every downstream path is byte-identical to an affinity-free
build.  Legacy lowerings are preserved verbatim and do NOT arm the
plane: self hostname anti-affinity (per-node cap 1), self-only zone
affinity (best-zone pin), zone-scope spread (subgroup split).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from karpenter_tpu.affinity import AFF_BIG, C_PAD, MAX_SELECTOR_CLASSES
from karpenter_tpu.apis.pod import (
    HOSTNAME_TOPOLOGY_KEY, ZONE_TOPOLOGY_KEY, PodSpec,
)
from karpenter_tpu.utils.logging import get_logger

log = get_logger("affinity.encode")

# required-edge depth ranks are resolved by fixed-point iteration; real
# dependency chains are shallow (a service and its cache), so the cap
# only guards against adversarial/cyclic windows
_DEPTH_ITERS = 64


def _matches(selector, labels_dict) -> bool:
    return all(labels_dict.get(k) == v for k, v in selector)


@dataclass
class AffinityIndex:
    """The per-window affinity lowering, aligned with the encoded group
    order.  ``member[c, g]`` is the one matrix everything else derives
    from; the int32 bitmask lane (``g_sel``/``g_anti``/``g_req``/
    ``bounds``) is the device subset — hostname-scope classes only,
    disarmed wholesale when the window exceeds
    ``MAX_SELECTOR_CLASSES`` (the choke and validator still enforce
    every edge host-side)."""

    classes: tuple          # ((selector, topology_key), ...) [C_all]
    member: np.ndarray      # bool [C_all, G]
    req_host: np.ndarray    # bool [G, C_all] — carrier of required host term
    anti_host: np.ndarray   # bool [G, C_all]
    req_zone: np.ndarray    # bool [G, C_all]
    anti_zone: np.ndarray   # bool [G, C_all]
    host_bound: np.ndarray  # int32 [C_all]; AFF_BIG = unbounded
    comp: np.ndarray        # int32 [G] — connected-component id
    req_depth: np.ndarray   # int32 [G] — FFD sort key (targets first)
    edge_count: int         # live directed (carrier -> member) edges
    device_armed: bool
    g_sel: np.ndarray       # int32 [G] — device class bitmasks
    g_anti: np.ndarray      # int32 [G]
    g_req: np.ndarray       # int32 [G]
    aff_flag: np.ndarray    # int32 [G] 0/1 — explain bit 'affinity_unsatisfied'
    spread_flag: np.ndarray  # int32 [G] 0/1 — explain bit 'spread_bound'
    bounds: np.ndarray      # int32 [C_PAD] — device per-node class bounds

    @property
    def num_classes(self) -> int:
        return len(self.classes)

    @property
    def num_groups(self) -> int:
        return int(self.member.shape[1])

    @property
    def req_mat(self) -> np.ndarray:
        """Dense int32 [G, G]: ``req_mat[g, h]`` = 1 when g carries a
        required edge targeting h (any scope) — the validator/router
        view; never shipped to the device."""
        req = self.req_host | self.req_zone                 # [G, C]
        return (req.astype(np.int32) @ self.member.astype(np.int32)
                > 0).astype(np.int32)

    @property
    def anti_mat(self) -> np.ndarray:
        """Dense int32 [G, G]: anti edges, symmetric closure (kube
        enforces anti-affinity in both directions at schedule time)."""
        anti = self.anti_host | self.anti_zone
        m = (anti.astype(np.int32) @ self.member.astype(np.int32)
             > 0).astype(np.int32)
        return (m | m.T).astype(np.int32)

    def permute(self, order: np.ndarray) -> "AffinityIndex":
        """Re-align every per-group axis with a sorted group order
        (``new[i] = old[order[i]]``) — called once, after the FFD
        lexsort that consumed ``req_depth``."""
        inv_comp = self.comp[order]
        # relabel component ids to the min NEW index per component so
        # ids stay order-canonical after the permutation
        relabel: dict[int, int] = {}
        comp_new = np.empty_like(inv_comp)
        for i, c in enumerate(inv_comp.tolist()):
            comp_new[i] = relabel.setdefault(c, i)
        return AffinityIndex(
            classes=self.classes,
            member=self.member[:, order],
            req_host=self.req_host[order], anti_host=self.anti_host[order],
            req_zone=self.req_zone[order], anti_zone=self.anti_zone[order],
            host_bound=self.host_bound, comp=comp_new,
            req_depth=self.req_depth[order], edge_count=self.edge_count,
            device_armed=self.device_armed,
            g_sel=self.g_sel[order], g_anti=self.g_anti[order],
            g_req=self.g_req[order],
            aff_flag=self.aff_flag[order],
            spread_flag=self.spread_flag[order],
            bounds=self.bounds,
        )


class _UnionFind:
    def __init__(self, n: int):
        self.p = list(range(n))

    def find(self, a: int) -> int:
        while self.p[a] != a:
            self.p[a] = self.p[self.p[a]]
            a = self.p[a]
        return a

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            # smaller root wins: component ids stay deterministic
            if rb < ra:
                ra, rb = rb, ra
            self.p[rb] = ra


def group_terms(rep: PodSpec):
    """(armed affinity terms, bounded hostname spread constraints) for
    one representative — the single place that knows which terms the
    legacy lowerings already consumed.  Excluded here, preserved there:
    self hostname anti-affinity (encode's per-node cap 1), zone-scope
    spread (the subgroup split), ScheduleAnyway hostname spread (soft —
    a cost term would be the honest lowering; currently a documented
    no-op, matching the pre-affinity encoder)."""
    own = rep.labels_dict
    terms = []
    for t in rep.affinity:
        if t.topology_key == HOSTNAME_TOPOLOGY_KEY and t.anti \
                and _matches(t.label_selector, own):
            continue                      # legacy: self anti -> cap 1
        terms.append(t)
    spreads = [c for c in rep.topology_spread
               if c.topology_key == HOSTNAME_TOPOLOGY_KEY
               and c.when_unsatisfiable == "DoNotSchedule"
               and c.label_selector]
    return terms, spreads


def hostname_cap(rep: PodSpec) -> int | None:
    """Per-node cap from EMPTY-selector hostname spread (DoNotSchedule):
    the constraint self-selects the pod's own group, so 'at most
    max_skew matching pods per node' lowers exactly onto the existing
    cap_per_node machinery — no plane arming, no kernel change.
    ``None`` when the pod carries no such constraint (the caller's
    BIG_CAP sentinel semantics must stay untouched)."""
    caps = [c.max_skew for c in rep.topology_spread
            if c.topology_key == HOSTNAME_TOPOLOGY_KEY
            and c.when_unsatisfiable == "DoNotSchedule"
            and not c.label_selector]
    return min(caps) if caps else None


def build_affinity_index(reps: list[PodSpec]) -> AffinityIndex | None:
    """Lower one window's affinity surface to the dense index, or
    ``None`` when nothing arms (the strict-superset gate).

    A term arms the plane only when it reaches BEYOND its own group:
    an anti/required selector matching at least one other group, or a
    required selector matching nothing (the honest lowering is 'cannot
    place', not a silent drop).  A bounded spread class arms when any
    group is a member.  Self-only zone affinity and self-only zone
    anti-affinity keep their legacy behavior (best-zone pin / no-op).
    """
    G = len(reps)
    if G == 0:
        return None
    labels = [rep.labels_dict for rep in reps]
    per_group = [group_terms(rep) for rep in reps]
    if not any(ts or ss for ts, ss in per_group):
        return None

    # ---- selector-class universe (deterministic first-seen order) ----
    classes: list[tuple] = []
    cls_of: dict[tuple, int] = {}

    def _cls(selector, key) -> int:
        k = (tuple(selector), key)
        if k not in cls_of:
            cls_of[k] = len(classes)
            classes.append(k)
        return cls_of[k]

    # pass 1: which (term, class) pairs are LIVE (arm the plane)?
    # membership is evaluated against every group's labels up front.
    def _members(selector) -> list[int]:
        return [g for g in range(G) if _matches(selector, labels[g])]

    entries = []       # (g, term, cls_idx, members)
    spread_entries = []  # (g, constraint, cls_idx, members)
    for g, (terms, spreads) in enumerate(per_group):
        for t in terms:
            mem = _members(t.label_selector)
            others = [h for h in mem if h != g]
            if t.topology_key == ZONE_TOPOLOGY_KEY and not others:
                # legacy paths own the self-only / empty zone terms:
                # _has_zone_affinity pins, self zone-anti is a no-op
                continue
            if t.anti and not others:
                continue                      # anti matching nothing: no-op
            c = _cls(t.label_selector, t.topology_key)
            entries.append((g, t, c, mem))
        for s in spreads:
            mem = _members(s.label_selector)
            if not mem:
                continue                      # vacuous bound: no members
            c = _cls(s.label_selector, HOSTNAME_TOPOLOGY_KEY)
            spread_entries.append((g, s, c, mem))
    if not entries and not spread_entries:
        return None

    C_all = len(classes)
    member = np.zeros((C_all, G), dtype=bool)
    for (sel, _key), c in cls_of.items():
        for g in range(G):
            if _matches(sel, labels[g]):
                member[c, g] = True
    req_host = np.zeros((G, C_all), dtype=bool)
    anti_host = np.zeros((G, C_all), dtype=bool)
    req_zone = np.zeros((G, C_all), dtype=bool)
    anti_zone = np.zeros((G, C_all), dtype=bool)
    host_bound = np.full(C_all, AFF_BIG, dtype=np.int32)
    edge_count = 0
    uf = _UnionFind(G)
    for g, t, c, mem in entries:
        if t.topology_key == HOSTNAME_TOPOLOGY_KEY:
            (anti_host if t.anti else req_host)[g, c] = True
        else:
            (anti_zone if t.anti else req_zone)[g, c] = True
        for h in mem:
            if h != g:
                edge_count += 1
                uf.union(g, h)
    for g, s, c, mem in spread_entries:
        host_bound[c] = min(int(host_bound[c]), int(s.max_skew))
        for h in mem:
            uf.union(g, h)
            if mem:
                uf.union(mem[0], h)
    comp = np.array([uf.find(g) for g in range(G)], dtype=np.int32)

    # ---- required-edge depth ranks (targets pack first) --------------
    has_req = req_host.any(axis=1)
    depth = np.zeros(G, dtype=np.int32)
    if has_req.any():
        tgt = (req_host.astype(np.int32) @ member.astype(np.int32)) > 0
        np.fill_diagonal(tgt, False)
        for _ in range(min(G, _DEPTH_ITERS)):
            td = np.where(tgt, depth[None, :], -1).max(axis=1)
            new = np.where(has_req, np.minimum(td + 1, _DEPTH_ITERS),
                           0).astype(np.int32)
            if (new == depth).all():
                break
            depth = new

    # ---- device lane: hostname classes -> int32 bitmasks -------------
    host_cls = [c for c in range(C_all)
                if classes[c][1] == HOSTNAME_TOPOLOGY_KEY
                and (req_host[:, c].any() or anti_host[:, c].any()
                     or host_bound[c] < AFF_BIG)]
    device_armed = len(host_cls) <= MAX_SELECTOR_CLASSES
    if not device_armed:
        log.warning("affinity device lane disarmed: selector classes "
                    "exceed budget (choke-point enforcement only)",
                    classes=len(host_cls), budget=MAX_SELECTOR_CLASSES)
        host_cls = []
    bit_of = {c: i for i, c in enumerate(host_cls)}
    g_sel = np.zeros(G, dtype=np.int32)
    g_anti = np.zeros(G, dtype=np.int32)
    g_req = np.zeros(G, dtype=np.int32)
    bounds = np.full(C_PAD, AFF_BIG, dtype=np.int32)
    for c, i in bit_of.items():
        g_sel |= np.where(member[c], np.int32(1 << i), 0).astype(np.int32)
        g_req |= np.where(req_host[:, c], np.int32(1 << i), 0) \
            .astype(np.int32)
        g_anti |= np.where(anti_host[:, c], np.int32(1 << i), 0) \
            .astype(np.int32)
        bounds[i] = host_bound[c]

    # explain flags: a group can be dropped as a CARRIER of a term or
    # as a MEMBER another group's term targets — both get the bit
    any_aff_cls = np.zeros(C_all, dtype=bool)
    for g, _t, c, _mem in entries:
        any_aff_cls[c] = True
    aff_carrier = (req_host | anti_host | req_zone | anti_zone).any(axis=1)
    aff_member = member[any_aff_cls].any(axis=0) if any_aff_cls.any() \
        else np.zeros(G, dtype=bool)
    bounded_cls = host_bound < AFF_BIG
    spread_member = member[bounded_cls].any(axis=0) if bounded_cls.any() \
        else np.zeros(G, dtype=bool)
    return AffinityIndex(
        classes=tuple(classes), member=member,
        req_host=req_host, anti_host=anti_host,
        req_zone=req_zone, anti_zone=anti_zone,
        host_bound=host_bound, comp=comp, req_depth=depth,
        edge_count=edge_count, device_armed=device_armed,
        g_sel=g_sel, g_anti=g_anti, g_req=g_req,
        aff_flag=(aff_carrier | aff_member).astype(np.int32),
        spread_flag=spread_member.astype(np.int32),
        bounds=bounds,
    )


def pack_affinity(index: AffinityIndex, G_pad: int) -> np.ndarray:
    """The int32 suffix leaf the kernel consumes — O(G) class bitmasks
    plus the C_PAD bound row, zero-padded to the group bucket (padding
    groups carry empty masks and place nothing):

        [0,    G)       g_sel
        [G,    2G)      g_anti
        [2G,   3G)      g_req
        [3G,   4G)      aff_flag
        [4G,   5G)      spread_flag
        [5G,   5G+C_PAD) bounds   (AFF_BIG = unbounded)
    """
    G = index.num_groups
    buf = np.zeros(5 * G_pad + C_PAD, dtype=np.int32)
    for i, col in enumerate((index.g_sel, index.g_anti, index.g_req,
                             index.aff_flag, index.spread_flag)):
        buf[i * G_pad:i * G_pad + G] = col
    buf[5 * G_pad:] = index.bounds
    return buf


def unpack_affinity(buf: np.ndarray, G_pad: int):
    """Host-side inverse of :func:`pack_affinity` (tests, oracle)."""
    cols = [np.asarray(buf[i * G_pad:(i + 1) * G_pad]) for i in range(5)]
    return (*cols, np.asarray(buf[5 * G_pad:5 * G_pad + C_PAD]))


def zone_pin_prepass(entries) -> dict:
    """Co-pin required zone-scope components to one zone.

    ``entries``: list of ``(sig, labels_dict, terms, viable_zones)``
    per signature group, in deterministic encode order.  Returns
    ``{sig: zone}`` for every signature that must be pinned — required
    components land on the lexicographically-first zone viable for ALL
    members (an empty intersection leaves the component unpinned; the
    decode choke then drops carriers honestly), then anti-zone carriers
    greedily take their first viable zone not already pinned to a
    matching member (graph-coloring in entry order)."""
    n = len(entries)
    if n == 0:
        return {}

    def members_of(selector):
        return [j for j in range(n)
                if _matches(selector, entries[j][1])]

    uf = _UnionFind(n)
    any_req = False
    for i, (_sig, _labels, terms, _vz) in enumerate(entries):
        for t in terms:
            if t.topology_key != ZONE_TOPOLOGY_KEY or t.anti:
                continue
            for j in members_of(t.label_selector):
                if j != i:
                    any_req = True
                    uf.union(i, j)
    pins: dict = {}
    pin_by_idx: dict[int, str] = {}
    if any_req:
        comps: dict[int, list[int]] = {}
        for i in range(n):
            comps.setdefault(uf.find(i), []).append(i)
        for root in sorted(comps):
            idxs = comps[root]
            if len(idxs) < 2:
                continue
            common = set(entries[idxs[0]][3])
            for j in idxs[1:]:
                common &= set(entries[j][3])
            if not common:
                continue              # unpinnable: the choke is honest
            zone = sorted(common)[0]
            for j in idxs:
                pins[entries[j][0]] = zone
                pin_by_idx[j] = zone
    # anti-zone carriers: avoid every matching member's pinned zone
    for i, (sig, _labels, terms, vz) in enumerate(entries):
        taken = set()
        for t in terms:
            if t.topology_key != ZONE_TOPOLOGY_KEY or not t.anti:
                continue
            for j in members_of(t.label_selector):
                if j != i and j in pin_by_idx:
                    taken.add(pin_by_idx[j])
        if not taken:
            continue
        cur = pin_by_idx.get(i)
        if cur is not None and cur not in taken:
            continue
        free = [z for z in sorted(vz) if z not in taken]
        if free:
            pins[sig] = free[0]
            pin_by_idx[i] = free[0]
    return pins
