"""Host oracle for the affinity-gated scan — the parity twin.

Recomputes, with numpy on the host, exactly what
``affinity/kernel.solve_packed_affinity`` computes on device: node_off
/ assign / unplaced bit-identical, explain words bit-identical (base
words via the established ``explain/greedy`` oracle, the two affinity
bits via the same flag test), cost equal up to float-reduction order.

Bit-identity holds STRUCTURALLY: every affinity gate is exact int32
arithmetic in the identical order as the kernel — shared ``AFF_BIG``
sentinel, shared ``C_PAD`` class width, no float enters the affinity
terms at all.  Change one side, change both — docs/design/affinity.md
"parity contract".
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.affinity import AFF_BIG, C_PAD
from karpenter_tpu.solver.types import FIT_BIG as _BIG


def _fit_counts_np(resid: np.ndarray, req: np.ndarray) -> np.ndarray:
    per_dim = np.where(req[None, :] > 0,
                       resid // np.maximum(req[None, :], 1), _BIG)
    return per_dim.min(axis=1).astype(np.int32)


def spread_allowance_np(node_cnt: np.ndarray, member: np.ndarray,
                        bounds: np.ndarray) -> np.ndarray:
    """numpy mirror of kernel._spread_allowance."""
    live = (member[None, :] > 0) & (bounds[None, :] < AFF_BIG)
    room = np.where(live, bounds[None, :] - node_cnt, AFF_BIG)
    return room.min(axis=1).astype(np.int32)


def affinity_words_np(problem, unplaced) -> np.ndarray:
    """int32 [G] with only the two affinity reason bits — the host
    mirror of kernel._affinity_words, consumed by
    explain/greedy.reason_words for every affinity-armed problem."""
    from karpenter_tpu.explain import BIT

    aff = getattr(problem, "aff", None)
    G = problem.num_groups
    if aff is None or G == 0:
        return np.zeros(G, dtype=np.int32)
    count = np.asarray(problem.group_count, dtype=np.int64)
    un = np.asarray(unplaced, dtype=np.int64)
    live_un = (count > 0) & (un > 0)
    bits = np.where(live_un & (aff.aff_flag > 0),
                    np.int32(1 << BIT["affinity_unsatisfied"]),
                    np.int32(0))
    bits = bits | np.where(live_un & (aff.spread_flag > 0),
                           np.int32(1 << BIT["spread_bound"]),
                           np.int32(0))
    return bits.astype(np.int32)


def solve_affinity_host(problem, N: int, right_size: bool = True):
    """Run the affinity-gated FFD on the host.

    Returns ``(node_off [N], assign [G, N], unplaced [G], cost, words
    [G])`` — the first four bit-identical to the device kernel's packed
    result (cost up to reduction order), the words identical to the
    device's appended reason words.  ``problem`` is an EncodedProblem
    with the affinity index attached (``problem.aff``)."""
    G = problem.num_groups
    catalog = problem.catalog
    off_alloc = catalog.offering_alloc().astype(np.int32)
    off_price = catalog.off_price.astype(np.float32)
    off_rank = catalog.offering_rank_price().astype(np.float32)
    compat = np.ascontiguousarray(problem.compat, dtype=bool)
    req_g = problem.group_req.astype(np.int32)
    count_g = problem.group_count.astype(np.int32)
    cap_g = np.minimum(problem.group_cap,
                       np.iinfo(np.int32).max).astype(np.int32)
    aff = problem.aff
    g_sel = aff.g_sel
    g_anti = aff.g_anti
    g_req = aff.g_req
    bounds = aff.bounds

    R = off_alloc.shape[1]
    node_off = np.full(N, -1, dtype=np.int32)
    node_resid = np.zeros((N, R), dtype=np.int32)
    node_sel = np.zeros(N, dtype=np.int32)
    node_anti = np.zeros(N, dtype=np.int32)
    node_cnt = np.zeros((N, C_PAD), dtype=np.int32)
    ptr = 0
    assign = np.zeros((G, N), dtype=np.int32)
    unplaced = np.zeros(G, dtype=np.int32)

    for gi in range(G):
        req = req_g[gi]
        count = int(count_g[gi])
        cap = int(cap_g[gi])
        compat_g = compat[gi]
        sel, anti, reqm = int(g_sel[gi]), int(g_anti[gi]), int(g_req[gi])
        member = ((sel >> np.arange(C_PAD, dtype=np.int32)) & 1) \
            .astype(np.int32)

        is_open = node_off >= 0
        node_compat = np.where(is_open,
                               compat_g[np.clip(node_off, 0, None)], False)
        fit = _fit_counts_np(node_resid, req)
        fit = np.where(node_compat, fit, 0)
        fit = np.minimum(fit, cap)
        ok_anti = ((node_sel & anti) == 0) & ((node_anti & sel) == 0)
        ok_req = (reqm & ~node_sel) == 0
        fit = np.where(ok_anti & ok_req, fit, 0)
        allow = spread_allowance_np(node_cnt, member, bounds)
        fit = np.minimum(fit, np.clip(allow, 0, None))
        cumfit = np.cumsum(fit) - fit
        take = np.clip(count - cumfit, 0, fit).astype(np.int32)
        placed = int(take.sum())
        node_resid = node_resid - take[:, None] * req[None, :]
        node_cnt = node_cnt + take[:, None] * member[None, :]
        node_sel = np.where(take > 0, node_sel | sel,
                            node_sel).astype(np.int32)
        node_anti = np.where(take > 0, node_anti | anti,
                             node_anti).astype(np.int32)
        rem = count - placed

        can_open = (reqm & ~sel) == 0
        bound_new = int(np.min(np.where((member > 0) & (bounds < AFF_BIG),
                                        bounds, AFF_BIG)))
        fit_empty = _fit_counts_np(off_alloc, req)
        fit_empty = np.where(compat_g, fit_empty, 0)
        fit_empty = np.minimum(fit_empty, cap)
        fit_empty = np.minimum(fit_empty, rem)
        fit_empty = np.where(can_open, fit_empty, 0)
        fit_empty = np.minimum(fit_empty, bound_new)
        with np.errstate(divide="ignore", invalid="ignore"):
            cpp = np.where(fit_empty > 0,
                           off_rank / fit_empty.astype(np.float32), np.inf)
        best = int(np.argmin(cpp))
        bf = int(fit_empty[best])

        n_new = -(-rem // max(bf, 1)) if bf > 0 else 0
        n_new = min(n_new, N - ptr)
        new_pos = np.arange(N, dtype=np.int32) - ptr
        is_new = (new_pos >= 0) & (new_pos < n_new)
        pods_new = np.where(is_new, np.clip(rem - new_pos * bf, 0, bf),
                            0).astype(np.int32)
        opened = is_new & (pods_new > 0)
        node_off = np.where(opened, best, node_off).astype(np.int32)
        node_resid = np.where(opened[:, None],
                              off_alloc[best][None, :]
                              - pods_new[:, None] * req[None, :],
                              node_resid)
        node_cnt = np.where(opened[:, None],
                            pods_new[:, None] * member[None, :], node_cnt)
        node_sel = np.where(opened, sel, node_sel).astype(np.int32)
        node_anti = np.where(opened, anti, node_anti).astype(np.int32)
        ptr += int(opened.sum())
        unplaced[gi] = rem - int(pods_new.sum())
        assign[gi] = take + pods_new

    if right_size and G:
        load = off_alloc[np.clip(node_off, 0, None)] - node_resid
        node_off = _right_size_np(node_off, load, assign, compat,
                                  off_alloc, off_rank)
    is_open = node_off >= 0
    # cost word: excluded from bit-parity up to reduction order (see
    # docs/design/parity.md) — the one sanctioned float reduction
    cost = float(np.where(  # graftlint: disable=GL202 (cost word)
        is_open, off_price[np.clip(node_off, 0, None)],
        np.float32(0.0)).sum())
    from karpenter_tpu.explain.greedy import reason_words

    # reason_words already folds the two affinity bits for armed
    # problems (via affinity_words_np) — no second flag pass here
    words = reason_words(problem, unplaced)
    return node_off, assign, unplaced, cost, words


def _right_size_np(node_off, load, assign, compat, off_alloc, off_rank):
    """numpy mirror of kernel._right_size_affinity."""
    N = node_off.shape[0]
    is_open = node_off >= 0
    safe_off = np.clip(node_off, 0, None)
    present = (assign > 0).astype(np.float32)
    incompat = (~compat).astype(np.float32)
    incompat_count = np.einsum("gn,go->no", present, incompat)
    all_compat = incompat_count < 0.5
    fits = (off_alloc[None, :, :] >= load[:, None, :]).all(axis=2)
    candidate = all_compat & fits & is_open[:, None]
    rank_eff = np.broadcast_to(off_rank[None, :], (N, off_rank.shape[0]))
    cand_price = np.where(candidate, rank_eff, np.inf)
    best = cand_price.argmin(axis=1).astype(np.int32)
    best_price = cand_price.min(axis=1)
    cur_price = np.take_along_axis(rank_eff, safe_off[:, None],
                                   axis=1)[:, 0]
    improve = is_open & (best_price < cur_price - np.float32(1e-9))
    return np.where(improve, best, node_off).astype(np.int32)
