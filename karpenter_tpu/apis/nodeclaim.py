"""NodeClaim / Node / NodePool typed objects.

Capability parity with karpenter-core's NodeClaim lifecycle as driven by the
reference (pkg/cloudprovider/cloudprovider.go:420-494 builds NodeClaims with
labels from requirements + instance type; registration controller syncs
node<->claim, registration/controller.go:67).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from karpenter_tpu.apis.pod import Taint
from karpenter_tpu.apis.requirements import Requirements


@dataclass
class NodeClaim:
    name: str
    nodeclass_name: str = ""
    nodepool_name: str = ""
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = "on-demand"
    provider_id: str = ""            # "tpu:///<region>/<instance-id>" once launched
    node_name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    taints: tuple[Taint, ...] = ()
    startup_taints: tuple[Taint, ...] = ()
    requirements: Requirements = field(default_factory=Requirements)
    # lifecycle
    created_at: float = field(default_factory=time.time)
    # stamped once by the registration controller; anchors the
    # never-ready grace window (a node object recreated by re-adoption
    # must NOT reset it — interruption suppression keys on the claim)
    registered_at: float = 0.0
    registered: bool = False
    initialized: bool = False
    launched: bool = False
    deleted: bool = False
    finalizers: list[str] = field(default_factory=list)
    resource_version: int = 0
    uid: str = ""
    # resolved placement (written by the actuator from the solve plan)
    subnet_id: str = ""
    image_id: str = ""
    security_group_ids: tuple[str, ...] = ()
    hourly_price: float = 0.0


@dataclass
class Node:
    """A registered cluster node (the k8s Node analogue)."""

    name: str
    provider_id: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    ready: bool = False
    conditions: dict[str, str] = field(default_factory=dict)  # type -> status
    addresses: list[str] = field(default_factory=list)
    created_at: float = field(default_factory=time.time)
    deleted: bool = False
    resource_version: int = 0
    uid: str = ""


# epsilon ceiling: an overcommit bound at or above 0.5 means z(eps) <= 0
# — "pack with no variance buffer at all", which is never what a
# violation-probability bound is for; larger values clamp here
OVERCOMMIT_MAX = 0.45


def parse_overcommit(q) -> float:
    """Strict overcommit epsilon: number (or None) -> clamped float.

    None/0 -> 0.0 (stochastic plane off).  bools, strings, and
    non-finite floats are rejected — epsilon gates the solver's
    chance-constraint feasibility term, so a lenient parse would let a
    malformed pool spec silently drop the violation bound."""
    if q is None:
        return 0.0
    if isinstance(q, bool) or not isinstance(q, (int, float)):
        raise ValueError(f"bad overcommit {q!r}: must be a number")
    q = float(q)
    if q != q or q in (float("inf"), float("-inf")):
        raise ValueError(f"bad overcommit {q!r}: must be finite")
    return max(0.0, min(OVERCOMMIT_MAX, q))


@dataclass
class NodePool:
    """Provisioning pool: requirements + nodeclass ref + disruption policy
    (karpenter-core NodePool analogue; the reference consumes these through
    GetInstanceTypes per-NodePool filtering, cloudprovider.go:553)."""

    name: str
    nodeclass_name: str = ""
    requirements: Requirements = field(default_factory=Requirements)
    taints: tuple[Taint, ...] = ()
    startup_taints: tuple[Taint, ...] = ()
    labels: dict[str, str] = field(default_factory=dict)
    weight: int = 10
    cpu_limit_milli: int = 0         # 0 = unlimited
    memory_limit_mib: int = 0
    consolidation_policy: str = "WhenEmptyOrUnderutilized"
    consolidate_after_seconds: float = 30.0
    # priority-preemption disruption budget: max pod evictions the
    # PreemptionController may execute against this pool's nodes per
    # reconcile round (karpenter's spec.disruption.budgets analogue).
    # 0 disables preemption for the pool; -1 = unbounded.
    preemption_budget: int = 16
    # chance-constrained overcommit (karpenter_tpu/stochastic): the
    # per-node violation-probability bound epsilon.  0 disables the
    # stochastic plane for this pool (every solve stays deterministic —
    # strict superset); with epsilon > 0, pods carrying a usage
    # distribution pack by mean + z(epsilon)*sqrt(sum variance) instead
    # of by request.  Validated at construction: non-numbers REJECT
    # (a typo'd manifest must not silently disable the violation
    # bound), out-of-range values CLAMP into [0, OVERCOMMIT_MAX].
    overcommit: float = 0.0
    resource_version: int = 0

    def __post_init__(self):
        self.overcommit = parse_overcommit(self.overcommit)


def provider_id(region: str, instance_id: str) -> str:
    """(ref builds 'ibm:///<region>/<id>', vpc/instance/provider.go:841-880)"""
    return f"tpu:///{region}/{instance_id}"


def parse_provider_id(pid: str) -> tuple[str, str] | None:
    """-> (region, instance_id) or None (ref extractInstanceIDFromProviderID,
    vpc/instance/provider.go:1176)."""
    if not pid or not pid.startswith("tpu:///"):
        return None
    rest = pid[len("tpu:///"):]
    parts = rest.split("/", 1)
    if len(parts) != 2 or not parts[0] or not parts[1]:
        return None
    return parts[0], parts[1]
