"""NodeClass: the user-facing provisioning config object.

Capability parity with the reference's ``IBMNodeClass`` CRD
(pkg/apis/v1alpha1/ibmnodeclass_types.go): spec fields, the CEL cross-field
validation rules (:481-488), and the resolved status surface (:663-726).
Validation here is plain Python (``validate()``) instead of CEL, enforced by
the nodeclass status controller and at admission by the fake kube store.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field, asdict
from typing import Dict, List, Optional, Tuple


class ValidationError(ValueError):
    pass


# --- sub-specs -------------------------------------------------------------

@dataclass(frozen=True)
class InstanceRequirements:
    """Automatic instance-type selection criteria
    (ibmnodeclass_types.go:250-284)."""

    architecture: str = ""          # amd64 | arm64 | s390x
    min_cpu: int = 0                # cores
    min_memory_gib: int = 0
    max_hourly_price: float = 0.0   # 0 = no ceiling
    gpu: bool = False


@dataclass(frozen=True)
class SubnetSelectionCriteria:
    minimum_available_ips: int = 0
    required_tags: Tuple[Tuple[str, str], ...] = ()


@dataclass(frozen=True)
class PlacementStrategy:
    """Zone/subnet placement strategy (ibmnodeclass_types.go:41-82)."""

    zone_balance: str = "Balanced"  # Balanced | AvailabilityFirst | CostOptimized
    subnet_selection: SubnetSelectionCriteria = SubnetSelectionCriteria()


@dataclass(frozen=True)
class ImageSelector:
    """Semantic image selection os/major/minor/arch/variant
    (ibmnodeclass_types.go:441-479)."""

    os: str = "ubuntu"
    major_version: str = ""
    minor_version: str = ""
    architecture: str = "amd64"
    variant: str = ""


@dataclass(frozen=True)
class VolumeSpec:
    """(ibmnodeclass_types.go:302-436)"""

    capacity_gb: int = 100
    profile: str = "general-purpose"
    iops: int = 0
    bandwidth: int = 0
    encryption_key: str = ""
    delete_on_termination: bool = True


@dataclass(frozen=True)
class BlockDeviceMapping:
    device_name: str = ""
    root_volume: bool = False
    volume: VolumeSpec = VolumeSpec()


@dataclass(frozen=True)
class KubeletConfig:
    """Subset mirrored from ibmnodeclass_types.go:318-387."""

    max_pods: int = 0               # 0 = provider heuristic
    system_reserved: Tuple[Tuple[str, str], ...] = ()
    kube_reserved: Tuple[Tuple[str, str], ...] = ()
    eviction_hard: Tuple[Tuple[str, str], ...] = ()
    cluster_dns: Tuple[str, ...] = ()


@dataclass(frozen=True)
class HealthCheck:
    protocol: str = "tcp"
    port: int = 0
    interval: int = 5
    timeout: int = 2
    retries: int = 2


@dataclass(frozen=True)
class LoadBalancerTarget:
    load_balancer_id: str = ""
    pool_name: str = ""
    port: int = 0
    weight: int = 50
    health_check: Optional[HealthCheck] = None


@dataclass(frozen=True)
class LoadBalancerIntegration:
    """(ibmnodeclass_types.go:146-244)"""

    enabled: bool = False
    target_groups: Tuple[LoadBalancerTarget, ...] = ()
    auto_deregister: bool = True
    registration_timeout: int = 300


@dataclass(frozen=True)
class DynamicPoolConfig:
    """IKS dynamic worker-pool config (ibmnodeclass_types.go:84-144)."""

    enabled: bool = False
    pool_name_prefix: str = "karpenter"
    empty_pool_ttl_seconds: int = 600
    cleanup_policy: str = "Delete"  # Delete | Retain


# --- spec / status ---------------------------------------------------------

@dataclass
class NodeClassSpec:
    region: str = ""
    zone: str = ""
    instance_profile: str = ""
    instance_requirements: Optional[InstanceRequirements] = None
    image: str = ""
    image_selector: Optional[ImageSelector] = None
    vpc: str = ""
    subnet: str = ""
    security_groups: Tuple[str, ...] = ()
    ssh_keys: Tuple[str, ...] = ()
    resource_group: str = ""
    placement_target: str = ""
    tags: Tuple[Tuple[str, str], ...] = ()
    placement_strategy: Optional[PlacementStrategy] = None
    user_data: str = ""
    user_data_append: str = ""
    bootstrap_mode: str = "auto"    # auto | cloud-init | iks-api
    iks_cluster_id: str = ""
    iks_worker_pool_id: str = ""
    iks_dynamic_pools: Optional[DynamicPoolConfig] = None
    load_balancer_integration: Optional[LoadBalancerIntegration] = None
    block_device_mappings: Tuple[BlockDeviceMapping, ...] = ()
    kubelet: Optional[KubeletConfig] = None
    api_server_endpoint: str = ""


@dataclass(frozen=True)
class Condition:
    type: str
    status: str                      # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition: float = 0.0


@dataclass
class NodeClassStatus:
    """(ibmnodeclass_types.go:663-726)"""

    last_validation_time: float = 0.0
    validation_error: str = ""
    selected_instance_types: List[str] = field(default_factory=list)
    selected_subnets: List[str] = field(default_factory=list)
    resolved_security_groups: List[str] = field(default_factory=list)
    resolved_image_id: str = ""
    conditions: List[Condition] = field(default_factory=list)

    def set_condition(self, type_: str, status: str, reason: str = "",
                      message: str = "", now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        for i, c in enumerate(self.conditions):
            if c.type == type_:
                if c.status == status and c.reason == reason and c.message == message:
                    return
                # Keep last_transition when only reason/message change.
                transition = now if c.status != status else c.last_transition
                self.conditions[i] = Condition(type_, status, reason, message, transition)
                return
        self.conditions.append(Condition(type_, status, reason, message, now))

    def condition(self, type_: str) -> Optional[Condition]:
        for c in self.conditions:
            if c.type == type_:
                return c
        return None

    def is_ready(self) -> bool:
        c = self.condition("Ready")
        return c is not None and c.status == "True"


@dataclass
class NodeClass:
    name: str
    spec: NodeClassSpec = field(default_factory=NodeClassSpec)
    status: NodeClassStatus = field(default_factory=NodeClassStatus)
    annotations: Dict[str, str] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    deleted: bool = False            # deletionTimestamp analogue
    resource_version: int = 0
    uid: str = ""

    # -- hash for drift (ref hash/controller.go:62-84, hashstructure/v2) ---

    def spec_hash(self) -> str:
        """Deterministic hash of the spec for drift detection."""
        def default(o):
            if hasattr(o, "__dataclass_fields__"):
                return asdict(o)
            return str(o)
        payload = json.dumps(asdict(self.spec), sort_keys=True, default=default)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    # -- CEL-equivalent cross-field validation (ibmnodeclass_types.go:481-488)

    def validate(self) -> List[str]:
        """Returns a list of violations (empty = valid)."""
        s = self.spec
        errs: List[str] = []
        if not s.region:
            errs.append("spec.region is required")
        if bool(s.instance_profile) == bool(s.instance_requirements):
            errs.append("exactly one of spec.instanceProfile or "
                        "spec.instanceRequirements must be set")
        if s.image and s.image_selector:
            errs.append("spec.image and spec.imageSelector are mutually exclusive")
        if not s.image and not s.image_selector:
            errs.append("one of spec.image or spec.imageSelector must be set")
        if s.bootstrap_mode not in ("auto", "cloud-init", "iks-api"):
            errs.append(f"spec.bootstrapMode invalid: {s.bootstrap_mode!r}")
        if s.bootstrap_mode == "iks-api" and not s.iks_cluster_id:
            errs.append("spec.bootstrapMode=iks-api requires spec.iksClusterID")
        if s.zone and s.region and not s.zone.startswith(s.region):
            errs.append(f"spec.zone {s.zone!r} not in region {s.region!r}")
        if s.subnet and not s.subnet.startswith("subnet-") and not s.subnet.startswith("0"):
            errs.append(f"spec.subnet {s.subnet!r} is not a subnet id")
        if s.placement_strategy and s.placement_strategy.zone_balance not in (
                "Balanced", "AvailabilityFirst", "CostOptimized"):
            errs.append("spec.placementStrategy.zoneBalance invalid")
        root_vols = [b for b in s.block_device_mappings if b.root_volume]
        if len(root_vols) > 1:
            errs.append("at most one blockDeviceMapping may be rootVolume")
        return errs


# Annotation keys (ref pkg/apis/v1alpha1/annotations.go:17-36).
ANNOTATION_NODECLASS_HASH = "karpenter-tpu.sh/nodeclass-hash"
ANNOTATION_NODECLASS_HASH_VERSION = "karpenter-tpu.sh/nodeclass-hash-version"
ANNOTATION_SUBNET = "karpenter-tpu.sh/subnet-id"
ANNOTATION_SECURITY_GROUPS = "karpenter-tpu.sh/security-groups"
ANNOTATION_IMAGE = "karpenter-tpu.sh/image-id"
NODECLASS_HASH_VERSION = "v1"
