"""NodeClass: the user-facing provisioning config object.

Capability parity with the reference's ``IBMNodeClass`` CRD
(pkg/apis/v1alpha1/ibmnodeclass_types.go): spec fields, the CEL cross-field
validation rules (:481-488), and the resolved status surface (:663-726).
Validation here is plain Python (``validate()``) instead of CEL, enforced by
the nodeclass status controller and at admission by the fake kube store.
"""

from __future__ import annotations

import hashlib
import json
import re
import time
from dataclasses import dataclass, field, asdict

# cloud resource id shape: alphanumerics plus - _ . (loose enough for
# every provider id style, strict enough to catch whitespace/injection)
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


class ValidationError(ValueError):
    pass


# --- sub-specs -------------------------------------------------------------

@dataclass(frozen=True)
class InstanceRequirements:
    """Automatic instance-type selection criteria
    (ibmnodeclass_types.go:250-284)."""

    architecture: str = ""          # amd64 | arm64 | s390x
    min_cpu: int = 0                # cores
    min_memory_gib: int = 0
    max_hourly_price: float = 0.0   # 0 = no ceiling
    gpu: bool = False


@dataclass(frozen=True)
class SubnetSelectionCriteria:
    minimum_available_ips: int = 0
    required_tags: tuple[tuple[str, str], ...] = ()


@dataclass(frozen=True)
class PlacementStrategy:
    """Zone/subnet placement strategy (ibmnodeclass_types.go:41-82)."""

    zone_balance: str = "Balanced"  # Balanced | AvailabilityFirst | CostOptimized
    subnet_selection: SubnetSelectionCriteria = SubnetSelectionCriteria()


@dataclass(frozen=True)
class ImageSelector:
    """Semantic image selection os/major/minor/arch/variant
    (ibmnodeclass_types.go:441-479)."""

    os: str = "ubuntu"
    major_version: str = ""
    minor_version: str = ""
    architecture: str = "amd64"
    variant: str = ""


@dataclass(frozen=True)
class VolumeSpec:
    """(ibmnodeclass_types.go:302-436)"""

    capacity_gb: int = 100
    profile: str = "general-purpose"
    iops: int = 0
    bandwidth: int = 0
    encryption_key: str = ""
    delete_on_termination: bool = True


@dataclass(frozen=True)
class BlockDeviceMapping:
    device_name: str = ""
    root_volume: bool = False
    volume: VolumeSpec = VolumeSpec()


@dataclass(frozen=True)
class KubeletConfig:
    """Subset mirrored from ibmnodeclass_types.go:318-387."""

    max_pods: int = 0               # 0 = provider heuristic
    system_reserved: tuple[tuple[str, str], ...] = ()
    kube_reserved: tuple[tuple[str, str], ...] = ()
    eviction_hard: tuple[tuple[str, str], ...] = ()
    cluster_dns: tuple[str, ...] = ()


@dataclass(frozen=True)
class HealthCheck:
    protocol: str = "tcp"
    port: int = 0
    interval: int = 5
    timeout: int = 2
    retries: int = 2
    path: str = ""          # required for http/https (healthcheck.go:161)


@dataclass(frozen=True)
class LoadBalancerTarget:
    load_balancer_id: str = ""
    pool_name: str = ""
    port: int = 0
    weight: int = 50
    health_check: HealthCheck | None = None


@dataclass(frozen=True)
class LoadBalancerIntegration:
    """(ibmnodeclass_types.go:146-244)"""

    enabled: bool = False
    target_groups: tuple[LoadBalancerTarget, ...] = ()
    auto_deregister: bool = True
    registration_timeout: int = 300


@dataclass(frozen=True)
class DynamicPoolConfig:
    """IKS dynamic worker-pool config (ibmnodeclass_types.go:84-144)."""

    enabled: bool = False
    pool_name_prefix: str = "karpenter"
    empty_pool_ttl_seconds: int = 600
    cleanup_policy: str = "Delete"  # Delete | Retain


# --- spec / status ---------------------------------------------------------

@dataclass
class NodeClassSpec:
    region: str = ""
    zone: str = ""
    instance_profile: str = ""
    instance_requirements: InstanceRequirements | None = None
    image: str = ""
    image_selector: ImageSelector | None = None
    vpc: str = ""
    subnet: str = ""
    security_groups: tuple[str, ...] = ()
    ssh_keys: tuple[str, ...] = ()
    resource_group: str = ""
    placement_target: str = ""
    tags: tuple[tuple[str, str], ...] = ()
    placement_strategy: PlacementStrategy | None = None
    user_data: str = ""
    user_data_append: str = ""
    bootstrap_mode: str = "auto"    # auto | cloud-init | iks-api
    iks_cluster_id: str = ""
    iks_worker_pool_id: str = ""
    iks_dynamic_pools: DynamicPoolConfig | None = None
    load_balancer_integration: LoadBalancerIntegration | None = None
    block_device_mappings: tuple[BlockDeviceMapping, ...] = ()
    kubelet: KubeletConfig | None = None
    api_server_endpoint: str = ""


@dataclass(frozen=True)
class Condition:
    type: str
    status: str                      # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition: float = 0.0


@dataclass
class NodeClassStatus:
    """(ibmnodeclass_types.go:663-726)"""

    last_validation_time: float = 0.0
    validation_error: str = ""
    selected_instance_types: list[str] = field(default_factory=list)
    selected_subnets: list[str] = field(default_factory=list)
    resolved_security_groups: list[str] = field(default_factory=list)
    resolved_image_id: str = ""
    conditions: list[Condition] = field(default_factory=list)

    def set_condition(self, type_: str, status: str, reason: str = "",
                      message: str = "", now: float | None = None) -> None:
        now = time.time() if now is None else now
        for i, c in enumerate(self.conditions):
            if c.type == type_:
                if c.status == status and c.reason == reason and c.message == message:
                    return
                # Keep last_transition when only reason/message change.
                transition = now if c.status != status else c.last_transition
                self.conditions[i] = Condition(type_, status, reason, message, transition)
                return
        self.conditions.append(Condition(type_, status, reason, message, now))

    def condition(self, type_: str) -> Condition | None:
        for c in self.conditions:
            if c.type == type_:
                return c
        return None

    def is_ready(self) -> bool:
        c = self.condition("Ready")
        return c is not None and c.status == "True"


@dataclass
class NodeClass:
    name: str
    spec: NodeClassSpec = field(default_factory=NodeClassSpec)
    status: NodeClassStatus = field(default_factory=NodeClassStatus)
    annotations: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    deleted: bool = False            # deletionTimestamp analogue
    resource_version: int = 0
    uid: str = ""

    # -- hash for drift (ref hash/controller.go:62-84, hashstructure/v2) ---

    def spec_hash(self) -> str:
        """Deterministic hash of the spec for drift detection."""
        def default(o):
            if hasattr(o, "__dataclass_fields__"):
                return asdict(o)
            return str(o)
        payload = json.dumps(asdict(self.spec), sort_keys=True, default=default)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    # -- CEL-equivalent cross-field validation (ibmnodeclass_types.go:481-488)

    def validate(self) -> list[str]:
        """Returns a list of violations (empty = valid)."""
        s = self.spec
        errs: list[str] = []
        if not s.region:
            errs.append("spec.region is required")
        if bool(s.instance_profile) == bool(s.instance_requirements):
            errs.append("exactly one of spec.instanceProfile or "
                        "spec.instanceRequirements must be set")
        if s.image and s.image_selector:
            errs.append("spec.image and spec.imageSelector are mutually exclusive")
        if not s.image and not s.image_selector:
            errs.append("one of spec.image or spec.imageSelector must be set")
        if s.bootstrap_mode not in ("auto", "cloud-init", "iks-api"):
            errs.append(f"spec.bootstrapMode invalid: {s.bootstrap_mode!r}")
        if s.bootstrap_mode == "iks-api" and not s.iks_cluster_id:
            errs.append("spec.bootstrapMode=iks-api requires spec.iksClusterID")
        if s.zone and s.region and not s.zone.startswith(s.region):
            errs.append(f"spec.zone {s.zone!r} not in region {s.region!r}")
        if s.subnet and not s.subnet.startswith("subnet-") and not s.subnet.startswith("0"):
            errs.append(f"spec.subnet {s.subnet!r} is not a subnet id")
        # format checks (ref status/controller.go:222 format validation)
        for sg in s.security_groups:
            if not sg or not _ID_RE.match(sg):
                errs.append(f"spec.securityGroups entry {sg!r} is not a "
                            "security group id")
        for key in s.ssh_keys:
            if not key or not _ID_RE.match(key):
                errs.append(f"spec.sshKeys entry {key!r} is not a key id")
        if s.vpc and not _ID_RE.match(s.vpc):
            errs.append(f"spec.vpc {s.vpc!r} is not a VPC id")
        if s.instance_requirements is not None:
            r = s.instance_requirements
            if r.architecture and r.architecture not in ("amd64", "arm64",
                                                         "s390x"):
                errs.append("spec.instanceRequirements.architecture invalid")
            if r.min_cpu < 0 or r.min_memory_gib < 0 or r.max_hourly_price < 0:
                errs.append("spec.instanceRequirements values must be >= 0")
        if s.placement_strategy:
            p = s.placement_strategy
            if p.zone_balance not in ("Balanced", "AvailabilityFirst",
                                      "CostOptimized"):
                errs.append("spec.placementStrategy.zoneBalance invalid")
            if p.subnet_selection.minimum_available_ips < 0:
                errs.append("spec.placementStrategy.subnetSelection."
                            "minimumAvailableIPs must be >= 0")
        if s.kubelet is not None:
            if s.kubelet.max_pods < 0 or s.kubelet.max_pods > 1000:
                errs.append("spec.kubelet.maxPods must be in [0, 1000]")
        root_vols = [b for b in s.block_device_mappings if b.root_volume]
        if len(root_vols) > 1:
            errs.append("at most one blockDeviceMapping may be rootVolume")
        for b in s.block_device_mappings:
            if b.volume.capacity_gb < 10 or b.volume.capacity_gb > 16000:
                errs.append(f"blockDeviceMapping volume capacity "
                            f"{b.volume.capacity_gb}GB out of range [10, 16000]")
        if s.load_balancer_integration and s.load_balancer_integration.enabled:
            for tg in s.load_balancer_integration.target_groups:
                if not tg.load_balancer_id:
                    errs.append("loadBalancerIntegration targetGroups entries "
                                "require loadBalancerID")
                if not (0 < tg.port < 65536):
                    errs.append(f"loadBalancer target port {tg.port} invalid")
        return errs


# Annotation keys (ref pkg/apis/v1alpha1/annotations.go:17-36).
ANNOTATION_NODECLASS_HASH = "karpenter-tpu.sh/nodeclass-hash"
ANNOTATION_NODECLASS_HASH_VERSION = "karpenter-tpu.sh/nodeclass-hash-version"
ANNOTATION_SUBNET = "karpenter-tpu.sh/subnet-id"
ANNOTATION_SECURITY_GROUPS = "karpenter-tpu.sh/security-groups"
ANNOTATION_IMAGE = "karpenter-tpu.sh/image-id"
NODECLASS_HASH_VERSION = "v1"


# --- JSON (CRD-shaped) parsing ---------------------------------------------
# Admission requests arrive as the CRD's camelCase JSON (the shape
# deploy/crds/tpunodeclass.yaml declares); this is the webhook-side
# deserializer (ref ibmnodeclass_webhook.go decodes the same way via
# apimachinery).

def _pairs(d: dict | None) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in (d or {}).items()))


def _obj(d, allowed: tuple[str, ...], ctx: str) -> dict | None:
    """Validate a nested object: must be a dict (or None) and use only
    known keys — a misspelled nested field (minCpu for minCPU) silently
    defaulting would admit specs the controller then ignores."""
    if d is None:
        return None
    if not isinstance(d, dict):
        raise ValidationError(f"spec.{ctx} must be an object, "
                              f"got {type(d).__name__}")
    unknown = set(d) - set(allowed)
    if unknown:
        raise ValidationError(
            f"unknown fields in spec.{ctx}: {sorted(unknown)}")
    return d


def nodeclass_from_dict(doc: dict) -> "NodeClass":
    """Parse a CRD-shaped dict (metadata + camelCase spec) into a
    NodeClass.  Unknown fields — top-level OR nested — raise
    ValidationError: an admission webhook that silently drops fields
    would accept specs the controller then ignores."""
    meta = doc.get("metadata") or {}
    spec = dict(doc.get("spec") or {})
    if not isinstance(meta, dict):
        raise ValidationError("metadata must be an object")

    def take(key, default=None):
        return spec.pop(key, default)

    ir = _obj(take("instanceRequirements"),
              ("architecture", "minCPU", "minMemoryGiB", "minMemory",
               "maxHourlyPrice", "gpu"), "instanceRequirements")
    sel = _obj(take("imageSelector"),
               ("os", "majorVersion", "minorVersion", "architecture",
                "variant"), "imageSelector")
    ps = _obj(take("placementStrategy"),
              ("zoneBalance", "subnetSelection"), "placementStrategy")
    if ps is not None:
        _obj(ps.get("subnetSelection"),
             ("minimumAvailableIPs", "requiredTags"),
             "placementStrategy.subnetSelection")
    dyn = _obj(take("iksDynamicPools"),
               ("enabled", "poolNamePrefix", "emptyPoolTTLSeconds",
                "cleanupPolicy"), "iksDynamicPools")
    lbi = _obj(take("loadBalancerIntegration"),
               ("enabled", "targetGroups", "autoDeregister",
                "registrationTimeout"), "loadBalancerIntegration")
    if lbi is not None:
        for i, tg in enumerate(lbi.get("targetGroups") or ()):
            _obj(tg, ("loadBalancerID", "poolName", "port", "weight",
                      "healthCheck"), f"loadBalancerIntegration."
                                      f"targetGroups[{i}]")
            _obj(tg.get("healthCheck"),
                 ("protocol", "port", "path", "interval", "timeout",
                  "retries", "intervalSeconds", "timeoutSeconds",
                  "maxRetries"),
                 f"loadBalancerIntegration.targetGroups[{i}].healthCheck")
    bdms = take("blockDeviceMappings") or []
    for i, b in enumerate(bdms):
        _obj(b, ("deviceName", "rootVolume", "volume"),
             f"blockDeviceMappings[{i}]")
        _obj(b.get("volume"),
             ("capacityGB", "profile", "iops", "bandwidth",
              "encryptionKey", "deleteOnTermination"),
             f"blockDeviceMappings[{i}].volume")
    kubelet = _obj(take("kubelet"),
                   ("maxPods", "systemReserved", "kubeReserved",
                    "evictionHard", "clusterDNS"), "kubelet")

    parsed = NodeClassSpec(
        region=take("region", ""),
        zone=take("zone", ""),
        instance_profile=take("instanceProfile", ""),
        instance_requirements=InstanceRequirements(
            architecture=ir.get("architecture", ""),
            min_cpu=int(ir.get("minCPU", 0)),
            min_memory_gib=int(ir.get("minMemoryGiB", ir.get("minMemory", 0))),
            max_hourly_price=float(ir.get("maxHourlyPrice", 0.0)),
            gpu=bool(ir.get("gpu", False))) if ir is not None else None,
        image=take("image", ""),
        image_selector=ImageSelector(
            os=sel.get("os", "ubuntu"),
            major_version=str(sel.get("majorVersion", "")),
            minor_version=str(sel.get("minorVersion", "")),
            architecture=sel.get("architecture", "amd64"),
            variant=sel.get("variant", "")) if sel is not None else None,
        vpc=take("vpc", ""),
        subnet=take("subnet", ""),
        security_groups=tuple(take("securityGroups") or ()),
        ssh_keys=tuple(take("sshKeys") or ()),
        resource_group=take("resourceGroup", ""),
        placement_target=take("placementTarget", ""),
        tags=_pairs(take("tags")),
        placement_strategy=PlacementStrategy(
            zone_balance=ps.get("zoneBalance", "Balanced"),
            subnet_selection=SubnetSelectionCriteria(
                minimum_available_ips=int(
                    (ps.get("subnetSelection") or {})
                    .get("minimumAvailableIPs", 0)),
                required_tags=_pairs(
                    (ps.get("subnetSelection") or {}).get("requiredTags"))))
        if ps is not None else None,
        user_data=take("userData", ""),
        user_data_append=take("userDataAppend", ""),
        bootstrap_mode=take("bootstrapMode", "auto"),
        iks_cluster_id=take("iksClusterID", ""),
        iks_worker_pool_id=take("iksWorkerPoolID", ""),
        iks_dynamic_pools=DynamicPoolConfig(
            enabled=bool(dyn.get("enabled", False)),
            pool_name_prefix=dyn.get("poolNamePrefix", "karpenter"),
            empty_pool_ttl_seconds=int(dyn.get("emptyPoolTTLSeconds", 600)),
            cleanup_policy=dyn.get("cleanupPolicy", "Delete"))
        if dyn is not None else None,
        load_balancer_integration=LoadBalancerIntegration(
            enabled=bool(lbi.get("enabled", False)),
            target_groups=tuple(
                LoadBalancerTarget(
                    load_balancer_id=tg.get("loadBalancerID", ""),
                    pool_name=tg.get("poolName", ""),
                    port=int(tg.get("port", 0)),
                    weight=int(tg.get("weight", 50)),
                    # the CRD names the timings intervalSeconds/
                    # timeoutSeconds/maxRetries; the short forms are kept
                    # for programmatic callers
                    health_check=HealthCheck(
                        protocol=tg["healthCheck"].get("protocol", "tcp"),
                        port=int(tg["healthCheck"].get("port", 0)),
                        interval=int(tg["healthCheck"].get(
                            "intervalSeconds",
                            tg["healthCheck"].get("interval", 5))),
                        timeout=int(tg["healthCheck"].get(
                            "timeoutSeconds",
                            tg["healthCheck"].get("timeout", 2))),
                        retries=int(tg["healthCheck"].get(
                            "maxRetries",
                            tg["healthCheck"].get("retries", 2))),
                        path=tg["healthCheck"].get("path", ""))
                    if tg.get("healthCheck") else None)
                for tg in (lbi.get("targetGroups") or ())),
            auto_deregister=bool(lbi.get("autoDeregister", True)),
            registration_timeout=int(lbi.get("registrationTimeout", 300)))
        if lbi is not None else None,
        block_device_mappings=tuple(
            BlockDeviceMapping(
                device_name=b.get("deviceName", ""),
                root_volume=bool(b.get("rootVolume", False)),
                volume=VolumeSpec(
                    capacity_gb=int((b.get("volume") or {})
                                    .get("capacityGB", 100)),
                    profile=(b.get("volume") or {})
                    .get("profile", "general-purpose"),
                    iops=int((b.get("volume") or {}).get("iops", 0)),
                    bandwidth=int((b.get("volume") or {})
                                  .get("bandwidth", 0)),
                    encryption_key=(b.get("volume") or {})
                    .get("encryptionKey", ""),
                    delete_on_termination=bool(
                        (b.get("volume") or {})
                        .get("deleteOnTermination", True))))
            for b in bdms),
        kubelet=KubeletConfig(
            max_pods=int(kubelet.get("maxPods", 0)),
            system_reserved=_pairs(kubelet.get("systemReserved")),
            kube_reserved=_pairs(kubelet.get("kubeReserved")),
            eviction_hard=_pairs(kubelet.get("evictionHard")),
            cluster_dns=tuple(kubelet.get("clusterDNS") or ()))
        if kubelet is not None else None,
        api_server_endpoint=take("apiServerEndpoint", ""),
    )
    if spec:
        raise ValidationError(f"unknown spec fields: {sorted(spec)}")
    name = meta.get("name") or doc.get("name") or ""
    if not name:
        raise ValidationError("metadata.name is required")
    return NodeClass(name=name, spec=parsed,
                     annotations=dict(meta.get("annotations") or {}),
                     labels=dict(meta.get("labels") or {}))
