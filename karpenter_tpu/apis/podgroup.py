"""Gang-scheduling API: the PodGroup a pending pod may belong to.

The workloads a TPU provisioner actually serves are multi-host
pjit/pallas jobs: N replicas that are useless unless *all* of them land,
and land on a *contiguous slice* of the right torus shape.  A
:class:`PodGroup` is the demand-side declaration of that contract
(the k8s coscheduling PodGroup / JobSet analogue):

- ``name``        — the group key; every member pod carries the same one;
- ``min_member``  — admission threshold: the gang enters the provision
                    queue only once this many members are pending
                    (controllers/gang.py parks it until then);
- ``slice_shape`` — optional torus sub-slice the gang needs, parsed from
                    ``"4x4"`` / ``"2x2x2"`` strings (gang/topology.py
                    lowers it to placement bitmasks over the catalog's
                    per-type tori);
- ``deadline_seconds`` — how long a sub-``min_member`` gang may sit
                    parked before the controller releases its members
                    as ordinary per-pod work (degraded fallback).

Validation is strict and happens in ``__post_init__`` — a malformed
group spec must never silently become "no gang" (the member pods would
place per-pod and the job would deadlock at runtime instead of at
admission).  The tuple from :meth:`PodGroup.signature` folds into the
pod constraint signature exactly like ``priority`` did, so gang members
never share an encode group with non-members.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

# torus sub-slices are at most 3-D (TPU pod slices are 2-D/3-D tori) and
# every axis is a small positive int.  64 chips is the largest torus the
# topology layer's single-word chip bitmasks represent (gang/topology.py)
# — a shape that cannot be represented must be rejected at admission,
# never become a silently-unplaceable-forever gang.
MAX_SLICE_DIMS = 3
MAX_SLICE_CHIPS = 64

_SLICE_RE = re.compile(r"^[0-9]+(x[0-9]+){0,%d}$" % (MAX_SLICE_DIMS - 1))


def parse_slice_shape(q) -> tuple[int, ...] | None:
    """``"4x4"`` -> ``(4, 4)``; ``None``/``""`` -> ``None``.

    Accepts a string, a tuple/list of ints, or None.  Anything else —
    zero axes, non-positive axes, more than :data:`MAX_SLICE_DIMS`
    dims — hard-rejects: the shape feeds straight into the topology
    layer's bitmask enumeration, and a lenient parse would turn a typo'd
    manifest into an unplaceable-forever gang with no admission error.
    """
    if q is None or q == "":
        return None
    if isinstance(q, str):
        s = q.strip().lower()
        if not _SLICE_RE.match(s):
            raise ValueError(f"bad slice shape {q!r}: want 'AxB' / 'AxBxC'")
        dims = tuple(int(d) for d in s.split("x"))
    elif isinstance(q, (tuple, list)):
        dims = tuple(q)
    else:
        raise ValueError(f"bad slice shape {q!r}: must be str or tuple")
    if not dims or len(dims) > MAX_SLICE_DIMS:
        raise ValueError(f"bad slice shape {q!r}: 1..{MAX_SLICE_DIMS} dims")
    for d in dims:
        if isinstance(d, bool) or not isinstance(d, int) or d < 1:
            raise ValueError(f"bad slice shape {q!r}: axes must be ints >= 1")
    if math.prod(dims) > MAX_SLICE_CHIPS:
        raise ValueError(f"bad slice shape {q!r}: > {MAX_SLICE_CHIPS} chips")
    return dims


@dataclass(frozen=True)
class PodGroup:
    """One gang's contract: group key + admission + topology demand."""

    name: str
    min_member: int = 1
    slice_shape: tuple[int, ...] | None = None
    deadline_seconds: float = 120.0

    def __post_init__(self):
        if not isinstance(self.name, str) or not self.name:
            raise ValueError(f"bad gang name {self.name!r}: non-empty str")
        mm = self.min_member
        if isinstance(mm, bool) or not isinstance(mm, int) or mm < 1:
            raise ValueError(f"bad gang min_member {mm!r}: int >= 1")
        object.__setattr__(self, "slice_shape",
                           parse_slice_shape(self.slice_shape))
        dl = self.deadline_seconds
        if isinstance(dl, bool) or not isinstance(dl, (int, float)) \
                or not math.isfinite(dl) or dl <= 0:
            raise ValueError(f"bad gang deadline {dl!r}: finite seconds > 0")
        object.__setattr__(self, "deadline_seconds", float(dl))

    @property
    def chips(self) -> int:
        """Torus chips the slice occupies (0 = no topology demand)."""
        return math.prod(self.slice_shape) if self.slice_shape else 0

    def signature(self) -> tuple:
        """The constraint-signature component: pods of different gangs
        (or different gang contracts) are never interchangeable."""
        return (self.name, self.min_member, self.slice_shape)
