"""Pod-side scheduling inputs: requests, tolerations, spread, affinity.

These are the *demand* half of the placement problem.  Resource quantities
are normalized to integer units at parse time (milliCPU, MiB, GPU count,
one pod slot) so the device solve is exact integer arithmetic — no float
floor-division hazards on TPU.
"""

from __future__ import annotations

import math
import re
import threading
from dataclasses import dataclass, field

from karpenter_tpu.apis.podgroup import PodGroup
from karpenter_tpu.apis.requirements import Requirements

# Resource axis order used by every dense tensor in the system.
RESOURCE_AXES = ("cpu", "memory", "gpu", "pods")
NUM_RESOURCES = len(RESOURCE_AXES)

_QTY_RE = re.compile(r"^([0-9]*\.?[0-9]+)([a-zA-Z]*)$")

_MEM_MULT = {  # to MiB
    "": 1 / (1024 * 1024), "k": 1000 / (1024 * 1024), "M": 1000_000 / (1024 * 1024),
    "G": 1e9 / (1024 * 1024), "T": 1e12 / (1024 * 1024),
    "Ki": 1 / 1024, "Mi": 1.0, "Gi": 1024.0, "Ti": 1024.0 * 1024,
}


def parse_cpu_milli(q) -> int:
    """'500m' -> 500; '2' -> 2000; 1.5 -> 1500."""
    if isinstance(q, (int, float)):
        return int(round(q * 1000))
    m = _QTY_RE.match(q.strip())
    if not m:
        raise ValueError(f"bad cpu quantity {q!r}")
    num, suffix = float(m.group(1)), m.group(2)
    if suffix == "m":
        return int(round(num))
    if suffix == "":
        return int(round(num * 1000))
    raise ValueError(f"bad cpu suffix {q!r}")


# k8s PriorityClass bounds: the API type is int32, and user-defined
# classes are capped at 1e9 (values above are reserved for
# system-critical classes) — out-of-range ints CLAMP (a cluster admin's
# oversized class must not reject the pod), non-ints REJECT (a typo'd
# priorityClassName resolution bug must not silently become priority 0).
PRIORITY_MIN = -(2 ** 31)
PRIORITY_MAX = 10 ** 9


def parse_priority(q) -> int:
    """Strict priorityClassName-style value: int (or None) -> clamped int.

    None -> 0 (no priority class).  bools, floats, and strings are
    rejected — priority feeds straight into the solver's int32
    ``group_prio`` tensor and the preemption planner's no-inversion
    guarantee, so a lenient parse here would let a malformed manifest
    silently outrank (or be outranked by) every correct pod."""
    if q is None:
        return 0
    if isinstance(q, bool) or not isinstance(q, int):
        raise ValueError(f"bad priority {q!r}: must be an int")
    return max(PRIORITY_MIN, min(PRIORITY_MAX, q))


def parse_memory_mib(q) -> int:
    """'4Gi' -> 4096; '512Mi' -> 512; bytes int -> MiB.

    Rounds *up* so sub-MiB requests never vanish from capacity accounting
    (a request of '512Ki' must cost 1 MiB, not 0).
    """
    if isinstance(q, (int, float)):
        return int(math.ceil(q / (1024 * 1024)))
    m = _QTY_RE.match(q.strip())
    if not m:
        raise ValueError(f"bad memory quantity {q!r}")
    num, suffix = float(m.group(1)), m.group(2)
    if suffix not in _MEM_MULT:
        raise ValueError(f"bad memory suffix {q!r}")
    return int(math.ceil(num * _MEM_MULT[suffix] - 1e-9))


@dataclass(frozen=True)
class ResourceRequests:
    """Integer-normalized resource vector (cpu milli, memory MiB, gpu, pods)."""

    cpu_milli: int = 0
    memory_mib: int = 0
    gpu: int = 0
    pods: int = 1

    @classmethod
    def parse(cls, requests: dict[str, object]) -> "ResourceRequests":
        return cls(
            cpu_milli=parse_cpu_milli(requests.get("cpu", 0)),
            memory_mib=parse_memory_mib(requests.get("memory", 0)),
            gpu=int(requests.get("nvidia.com/gpu", requests.get("gpu", 0)) or 0),
            pods=1,
        )

    def as_tuple(self) -> tuple[int, int, int, int]:
        return (self.cpu_milli, self.memory_mib, self.gpu, self.pods)

    def __add__(self, other: "ResourceRequests") -> "ResourceRequests":
        return ResourceRequests(self.cpu_milli + other.cpu_milli,
                                self.memory_mib + other.memory_mib,
                                self.gpu + other.gpu,
                                self.pods + other.pods)


# usage scalars feed straight into int32 device tensors
# (stochastic/encode.stack_usage) — a value past this bound would crash
# the encode or silently wrap to a NEGATIVE variance, voiding the
# violation bound, so it hard-rejects here instead
USAGE_MAX = 2 ** 31 - 1


def _usage_int(v, what: str) -> int:
    """parse_priority-style strictness for one usage scalar: ints only
    (bools and floats REJECT — a float mean would silently break the
    solver's exact integer mean arithmetic; NaN/inf literals arrive as
    floats and reject on the same branch), non-negative, int32-bounded
    (the dense tensors the solver consumes are int32)."""
    if isinstance(v, bool) or not isinstance(v, int):
        raise ValueError(f"bad usage {what} {v!r}: must be a "
                         f"non-negative int")
    if not 0 <= v <= USAGE_MAX:
        raise ValueError(f"bad usage {what} {v!r}: must be in "
                         f"[0, {USAGE_MAX}] (int32 tensor bound)")
    return v


@dataclass(frozen=True)
class UsageDistribution:
    """Per-resource usage distribution for chance-constrained packing
    (karpenter_tpu/stochastic): ``mean`` in the SAME integer units as
    :class:`ResourceRequests` (milliCPU, MiB, accel, pod slots) and
    ``var`` in those units SQUARED, both per pod.  A pod without a
    distribution behaves exactly as ``usage=(requests, 0)`` — the
    stochastic plane is a strict superset of deterministic packing.

    Validation is hard-reject at construction (the parse_priority
    convention): negative variance, variance on an axis whose mean is
    zero ("variance without mean"), and non-int values (bools, floats —
    which is also how NaN/inf are rejected) never enter the system, so
    the solver's quantile check can assume finite non-negative integers
    and never re-validates.
    """

    mean: ResourceRequests = field(default_factory=ResourceRequests)
    var: tuple[int, int, int, int] = (0, 0, 0, 0)

    def __post_init__(self):
        if not isinstance(self.mean, ResourceRequests):
            raise ValueError(f"bad usage mean {self.mean!r}: must be a "
                             f"ResourceRequests")
        mean = tuple(_usage_int(v, "mean") for v in self.mean.as_tuple())
        if not isinstance(self.var, (tuple, list)) \
                or len(self.var) != NUM_RESOURCES:
            raise ValueError(f"bad usage variance {self.var!r}: must be "
                             f"a {NUM_RESOURCES}-tuple")
        var = tuple(_usage_int(v, "variance") for v in self.var)
        for m, v, axis in zip(mean, var, RESOURCE_AXES):
            if v > 0 and m == 0:
                raise ValueError(
                    f"bad usage: variance {v} on {axis} with zero mean "
                    f"(variance without mean)")
        object.__setattr__(self, "var", var)

    def signature(self) -> tuple:
        return (self.mean.as_tuple(), self.var)


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass(frozen=True)
class Toleration:
    key: str = ""               # "" + Exists tolerates everything
    operator: str = "Equal"     # Equal | Exists
    value: str = ""
    effect: str = ""            # "" matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


def tolerates_all(tolerations: tuple[Toleration, ...], taints: tuple[Taint, ...]) -> bool:
    """A pod can schedule onto a node iff every NoSchedule/NoExecute taint is
    tolerated (PreferNoSchedule is soft and ignored for feasibility)."""
    for t in taints:
        if t.effect == "PreferNoSchedule":
            continue
        if not any(tol.tolerates(t) for tol in tolerations):
            return False
    return True


def tolerates_soft(tolerations: tuple[Toleration, ...],
                   taints: tuple[Taint, ...]) -> bool:
    """PreferNoSchedule counterpart of :func:`tolerates_all`: True when
    every SOFT taint is tolerated.  Used for pool-preference ordering
    (the provisioner tries soft-tainted pools last for intolerant pods),
    never for feasibility — kube semantics: 'prefer not to schedule,
    but allow'."""
    for t in taints:
        if t.effect != "PreferNoSchedule":
            continue
        if not any(tol.tolerates(t) for tol in tolerations):
            return False
    return True


# The two topology domains the solver models (node = offering slot,
# zone = catalog zone).  Any other key is a hard reject: a typo'd
# topology_key must not silently degrade to "no constraint".
HOSTNAME_TOPOLOGY_KEY = "kubernetes.io/hostname"
ZONE_TOPOLOGY_KEY = "topology.kubernetes.io/zone"
TOPOLOGY_KEYS = frozenset({HOSTNAME_TOPOLOGY_KEY, ZONE_TOPOLOGY_KEY})


def _selector_tuple(sel, what: str, allow_empty: bool):
    """parse_priority-style strictness for one label selector: a
    tuple/list of (str key, str value) pairs with non-empty keys.
    Returns the normalized tuple-of-tuples form (signatures and the
    affinity encoder both key on the exact tuple value)."""
    if not isinstance(sel, (tuple, list)):
        raise ValueError(f"bad {what} label_selector {sel!r}: must be a "
                         f"tuple of (key, value) pairs")
    out = []
    for item in sel:
        if (not isinstance(item, (tuple, list)) or len(item) != 2
                or not isinstance(item[0], str)
                or not isinstance(item[1], str) or not item[0]):
            raise ValueError(f"bad {what} label_selector entry {item!r}: "
                             f"must be a (non-empty str, str) pair")
        out.append((item[0], item[1]))
    if not out and not allow_empty:
        raise ValueError(f"bad {what}: label_selector must not be empty "
                         f"(an edge with no selector matches nothing)")
    return tuple(out)


def _topology_key(key, what: str) -> str:
    if key not in TOPOLOGY_KEYS:
        raise ValueError(f"bad {what} topology_key {key!r}: must be one "
                         f"of {sorted(TOPOLOGY_KEYS)}")
    return key


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = "topology.kubernetes.io/zone"
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: tuple[tuple[str, str], ...] = ()

    def __post_init__(self):
        # hard-reject at construction (the parse_priority convention):
        # a zero/negative skew or a bool would flow straight into the
        # int32 spread-bound tensor as a nonsense cap
        if isinstance(self.max_skew, bool) \
                or not isinstance(self.max_skew, int) or self.max_skew < 1:
            raise ValueError(f"bad topology_spread max_skew "
                             f"{self.max_skew!r}: must be an int >= 1")
        _topology_key(self.topology_key, "topology_spread")
        if self.when_unsatisfiable not in ("DoNotSchedule",
                                           "ScheduleAnyway"):
            raise ValueError(
                f"bad topology_spread when_unsatisfiable "
                f"{self.when_unsatisfiable!r}: must be DoNotSchedule or "
                f"ScheduleAnyway")
        # empty selector stays valid: it self-selects the pod's own
        # group (the pre-affinity spread semantics)
        object.__setattr__(
            self, "label_selector",
            _selector_tuple(self.label_selector, "topology_spread",
                            allow_empty=True))


@dataclass(frozen=True)
class PodAffinityTerm:
    """Simplified (anti-)affinity: match pods by label selector within a
    topology domain."""

    label_selector: tuple[tuple[str, str], ...] = ()
    topology_key: str = "kubernetes.io/hostname"
    anti: bool = False

    def __post_init__(self):
        object.__setattr__(
            self, "label_selector",
            _selector_tuple(self.label_selector, "affinity",
                            allow_empty=False))
        _topology_key(self.topology_key, "affinity")
        if not isinstance(self.anti, bool):
            raise ValueError(f"bad affinity anti {self.anti!r}: must be "
                             f"a bool")

    def matches(self, labels: tuple[tuple[str, str], ...]) -> bool:
        """True when every selector pair appears in ``labels``."""
        lab = dict(labels)
        return all(lab.get(k) == v for k, v in self.label_selector)


def pod_key(pod: "PodSpec") -> str:
    """Canonical pod identity: 'namespace/name'.  Every plan, nomination,
    and validator structure keys pods this way — bare names collide across
    namespaces.  Memoized on the (frozen) pod: the provisioner calls this
    for every pod on every solve window."""
    cached = getattr(pod, "_key_cache", None)
    if cached is None:
        cached = f"{pod.namespace}/{pod.name}"
        object.__setattr__(pod, "_key_cache", cached)
    return cached


_SIG_IDS: dict[tuple, int] = {}  # signature tuple -> interned id
_SIG_IDS_LOCK = threading.Lock()


@dataclass(frozen=True)
class PodSpec:
    """A pending pod as seen by the provisioner."""

    name: str
    namespace: str = "default"
    requests: ResourceRequests = field(default_factory=ResourceRequests)
    node_selector: tuple[tuple[str, str], ...] = ()
    required_requirements: tuple = ()      # tuple of Requirement (nodeAffinity required)
    # preferredDuringSchedulingIgnoredDuringExecution: (weight 1-100,
    # Requirement) terms — soft preferences lowered to cost penalties in
    # offering choice, never to hard masks (SURVEY §7.4)
    preferred_requirements: tuple = ()     # tuple of (int, Requirement)
    tolerations: tuple[Toleration, ...] = ()
    topology_spread: tuple[TopologySpreadConstraint, ...] = ()
    affinity: tuple[PodAffinityTerm, ...] = ()
    labels: tuple[tuple[str, str], ...] = ()
    # priorityClassName-style int (parse_priority semantics) — the
    # preemption plane's ordering key.  Validated at construction: every
    # PodSpec in the system carries an in-bounds int, so the solver's
    # group_prio tensor and the no-inversion checks never re-validate.
    priority: int = 0
    # gang membership (apis/podgroup.py): members of one PodGroup place
    # all-or-nothing, optionally on a contiguous torus slice.  None =
    # ordinary per-pod scheduling.  Strictly a PodGroup or None — a
    # malformed gang spec must fail at construction, not place per-pod.
    gang: PodGroup | None = None
    # usage distribution (karpenter_tpu/stochastic): mean/variance per
    # resource for chance-constrained packing under a NodePool
    # overcommit bound.  None = deterministic (mean=requests, var=0).
    # Strictly a UsageDistribution or None — its own __post_init__
    # hard-rejects malformed distributions.
    usage: UsageDistribution | None = None

    def __post_init__(self):
        object.__setattr__(self, "priority", parse_priority(self.priority))
        if self.gang is not None and not isinstance(self.gang, PodGroup):
            raise ValueError(f"bad gang {self.gang!r}: must be a PodGroup")
        if self.usage is not None \
                and not isinstance(self.usage, UsageDistribution):
            raise ValueError(f"bad usage {self.usage!r}: must be a "
                             f"UsageDistribution")
        for t in self.affinity:
            if not isinstance(t, PodAffinityTerm):
                raise ValueError(f"bad affinity term {t!r}: must be a "
                                 f"PodAffinityTerm")
            # required hostname affinity to the pod's OWN labels is a
            # self-edge: it is satisfied by the pod itself on any node
            # (kube counts the incoming pod), so it can never constrain
            # anything — reject it as a manifest bug rather than carry
            # a vacuous edge through the solver
            if (not t.anti and t.topology_key == HOSTNAME_TOPOLOGY_KEY
                    and self.labels and t.matches(self.labels)):
                raise ValueError(
                    f"bad affinity term {t!r}: required hostname "
                    f"affinity matching the pod's own labels is a "
                    f"vacuous self-edge")
        for c in self.topology_spread:
            if not isinstance(c, TopologySpreadConstraint):
                raise ValueError(f"bad topology_spread {c!r}: must be a "
                                 f"TopologySpreadConstraint")

    def scheduling_requirements(self) -> Requirements:
        reqs = Requirements.from_selector(dict(self.node_selector))
        for r in self.required_requirements:
            reqs.add(r)
        return reqs

    @property
    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)

    def constraint_signature(self) -> tuple:
        """Pods with identical signatures are interchangeable for placement —
        the host-side grouping key for the solver (solver/encode.py).
        Memoized: the provisioner re-encodes the same PodSpec instances every
        solve window, and signature construction dominates encode time at
        10k pods."""
        cached = getattr(self, "_sig_cache", None)
        if cached is not None:
            return cached
        sig = self._constraint_signature()
        object.__setattr__(self, "_sig_cache", sig)
        return sig

    def signature_key(self) -> str:
        """Stable STRING form of the constraint signature — THE
        grouping/routing key string shared by the shard router
        (sharded/router.py), the ledger arrival table
        (obs/ledger.arrival), and the whatif forecast matching
        (whatif/scenario.wave_from_forecast).  One definition: if the
        string form ever changes, every consumer changes with it —
        forecasted waves silently stop matching baseline groups
        otherwise.  Memoized like the signature itself: the intake
        path and the shard router both call this per pod."""
        cached = getattr(self, "_sig_key", None)
        if cached is None:
            cached = repr(self.constraint_signature())
            object.__setattr__(self, "_sig_key", cached)
        return cached

    def signature_id(self) -> int:
        """Process-wide interned integer for the constraint signature —
        grouping 10k pods by int avoids re-hashing nested tuples on every
        encode.  Interning is locked: a racing setdefault(sig, len(map))
        could hand the same id to two different signatures."""
        cached = getattr(self, "_sig_id", None)
        if cached is None:
            sig = self.constraint_signature()
            with _SIG_IDS_LOCK:
                cached = _SIG_IDS.setdefault(sig, len(_SIG_IDS))
            object.__setattr__(self, "_sig_id", cached)
        return cached

    def _constraint_signature(self) -> tuple:
        # empty fast paths: the common pod carries no constraints, and
        # building 7 generator+sorted() pipelines per pod dominated cold
        # encode at 10k pods (~110 ms; first-restart-window budget)
        return (
            self.requests.as_tuple(),
            # priority splits groups: pods of different priorities are NOT
            # interchangeable once the preemption plane ranks them
            self.priority,
            # gang splits groups the same way: members place atomically,
            # so a member and a lookalike singleton must never share a row
            self.gang.signature() if self.gang is not None else None,
            # usage splits groups too: pods with different distributions
            # consume different chance-constrained capacity, so they are
            # NOT interchangeable under an overcommit bound
            self.usage.signature() if self.usage is not None else None,
            tuple(sorted(self.labels)) if self.labels else (),
            tuple(sorted(self.node_selector)) if self.node_selector else (),
            tuple(sorted(r.signature for r in self.required_requirements))
            if self.required_requirements else (),
            tuple(sorted((w, r.signature)
                         for w, r in self.preferred_requirements))
            if self.preferred_requirements else (),
            tuple(sorted((t.key, t.operator, t.value, t.effect)
                         for t in self.tolerations))
            if self.tolerations else (),
            tuple(sorted((c.max_skew, c.topology_key, c.when_unsatisfiable,
                          c.label_selector) for c in self.topology_spread))
            if self.topology_spread else (),
            tuple(sorted((a.label_selector, a.topology_key, a.anti)
                         for a in self.affinity)) if self.affinity else (),
        )


def fingerprint_token(pod: "PodSpec") -> tuple[str, int]:
    """THE canonical encode-memo token — (pod key, interned signature
    id) — memoized on the pod as ``_fpt``.  Single definition: both the
    encode fingerprint (solver/encode.py) and watch-time interning below
    must produce the identical token or the whole-encode memo silently
    misses every window."""
    tok = getattr(pod, "_fpt", None)
    if tok is None:
        tok = (pod_key(pod), pod.signature_id())
        object.__setattr__(pod, "_fpt", tok)
    return tok


def intern_signatures(pods) -> None:
    """Eagerly intern constraint signatures (and the encode fingerprint
    token) for a batch of pods.  The per-pod signature construction is
    the dominant cold-encode cost at 10k pods (~90 ms); production pods
    arrive through the watch stream, so the provisioner interns at
    ingestion time and the solve window's encode finds every token
    cached — the restart-window budget never pays it all at once."""
    for p in pods:
        fingerprint_token(p)


def make_pods(count: int, name_prefix: str = "pod", **kwargs) -> list[PodSpec]:
    """Convenience fan-out for tests/benchmarks."""
    return [PodSpec(name=f"{name_prefix}-{i}", **kwargs) for i in range(count)]
