"""Scheduling requirements: label-key operators over value sets.

Capability parity with Kubernetes/Karpenter NodeSelectorRequirement semantics
as consumed by the reference's compatibility filter
(pkg/cloudprovider/cloudprovider.go:321-352): In / NotIn / Exists /
DoesNotExist / Gt / Lt over node label values.

These requirements are the *host-side* representation; the solver encodes
them into boolean compatibility masks (pods x offerings) before the device
solve (see solver/encode.py).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from collections.abc import Iterable


class Operator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


# Well-known label keys (mirrors karpenter/k8s well-known labels).
LABEL_ARCH = "kubernetes.io/arch"
LABEL_OS = "kubernetes.io/os"
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_CAPACITY_TYPE = "karpenter.sh/capacity-type"
LABEL_NODEPOOL = "karpenter.sh/nodepool"
LABEL_INSTANCE_FAMILY = "karpenter-tpu.sh/instance-family"
LABEL_INSTANCE_SIZE = "karpenter-tpu.sh/instance-size"
LABEL_HOSTNAME = "kubernetes.io/hostname"

CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_SPOT = "spot"


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: Operator
    values: tuple[str, ...] = ()

    def matches(self, labels: dict[str, str]) -> bool:
        """Does a node with these labels satisfy the requirement?"""
        present = self.key in labels
        value = labels.get(self.key)
        op = self.operator
        if op == Operator.IN:
            return present and value in self.values
        if op == Operator.NOT_IN:
            return not present or value not in self.values
        if op == Operator.EXISTS:
            return present
        if op == Operator.DOES_NOT_EXIST:
            return not present
        if op in (Operator.GT, Operator.LT):
            if not present or not self.values:
                return False
            left, right = _num(value), _num(self.values[0])
            if left is None or right is None:
                return False
            return left > right if op == Operator.GT else left < right
        raise ValueError(f"unknown operator {op}")

    def allows_value(self, value: str | None) -> bool:
        """Does the requirement allow a specific value for its key
        (value None = label absent)?"""
        labels = {} if value is None else {self.key: value}
        return self.matches(labels)

    @property
    def signature(self) -> tuple:
        return (self.key, self.operator.value, tuple(sorted(self.values)))


def _num(v: str | None):
    try:
        return float(v)  # type: ignore[arg-type]
    except (TypeError, ValueError):
        return None


@dataclass
class Requirements:
    """A conjunction of requirements, deduped per key (AND across keys,
    operator semantics within a key)."""

    items: list[Requirement] = field(default_factory=list)

    @classmethod
    def from_selector(cls, selector: dict[str, str]) -> "Requirements":
        return cls([Requirement(k, Operator.IN, (v,)) for k, v in sorted(selector.items())])

    def add(self, req: Requirement) -> "Requirements":
        self.items.append(req)
        return self

    def merged(self, other: "Requirements") -> "Requirements":
        return Requirements(self.items + other.items)

    def matches(self, labels: dict[str, str]) -> bool:
        return all(r.matches(labels) for r in self.items)

    def allowed_values(self, key: str, candidates: Iterable[str]) -> list[str]:
        """Filter candidate values for ``key`` to those every requirement on
        that key admits."""
        reqs = [r for r in self.items if r.key == key]
        return [c for c in candidates if all(r.allows_value(c) for r in reqs)]

    def has_key(self, key: str) -> bool:
        return any(r.key == key for r in self.items)

    def get(self, key: str) -> list[Requirement]:
        return [r for r in self.items if r.key == key]

    @property
    def signature(self) -> tuple:
        return tuple(sorted(r.signature for r in self.items))

    def __iter__(self):
        return iter(self.items)

    def __len__(self):
        return len(self.items)
