from karpenter_tpu.apis.requirements import Requirement, Requirements, Operator
from karpenter_tpu.apis.pod import (
    PodSpec, Toleration, Taint, TopologySpreadConstraint, PodAffinityTerm,
    ResourceRequests,
)
from karpenter_tpu.apis.nodeclass import (
    NodeClass, NodeClassSpec, NodeClassStatus, InstanceRequirements,
    PlacementStrategy, SubnetSelectionCriteria, ImageSelector, VolumeSpec,
    BlockDeviceMapping, KubeletConfig, LoadBalancerIntegration, LoadBalancerTarget,
    DynamicPoolConfig, ValidationError, Condition,
)
from karpenter_tpu.apis.nodeclaim import NodeClaim, Node, NodePool

__all__ = [
    "Requirement", "Requirements", "Operator",
    "PodSpec", "Toleration", "Taint", "TopologySpreadConstraint",
    "PodAffinityTerm", "ResourceRequests",
    "NodeClass", "NodeClassSpec", "NodeClassStatus", "InstanceRequirements",
    "PlacementStrategy", "SubnetSelectionCriteria", "ImageSelector",
    "VolumeSpec", "BlockDeviceMapping", "KubeletConfig",
    "LoadBalancerIntegration", "LoadBalancerTarget", "DynamicPoolConfig",
    "ValidationError", "Condition",
    "NodeClaim", "Node", "NodePool",
]
