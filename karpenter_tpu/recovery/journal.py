"""Write-ahead intent journal: durable record of every mutating actuation.

Format (append-only JSONL; docs/design/recovery.md "journal format"):

- ``{"rec":"intent","id","kind","t","owner","payload"}`` — written and
  **fsynced before the first RPC** of the actuation it describes (the
  write-ahead guarantee: the cloud can never hold a resource the journal
  has no intent for);
- ``{"rec":"note","id","stage","t","data"}`` — per-stage progress
  (VNI id, volume ids, instance id) written *after* each RPC returns, so
  replay knows exactly how far the sequence got;
- ``{"rec":"done","id","t","outcome","detail"}`` — completion.  A crash
  between the intent and its ``done`` leaves the intent *open*; the
  restart reconciler fences or finishes it;
- ``{"rec":"state","key","t","value"}`` — keyed control-plane state
  (nominations, ``preempted_keys``, gang admissions) with newest-wins
  semantics; ``value: null`` is a tombstone.  Restart rebuilds volatile
  controller state from the surviving map.

Timestamps come from ``time.time()`` **at call time**, so the chaos
VirtualClock stamps journal records in scenario time (deterministic
replay).  Idempotency keys are derived from the intent id
(``<intent-id>/<stage>``): a replayed create with the same key is a
lookup on the cloud side, never a duplicate.

Durability is fsync-batched: intent records always fsync (write-ahead);
notes/dones/state fsync every ``fsync_interval`` records or at flush.
The file is bounded: once more than ``max_records`` have accumulated,
compaction rewrites it keeping only open intents and the newest state
record per key (atomic ``os.replace``).
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from dataclasses import dataclass, field

from karpenter_tpu.recovery import crashpoints
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("recovery.journal")

# intent kinds the actuation plane records (docs/design/recovery.md)
KIND_NODE_CREATE = "node_create"
KIND_CLAIM_DELETE = "claim_delete"
KIND_EVICTION = "eviction"
KIND_GANG_PLACEMENT = "gang_placement"
KIND_REPACK_MIGRATION = "repack_migration"
KIND_ORPHAN_DELETE = "orphan_delete"


@dataclass
class Intent:
    """One open intent handle (yielded by :meth:`IntentJournal.intent`)."""

    id: str
    kind: str
    payload: dict
    journal: "IntentJournal | None" = None
    notes: dict[str, dict] = field(default_factory=dict)
    outcome: str = ""          # set at completion

    def idem_key(self, stage: str) -> str:
        """Deterministic idempotency key for one staged RPC.  Empty when
        journaling is off (NullJournal) or the journal's ``idempotency``
        switch is off (the deliberately-broken chaos fixture) — the
        cloud treats "" as no-key."""
        if not self.id or (self.journal is not None
                           and not getattr(self.journal, "idempotency",
                                           True)):
            return ""
        return f"{self.id}/{stage}"

    def note(self, stage: str, **data) -> None:
        self.notes[stage] = data
        if self.journal is not None:
            self.journal._append({"rec": "note", "id": self.id,
                                  "stage": stage, "t": time.time(),
                                  "data": data})


class _NullIntent(Intent):
    def idem_key(self, stage: str) -> str:
        return ""

    def note(self, stage: str, **data) -> None:
        pass


class _IntentCtx:
    """Context manager around one intent: write-ahead on enter, done on
    exit.  ``ok`` exceptions complete as success (the actuator's
    delete path *raises* NodeClaimNotFoundError on success — the
    finalizer-release contract).  :class:`SimulatedCrash` (and any other
    BaseException that is not an Exception) writes NOTHING — a real
    crash does not get to record its own completion."""

    def __init__(self, journal: "IntentJournal", kind: str, payload: dict,
                 ok: tuple[type[BaseException], ...] = ()):
        self.journal = journal
        self.kind = kind
        self.payload = payload
        self.ok = ok
        self.intent: Intent | None = None

    def __enter__(self) -> Intent:
        self.intent = self.journal.open(self.kind, self.payload)
        return self.intent

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is None:
            self.journal.complete(self.intent, "ok")
        elif isinstance(exc, self.ok):
            self.journal.complete(self.intent, "ok",
                                  detail=type(exc).__name__)
        elif isinstance(exc, Exception):
            # the actuation failed CLEANLY (its own compensation ran);
            # the intent closes so replay does not re-drive it
            self.journal.complete(self.intent, "failed",
                                  detail=str(exc)[:200])
        # a BaseException (SimulatedCrash, KeyboardInterrupt) writes no
        # completion: the intent stays open for the reconciler
        return False


class NullJournal:
    """Do-nothing journal with the full surface — the default wiring, so
    actuation code reads unconditionally (null-object pattern)."""

    path = ""

    def intent(self, kind: str, ok: tuple = (), **payload) -> "_NullCtx":
        return _NullCtx()

    def state(self, key: str, value) -> None:
        pass

    def open(self, kind: str, payload: dict) -> Intent:
        return _NullIntent(id="", kind=kind, payload=payload)

    def complete(self, intent: Intent, outcome: str, detail: str = "") -> None:
        pass

    def complete_id(self, intent_id: str, outcome: str,
                    detail: str = "") -> None:
        pass

    def open_intents(self) -> list[Intent]:
        return []

    def state_map(self) -> dict:
        return {}

    def flush(self) -> None:
        pass

    def compact(self) -> None:
        pass

    def close(self) -> None:
        pass

    def stats(self) -> dict:
        return {"enabled": False}


class _NullCtx:
    def __enter__(self) -> Intent:
        return _NullIntent(id="", kind="", payload={})

    def __exit__(self, *exc) -> bool:
        return False


NULL_JOURNAL = NullJournal()


class IntentJournal(NullJournal):
    """The real journal (see module docstring)."""

    def __init__(self, path: str, *, owner: str = "",
                 fsync_interval: int = 16, max_records: int = 4096,
                 max_state_keys: int = 65536, fsync: bool = True,
                 idempotency: bool = True):
        self.path = str(path)
        self.owner = owner or "operator"
        # False ONLY in the deliberately-broken chaos fixture: intents
        # mint no idempotency keys, so a replayed create duplicates —
        # provably failing the no-double-create invariant
        self.idempotency = idempotency
        self.fsync_interval = max(1, int(fsync_interval))
        self.max_records = max(64, int(max_records))
        self.max_state_keys = max_state_keys
        self._fsync_enabled = fsync
        self._lock = threading.RLock()
        self._fh: io.TextIOBase | None = None
        self._unsynced = 0
        self._records = 0          # records in the file (approx, see load)
        self._compactions = 0
        # in-memory mirrors of what is on disk (kept current so open
        # intents / state reads never re-parse the file on the hot path)
        self._open: dict[str, Intent] = {}
        self._state: dict[str, object] = {}
        self._seq = 0
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self._load()

    # -- write path --------------------------------------------------------

    def intent(self, kind: str, ok: tuple = (), **payload) -> _IntentCtx:
        """Open a write-ahead intent for the ``with`` block; the intent
        record is durable before the block body (the first RPC) runs."""
        return _IntentCtx(self, kind, payload, ok=tuple(ok))

    def open(self, kind: str, payload: dict) -> Intent:
        with self._lock:
            self._seq += 1
            intent = Intent(id=f"{self.owner}-{self._seq:06d}", kind=kind,
                            payload=dict(payload), journal=self)
            self._open[intent.id] = intent
            self._append({"rec": "intent", "id": intent.id, "kind": kind,
                          "t": time.time(), "owner": self.owner,
                          "payload": intent.payload}, durable=True)
            metrics.JOURNAL_OPEN_INTENTS.set(len(self._open))
        return intent

    def complete(self, intent: Intent, outcome: str, detail: str = "") -> None:
        if intent is None or not intent.id:
            return
        self.complete_id(intent.id, outcome, detail)
        intent.outcome = outcome

    def complete_id(self, intent_id: str, outcome: str,
                    detail: str = "") -> None:
        with self._lock:
            rec = {"rec": "done", "id": intent_id, "t": time.time(),
                   "outcome": outcome}
            if detail:
                rec["detail"] = detail
            self._append(rec, durable=True)
            self._open.pop(intent_id, None)
            metrics.JOURNAL_OPEN_INTENTS.set(len(self._open))

    def state(self, key: str, value) -> None:
        """Keyed newest-wins state record; ``None`` tombstones the key."""
        with self._lock:
            if value is None:
                if key not in self._state:
                    return          # tombstoning the absent: no record
                self._state.pop(key, None)
            else:
                self._state[key] = value
                while len(self._state) > self.max_state_keys:
                    self._state.pop(next(iter(self._state)))
            self._append({"rec": "state", "key": key, "t": time.time(),
                          "value": value})

    def _append(self, rec: dict, durable: bool = False) -> None:
        # the mid-journal-append crashpoint: the process dies with the
        # record composed but never written — exactly a torn write
        crashpoints.hit("journal.append")
        with self._lock:
            fh = self._handle_locked()
            fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
            fh.flush()
            self._records += 1
            self._unsynced += 1
            metrics.JOURNAL_RECORDS.labels(rec["rec"]).inc()
            if durable or self._unsynced >= self.fsync_interval:
                self._fsync_locked()
            if self._records > self.max_records:
                self._compact_locked()

    def _fsync_locked(self) -> None:
        if self._fsync_enabled and self._fh is not None:
            os.fsync(self._fh.fileno())
        self._unsynced = 0

    def _handle_locked(self) -> io.TextIOBase:
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fsync_locked()
            metrics.JOURNAL_BYTES.set(self._size())

    def close(self) -> None:
        with self._lock:
            self.flush()
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- read path ---------------------------------------------------------

    def open_intents(self) -> list[Intent]:
        with self._lock:
            return list(self._open.values())

    def state_map(self) -> dict:
        with self._lock:
            return dict(self._state)

    def _load(self) -> None:
        """Reopen after a (real or simulated) crash: rebuild the open-set
        and state map from disk, tolerating a torn final line."""
        intents, state, records, max_seq = read_journal(self.path)
        with self._lock:
            self._open = {i.id: i for i in intents if not i.outcome}
            for i in self._open.values():
                i.journal = self
            self._state = state
            self._records = records
            self._seq = max_seq
            metrics.JOURNAL_OPEN_INTENTS.set(len(self._open))

    # -- compaction --------------------------------------------------------

    def compact(self) -> None:
        with self._lock:
            self._compact_locked()

    def _compact_locked(self) -> None:
        """Rewrite keeping only open intents (with their notes) and the
        newest state record per key.  Crash-safe: written to a temp file
        and atomically swapped in."""
        tmp = self.path + ".compact"
        now = time.time()
        n = 0
        with open(tmp, "w", encoding="utf-8") as out:
            # the seq high-water mark MUST survive compaction: dropped
            # (completed) intents are the only other record of it, and a
            # reused intent id would reuse its idempotency keys — a new
            # create would silently return a stale cloud resource
            out.write(json.dumps({"rec": "seq", "n": self._seq, "t": now},
                                 separators=(",", ":")) + "\n")
            n += 1
            for intent in self._open.values():
                out.write(json.dumps(
                    {"rec": "intent", "id": intent.id, "kind": intent.kind,
                     "t": now, "owner": self.owner,
                     "payload": intent.payload},
                    separators=(",", ":")) + "\n")
                n += 1
                for stage, data in intent.notes.items():
                    out.write(json.dumps(
                        {"rec": "note", "id": intent.id, "stage": stage,
                         "t": now, "data": data},
                        separators=(",", ":")) + "\n")
                    n += 1
            for key, value in self._state.items():
                out.write(json.dumps(
                    {"rec": "state", "key": key, "t": now, "value": value},
                    separators=(",", ":")) + "\n")
                n += 1
            out.flush()
            if self._fsync_enabled:
                os.fsync(out.fileno())
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        os.replace(tmp, self.path)
        self._records = n
        self._unsynced = 0
        self._compactions += 1
        metrics.JOURNAL_COMPACTIONS.inc()
        metrics.JOURNAL_BYTES.set(self._size())
        log.info("journal compacted", path=self.path, records=n,
                 open_intents=len(self._open))

    # -- introspection -----------------------------------------------------

    def _size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "path": self.path,
                "owner": self.owner,
                "records": self._records,
                "open_intents": len(self._open),
                "state_keys": len(self._state),
                "bytes": self._size(),
                "compactions": self._compactions,
            }


def read_journal(path: str) -> tuple[list[Intent], dict, int, int]:
    """Parse a journal file -> (all intents with outcome filled where
    completed, state map, record count, max seq seen).  A torn final
    line (crash mid-write) is skipped; torn middle lines are skipped
    too with a warning — replay must survive exactly the failure it
    exists for."""
    intents: dict[str, Intent] = {}
    state: dict[str, object] = {}
    records = 0
    max_seq = 0
    if not os.path.exists(path):
        return [], {}, 0, 0
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                log.warning("journal: skipping torn record", path=path,
                            line=lineno)
                continue
            records += 1
            kind = rec.get("rec")
            if kind == "intent":
                intent = Intent(id=rec["id"], kind=rec.get("kind", ""),
                                payload=rec.get("payload") or {})
                intents[intent.id] = intent
                try:
                    max_seq = max(max_seq,
                                  int(intent.id.rsplit("-", 1)[-1]))
                except ValueError:
                    pass
            elif kind == "note":
                i = intents.get(rec.get("id", ""))
                if i is not None:
                    i.notes[rec.get("stage", "")] = rec.get("data") or {}
            elif kind == "done":
                i = intents.get(rec.get("id", ""))
                if i is not None:
                    i.outcome = rec.get("outcome", "ok")
                # a done whose intent record was torn still spends its id
                try:
                    max_seq = max(max_seq, int(
                        rec.get("id", "").rsplit("-", 1)[-1]))
                except ValueError:
                    pass
            elif kind == "state":
                key = rec.get("key", "")
                value = rec.get("value")
                if value is None:
                    state.pop(key, None)
                else:
                    state[key] = value
            elif kind == "seq":
                # compaction checkpoint: ids below this are spent even
                # though their intents were dropped from the file
                try:
                    max_seq = max(max_seq, int(rec.get("n", 0)))
                except (TypeError, ValueError):
                    pass
    return list(intents.values()), state, records, max_seq
