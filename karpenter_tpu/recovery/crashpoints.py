"""Deterministic crashpoint injection (docs/design/recovery.md).

The journal and the actuation plane expose named *crashpoints* — the
exact instants where a process death is most damaging (before the first
RPC, between staged allocations, after a create but before its response
is durable, mid-eviction, mid-journal-append).  Production code calls
:func:`hit` at each one; with no injector installed that is a single
``None`` check.  The crashpoint chaos harness (``chaos/crash.py``)
installs a seeded :class:`CrashInjector` that kills the "process" by
raising :class:`SimulatedCrash` at predetermined hit counts.

``SimulatedCrash`` subclasses ``BaseException`` on purpose: a real
``kill -9`` does not stop for ``except Exception`` handlers, so the
simulated one must tear through the retry stack, the degraded-mode
wrappers, and the per-node create loop exactly the same way.
"""

from __future__ import annotations

import random
from contextlib import contextmanager

# The catalog (docs/design/recovery.md "crashpoint catalog").  Names are
# stable: the chaos matrix and the replay commands key on them.
CRASHPOINTS: tuple[str, ...] = (
    "actuate.pre_rpc",          # intent durable, no RPC issued yet
    "actuate.mid_create",       # VNI allocated, instance not
    "actuate.post_create",      # instance exists, response not yet durable
    "provision.pre_nominate",   # claim registered, pods not nominated
    "preempt.mid_evict",        # some of a plan's victims evicted
    "journal.append",           # the journal write itself interrupted
)


class SimulatedCrash(BaseException):
    """The operator process died here.  BaseException: nothing in the
    controller plane may catch and survive it."""

    def __init__(self, crashpoint: str, hit_no: int):
        super().__init__(f"simulated crash at {crashpoint} (hit {hit_no})")
        self.crashpoint = crashpoint
        self.hit_no = hit_no


class CrashInjector:
    """Crash at seeded, deterministic hit counts of ONE crashpoint.

    The schedule is fully determined by ``(crashpoint, seed)``: crash
    hit numbers are drawn once from a dedicated stream, so the same
    cell replays the same crashes — the determinism contract the
    trace-digest comparison enforces.
    """

    def __init__(self, crashpoint: str, seed: int, *, max_crashes: int = 3,
                 first_hit_range: tuple[int, int] = (1, 4),
                 gap_range: tuple[int, int] = (2, 8)):
        if crashpoint not in CRASHPOINTS:
            raise ValueError(f"unknown crashpoint {crashpoint!r}; "
                             f"known: {CRASHPOINTS}")
        self.crashpoint = crashpoint
        self.seed = seed
        rng = random.Random(f"crash:{crashpoint}:{seed}")
        hits: list[int] = []
        nxt = rng.randint(*first_hit_range)
        for _ in range(max_crashes):
            hits.append(nxt)
            nxt += rng.randint(*gap_range)
        self.crash_hits = frozenset(hits)
        self.counts: dict[str, int] = {}
        self.crashes = 0
        self.armed = True

    def disarm(self) -> None:
        """Quiesce: count hits but never crash again."""
        self.armed = False

    def hit(self, name: str) -> None:
        n = self.counts.get(name, 0) + 1
        self.counts[name] = n
        if self.armed and name == self.crashpoint and n in self.crash_hits:
            self.crashes += 1
            raise SimulatedCrash(name, n)


_injector: CrashInjector | None = None


def hit(name: str) -> None:
    """Production no-op; under an installed injector, maybe die here."""
    inj = _injector
    if inj is not None:
        inj.hit(name)


@contextmanager
def installed(injector: CrashInjector):
    """Install ``injector`` for the block (single-threaded harness use,
    same contract as VirtualClock.installed)."""
    global _injector
    prev = _injector
    _injector = injector
    try:
        yield injector
    finally:
        _injector = prev
