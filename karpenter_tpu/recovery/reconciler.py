"""Restart reconciler: replay open intents against ground truth.

On operator start, :meth:`Reconciler.recover` owns the whole restart
sequence (docs/design/recovery.md "fence-vs-finish decision table"):

1. **replay** (``recovery.replay`` span): read the journal — open
   intents (the actuations a crash interrupted) plus the newest-wins
   state map (nominations, preemption ``preempted_keys``, gang
   admissions);
2. **fence or finish** (``recovery.fence`` span): each open intent is
   resolved against cloud + cluster ground truth, never against the
   journal alone.  A ``node_create`` whose pods still wait is *finished*
   — the staged create replays with the intent's idempotency keys, so
   every RPC that already succeeded is a lookup, not a duplicate — and
   its pods nominated; one whose pods moved on is *fenced* — the
   half-built VNI/volumes/instance are deleted (idempotent-create to
   learn a leaked id, then delete).  Evictions re-pend their noted
   victims; gang placements re-nominate whole or not at all; repack
   migrations conservatively re-pend; claim/orphan deletes re-drive the
   delete (not-found tolerated);
3. **state rebuild**: surviving nominations re-apply where pod and claim
   both still exist; ``preempted_keys`` / gang admission stamps are
   returned for the controllers to adopt.

The caller (operator, chaos harness) then hands off to the existing AOT
prewarm + resident rebuild, so one ``recover()`` path owns the restart.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from karpenter_tpu.apis.nodeclaim import NodeClaim, parse_provider_id, provider_id
from karpenter_tpu.apis.requirements import (
    LABEL_CAPACITY_TYPE, LABEL_NODEPOOL, LABEL_ZONE,
)
from karpenter_tpu.cloud.errors import is_not_found
from karpenter_tpu.constants import CLAIM_FINALIZER
from karpenter_tpu.recovery.journal import (
    KIND_CLAIM_DELETE, KIND_EVICTION, KIND_GANG_PLACEMENT, KIND_NODE_CREATE,
    KIND_ORPHAN_DELETE, KIND_REPACK_MIGRATION, Intent, IntentJournal,
)
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("recovery.reconciler")


@dataclass
class RecoveryReport:
    """What one restart recovery did — the /statusz recovery block."""

    replayed: int = 0              # open intents found in the journal
    finished: int = 0              # completed against ground truth
    fenced: int = 0                # leftovers deleted / state released
    errors: int = 0                # recovery actions that themselves failed
    by_kind: dict[str, str] = field(default_factory=dict)  # id -> outcome
    nominations_restored: int = 0
    preempted_keys: set[str] = field(default_factory=set)
    gang_admitted: dict[str, float] = field(default_factory=dict)
    gang_parked: dict[str, float] = field(default_factory=dict)
    replay_s: float = 0.0
    fence_s: float = 0.0
    duration_s: float = 0.0

    def to_dict(self) -> dict:
        return {
            "replayed": self.replayed, "finished": self.finished,
            "fenced": self.fenced, "errors": self.errors,
            "nominations_restored": self.nominations_restored,
            "preempted_keys": len(self.preempted_keys),
            "gang_admitted": len(self.gang_admitted),
            "gang_parked": len(self.gang_parked),
            "replay_s": round(self.replay_s, 6),
            "fence_s": round(self.fence_s, 6),
            "duration_s": round(self.duration_s, 6),
            "intents": dict(self.by_kind),
        }


class Reconciler:
    def __init__(self, journal: IntentJournal, cloud, cluster):
        self.journal = journal
        self.cloud = cloud
        self.cluster = cluster

    # -- entry -------------------------------------------------------------

    def recover(self) -> RecoveryReport:
        report = RecoveryReport()
        t0 = time.perf_counter()
        with obs.span("recovery.replay") as sp:
            open_intents = self.journal.open_intents()
            state = self.journal.state_map()
            report.replayed = len(open_intents)
            sp.set("open_intents", len(open_intents))
            sp.set("state_keys", len(state))
        report.replay_s = time.perf_counter() - t0
        metrics.RECOVERY_DURATION.labels("replay").observe(report.replay_s)
        t1 = time.perf_counter()
        with obs.span("recovery.fence", intents=len(open_intents)) as sp:
            for intent in open_intents:
                outcome = self._resolve(intent, report)
                report.by_kind[f"{intent.kind}:{intent.id}"] = outcome
                metrics.RECOVERY_INTENTS.labels(intent.kind, outcome).inc()
                if outcome == "finished":
                    report.finished += 1
                elif outcome == "error":
                    report.errors += 1
                else:
                    report.fenced += 1
            self._rebuild_state(state, report)
            sp.set("finished", report.finished)
            sp.set("fenced", report.fenced)
            sp.set("nominations_restored", report.nominations_restored)
        report.fence_s = time.perf_counter() - t1
        metrics.RECOVERY_DURATION.labels("fence").observe(report.fence_s)
        # drop replayed intents + dead state from the file so restart
        # cost stays bounded no matter how many crashes preceded us
        self.journal.compact()
        self.journal.flush()
        report.duration_s = time.perf_counter() - t0
        obs.instant("recovery.done", replayed=report.replayed,
                    finished=report.finished, fenced=report.fenced,
                    errors=report.errors)
        if report.replayed:
            log.info("recovery replayed open intents",
                     replayed=report.replayed, finished=report.finished,
                     fenced=report.fenced, errors=report.errors)
        return report

    # -- per-kind resolution -----------------------------------------------

    def _resolve(self, intent: Intent, report: RecoveryReport) -> str:
        handler = {
            KIND_NODE_CREATE: self._recover_node_create,
            KIND_CLAIM_DELETE: self._recover_instance_delete,
            KIND_ORPHAN_DELETE: self._recover_instance_delete,
            KIND_EVICTION: self._recover_eviction,
            KIND_GANG_PLACEMENT: self._recover_gang_placement,
            KIND_REPACK_MIGRATION: self._recover_repack_migration,
        }.get(intent.kind, self._fence_unknown)
        try:
            outcome = handler(intent, report)
        except Exception as e:  # noqa: BLE001 — recovery must finish the sweep
            log.error("recovery handler failed; intent left to backstops",
                      intent=intent.id, kind=intent.kind, error=str(e)[:200])
            metrics.ERRORS.labels("recovery", intent.kind).inc()
            self.journal.complete(intent, "error", detail=str(e)[:200])
            return "error"
        self.journal.complete(intent, outcome)
        return outcome

    def _pods_pending(self, pod_keys) -> list:
        out = []
        for key in pod_keys or ():
            p = self.cluster.get("pods", key)
            if p is not None and not p.bound_node:
                out.append((key, p))
        return out

    def _nominate_pending(self, pod_keys, claim_name: str,
                          report: RecoveryReport) -> int:
        n = 0
        for key, p in self._pods_pending(pod_keys):
            if not p.nominated_node:
                p.nominated_node = claim_name
                self.journal.state(f"nom/{key}", claim_name)
                n += 1
        report.nominations_restored += n
        return n

    def _recover_node_create(self, intent: Intent,
                             report: RecoveryReport) -> str:
        pl = intent.payload
        node_name = pl.get("node", "")
        claim = self.cluster.get_nodeclaim(node_name)
        if claim is not None and not claim.deleted:
            # the create committed (claim registered); only the
            # nomination may have been lost — finish it
            self._nominate_pending(pl.get("pods"), node_name, report)
            return "finished"
        waiting = [key for key, p in self._pods_pending(pl.get("pods"))
                   if not p.nominated_node]
        if waiting:
            return self._finish_create(intent, report)
        return self._fence_create(intent)

    def _finish_create(self, intent: Intent, report: RecoveryReport) -> str:
        """Replay the staged create with the intent's idempotency keys:
        every stage that already succeeded is a lookup on the cloud
        side, so a finished replay can never double-allocate.

        This mirrors Actuator._staged_create/_register_claim from the
        intent PAYLOAD rather than calling them: the live path re-derives
        subnet/image/bootstrap from a NodeClass that may have changed (or
        vanished) since the crash, and recovery must complete the
        decision that was journaled, not re-make it.  Anything added to
        the live create that replay needs must ride the payload
        (user_data and sgs already do)."""
        pl = intent.payload
        node_name = pl["node"]
        vni = self.cloud.create_vni(pl.get("subnet", ""),
                                    idempotency_key=intent.idem_key("vni"))
        vol_ids = []
        try:
            for i, vol in enumerate(pl.get("volumes") or ()):
                v = self.cloud.create_volume(
                    capacity_gb=int(vol.get("capacity_gb", 100)),
                    profile=vol.get("profile", "general-purpose"),
                    volume_id=f"vol-{node_name}-{i}",
                    idempotency_key=intent.idem_key(f"vol{i}"))
                vol_ids.append(v.id)
            from karpenter_tpu.core.actuator import KARPENTER_TAGS

            inst = self.cloud.create_instance(
                name=node_name, profile=pl.get("type", ""),
                zone=pl.get("zone", ""), subnet_id=pl.get("subnet", ""),
                image_id=pl.get("image", ""),
                capacity_type=pl.get("capacity_type", "on-demand"),
                security_group_ids=tuple(pl.get("sgs") or ()),
                user_data=pl.get("user_data", ""),
                vni_id=vni.id, volume_ids=tuple(vol_ids),
                tags={**KARPENTER_TAGS,
                      "karpenter.sh/nodepool": pl.get("nodepool", "default"),
                      "karpenter-tpu.sh/nodeclass": pl.get("nodeclass", ""),
                      "karpenter.sh/intent-id": intent.id},
                idempotency_key=intent.idem_key("inst"))
        except Exception:
            # the replay itself failed (quota, capacity, blackout): the
            # same partial-sequence cleanup the live path guarantees —
            # nothing the replay allocated may leak
            for vid in vol_ids:
                self._delete_tolerant("delete_volume", vid)
            self._delete_tolerant("delete_vni", vni.id)
            raise
        region = pl.get("region", "")
        pid = provider_id(region, inst.id)
        # the instance may already be registered under a different claim
        # row (a racing sweep adopted it) — never register twice
        for c in self.cluster.nodeclaims():
            if c.provider_id == pid and not c.deleted:
                self._nominate_pending(pl.get("pods"), c.name, report)
                return "finished"
        # pool taints ride the claim exactly as the live path's
        # _register_claim sets them (registration syncs them to the node)
        pool = self.cluster.get("nodepools", pl.get("nodepool", "default"))
        claim = NodeClaim(
            name=node_name, nodeclass_name=pl.get("nodeclass", ""),
            nodepool_name=pl.get("nodepool", "default"),
            taints=tuple(pool.taints) if pool is not None else (),
            startup_taints=tuple(pool.startup_taints)
            if pool is not None else (),
            instance_type=pl.get("type", ""), zone=pl.get("zone", ""),
            capacity_type=pl.get("capacity_type", "on-demand"),
            provider_id=pid,
            labels={LABEL_ZONE: pl.get("zone", ""),
                    LABEL_CAPACITY_TYPE: pl.get("capacity_type",
                                                "on-demand"),
                    LABEL_NODEPOOL: pl.get("nodepool", "default")},
            subnet_id=pl.get("subnet", ""), image_id=pl.get("image", ""),
            hourly_price=float(pl.get("price", 0.0)),
            launched=True, finalizers=[CLAIM_FINALIZER])
        self.cluster.add_nodeclaim(claim)
        self.cluster.record_event(
            "NodeClaim", claim.name, "Normal", "Recovered",
            f"create intent {intent.id} finished on restart -> {inst.id}")
        self._nominate_pending(pl.get("pods"), claim.name, report)
        return "finished"

    def _fence_create(self, intent: Intent) -> str:
        """Nobody is waiting for this node: delete whatever the crashed
        sequence half-built.  Ids come from stage notes when the note
        survived, else from an idempotent re-create (which returns the
        leaked resource under the same key) followed by a delete."""
        pl = intent.payload
        inst_id = (intent.notes.get("instance") or {}).get("id", "")
        if not inst_id and hasattr(self.cloud, "find_by_idempotency"):
            inst_id = self.cloud.find_by_idempotency(
                intent.idem_key("inst")) or ""
        if inst_id:
            self._delete_tolerant("delete_instance", inst_id)
            # the instance delete releases its attached VNI/volumes
            return "fenced"
        for i in range(len(pl.get("volumes") or ())):
            vid = (intent.notes.get(f"vol{i}") or {}).get("id", "")
            if not vid and intent.idem_key(f"vol{i}"):
                vid = self.cloud.create_volume(
                    volume_id=f"vol-{pl.get('node', '')}-{i}",
                    idempotency_key=intent.idem_key(f"vol{i}")).id
            if vid:
                self._delete_tolerant("delete_volume", vid)
        vni_id = (intent.notes.get("vni") or {}).get("id", "")
        if not vni_id and intent.idem_key("vni") and pl.get("subnet"):
            vni_id = self.cloud.create_vni(
                pl["subnet"], idempotency_key=intent.idem_key("vni")).id
        if vni_id:
            self._delete_tolerant("delete_vni", vni_id)
        return "fenced"

    def _delete_tolerant(self, op: str, resource_id: str) -> None:
        try:
            getattr(self.cloud, op)(resource_id)
        except Exception as e:  # noqa: BLE001 — classified below
            if not is_not_found(e):
                log.warning("recovery cleanup delete failed", op=op,
                            resource=resource_id, error=str(e)[:120])
                metrics.ERRORS.labels("recovery", "cleanup_delete").inc()

    def _recover_instance_delete(self, intent: Intent,
                                 report: RecoveryReport) -> str:
        inst_id = intent.payload.get("instance", "")
        if not inst_id:
            claim = self.cluster.get_nodeclaim(
                intent.payload.get("claim", ""))
            parsed = parse_provider_id(claim.provider_id) \
                if claim is not None else None
            inst_id = parsed[1] if parsed else ""
        if not inst_id:
            return "fenced"
        self._delete_tolerant("delete_instance", inst_id)
        return "finished"

    def _recover_eviction(self, intent: Intent,
                          report: RecoveryReport) -> str:
        """Re-pend the victims that were already evicted (idempotent);
        victims the crash spared keep their capacity — the plan's
        beneficiary context died with the process, so re-driving the
        remaining evictions would evict for nobody."""
        evicted = [d.get("pod", "") for s, d in intent.notes.items()
                   if s.startswith("evicted")]
        for key in evicted:
            p = self.cluster.get("pods", key)
            if p is not None and not p.bound_node:
                p.nominated_node = ""
                p.enqueued_at = 0.0
                report.preempted_keys.add(key)
        return "fenced"

    def _recover_gang_placement(self, intent: Intent,
                                report: RecoveryReport) -> str:
        """All-or-nothing, like the placement itself: a live claim gets
        the whole remaining membership nominated; a dead claim releases
        every member back to pending."""
        pl = intent.payload
        claim = self.cluster.get_nodeclaim(pl.get("claim", ""))
        if claim is not None and not claim.deleted:
            self._nominate_pending(pl.get("pods"), claim.name, report)
            return "finished"
        for key, p in self._pods_pending(pl.get("pods")):
            if p.nominated_node == pl.get("claim", ""):
                p.nominated_node = ""
                p.enqueued_at = 0.0
        return "fenced"

    def _recover_repack_migration(self, intent: Intent,
                                  report: RecoveryReport) -> str:
        """Conservative fence: interrupted migrations re-pend their pods
        (the next solve window re-places them against current ground
        truth); drained-source deletion is left to the consolidation
        plane, which re-derives emptiness itself."""
        for m in intent.payload.get("migrations") or ():
            key = m[0] if isinstance(m, (list, tuple)) else m
            p = self.cluster.get("pods", key)
            if p is not None and not p.bound_node:
                p.nominated_node = ""
                p.enqueued_at = 0.0
        return "fenced"

    def _fence_unknown(self, intent: Intent,
                       report: RecoveryReport) -> str:
        log.warning("unknown intent kind fenced", intent=intent.id,
                    kind=intent.kind)
        return "fenced"

    # -- journal state rebuild ---------------------------------------------

    def _rebuild_state(self, state: dict, report: RecoveryReport) -> None:
        for key, value in state.items():
            if key.startswith("nom/"):
                pod_key = key[len("nom/"):]
                p = self.cluster.get("pods", pod_key)
                claim = self.cluster.get_nodeclaim(str(value))
                if p is not None and not p.bound_node \
                        and not p.nominated_node \
                        and claim is not None and not claim.deleted:
                    p.nominated_node = claim.name
                    report.nominations_restored += 1
                elif p is None or p.bound_node:
                    self.journal.state(key, None)   # resolved: tombstone
            elif key.startswith("claimpods/"):
                claim = self.cluster.get_nodeclaim(key[len("claimpods/"):])
                if claim is not None and not claim.deleted:
                    self._nominate_pending(value, claim.name, report)
                else:
                    self.journal.state(key, None)
            elif key.startswith("preempted/"):
                pod_key = key[len("preempted/"):]
                p = self.cluster.get("pods", pod_key)
                if p is None or p.bound_node:
                    self.journal.state(key, None)
                else:
                    report.preempted_keys.add(pod_key)
            elif key.startswith("gang/admitted/"):
                report.gang_admitted[key[len("gang/admitted/"):]] = \
                    float(value)
            elif key.startswith("gang/first_seen/"):
                report.gang_parked[key[len("gang/first_seen/"):]] = \
                    float(value)
