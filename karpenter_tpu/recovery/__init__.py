"""Crash-recovery plane: write-ahead intent journal + restart reconciler.

The operator process is the last single point of silent state loss the
chaos matrix could not reach: a crash between ``rpc.create_vni`` and
``rpc.create_instance`` leaks resources, a crash after a create but
before nomination strands a node for the orphan reaper, and a restart
forgets in-flight evictions, gang admissions, and repack migrations
entirely.  This package closes that gap (docs/design/recovery.md):

- :mod:`~karpenter_tpu.recovery.journal` — an append-only JSONL
  write-ahead journal: every mutating actuation records a durable
  *intent* before its first RPC and a completion record after; cloud
  creates carry a deterministic idempotency key derived from the intent
  id so a replayed create is a lookup, not a duplicate;
- :mod:`~karpenter_tpu.recovery.reconciler` — the restart path: replay
  open intents against cloud + cluster ground truth, fence or finish
  each one, rebuild nominations / gang park state / preemption
  ``preempted_keys`` from the journal's state records, then hand off to
  the existing AOT prewarm + resident rebuild;
- :mod:`~karpenter_tpu.recovery.crashpoints` — the deterministic
  crash-injection hook the crashpoint chaos dimension
  (``chaos/crash.py``) drives.
"""

from __future__ import annotations

from karpenter_tpu.recovery.crashpoints import (  # noqa: F401
    CrashInjector, SimulatedCrash, hit, installed,
)
from karpenter_tpu.recovery.journal import (  # noqa: F401
    NULL_JOURNAL, Intent, IntentJournal, NullJournal, read_journal,
)
from karpenter_tpu.recovery.reconciler import (  # noqa: F401
    Reconciler, RecoveryReport,
)
