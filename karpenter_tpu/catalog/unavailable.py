"""Unavailable-offerings cache: TTL'd blackout set for (type, zone, capacity).

Parity with the reference's ``pkg/cache/unavailable_offerings.go:24-87`` —
the shared availability feedback channel between the catalog, interruption
controller, and spot-preemption controller (wired at operator.go:62-63).
In the TPU build this is the *writer* of the availability mask column of the
device-resident catalog tensors.
"""

from __future__ import annotations

import time
from typing import Callable, List, Tuple

from karpenter_tpu.utils.cache import TTLCache


def offering_key(instance_type: str, zone: str, capacity_type: str) -> str:
    return f"{instance_type}:{zone}:{capacity_type}"


class UnavailableOfferings:
    DEFAULT_TTL = 3600.0  # spot preemption blacks out for 1h (preemption/controller.go:97)

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._cache = TTLCache(default_ttl=self.DEFAULT_TTL, clock=clock)
        self._generation = 0

    def mark_unavailable(self, instance_type: str, zone: str, capacity_type: str,
                         ttl: float = None, reason: str = "") -> None:
        self._cache.set(offering_key(instance_type, zone, capacity_type),
                        reason or "unavailable", ttl)
        self._generation += 1

    def is_unavailable(self, instance_type: str, zone: str, capacity_type: str) -> bool:
        return self._cache.contains(offering_key(instance_type, zone, capacity_type))

    def is_unavailable_key(self, key: str) -> bool:
        return self._cache.contains(key)

    def unavailable_keys(self) -> List[str]:
        return list(self._cache.keys())

    def cleanup(self) -> int:
        """Called by the hourly catalog-refresh singleton
        (controllers/providers/instancetype/instancetype.go:58)."""
        purged = self._cache.cleanup()
        if purged:
            self._generation += 1
        return purged

    @property
    def generation(self) -> int:
        """Bumped on every write *and* on TTL expiry — lets the catalog
        arrays know when the availability mask must be re-derived.  Reading
        the generation purges expired entries first so expiry is observable
        without waiting for the hourly cleanup sweep."""
        self.cleanup()
        return self._generation
