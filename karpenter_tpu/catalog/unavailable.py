"""Unavailable-offerings cache: TTL'd blackout set for (type, zone, capacity).

Parity with the reference's ``pkg/cache/unavailable_offerings.go:24-87`` —
the shared availability feedback channel between the catalog, interruption
controller, and spot-preemption controller (wired at operator.go:62-63).
In the TPU build this is the *writer* of the availability mask column of the
device-resident catalog tensors.
"""

from __future__ import annotations

import time
from collections.abc import Callable

from karpenter_tpu.utils.cache import TTLCache


def offering_key(instance_type: str, zone: str, capacity_type: str) -> str:
    return f"{instance_type}:{zone}:{capacity_type}"


class UnavailableOfferings:
    DEFAULT_TTL = 3600.0  # spot preemption blacks out for 1h (preemption/controller.go:97)

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._cache = TTLCache(default_ttl=self.DEFAULT_TTL, clock=clock)

    def mark_unavailable(self, instance_type: str, zone: str, capacity_type: str,
                         ttl: float = None, reason: str = "") -> None:
        self._cache.set(offering_key(instance_type, zone, capacity_type),
                        reason or "unavailable", ttl)

    def is_unavailable(self, instance_type: str, zone: str, capacity_type: str) -> bool:
        return self._cache.contains(offering_key(instance_type, zone, capacity_type))

    def is_unavailable_key(self, key: str) -> bool:
        return self._cache.contains(key)

    def unavailable_keys(self) -> list[str]:
        return list(self._cache.keys())

    def cleanup(self) -> int:
        """Called by the hourly catalog-refresh singleton
        (controllers/providers/instancetype/instancetype.go:58)."""
        return self._cache.cleanup()

    @property
    def generation(self) -> frozenset:
        """The set of currently-live blackout keys.  Consumers (catalog
        arrays, availability-cached lists) compare generations for equality;
        any write *or* TTL expiry — including lazy expiry inside the cache —
        changes the value, so stale masks can never survive an expired
        blackout."""
        return frozenset(self._cache.keys())
