"""Pricing provider with TTL map + batched fetch.

Parity with ``pkg/providers/common/pricing/``: 12h TTL price map with
double-checked refresh (ibm_provider.go:34-62, :115-137), per-entry fetches
deduped and coalesced through the generic batcher (the PricingBatcher
instance: 200ms idle / 2s max / 200 items, batcher/getpricing.go:38-92),
prices uniform across zones within a region (:156-171).
"""

from __future__ import annotations

import threading
import time
from collections.abc import Callable, Sequence

from karpenter_tpu.utils.batcher import Batcher, BatcherOptions
from karpenter_tpu.utils.logging import get_logger

log = get_logger("catalog.pricing")


class PricingProvider:
    TTL = 12 * 3600.0  # 12h (ibm_provider.go:34)

    def __init__(self, client, clock: Callable[[], float] = time.monotonic,
                 batcher_options: BatcherOptions | None = None):
        self._client = client
        self._clock = clock
        self._lock = threading.Lock()
        self._refresh_lock = threading.Lock()
        self._prices: dict[str, float] = {}
        self._fetched_at: float = -1e18
        self._batcher: Batcher = Batcher(
            self._fetch_batch,
            batcher_options or BatcherOptions(idle_timeout=0.2, max_timeout=2.0,
                                              max_items=200, name="pricing"))

    # -- public (provider.go:26-35) ---------------------------------------

    def get_price(self, instance_type: str, zone: str = "") -> float:
        """$/h on-demand; zone-uniform within the region (:156-171).
        Returns 0.0 when unknown (callers rank price-less types by size)."""
        self._ensure_fresh()
        with self._lock:
            return self._prices.get(instance_type, 0.0)

    def get_prices(self, zone: str = "") -> dict[str, float]:
        self._ensure_fresh()
        with self._lock:
            return dict(self._prices)

    def refresh(self) -> None:
        """Force re-fetch regardless of TTL (12h singleton hook,
        controllers/providers/pricing/controller.go:62)."""
        self._fetch_all(force=True)

    def close(self) -> None:
        self._batcher.close()

    # -- internals ---------------------------------------------------------

    def _ensure_fresh(self) -> None:
        with self._lock:
            fresh = self._clock() - self._fetched_at < self.TTL
        if not fresh:
            self._fetch_all()

    def _fetch_all(self, force: bool = False) -> None:
        # Double-checked refresh: one thread fetches, concurrent callers
        # block on the refresh lock and see fresh data when it releases
        # (:115-137).
        with self._refresh_lock:
            with self._lock:
                if not force and self._clock() - self._fetched_at < self.TTL \
                        and self._prices:
                    return
            # deliberate: _refresh_lock serializes REFRESHERS across this
            # RPC (double-checked refresh); readers only take _lock and
            # never stall behind it
            names = [p.name for p in self._client.list_instance_profiles()]  # graftlint: disable=GL101
            # dedupe (getpricing.go dedups by catalog entry id)
            futures = {n: self._batcher.add(n) for n in dict.fromkeys(names)}
            prices = {}
            for name, fut in futures.items():
                try:
                    value = fut.result(timeout=30)
                    if value is not None:
                        prices[name] = value
                except Exception as e:  # batch-level failure is non-fatal too
                    log.warning("pricing fetch failed", type=name, error=str(e))
            with self._lock:
                self._prices.update(prices)
                self._fetched_at = self._clock()
            log.info("pricing refreshed", entries=len(prices))

    def _fetch_batch(self, names: Sequence[str]) -> list[float | None]:
        # Per-item isolation: one failing entry must not poison the whole
        # window (the batcher propagates a handler exception to every
        # caller in the batch).
        out: list[float | None] = []
        for n in names:
            try:
                out.append(self._client.get_pricing(n))
            except Exception as e:  # noqa: BLE001 — miss is non-fatal
                log.warning("pricing fetch failed", type=n, error=str(e))
                out.append(None)
        return out


class StaticPricingProvider:
    """NoOp/static fallback (ref pricing controller fallback,
    controllers/providers/pricing/controller.go:38-50)."""

    def __init__(self, prices: dict[str, float] | None = None):
        self._prices = dict(prices or {})

    def get_price(self, instance_type: str, zone: str = "") -> float:
        return self._prices.get(instance_type, 0.0)

    def get_prices(self, zone: str = "") -> dict[str, float]:
        return dict(self._prices)

    def refresh(self) -> None:
        pass

    def close(self) -> None:
        pass
