"""Dense catalog tensors: the device-resident offering matrix.

SURVEY.md §3.5: periodic refreshers "write the device-resident catalog
tensors (types x zones x {cpu, mem, gpu, price_ondemand, price_spot,
avail})".  This module flattens the host ``InstanceType`` catalog into
structure-of-arrays form over the *offering* axis (type x zone x
capacity-type) that the solver consumes directly:

- integer allocatable capacity (milliCPU, MiB, gpu, pod slots) — exact
  integer arithmetic on device, no float floor hazards;
- float32 price vector with spot discounting already applied;
- boolean availability mask, refreshable in O(O) from the
  UnavailableOfferings blackout set without rebuilding the catalog;
- vocabularies (type/zone/arch/family/size names -> indices) so host-side
  requirements can be lowered to per-offering boolean masks.

Arrays are numpy on host; the solver moves them to device once per catalog
generation and keeps them resident between solves (SURVEY.md §7.4
"host<->device boundary").
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from collections.abc import Sequence

import numpy as np

from karpenter_tpu.apis.pod import NUM_RESOURCES
from karpenter_tpu.apis.requirements import (
    CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT,
    LABEL_ARCH, LABEL_CAPACITY_TYPE, LABEL_INSTANCE_FAMILY, LABEL_INSTANCE_SIZE,
    LABEL_INSTANCE_TYPE, LABEL_ZONE,
)
from karpenter_tpu.catalog.instancetype import InstanceType

CAPACITY_TYPES = (CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT)

_uid_counter = itertools.count(1)


@dataclass
class CatalogArrays:
    """Structure-of-arrays catalog over the offering axis."""

    # per-type
    type_names: list[str]
    type_alloc: np.ndarray          # int32 [T, R] allocatable (cpu_m, mem_mib, gpu, pods)
    type_arch: np.ndarray           # int32 [T] -> arch vocab index
    type_family: np.ndarray         # int32 [T] -> family vocab index
    type_size: np.ndarray           # int32 [T] -> size vocab index
    # per-offering (flattened type x zone x captype, only existing offerings)
    off_type: np.ndarray            # int32 [O]
    off_zone: np.ndarray            # int32 [O] -> zone vocab index
    off_cap: np.ndarray             # int32 [O] 0=on-demand 1=spot
    off_price: np.ndarray           # float32 [O] $/h (0 = unknown)
    off_avail: np.ndarray           # bool [O]
    # vocabularies
    zones: list[str]
    archs: list[str]
    families: list[str]
    sizes: list[str]
    # per-type accelerator torus dims (gang slice placement;
    # gang/topology.py lowers these to placement bitmask tables).  Host
    # list, not a device tensor: only the gang encoder consumes it.
    type_torus: list[tuple[int, ...]] = field(default_factory=list)
    # spot-risk ranking column (karpenter_tpu/stochastic/risk.py):
    # float32 [O] expected-eviction penalty per offering (0 = no
    # observed risk / on-demand).  Enters RANKING only — real cost
    # accounting (off_price) never moves.  risk_generation keys the
    # solver's device-resident rank tensors, so a re-priced model
    # re-uploads instead of serving stale ranks.
    off_risk: np.ndarray | None = None
    risk_generation: int = 0
    # provenance
    generation: int = 0
    availability_generation: object = None
    uid: int = -1                   # unique per build() — device-cache key
    _offering_index: dict[tuple[str, str, str], int] = field(default_factory=dict)

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, instance_types: Sequence[InstanceType],
              generation: int = 0) -> "CatalogArrays":
        type_names = [it.name for it in instance_types]
        zones = sorted({o.zone for it in instance_types for o in it.offerings})
        archs = sorted({it.architecture for it in instance_types})
        families = sorted({it.family for it in instance_types})
        sizes = sorted({it.size for it in instance_types})
        zone_idx = {z: i for i, z in enumerate(zones)}
        arch_idx = {a: i for i, a in enumerate(archs)}
        family_idx = {f: i for i, f in enumerate(families)}
        size_idx = {s: i for i, s in enumerate(sizes)}

        T = len(instance_types)
        type_alloc = np.zeros((T, NUM_RESOURCES), dtype=np.int32)
        type_arch = np.zeros(T, dtype=np.int32)
        type_family = np.zeros(T, dtype=np.int32)
        type_size = np.zeros(T, dtype=np.int32)
        off_type, off_zone, off_cap, off_price, off_avail = [], [], [], [], []
        offering_index: dict[tuple[str, str, str], int] = {}

        for t, it in enumerate(instance_types):
            type_alloc[t] = (it.allocatable_cpu_milli, it.allocatable_memory_mib,
                             it.gpu, it.pods)
            type_arch[t] = arch_idx[it.architecture]
            type_family[t] = family_idx[it.family]
            type_size[t] = size_idx[it.size]
            for o in it.offerings:
                offering_index[(it.name, o.zone, o.capacity_type)] = len(off_type)
                off_type.append(t)
                off_zone.append(zone_idx[o.zone])
                off_cap.append(CAPACITY_TYPES.index(o.capacity_type))
                off_price.append(o.price)
                off_avail.append(o.available)

        return cls(
            type_names=type_names,
            type_alloc=type_alloc,
            type_arch=type_arch, type_family=type_family, type_size=type_size,
            off_type=np.asarray(off_type, dtype=np.int32),
            off_zone=np.asarray(off_zone, dtype=np.int32),
            off_cap=np.asarray(off_cap, dtype=np.int32),
            off_price=np.asarray(off_price, dtype=np.float32),
            off_avail=np.asarray(off_avail, dtype=bool),
            zones=zones, archs=archs, families=families, sizes=sizes,
            type_torus=[it.torus_dims for it in instance_types],
            generation=generation, uid=next(_uid_counter),
            _offering_index=offering_index,
        )

    # -- views -------------------------------------------------------------

    @property
    def num_types(self) -> int:
        return len(self.type_names)

    @property
    def num_offerings(self) -> int:
        return int(self.off_type.shape[0])

    def offering_alloc(self) -> np.ndarray:
        """int32 [O, R] allocatable capacity per offering.  Memoized:
        type_alloc/off_type are immutable after build(), and encode calls
        this once per pod-signature group."""
        cached = getattr(self, "_alloc_cache", None)
        if cached is None:
            cached = self.type_alloc[self.off_type]
            self._alloc_cache = cached
        return cached

    def offering_rank_price(self) -> np.ndarray:
        """float32 [O] price used for *ranking only*: real price when known,
        else a size-proportional pseudo-price (cpu cores + mem GiB), mirroring
        the reference's fallback ranking for unpriced types
        (instancetype.go:88-110).  Plan cost accounting still uses
        ``off_price`` (0 for unknown), matching the reference's Offering
        semantics."""
        alloc = self.offering_alloc().astype(np.float32)
        pseudo = alloc[:, 0] / 1000.0 + alloc[:, 1] / 1024.0
        rank = np.where(self.off_price > 0, self.off_price,
                        pseudo).astype(np.float32)
        if self.off_risk is not None:
            # expected eviction cost (stochastic/risk.py): an offering
            # observed interrupted r of the time ranks as if its price
            # carried the replacement churn — ranking only, cost
            # accounting untouched
            rank = (rank * (1.0 + self.off_risk)).astype(np.float32)
        return rank

    def offering_label_values(self, o: int) -> dict[str, str]:
        """Node label values an offering would produce — the host-side
        bridge for lowering Requirements into masks."""
        t = int(self.off_type[o])
        return {
            LABEL_INSTANCE_TYPE: self.type_names[t],
            LABEL_ARCH: self.archs[int(self.type_arch[t])],
            LABEL_INSTANCE_FAMILY: self.families[int(self.type_family[t])],
            LABEL_INSTANCE_SIZE: self.sizes[int(self.type_size[t])],
            LABEL_ZONE: self.zones[int(self.off_zone[o])],
            LABEL_CAPACITY_TYPE: CAPACITY_TYPES[int(self.off_cap[o])],
        }

    def describe_offering(self, o: int) -> tuple[str, str, str]:
        t = int(self.off_type[o])
        return (self.type_names[t], self.zones[int(self.off_zone[o])],
                CAPACITY_TYPES[int(self.off_cap[o])])

    def describe_offerings(self, offs: np.ndarray):
        """Vectorized :meth:`describe_offering` over an index array —
        returns (type_names, zones, captypes, prices) as host lists.
        The per-offering string columns are materialized once per
        catalog (object arrays; ~O strings) so a decode touching
        hundreds of nodes costs four fancy-index gathers instead of
        per-node Python lookups (the decode hot path, VERDICT round 4
        item 1: host-side Python overhead rivals chip time)."""
        cached = getattr(self, "_desc_cache", None)
        if cached is None:
            cached = (np.array(self.type_names, object)[self.off_type],
                      np.array(self.zones, object)[self.off_zone],
                      np.array(CAPACITY_TYPES, object)[self.off_cap])
            self._desc_cache = cached
        tn, zn, cn = cached
        return (tn[offs].tolist(), zn[offs].tolist(), cn[offs].tolist(),
                self.off_price[offs].tolist())

    def find_offering(self, instance_type: str, zone: str, capacity_type: str) -> int | None:
        return self._offering_index.get((instance_type, zone, capacity_type))

    # -- availability refresh ---------------------------------------------

    def refresh_availability(self, unavailable) -> bool:
        """Re-derive the availability column from the blackout set; returns
        True when the mask changed (caller re-uploads to device)."""
        # capture the generation ONCE and derive the mask from that same
        # frozenset — reading keys and generation separately lets a TTL
        # expire in between, recording a generation the mask doesn't match
        gen = unavailable.generation
        if gen == self.availability_generation:
            return False
        mask = np.ones(self.num_offerings, dtype=bool)
        for key in gen:
            parts = key.split(":")
            if len(parts) != 3:
                continue
            idx = self._offering_index.get((parts[0], parts[1], parts[2]))
            if idx is not None:
                mask[idx] = False
        changed = not np.array_equal(mask, self.off_avail)
        self.off_avail = mask
        self.availability_generation = gen
        return changed
