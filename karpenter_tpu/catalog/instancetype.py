"""Instance-type catalog: profiles -> InstanceTypes with offerings.

Capability parity with ``pkg/providers/common/instancetype/instancetype.go``:
- profile -> capacity conversion with the pods heuristic (30/60/110 by cpu,
  instancetype.go:711-718) and family/size labels (:862-880);
- per-zone x per-capacity-type offerings with spot price = on-demand x
  discount% and availability from UnavailableOfferings (:749-773);
- kubelet-config-driven overhead (defaults kube/system-reserved 100m+1Gi
  each, eviction 500Mi — :792-858);
- FilterInstanceTypes by InstanceRequirements incl. price ceiling (:259-356);
- cost-efficiency ranking score = avg(price/cpu, price/memGB), lower better,
  falling back to cpu+memGB when price unknown (:88-110);
- exponential-backoff retry around the cloud list call (:440-446).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from collections.abc import Sequence

from karpenter_tpu.apis.nodeclass import InstanceRequirements, KubeletConfig, NodeClass
from karpenter_tpu.apis.pod import parse_cpu_milli, parse_memory_mib
from karpenter_tpu.apis.requirements import (
    CAPACITY_TYPE_ON_DEMAND, CAPACITY_TYPE_SPOT,
    LABEL_ARCH, LABEL_CAPACITY_TYPE, LABEL_INSTANCE_FAMILY, LABEL_INSTANCE_SIZE,
    LABEL_INSTANCE_TYPE, LABEL_ZONE, Requirements,
)
from karpenter_tpu.cloud.profile import InstanceProfile
from karpenter_tpu.cloud.retry import retry_with_backoff
from karpenter_tpu.utils.cache import TTLCache
from karpenter_tpu.utils.logging import get_logger

log = get_logger("catalog.instancetype")

DEFAULT_SPOT_DISCOUNT_PERCENT = 60  # options.go:76


def profile_family(name: str) -> str:
    """"bx2-4x16" -> "bx2" (instancetype.go:862-868)."""
    head = name.split("-", 1)[0]
    return head if head else "balanced"


def profile_size(name: str) -> str:
    """"bx2-4x16" -> "4x16" (instancetype.go:871-880)."""
    i = name.find("-")
    return name[i + 1:] if 0 <= i < len(name) - 1 else "small"


def pods_capacity(cpu: int) -> int:
    """Pods-per-node heuristic (instancetype.go:711-718)."""
    if cpu <= 2:
        return 30
    if cpu <= 4:
        return 60
    return 110


def default_torus(chips: int) -> tuple[int, ...]:
    """Deterministic torus dims for a type exposing ``chips``
    accelerators, following real TPU slice geometry: perfect-square
    power-of-two counts are 2-D meshes (4 -> (2, 2), 16 -> (4, 4),
    64 -> (8, 8) — the v5e shapes), other powers of two factor into
    <= 3 near-cubic axes largest-first (8 -> (2, 2, 2), 32 -> (4, 4, 2)),
    and non-power-of-two counts fall back to a 1-D ring.  The gang
    plane's topology layer (gang/topology.py) enumerates contiguous
    sub-slices against these dims; a type with no accelerators has no
    torus and can never host a slice-shaped gang."""
    if chips <= 0:
        return ()
    if chips & (chips - 1):          # not a power of two: 1-D ring
        return (chips,)
    root = math.isqrt(chips)
    if root * root == chips and root >= 2:
        return (root, root)
    dims = [1, 1, 1]
    i = 0
    n = chips
    while n > 1:
        dims[i % 3] *= 2
        n //= 2
        i += 1
    dims = sorted((d for d in dims if d > 1), reverse=True)
    return tuple(dims) if dims else (1,)


@dataclass(frozen=True)
class Offering:
    zone: str
    capacity_type: str           # on-demand | spot
    price: float                 # $/hour
    available: bool = True


@dataclass
class InstanceType:
    """A schedulable instance type: capacity + requirements + offerings."""

    name: str
    cpu_milli: int
    memory_mib: int
    gpu: int
    pods: int
    architecture: str
    family: str
    size: str
    offerings: list[Offering] = field(default_factory=list)
    # overhead (reserved out of capacity before pods fit)
    overhead_cpu_milli: int = 0
    overhead_memory_mib: int = 0
    # accelerator torus dims (gang slice placement); None = derive from
    # the accelerator count via default_torus(), () = no torus
    torus: tuple[int, ...] | None = None

    @property
    def torus_dims(self) -> tuple[int, ...]:
        return self.torus if self.torus is not None else default_torus(self.gpu)

    @property
    def allocatable_cpu_milli(self) -> int:
        return max(0, self.cpu_milli - self.overhead_cpu_milli)

    @property
    def allocatable_memory_mib(self) -> int:
        return max(0, self.memory_mib - self.overhead_memory_mib)

    def label_values(self) -> dict[str, str]:
        return {
            LABEL_INSTANCE_TYPE: self.name,
            LABEL_ARCH: self.architecture,
            LABEL_INSTANCE_FAMILY: self.family,
            LABEL_INSTANCE_SIZE: self.size,
        }

    def cheapest_offering(self) -> Offering | None:
        avail = [o for o in self.offerings if o.available and o.price > 0]
        return min(avail, key=lambda o: o.price) if avail else None


def compute_overhead(kubelet: KubeletConfig | None) -> tuple[int, int]:
    """-> (cpu_milli, memory_mib) reserved (instancetype.go:792-858).

    Defaults: kubeReserved 100m/1Gi + systemReserved 100m/1Gi +
    evictionHard memory 500Mi.
    """
    def parse_or(d, key, parser, default):
        # Per-field fallback (the reference keeps the default for each
        # malformed quantity individually, instancetype.go:801-843).
        if key not in d:
            return default
        try:
            return parser(d[key])
        except ValueError as e:
            log.warning("invalid kubelet reservation, using default",
                        key=key, value=d[key], error=str(e))
            return default

    kube = dict(kubelet.kube_reserved) if kubelet else {}
    system = dict(kubelet.system_reserved) if kubelet else {}
    evict = dict(kubelet.eviction_hard) if kubelet else {}
    kube_cpu = parse_or(kube, "cpu", parse_cpu_milli, 100)
    kube_mem = parse_or(kube, "memory", parse_memory_mib, 1024)
    sys_cpu = parse_or(system, "cpu", parse_cpu_milli, 100)
    sys_mem = parse_or(system, "memory", parse_memory_mib, 1024)
    evict_mem = parse_or(evict, "memory.available", parse_memory_mib, 500)
    return kube_cpu + sys_cpu, kube_mem + sys_mem + evict_mem


def instance_type_score(it: InstanceType, price: float) -> float:
    """Cost-efficiency rank, lower better (instancetype.go:88-110)."""
    cpu = it.cpu_milli / 1000.0
    mem_gb = it.memory_mib / 1024.0
    if price <= 0:
        return cpu + mem_gb
    return (price / max(cpu, 1e-9) + price / max(mem_gb, 1e-9)) / 2.0


def filter_instance_types(types: Sequence[InstanceType],
                          reqs: InstanceRequirements) -> list[InstanceType]:
    """Auto-selection filter (instancetype.go:259-356): architecture, minCPU,
    minMemory, maxHourlyPrice (vs cheapest available offering), gpu."""
    out = []
    for it in types:
        if reqs.architecture and it.architecture != reqs.architecture:
            continue
        if reqs.min_cpu and it.cpu_milli < reqs.min_cpu * 1000:
            continue
        if reqs.min_memory_gib and it.memory_mib < reqs.min_memory_gib * 1024:
            continue
        if reqs.gpu and it.gpu == 0:
            continue
        if reqs.max_hourly_price > 0:
            cheapest = it.cheapest_offering()
            if cheapest is None or cheapest.price > reqs.max_hourly_price:
                continue
        out.append(it)
    # Rank by cost efficiency (instancetype.go:359).
    def key(it: InstanceType):
        o = it.cheapest_offering()
        return instance_type_score(it, o.price if o else 0.0)
    out.sort(key=key)
    return out


class InstanceTypeProvider:
    """Builds and caches the InstanceType catalog from the cloud client.

    Ref ``NewProvider`` instancetype.go:71; list retry :440-446; zone cache
    1h :594-648; 30m catalog TTL.
    """

    def __init__(self, client, pricing_provider, unavailable: "UnavailableOfferings" = None,
                 spot_discount_percent: int = DEFAULT_SPOT_DISCOUNT_PERCENT,
                 catalog_ttl: float = 1800.0, clock=None):
        from karpenter_tpu.catalog.unavailable import UnavailableOfferings
        self._client = client
        self._pricing = pricing_provider
        self._unavailable = unavailable or UnavailableOfferings()
        self._spot_discount = spot_discount_percent or DEFAULT_SPOT_DISCOUNT_PERCENT
        self._clock = clock
        self._cache = TTLCache(default_ttl=catalog_ttl,
                               **({"clock": clock} if clock else {}))
        self._zone_cache = TTLCache(default_ttl=3600.0,
                                    **({"clock": clock} if clock else {}))
        self._avail_cache: dict = {}

    @property
    def unavailable_offerings(self):
        return self._unavailable

    def zones(self) -> list[str]:
        return self._zone_cache.get_or_set(
            "zones", lambda: retry_with_backoff(self._client.list_zones))

    def list(self, nodeclass: NodeClass | None = None) -> list[InstanceType]:
        """Full catalog with offerings; availability is re-applied whenever
        the blackout set changes (cheap equality check on its generation, so
        steady-state list() calls return the cached objects)."""
        kubelet = nodeclass.spec.kubelet if nodeclass else None
        key = ("catalog", self._kubelet_key(kubelet))
        base: list[InstanceType] = self._cache.get_or_set(
            key, lambda: self._build(kubelet))
        gen = self._unavailable.generation
        cached = self._avail_cache.get(key)
        if cached is not None and cached[0] == gen and cached[1] is base:
            return cached[2]
        applied = [self._with_fresh_availability(it) for it in base]
        self._avail_cache[key] = (gen, base, applied)
        return applied

    def get(self, name: str, nodeclass: NodeClass | None = None) -> InstanceType | None:
        for it in self.list(nodeclass):
            if it.name == name:
                return it
        return None

    def refresh(self) -> None:
        """Hourly singleton hook (controllers/providers/instancetype)."""
        self._cache = TTLCache(default_ttl=self._cache._default_ttl,
                               **({"clock": self._clock} if self._clock else {}))
        self._unavailable.cleanup()

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _kubelet_key(kubelet: KubeletConfig | None):
        return kubelet if kubelet is None else (
            kubelet.max_pods, kubelet.system_reserved, kubelet.kube_reserved,
            kubelet.eviction_hard)

    def _build(self, kubelet: KubeletConfig | None) -> list[InstanceType]:
        profiles: list[InstanceProfile] = retry_with_backoff(
            self._client.list_instance_profiles)
        zones = self.zones()
        if not zones:
            raise RuntimeError(f"no zones found for region {self._client.region}")
        oh_cpu, oh_mem = compute_overhead(kubelet)
        out = []
        for p in profiles:
            pods = kubelet.max_pods if (kubelet and kubelet.max_pods) else pods_capacity(p.cpu)
            it = InstanceType(
                name=p.name,
                cpu_milli=p.cpu * 1000,
                memory_mib=p.memory_gib * 1024,
                gpu=p.gpu,
                pods=pods,
                architecture=p.architecture,
                family=profile_family(p.name),
                size=profile_size(p.name),
                overhead_cpu_milli=oh_cpu,
                overhead_memory_mib=oh_mem,
            )
            caps = [CAPACITY_TYPE_ON_DEMAND] + (
                [CAPACITY_TYPE_SPOT] if p.supports_spot else [])
            for zone in zones:
                od_price = self._pricing.get_price(p.name, zone)
                for cap in caps:
                    price = od_price
                    if cap == CAPACITY_TYPE_SPOT:
                        price = od_price * self._spot_discount / 100.0
                    it.offerings.append(Offering(zone=zone, capacity_type=cap,
                                                 price=price, available=True))
            out.append(it)
        log.info("built instance-type catalog", types=len(out), zones=len(zones))
        return out

    def _with_fresh_availability(self, it: InstanceType) -> InstanceType:
        offerings = [
            Offering(o.zone, o.capacity_type, o.price,
                     available=not self._unavailable.is_unavailable(
                         it.name, o.zone, o.capacity_type))
            for o in it.offerings
        ]
        return InstanceType(
            name=it.name, cpu_milli=it.cpu_milli, memory_mib=it.memory_mib,
            gpu=it.gpu, pods=it.pods, architecture=it.architecture,
            family=it.family, size=it.size, offerings=offerings,
            overhead_cpu_milli=it.overhead_cpu_milli,
            overhead_memory_mib=it.overhead_memory_mib, torus=it.torus)
