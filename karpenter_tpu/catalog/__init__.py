from karpenter_tpu.catalog.instancetype import (
    InstanceProfile, InstanceType, Offering, InstanceTypeProvider,
    instance_type_score, filter_instance_types,
)
from karpenter_tpu.catalog.pricing import PricingProvider, StaticPricingProvider
from karpenter_tpu.catalog.unavailable import UnavailableOfferings, offering_key
from karpenter_tpu.catalog.arrays import CatalogArrays

__all__ = [
    "InstanceProfile", "InstanceType", "Offering", "InstanceTypeProvider",
    "instance_type_score", "filter_instance_types",
    "PricingProvider", "StaticPricingProvider",
    "UnavailableOfferings", "offering_key", "CatalogArrays",
]
