"""Host-side gang encoding: pending gang pods -> dense gang tensors.

Mirrors ``solver/encode.py``'s division of labor: the relational world
(requirements, taints, gang membership) is lowered ONCE on the host into
tensors the placement grid consumes with no per-host loops:

- ``gang_req``    int64 [Ng, R]  TOTAL resource demand of the gang
                                 (every member lands on one node);
- ``gang_size``   int32 [Ng]     members present in this plan window;
- ``gang_min``    int32 [Ng]     the PodGroup's min_member;
- ``gang_prio``   int32 [Ng]     max member priority;
- ``compat``      bool  [Ng, O]  offering feasibility (labels,
                                 availability, empty-node TOTAL fit);
- per-gang :class:`SliceTable` reference (shared across gangs of one
  shape) for the topology term.

Gangs are ordered priority DESC, then slice chips DESC, then dominant
resource share DESC, then name — the canonical order both planner paths
consume, so plans are comparable (the FFD-order analogue).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.apis.nodeclaim import NodePool
from karpenter_tpu.apis.pod import (
    NUM_RESOURCES, PodSpec, pod_key, tolerates_all,
)
from karpenter_tpu.catalog.arrays import CatalogArrays
from karpenter_tpu.gang.topology import SliceTable, slice_table
from karpenter_tpu.solver.encode import _nozone_compat

_EMPTY_SHAPE: tuple[int, ...] = ()


@dataclass(slots=True)
class GangInfo:
    """One gang's host-side record (names; tensors live on the problem)."""

    name: str
    pod_names: list[str]
    min_member: int
    shape: tuple[int, ...]
    chips: int
    priority: int


@dataclass
class GangProblem:
    """Dense atomic-placement input (see module docstring)."""

    gangs: list[GangInfo]
    gang_req: np.ndarray                 # int64 [Ng, R]
    gang_size: np.ndarray                # int32 [Ng]
    gang_min: np.ndarray                 # int32 [Ng]
    gang_prio: np.ndarray                # int32 [Ng]
    compat: np.ndarray                   # bool  [Ng, O]
    tables: list[SliceTable | None] = field(default_factory=list)  # [Ng]
    catalog: CatalogArrays = None
    rejected: list[str] = field(default_factory=list)   # pod keys

    @property
    def num_gangs(self) -> int:
        return len(self.gangs)


def _member_req(pod: PodSpec) -> np.ndarray:
    req = pod.requests.as_tuple()
    return np.array((req[0], req[1], req[2], max(req[3], 1)), dtype=np.int64)


def encode_gangs(pods: list[PodSpec], catalog: CatalogArrays,
                 nodepool: NodePool | None = None) -> GangProblem:
    """Group pending gang pods by PodGroup name and lower to tensors.

    Pods without a gang are ignored (they belong to the ordinary solve);
    members that do not tolerate the pool's taints reject the WHOLE gang
    (all-or-nothing admission: a gang that cannot fully run here must
    not half-run here).
    """
    nodepool = nodepool or NodePool(name="default")
    by_name: dict[str, list[PodSpec]] = {}
    for p in pods:
        if p.gang is not None:
            by_name.setdefault(p.gang.name, []).append(p)

    gangs: list[GangInfo] = []
    rows_req: list[np.ndarray] = []
    rows_compat: list[np.ndarray] = []
    tables: list[SliceTable | None] = []
    rejected: list[str] = []
    mask_cache: dict = {}
    O = catalog.num_offerings
    for name in by_name:
        members = by_name[name]
        rep = members[0]
        spec = rep.gang
        if nodepool.taints and any(
                not tolerates_all(p.tolerations, nodepool.taints)
                for p in members):
            rejected.extend(pod_key(p) for p in members)
            continue
        total = np.zeros(NUM_RESOURCES, dtype=np.int64)
        for p in members:
            total += _member_req(p)
        reqs = rep.scheduling_requirements().merged(nodepool.requirements)
        compat = _nozone_compat(reqs, tuple(int(v) for v in total),
                                catalog, mask_cache).copy()
        shape = spec.slice_shape or _EMPTY_SHAPE
        table = None
        if shape:
            table = slice_table(catalog, shape)
            compat &= table.count > 0
        gangs.append(GangInfo(
            name=name, pod_names=[pod_key(p) for p in members],
            min_member=spec.min_member, shape=shape,
            chips=spec.chips, priority=max(p.priority for p in members)))
        rows_req.append(total)
        rows_compat.append(compat)
        tables.append(table)

    Ng = len(gangs)
    gang_req = (np.stack(rows_req) if Ng
                else np.zeros((0, NUM_RESOURCES), np.int64))
    compat = (np.stack(rows_compat) if Ng
              else np.zeros((0, O), dtype=bool))
    gang_size = np.array([len(g.pod_names) for g in gangs], dtype=np.int32)
    gang_min = np.array([g.min_member for g in gangs], dtype=np.int32)
    gang_prio = np.array([g.priority for g in gangs], dtype=np.int32)
    if Ng:
        # canonical order: priority DESC, chips DESC, dominant share
        # DESC, name ASC — deterministic, shared by both planner paths
        mean_alloc = catalog.type_alloc.mean(axis=0) if catalog.num_types \
            else np.ones(NUM_RESOURCES)
        shares = np.where(mean_alloc[None, :] > 0,
                          gang_req.astype(np.float64)
                          / np.maximum(mean_alloc, 1e-12)[None, :],
                          0.0).max(axis=1)
        chips = np.array([g.chips for g in gangs], dtype=np.int64)
        order = np.lexsort((np.array([g.name for g in gangs]), -shares,
                            -chips, -gang_prio.astype(np.int64)))
        gangs = [gangs[i] for i in order]
        tables = [tables[i] for i in order]
        gang_req = np.ascontiguousarray(gang_req[order])
        gang_size = np.ascontiguousarray(gang_size[order])
        gang_min = np.ascontiguousarray(gang_min[order])
        gang_prio = np.ascontiguousarray(gang_prio[order])
        compat = np.ascontiguousarray(compat[order])
    return GangProblem(gangs=gangs, gang_req=gang_req, gang_size=gang_size,
                       gang_min=gang_min, gang_prio=gang_prio, compat=compat,
                       tables=tables, catalog=catalog, rejected=rejected)
