"""Slice topology: per-type torus dims -> placement bitmask tables.

The catalog carries each instance type's accelerator torus dims
(``CatalogArrays.type_torus``, derived from the accelerator count or set
explicitly on the ``InstanceType``).  This module lowers a gang's
``slice_shape`` against those tori ONCE into dense bitmask tensors:

- every *placement* of shape ``s`` in torus ``t`` (axis-aligned
  contiguous sub-block, every distinct axis permutation of ``s`` that
  fits, no wraparound) becomes one uint64 chip bitmask;
- per catalog + shape, the placements of every offering stack into a
  padded ``masks uint64 [O, Pmax]`` + ``valid bool [O, Pmax]`` table
  (:class:`SliceTable`), cached per catalog generation.

"Does offering ``o`` still fit shape ``s`` under occupancy ``occ``" is
then one batched AND over the ``[offerings, placements]`` grid —
``(masks & occ[:, None]) == 0`` — with no host loops on the hot path;
the planner's device kernel runs the identical integer arithmetic on
chip (gang/planner.py).

Tori are capped at 64 chips so one mask word covers any placement
(``apis/podgroup.MAX_SLICE_CHIPS`` rejects larger shapes at admission);
a type whose torus exceeds the cap simply exposes no placements.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass

import numpy as np

from karpenter_tpu.catalog.arrays import CatalogArrays

MAX_TORUS_CHIPS = 64

# (torus dims, shape) -> tuple of placement masks; both keys are tiny
# tuples, and distinct (torus, shape) pairs number in the dozens — the
# enumeration is pure combinatorics, valid forever
_PLACEMENT_CACHE: dict[tuple, tuple[int, ...]] = {}
# (catalog uid, generation, shape) -> SliceTable
_TABLE_CACHE: dict[tuple, "SliceTable"] = {}
_TABLE_CACHE_MAX = 32


@dataclass(frozen=True)
class SliceTable:
    """Padded per-offering placement bitmasks for ONE slice shape."""

    shape: tuple[int, ...]
    masks: np.ndarray        # uint64 [O, Pmax]; 0 where invalid
    valid: np.ndarray        # bool   [O, Pmax]
    count: np.ndarray        # int32  [O] valid placements per offering

    @property
    def pmax(self) -> int:
        return int(self.masks.shape[1])

    def free_grid(self, occupancy: np.ndarray) -> np.ndarray:
        """bool [O, Pmax]: placement p of offering o is valid AND chip-
        disjoint from ``occupancy`` (uint64 [O]) — THE batched fit test."""
        return self.valid & ((self.masks & occupancy[:, None]) == 0)

    def fits(self, occupancy: np.ndarray) -> np.ndarray:
        """bool [O]: some placement is still free under ``occupancy``."""
        return self.free_grid(occupancy).any(axis=1)


def _chip_index(dims: tuple[int, ...]) -> np.ndarray:
    """Row-major chip numbering of the torus grid."""
    return np.arange(math.prod(dims)).reshape(dims)


def enumerate_placements(torus: tuple[int, ...],
                         shape: tuple[int, ...]) -> tuple[int, ...]:
    """Every contiguous axis-aligned placement of ``shape`` in ``torus``
    as chip bitmasks, deduplicated, ascending — the deterministic order
    every planner path and the validator share.

    Distinct axis permutations of ``shape`` count as distinct
    orientations (a 2x4 job fits a 4x2 window); wraparound placements
    are excluded (a production slice must be physically contiguous).
    """
    if not torus or math.prod(torus) > MAX_TORUS_CHIPS:
        return ()
    key = (torus, shape)
    hit = _PLACEMENT_CACHE.get(key)
    if hit is not None:
        return hit
    idx = _chip_index(torus)
    masks: set[int] = set()
    for perm in sorted(set(itertools.permutations(shape))):
        if len(perm) > len(torus):
            continue
        # right-align the shape onto the torus axes, leading axes size 1
        full = (1,) * (len(torus) - len(perm)) + perm
        if any(s > t for s, t in zip(full, torus)):
            continue
        origins = [range(t - s + 1) for s, t in zip(full, torus)]
        for origin in itertools.product(*origins):
            block = idx[tuple(slice(o, o + s)
                              for o, s in zip(origin, full))]
            mask = 0
            for c in block.ravel().tolist():
                mask |= 1 << c
            masks.add(mask)
    out = tuple(sorted(masks))
    _PLACEMENT_CACHE[key] = out
    return out


def type_placements(catalog: CatalogArrays, t: int,
                    shape: tuple[int, ...]) -> tuple[int, ...]:
    """Placement masks of ``shape`` on type ``t``'s torus (possibly ())."""
    tori = catalog.type_torus
    torus = tori[t] if t < len(tori) else ()
    return enumerate_placements(tuple(torus), shape)


def slice_table(catalog: CatalogArrays,
                shape: tuple[int, ...]) -> SliceTable:
    """The ``[offerings, placements]`` bitmask table for ``shape``,
    memoized per catalog generation (offerings of one type share that
    type's placements; the table is availability-independent — blackouts
    gate *creates*, not geometry)."""
    key = (catalog.uid, catalog.generation, shape)
    hit = _TABLE_CACHE.get(key)
    if hit is not None:
        return hit
    per_type = [type_placements(catalog, t, shape)
                for t in range(catalog.num_types)]
    pmax = max((len(p) for p in per_type), default=0)
    O = catalog.num_offerings
    masks = np.zeros((O, max(pmax, 1)), dtype=np.uint64)
    valid = np.zeros((O, max(pmax, 1)), dtype=bool)
    for o in range(O):
        plc = per_type[int(catalog.off_type[o])]
        if plc:
            masks[o, :len(plc)] = np.array(plc, dtype=np.uint64)
            valid[o, :len(plc)] = True
    table = SliceTable(shape=shape, masks=masks, valid=valid,
                       count=valid.sum(axis=1).astype(np.int32))
    while len(_TABLE_CACHE) >= _TABLE_CACHE_MAX:
        _TABLE_CACHE.pop(next(iter(_TABLE_CACHE)))
    _TABLE_CACHE[key] = table
    return table


def clear_topology_cache() -> None:
    """Test hook: drop every cached placement table."""
    _PLACEMENT_CACHE.clear()
    _TABLE_CACHE.clear()


def mask_chips(mask: int) -> int:
    """Chip count of a placement bitmask (host-side popcount)."""
    return int(mask).bit_count()


def split_mask_words(masks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64 masks -> (lo, hi) int32 word pairs for the device kernel
    (TPU jit runs 32-bit; bitwise AND is word-local, so the disjointness
    test decomposes exactly)."""
    lo = (masks & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)
    hi = (masks >> np.uint64(32)).astype(np.uint32).view(np.int32)
    return lo, hi
