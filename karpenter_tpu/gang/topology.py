"""Slice topology: per-type torus dims -> placement bitmask tables.

The catalog carries each instance type's accelerator torus dims
(``CatalogArrays.type_torus``, derived from the accelerator count or set
explicitly on the ``InstanceType``).  This module lowers a gang's
``slice_shape`` against those tori ONCE into dense bitmask tensors:

- every *placement* of shape ``s`` in torus ``t`` (axis-aligned
  contiguous sub-block, every distinct axis permutation of ``s`` that
  fits, no wraparound) becomes one uint64 chip bitmask;
- per catalog + shape, the placements of every offering stack into a
  padded ``masks uint64 [O, Pmax]`` + ``valid bool [O, Pmax]`` table
  (:class:`SliceTable`), cached per catalog generation.

"Does offering ``o`` still fit shape ``s`` under occupancy ``occ``" is
then one batched AND over the ``[offerings, placements]`` grid —
``(masks & occ[:, None]) == 0`` — with no host loops on the hot path;
the planner's device kernel runs the identical integer arithmetic on
chip (gang/planner.py).

Tori are capped at 64 chips so one mask word covers any placement
(``apis/podgroup.MAX_SLICE_CHIPS`` rejects larger shapes at admission);
a type whose torus exceeds the cap simply exposes no placements.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass

import numpy as np

from karpenter_tpu.catalog.arrays import CatalogArrays

MAX_TORUS_CHIPS = 64

# (torus dims, shape) -> tuple of placement masks; both keys are tiny
# tuples, and distinct (torus, shape) pairs number in the dozens — the
# enumeration is pure combinatorics, valid forever
_PLACEMENT_CACHE: dict[tuple, tuple[int, ...]] = {}
# (torus dims, mask) -> rank-ordered global chip ids (pure geometry)
_RANK_CACHE: dict[tuple, tuple[int, ...]] = {}
_RANK_CACHE_MAX = 4096
# (catalog uid, generation, shape) -> SliceTable
_TABLE_CACHE: dict[tuple, "SliceTable"] = {}
_TABLE_CACHE_MAX = 32


@dataclass(frozen=True)
class SliceTable:
    """Padded per-offering placement bitmasks for ONE slice shape."""

    shape: tuple[int, ...]
    masks: np.ndarray        # uint64 [O, Pmax]; 0 where invalid
    valid: np.ndarray        # bool   [O, Pmax]
    count: np.ndarray        # int32  [O] valid placements per offering
    # optimal rank-assignment max-hop per placement (the rank-aware
    # scoring term: the planner picks the free placement minimizing
    # (hop, index) — one more batched column over the same grid, zero
    # extra dispatches); 0 where invalid (masked by ``valid`` first)
    hops: np.ndarray = None  # int32 [O, Pmax]

    @property
    def pmax(self) -> int:
        return int(self.masks.shape[1])

    def free_grid(self, occupancy: np.ndarray) -> np.ndarray:
        """bool [O, Pmax]: placement p of offering o is valid AND chip-
        disjoint from ``occupancy`` (uint64 [O]) — THE batched fit test."""
        return self.valid & ((self.masks & occupancy[:, None]) == 0)

    def fits(self, occupancy: np.ndarray) -> np.ndarray:
        """bool [O]: some placement is still free under ``occupancy``."""
        return self.free_grid(occupancy).any(axis=1)


def _chip_index(dims: tuple[int, ...]) -> np.ndarray:
    """Row-major chip numbering of the torus grid."""
    return np.arange(math.prod(dims)).reshape(dims)


def enumerate_placements(torus: tuple[int, ...],
                         shape: tuple[int, ...]) -> tuple[int, ...]:
    """Every contiguous axis-aligned placement of ``shape`` in ``torus``
    as chip bitmasks, deduplicated, ascending — the deterministic order
    every planner path and the validator share.

    Distinct axis permutations of ``shape`` count as distinct
    orientations (a 2x4 job fits a 4x2 window); wraparound placements
    are excluded (a production slice must be physically contiguous).
    """
    if not torus or math.prod(torus) > MAX_TORUS_CHIPS:
        return ()
    key = (torus, shape)
    hit = _PLACEMENT_CACHE.get(key)
    if hit is not None:
        return hit
    idx = _chip_index(torus)
    masks: set[int] = set()
    for perm in sorted(set(itertools.permutations(shape))):
        if len(perm) > len(torus):
            continue
        # right-align the shape onto the torus axes, leading axes size 1
        full = (1,) * (len(torus) - len(perm)) + perm
        if any(s > t for s, t in zip(full, torus)):
            continue
        origins = [range(t - s + 1) for s, t in zip(full, torus)]
        for origin in itertools.product(*origins):
            block = idx[tuple(slice(o, o + s)
                              for o, s in zip(origin, full))]
            mask = 0
            for c in block.ravel().tolist():
                mask |= 1 << c
            masks.add(mask)
    out = tuple(sorted(masks))
    _PLACEMENT_CACHE[key] = out
    return out


def type_placements(catalog: CatalogArrays, t: int,
                    shape: tuple[int, ...]) -> tuple[int, ...]:
    """Placement masks of ``shape`` on type ``t``'s torus (possibly ())."""
    tori = catalog.type_torus
    torus = tori[t] if t < len(tori) else ()
    return enumerate_placements(tuple(torus), shape)


def slice_table(catalog: CatalogArrays,
                shape: tuple[int, ...]) -> SliceTable:
    """The ``[offerings, placements]`` bitmask table for ``shape``,
    memoized per catalog generation (offerings of one type share that
    type's placements; the table is availability-independent — blackouts
    gate *creates*, not geometry)."""
    key = (catalog.uid, catalog.generation, shape)
    hit = _TABLE_CACHE.get(key)
    if hit is not None:
        return hit
    per_type = [type_placements(catalog, t, shape)
                for t in range(catalog.num_types)]
    tori = catalog.type_torus
    # hop bounds memoized PER TYPE (offerings of one type share its
    # placement list — recomputing per offering would multiply the cold
    # build by zones x capacity-types)
    per_type_hops = []
    for t, plc in enumerate(per_type):
        torus = tuple(tori[t]) if t < len(tori) else ()
        per_type_hops.append([optimal_max_hop(_block_dims(torus, m))
                              for m in plc])
    pmax = max((len(p) for p in per_type), default=0)
    O = catalog.num_offerings
    masks = np.zeros((O, max(pmax, 1)), dtype=np.uint64)
    valid = np.zeros((O, max(pmax, 1)), dtype=bool)
    hops = np.zeros((O, max(pmax, 1)), dtype=np.int32)
    for o in range(O):
        t = int(catalog.off_type[o])
        plc = per_type[t]
        if plc:
            masks[o, :len(plc)] = np.array(plc, dtype=np.uint64)
            valid[o, :len(plc)] = True
            hops[o, :len(plc)] = per_type_hops[t]
    table = SliceTable(shape=shape, masks=masks, valid=valid,
                       count=valid.sum(axis=1).astype(np.int32),
                       hops=hops)
    while len(_TABLE_CACHE) >= _TABLE_CACHE_MAX:
        _TABLE_CACHE.pop(next(iter(_TABLE_CACHE)))
    _TABLE_CACHE[key] = table
    return table


def clear_topology_cache() -> None:
    """Test hook: drop every cached placement table."""
    _PLACEMENT_CACHE.clear()
    _TABLE_CACHE.clear()
    _RANK_CACHE.clear()


# ---------------------------------------------------------------------------
# Rank-aware placement: rank -> chip assignment within a chosen slice
# ---------------------------------------------------------------------------
#
# MPI-style gangs communicate ring-wise (rank i <-> rank i±1, wrapping
# n-1 <-> 0 for n >= 3); the assignment quality metric is the MAXIMUM
# hop distance (Manhattan, on the physical grid — placements are
# contiguous sub-blocks, never wrapped) between any communicating rank
# pair.  The constructive optimum over an a×b×[c] block:
#
# - n <= 2 chips: hop = n - 1 trivially;
# - >= 2 effective axes AND n even: a Hamiltonian cycle of the block
#   exists (grid graphs: cycle iff the vertex count is even) -> every
#   hop is 1, which is minimal;
# - otherwise (one effective axis with n >= 3, or all axes odd — note
#   all-odd => n odd): the block's grid graph is bipartite with unequal
#   color classes (or a path), so no Hamiltonian cycle exists and some
#   hop must be >= 2; the even/odd skip ordering over the snake path
#   achieves exactly 2.
#
# So the construction below is provably optimal, the bench's host
# brute-force oracle merely re-confirms it on small shapes, and the
# independent validator recounts the hop bound from the emitted
# assignment (solver/validate.py).


def _snake_path(dims: tuple[int, ...]) -> list[tuple[int, ...]]:
    """Boustrophedon Hamiltonian PATH over the block: consecutive
    coords are always grid-adjacent (hop 1); no wrap guarantee."""
    coords = [()]
    for d in dims:
        nxt = []
        for i, prefix in enumerate(coords):
            rng = range(d) if i % 2 == 0 else range(d - 1, -1, -1)
            nxt.extend(prefix + (k,) for k in rng)
        coords = nxt
    return coords


def _ham_cycle_2d(a: int, b: int) -> list[tuple[int, int]]:
    """Hamiltonian cycle of the a×b grid, ``a`` even: down column 0,
    then boustrophedon back up through columns 1..b-1 (ends at (0, 1),
    adjacent to the start)."""
    cyc = [(r, 0) for r in range(a)]
    for i, r in enumerate(range(a - 1, -1, -1)):
        cols = range(1, b) if i % 2 == 0 else range(b - 1, 0, -1)
        cyc.extend((r, c) for c in cols)
    return cyc


def _ham_cycle(dims: tuple[int, ...]) -> list[tuple[int, ...]]:
    """Hamiltonian cycle of the block (every dim >= 2, even vertex
    count).  2D: direct construction; 3D: a 2D cycle over the two axes
    whose product is even, extruded as alternating up/down columns
    along the third."""
    if len(dims) == 2:
        a, b = dims
        if a % 2 == 0:
            return _ham_cycle_2d(a, b)
        return [(r, c) for c, r in _ham_cycle_2d(b, a)]
    # 3D: rotate axes so the LAST TWO have an even product
    order = (0, 1, 2)
    if dims[1] * dims[2] % 2:
        order = (1, 0, 2) if dims[0] * dims[2] % 2 == 0 else (2, 0, 1)
    d = tuple(dims[i] for i in order)
    plane = _ham_cycle(d[1:])                       # even length m
    cyc3 = []
    for j, p in enumerate(plane):
        zs = range(d[0]) if j % 2 == 0 else range(d[0] - 1, -1, -1)
        cyc3.extend((z,) + p for z in zs)
    inv = [0] * 3
    for i, o in enumerate(order):
        inv[o] = i
    return [tuple(c[inv[i]] for i in range(3)) for c in cyc3]


def rank_order_coords(dims: tuple[int, ...]) -> list[tuple[int, ...]]:
    """Block coords in rank order, minimizing the max ring hop (the
    constructive optimum documented above)."""
    import math

    n = math.prod(dims) if dims else 1
    if n <= 2:
        return _snake_path(dims)
    eff = [d for d in dims if d > 1]
    if len(eff) >= 2 and n % 2 == 0:
        cyc = _ham_cycle(tuple(eff))
        # re-embed collapsed size-1 axes
        out = []
        for c in cyc:
            it = iter(c)
            out.append(tuple(next(it) if d > 1 else 0 for d in dims))
        return out
    # skip ordering over the snake path: consecutive ranks are path
    # distance <= 2 apart, both junctions are path neighbors -> max 2
    path = _snake_path(dims)
    return path[0::2] + path[1::2][::-1]


def optimal_max_hop(dims: tuple[int, ...]) -> int:
    """The provable optimum of the max ring hop for a block of ``dims``
    (see the construction notes above)."""
    import math

    n = math.prod(dims) if dims else 1
    if n <= 1:
        return 0
    if n == 2:
        return 1
    eff = sum(1 for d in dims if d > 1)
    return 1 if (eff >= 2 and n % 2 == 0) else 2


def _block_dims(torus: tuple[int, ...], mask: int) -> tuple[int, ...]:
    """Axis extents of a placement mask's bounding block (placements
    are contiguous axis-aligned blocks, so the bound IS the block)."""
    if not torus or mask == 0:
        return ()
    chips = [c for c in range(math.prod(torus)) if (mask >> c) & 1]
    coords = np.stack([np.unravel_index(c, torus) for c in chips])
    return tuple(int(hi - lo + 1)
                 for lo, hi in zip(coords.min(axis=0), coords.max(axis=0)))


def rank_chips(torus: tuple[int, ...], mask: int) -> tuple[int, ...]:
    """Global chip ids of ``mask``'s block in RANK ORDER (rank r runs
    on chip ``rank_chips[r]``): the optimal-hop ordering of the local
    block mapped back onto the torus grid.  Pure geometry, memoized."""
    key = (torus, mask)
    hit = _RANK_CACHE.get(key)
    if hit is not None:
        return hit
    if not torus or mask == 0:
        return ()
    chips = [c for c in range(math.prod(torus)) if (mask >> c) & 1]
    coords = np.stack([np.unravel_index(c, torus) for c in chips])
    origin = coords.min(axis=0)
    dims = tuple(int(v) for v in coords.max(axis=0) - origin + 1)
    if math.prod(dims) != len(chips):
        # not a solid block (foreign mask): identity order, still a
        # bijection — the validator's recount covers the hop claim
        out = tuple(chips)
    else:
        out = tuple(int(np.ravel_multi_index(
            tuple(origin + np.asarray(local)), torus))
            for local in rank_order_coords(dims))
    while len(_RANK_CACHE) >= _RANK_CACHE_MAX:
        _RANK_CACHE.pop(next(iter(_RANK_CACHE)))
    _RANK_CACHE[key] = out
    return out


def max_hop_of_chips(torus: tuple[int, ...], chips: tuple[int, ...]) -> int:
    """Recount the max ring hop of a rank->chip assignment: Manhattan
    distance on the grid between consecutive ranks, wrap included for
    n >= 3 — the independent recount the validator and bench oracle
    share with NO planner code in the loop."""
    n = len(chips)
    if n <= 1 or not torus:
        return 0
    coords = [np.unravel_index(c, torus) for c in chips]
    pairs = n if n > 2 else n - 1
    worst = 0
    for i in range(pairs):
        a, b = coords[i], coords[(i + 1) % n]
        worst = max(worst, sum(abs(int(x) - int(y))
                               for x, y in zip(a, b)))
    return worst


def best_placement(table: SliceTable, o: int) -> int:
    """The empty-node placement pick both planner paths share: the
    valid placement of offering ``o`` minimizing (rank-assignment max
    hop, index).  Axis-permuted orientations of one shape share a hop
    bound, so this coincides with index 0 today — the term exists so a
    shape whose orientations ever diverge scores correctly."""
    c = int(table.count[o])
    if c <= 0:
        return 0
    row = table.hops[o, :c].astype(np.int64)
    return int(np.argmin(row * (table.pmax + 1)
                         + np.arange(c, dtype=np.int64)))


def rank_assignment(catalog: CatalogArrays, o: int,
                    mask: int) -> tuple[tuple[int, ...], int]:
    """(rank-ordered chips, achieved max hop) for offering ``o``'s
    placement ``mask`` — the planner/greedy commit helper."""
    if mask == 0:
        return (), 0
    t = int(catalog.off_type[o])
    tori = catalog.type_torus
    torus = tuple(tori[t]) if t < len(tori) else ()
    chips = rank_chips(torus, mask)
    return chips, max_hop_of_chips(torus, chips)


def mask_chips(mask: int) -> int:
    """Chip count of a placement bitmask (host-side popcount)."""
    return int(mask).bit_count()


def split_mask_words(masks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64 masks -> (lo, hi) int32 word pairs for the device kernel
    (TPU jit runs 32-bit; bitwise AND is word-local, so the disjointness
    test decomposes exactly)."""
    lo = (masks & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)
    hi = (masks >> np.uint64(32)).astype(np.uint32).view(np.int32)
    return lo, hi
