"""Batched atomic gang placement: one vectorized grid per step.

The canonical algorithm (shared bit-for-bit with ``gang/greedy.py``, the
pure-python parity path — differential tests assert identical plans):

Gangs are visited in the encoded problem's order (priority DESC, chips
DESC, size DESC — ``gang/encode.py``).  For each gang:

1. **Open-node scan** — for every node the plan has opened, feasibility
   of "host this whole gang" is evaluated at once over the
   ``[nodes, placements]`` grid: the gang's slice fits iff some valid
   placement bitmask is chip-disjoint from the node's occupancy
   (``(mask & occ) == 0``), the node's offering is label-compatible, and
   the residual capacity covers the TOTAL member demand.  The oldest
   fitting node wins; the lowest free placement index is taken (the
   deterministic tie-break both paths share).
2. **New node** — otherwise the cheapest offering (price-rank, ties by
   index) whose ``compat`` row admits the gang is opened; the gang takes
   that offering's first placement.
3. Otherwise the gang is **unplaced whole**: every member stays pending.
   Partial placements are structurally impossible — members are only
   ever committed as one assignment row.

The grid step optionally runs as a jitted device kernel (int32 word
pairs for the chip bitmasks, bucket-padded shapes so recompiles stay
bounded); arithmetic is integer/bool exact on both paths, so the
backend choice never changes the plan.
"""

from __future__ import annotations

import time
from functools import lru_cache

import numpy as np

from karpenter_tpu.gang.encode import GangProblem
from karpenter_tpu.gang.topology import (
    best_placement, rank_assignment, split_mask_words,
)
from karpenter_tpu.gang.types import GangAssignment, GangNode, GangOptions, GangPlan
from karpenter_tpu.solver.types import bucket

# bucket rungs for the device grid (recompile bound): nodes x placements
_NODE_PAD = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
_PLACE_PAD = (2, 4, 8, 16, 32, 64, 128, 256)
# below this grid size the jit dispatch overhead beats the kernel win
_DEVICE_MIN_CELLS = 2048


@lru_cache(maxsize=1)
def _device_free_grid():
    """Jitted [Nn, P] slice-fit kernel, or None when jax is unusable."""
    try:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def free_grid(occ_lo, occ_hi, m_lo, m_hi, valid, resid, need,
                      label_ok, hops):
            # chip-disjointness decomposes exactly over the two 32-bit
            # mask words: (mask & occ) == 0  <=>  both words AND to zero
            disjoint = ((m_lo & occ_lo[:, None])
                        | (m_hi & occ_hi[:, None])) == 0
            free = valid & disjoint                          # [Nn, P]
            cap_ok = (resid >= need[None, :]).all(axis=1)    # [Nn]
            fits = label_ok & cap_ok & free.any(axis=1)
            # rank-aware scoring term: among free placements take the
            # one minimizing (rank-assignment max hop, index) — one
            # more column over the same grid, same dispatch
            P = valid.shape[1]
            idx = jnp.arange(P, dtype=jnp.int32)[None, :]
            score = jnp.where(free, hops * jnp.int32(P + 1) + idx,
                              jnp.int32(2 ** 30))
            first = jnp.where(fits, jnp.argmin(score, axis=1), -1)
            return fits, first.astype(jnp.int32)

        # force one trace so an unusable backend fails HERE, not mid-plan
        free_grid(np.zeros(1, np.int32), np.zeros(1, np.int32),
                  np.zeros((1, 2), np.int32), np.zeros((1, 2), np.int32),
                  np.ones((1, 2), bool), np.zeros((1, 4), np.int32),
                  np.zeros(4, np.int32), np.ones(1, bool),
                  np.zeros((1, 2), np.int32))
        return free_grid
    except Exception:  # noqa: BLE001 — device is an optimization, not a dep
        return None


class GangPlanner:
    """Pure function over an encoded gang problem."""

    def __init__(self, options: GangOptions | None = None):
        self.options = options or GangOptions()

    # -- grid step (the only backend-switched code) -----------------------

    def _free_grid(self, occ, masks, valid, resid, need, label_ok, hops):
        """(fits bool [Nn], best free placement int [Nn]; -1 = none) —
        "best" minimizes (rank-assignment max hop, placement index),
        the rank-aware scoring term both backends share."""
        Nn, P = valid.shape
        use = self.options.use_device
        if use != "off" and (use == "on" or Nn * P >= _DEVICE_MIN_CELLS):
            dev = _device_free_grid()
            if dev is None and use == "on":
                # forced-on must never silently fall back to numpy — a
                # parity harness comparing "device" vs host would be
                # comparing host vs host and certifying a kernel that
                # never ran (ResilientGangPlanner turns this into a
                # degraded-greedy plan with an ERRORS breadcrumb)
                raise RuntimeError(
                    "gang device kernel forced on (use_device='on') but "
                    "no usable jax backend is available")
            if dev is not None:
                Np = bucket(Nn, _NODE_PAD)
                Pp = bucket(P, _PLACE_PAD)
                occ_lo, occ_hi = split_mask_words(occ)
                m_lo, m_hi = split_mask_words(masks)
                pad = lambda a, shape: np.zeros(shape, a.dtype)  # noqa: E731
                ol = pad(occ_lo, Np); ol[:Nn] = occ_lo           # noqa: E702
                oh = pad(occ_hi, Np); oh[:Nn] = occ_hi           # noqa: E702
                ml = pad(m_lo, (Np, Pp)); ml[:Nn, :P] = m_lo     # noqa: E702
                mh = pad(m_hi, (Np, Pp)); mh[:Nn, :P] = m_hi     # noqa: E702
                va = np.zeros((Np, Pp), bool); va[:Nn, :P] = valid  # noqa: E702
                re_ = np.zeros((Np, resid.shape[1]), np.int32)
                re_[:Nn] = resid.astype(np.int32)
                lo = np.zeros(Np, bool); lo[:Nn] = label_ok      # noqa: E702
                hp = np.zeros((Np, Pp), np.int32)
                hp[:Nn, :P] = hops.astype(np.int32)
                from karpenter_tpu.faulttol import (DeviceFaultError,
                                                    device_guard)
                from karpenter_tpu.obs.prof import get_profiler

                try:
                    with device_guard("gang-grid") as guard:
                        with get_profiler().sampled("gang-grid") as probe:
                            fits, first = dev(ol, oh, ml, mh, va, re_,
                                              need.astype(np.int32), lo, hp)
                            probe.dispatched((fits, first))
                        fits, first = guard.fetch((fits, first))
                except DeviceFaultError:
                    if use == "on":
                        # forced-on: surface the fault (same contract as
                        # the missing-backend raise above) — the
                        # Resilient wrapper owns the degraded plan
                        raise
                else:
                    return (np.asarray(fits)[:Nn],
                            np.asarray(first)[:Nn].astype(np.int64))
        free = valid & ((masks & occ[:, None]) == 0)
        cap_ok = (resid >= need[None, :]).all(axis=1)
        fits = label_ok & cap_ok & free.any(axis=1)
        score = np.where(free,
                         hops.astype(np.int64) * (P + 1)
                         + np.arange(P, dtype=np.int64)[None, :],
                         2 ** 30)
        first = np.where(fits, np.argmin(score, axis=1), -1)
        return fits, first.astype(np.int64)

    # -- the plan ----------------------------------------------------------

    def plan(self, problem: GangProblem) -> GangPlan:
        t0 = time.perf_counter()
        out = GangPlan(backend="vector")
        catalog = problem.catalog
        out.unplaced.extend(problem.rejected)
        if problem.num_gangs == 0:
            out.plan_seconds = time.perf_counter() - t0
            return out
        off_rank = catalog.offering_rank_price()
        off_alloc = catalog.offering_alloc().astype(np.int64)
        off_price = catalog.off_price

        node_off: list[int] = []
        node_occ: list[int] = []               # uint64 chip bitmask
        node_resid: list[np.ndarray] = []
        assignments: dict[int, list[GangAssignment]] = {}
        max_nodes = self.options.max_nodes

        def commit(gang, n: int, mask: int) -> None:
            out.placed_gangs.append(gang.name)
            for pn in gang.pod_names:
                out.placements[pn] = n
            chips, hop = rank_assignment(catalog, node_off[n], mask)
            assignments.setdefault(n, []).append(GangAssignment(
                gang=gang.name, placement_mask=mask,
                pod_names=tuple(gang.pod_names),
                rank_chips=chips, max_hop=hop))

        for gi, gang in enumerate(problem.gangs):
            size = int(problem.gang_size[gi])
            if size < int(problem.gang_min[gi]):
                # structural guard: a sub-min_member gang never places
                # (the controller parks these; reject if one leaks in)
                out.unplaced_gangs.append(gang.name)
                out.unplaced.extend(gang.pod_names)
                continue
            need = problem.gang_req[gi]
            table = problem.tables[gi]
            compat = problem.compat[gi]
            placed = False
            # 1. open nodes: one batched [nodes, placements] grid
            if node_off:
                offs = np.asarray(node_off, dtype=np.int64)
                occ = np.asarray(node_occ, dtype=np.uint64)
                resid = np.stack(node_resid)
                label_ok = compat[offs]
                if table is not None:
                    masks = table.masks[offs]
                    valid = table.valid[offs]
                    hops = table.hops[offs]
                else:
                    masks = np.zeros((len(offs), 1), dtype=np.uint64)
                    valid = np.ones((len(offs), 1), dtype=bool)
                    hops = np.zeros((len(offs), 1), dtype=np.int32)
                fits, first = self._free_grid(occ, masks, valid, resid,
                                              need, label_ok, hops)
                hit = np.nonzero(fits)[0]
                if hit.size:
                    n = int(hit[0])                   # oldest node first
                    p = int(first[n])
                    mask = int(masks[n, p]) if table is not None else 0
                    node_occ[n] = int(node_occ[n]) | mask
                    node_resid[n] = node_resid[n] - need
                    commit(gang, n, mask)
                    placed = True
            # 2. new node: cheapest compatible offering
            if not placed and compat.any() and len(node_off) < max_nodes:
                rank = np.where(compat, off_rank.astype(np.float64), np.inf)
                best = int(np.argmin(rank))           # first min: det. ties
                mask = int(table.masks[best, best_placement(table, best)]) \
                    if table is not None else 0
                node_off.append(best)
                node_occ.append(mask)
                node_resid.append(off_alloc[best] - need)
                commit(gang, len(node_off) - 1, mask)
                placed = True
            if not placed:
                out.unplaced_gangs.append(gang.name)
                out.unplaced.extend(gang.pod_names)

        total = 0.0
        for n, off in enumerate(node_off):
            itype, zone, captype = catalog.describe_offering(off)
            price = float(off_price[off])
            total += price
            out.nodes.append(GangNode(
                instance_type=itype, zone=zone, capacity_type=captype,
                price=price, offering_index=off,
                assignments=assignments.get(n, [])))
        out.total_cost_per_hour = total
        out.plan_seconds = time.perf_counter() - t0
        return out
