"""Gang degraded mode: greedy fallback instead of a failed plan.

Mirrors ``solver/degraded.py`` and ``preempt/degraded.py``: the batched
planner can fail in ways the host loop cannot (a broken device kernel, a
shape bug in the grid padding).  None of those may stall the gang plane
while whole jobs sit parked — ``ResilientGangPlanner`` degrades that one
plan to ``gang/greedy.py`` with an ``ERRORS`` breadcrumb
(component="gang") and a ``degraded:`` backend tag.

The structural gate is deliberately cheap (O(members + nodes)); full
feasibility stays with ``validate_gang_plan`` (solver/validate.py),
which tests and the execution controller run on every plan.
"""

from __future__ import annotations

from karpenter_tpu.gang.encode import GangProblem
from karpenter_tpu.gang.greedy import GreedyGangPlanner
from karpenter_tpu.gang.planner import GangPlanner
from karpenter_tpu.gang.types import GangOptions, GangPlan
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("gang.degraded")


def gang_plan_defects(plan: GangPlan, problem: GangProblem) -> list[str]:
    """Structural sanity of a gang plan (cheap; the full oracle is
    validate_gang_plan)."""
    if plan is None:
        return ["planner returned no plan"]
    defects: list[str] = []
    members = {g.name: set(g.pod_names) for g in problem.gangs}
    placed: dict[str, set[str]] = {}
    seen: set[str] = set()
    for node in plan.nodes:
        if not (0 <= node.offering_index < problem.catalog.num_offerings):
            defects.append(f"node offering index {node.offering_index} "
                           f"out of range")
        for a in node.assignments:
            for pn in a.pod_names:
                if pn in seen:
                    defects.append(f"pod {pn} placed twice")
                seen.add(pn)
            placed.setdefault(a.gang, set()).update(a.pod_names)
    for name, pods in placed.items():
        want = members.get(name)
        if want is None:
            defects.append(f"placement of unknown gang {name}")
        elif pods != want:
            # the invariant the whole subsystem exists to uphold: a
            # partial gang must never even reach the execution gate
            defects.append(f"partial gang {name}: {len(pods)}/{len(want)} "
                           f"members placed")
    for pn in plan.unplaced:
        if pn in seen:
            defects.append(f"pod {pn} both placed and unplaced")
    return defects


class ResilientGangPlanner:
    """Wraps the batched planner; degrades single plans to greedy."""

    def __init__(self, primary: GangPlanner | None = None,
                 options: GangOptions | None = None):
        self.options = options or getattr(primary, "options", None) \
            or GangOptions()
        self.primary = primary or GangPlanner(self.options)
        self._fallback = None

    @property
    def fallback(self) -> GreedyGangPlanner:
        if self._fallback is None:
            self._fallback = GreedyGangPlanner(self.options)
        return self._fallback

    def plan(self, problem: GangProblem) -> GangPlan:
        try:
            plan = self.primary.plan(problem)
        except Exception as e:  # noqa: BLE001 — degrade, never fail the cycle
            log.error("gang planner failed; degrading to greedy",
                      error=str(e)[:200])
            return self._degrade(problem, "backend_failure")
        defects = gang_plan_defects(plan, problem)
        if defects:
            log.error("gang planner produced invalid plan; degrading",
                      defects=defects[:3])
            return self._degrade(problem, "invalid_plan")
        return plan

    def _degrade(self, problem: GangProblem, reason: str) -> GangPlan:
        metrics.ERRORS.labels("gang", f"degraded_{reason}").inc()
        with obs.span("gang.plan.degraded", reason=reason):
            plan = self.fallback.plan(problem)
        plan.backend = f"degraded:{plan.backend}"
        return plan
