"""Gang-plane interface types: plan, node, options.

A :class:`GangPlan` is the all-or-nothing counterpart of the solver's
Plan: instead of *pods to nodes* it names *gangs to torus slices* —
every gang either has all of its members on one node (occupying one
contiguous sub-slice of that node's accelerator torus) or appears in
``unplaced_gangs`` with every member unplaced.  Partial placements are
unrepresentable: an assignment row carries the whole member list.

Like the solver and the preemption planner, the gang planner is a pure
function over explicit inputs (encoded gang tensors + placement bitmask
tables) — stateless, deterministic, differential-testable
(docs/design/gang.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class GangOptions:
    """Gated planner config (mirrors SolverOptions/PlannerOptions)."""

    # "auto": jitted placement grid when a jax backend is importable,
    # numpy otherwise; "on"/"off" force.  Both paths are integer/bool
    # exact, so the choice never changes the plan.
    use_device: str = "auto"
    # static bound on nodes one plan may open
    max_nodes: int = 4096


@dataclass(slots=True, frozen=True)
class GangAssignment:
    """One gang occupying one contiguous sub-slice of a node's torus."""

    gang: str                        # PodGroup name
    placement_mask: int              # chip bitmask within the node torus
    pod_names: tuple[str, ...]       # ALL members — partiality is
                                     # structurally unrepresentable
    # rank-aware placement (MPI-style: rank r <-> chip rank_chips[r],
    # member pod_names[i] takes rank i mod chips): the slice's chips in
    # the hop-minimizing rank order, and the achieved max ring-hop —
    # validated by an independent recount in solver/validate.py
    rank_chips: tuple[int, ...] = ()
    max_hop: int = 0


@dataclass(slots=True)
class GangNode:
    """One node the plan wants created, with its slice assignments."""

    instance_type: str
    zone: str
    capacity_type: str
    price: float
    offering_index: int = -1
    assignments: list[GangAssignment] = field(default_factory=list)

    @property
    def pod_names(self) -> list[str]:
        return [pn for a in self.assignments for pn in a.pod_names]


@dataclass
class GangPlan:
    """Atomic gang placement result."""

    nodes: list[GangNode] = field(default_factory=list)
    placements: dict[str, int] = field(default_factory=dict)  # pod -> node idx
    placed_gangs: list[str] = field(default_factory=list)
    unplaced_gangs: list[str] = field(default_factory=list)
    unplaced: list[str] = field(default_factory=list)         # pod keys
    total_cost_per_hour: float = 0.0
    backend: str = ""
    plan_seconds: float = 0.0

    @property
    def placed_count(self) -> int:
        return len(self.placements)

    @property
    def empty(self) -> bool:
        return not self.nodes

    def to_plan(self, backend: str | None = None):
        """Lower to a solver :class:`Plan` so the execution path reuses
        the actuator contract and the independent plan validator."""
        from karpenter_tpu.solver.types import Plan, PlannedNode

        nodes = [PlannedNode(instance_type=n.instance_type, zone=n.zone,
                             capacity_type=n.capacity_type, price=n.price,
                             pod_names=list(n.pod_names),
                             offering_index=n.offering_index)
                 for n in self.nodes]
        return Plan(nodes=nodes, unplaced_pods=list(self.unplaced),
                    total_cost_per_hour=self.total_cost_per_hour,
                    backend=backend or self.backend,
                    solve_seconds=self.plan_seconds)

    def summary(self) -> dict[str, object]:
        return {
            "nodes": len(self.nodes),
            "gangs_placed": len(self.placed_gangs),
            "gangs_unplaced": len(self.unplaced_gangs),
            "pods_placed": self.placed_count,
            "cost_per_hour": round(self.total_cost_per_hour, 4),
            "backend": self.backend,
            "plan_seconds": round(self.plan_seconds, 6),
        }
