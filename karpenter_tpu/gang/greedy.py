"""Host greedy gang planner: the parity oracle and fallback path.

Implements the EXACT canonical algorithm of ``gang/planner.py``
(oldest-fitting-node first, lowest free placement, cheapest new
offering by (rank, index)) with plain python loops — no numpy grids, no
device.  Two jobs:

- **differential testing**: ``GreedyGangPlanner.plan`` must equal
  ``GangPlanner.plan`` on every input (tests/test_gang.py);
- **degraded fallback**: ``gang/degraded.py`` routes single plans here
  when the batched path fails, mirroring ``solver/degraded.py``.
"""

from __future__ import annotations

import time

from karpenter_tpu.gang.encode import GangProblem
from karpenter_tpu.gang.topology import best_placement, rank_assignment
from karpenter_tpu.gang.types import GangAssignment, GangNode, GangOptions, GangPlan


class GreedyGangPlanner:
    def __init__(self, options: GangOptions | None = None):
        self.options = options or GangOptions()

    def plan(self, problem: GangProblem) -> GangPlan:
        t0 = time.perf_counter()
        out = GangPlan(backend="greedy")
        catalog = problem.catalog
        out.unplaced.extend(problem.rejected)
        if problem.num_gangs == 0:
            out.plan_seconds = time.perf_counter() - t0
            return out
        off_rank = catalog.offering_rank_price()
        off_alloc = catalog.offering_alloc()
        off_price = catalog.off_price
        R = problem.gang_req.shape[1]

        node_off: list[int] = []
        node_occ: list[int] = []
        node_resid: list[list[int]] = []
        assignments: dict[int, list[GangAssignment]] = {}
        max_nodes = self.options.max_nodes

        def commit(gang, n: int, mask: int) -> None:
            out.placed_gangs.append(gang.name)
            for pn in gang.pod_names:
                out.placements[pn] = n
            chips, hop = rank_assignment(catalog, node_off[n], mask)
            assignments.setdefault(n, []).append(GangAssignment(
                gang=gang.name, placement_mask=mask,
                pod_names=tuple(gang.pod_names),
                rank_chips=chips, max_hop=hop))

        for gi, gang in enumerate(problem.gangs):
            size = int(problem.gang_size[gi])
            if size < int(problem.gang_min[gi]):
                out.unplaced_gangs.append(gang.name)
                out.unplaced.extend(gang.pod_names)
                continue
            need = [int(v) for v in problem.gang_req[gi]]
            table = problem.tables[gi]
            compat = problem.compat[gi]
            placed = False
            # 1. open nodes, oldest first; lowest free placement index
            for n in range(len(node_off)):
                o = node_off[n]
                if not compat[o]:
                    continue
                if any(node_resid[n][d] < need[d] for d in range(R)):
                    continue
                mask = -1
                if table is None:
                    mask = 0
                else:
                    # rank-aware pick: lowest (hop, index) free placement
                    # — the same scoring term the batched grid applies
                    row = table.masks[o]
                    hops = table.hops[o]
                    best_score = None
                    for p in range(int(table.count[o])):
                        if (int(row[p]) & node_occ[n]) == 0:
                            score = (int(hops[p]), p)
                            if best_score is None or score < best_score:
                                best_score = score
                                mask = int(row[p])
                if mask < 0:
                    continue
                node_occ[n] |= mask
                for d in range(R):
                    node_resid[n][d] -= need[d]
                commit(gang, n, mask)
                placed = True
                break
            # 2. new node: cheapest compatible offering (rank, index)
            if not placed and len(node_off) < max_nodes:
                best, best_rank = -1, None
                for o in range(catalog.num_offerings):
                    if not compat[o]:
                        continue
                    r = float(off_rank[o])
                    if best_rank is None or r < best_rank:
                        best, best_rank = o, r
                if best >= 0:
                    mask = int(table.masks[best, best_placement(table,
                                                                best)]) \
                        if table is not None else 0
                    node_off.append(best)
                    node_occ.append(mask)
                    node_resid.append([int(off_alloc[best, d]) - need[d]
                                       for d in range(R)])
                    commit(gang, len(node_off) - 1, mask)
                    placed = True
            if not placed:
                out.unplaced_gangs.append(gang.name)
                out.unplaced.extend(gang.pod_names)

        total = 0.0
        for n, off in enumerate(node_off):
            itype, zone, captype = catalog.describe_offering(off)
            price = float(off_price[off])
            total += price
            out.nodes.append(GangNode(
                instance_type=itype, zone=zone, capacity_type=captype,
                price=price, offering_index=off,
                assignments=assignments.get(n, [])))
        out.total_cost_per_hour = total
        out.plan_seconds = time.perf_counter() - t0
        return out
