"""Gang scheduling + TPU-topology-aware slice placement.

The plane that turns the per-pod packer into an accelerator scheduler
(docs/design/gang.md): :mod:`apis/podgroup` declares the gang contract,
:mod:`gang/topology` lowers per-type torus dims into placement bitmask
tables, :mod:`gang/encode` builds dense gang tensors from pending pods,
:mod:`gang/planner` places gangs atomically (vectorized grid, optional
jitted device kernel), :mod:`gang/greedy` is the bit-identical host
parity oracle, and :mod:`gang/degraded` degrades a failed batched plan
to greedy instead of stranding the gang.  Execution lives in
``controllers/gang.py`` behind ``KARPENTER_ENABLE_GANG``.
"""

from karpenter_tpu.apis.podgroup import PodGroup, parse_slice_shape
from karpenter_tpu.gang.degraded import ResilientGangPlanner, gang_plan_defects
from karpenter_tpu.gang.encode import GangInfo, GangProblem, encode_gangs
from karpenter_tpu.gang.greedy import GreedyGangPlanner
from karpenter_tpu.gang.planner import GangPlanner
from karpenter_tpu.gang.topology import (
    SliceTable, enumerate_placements, slice_table,
)
from karpenter_tpu.gang.types import (
    GangAssignment, GangNode, GangOptions, GangPlan,
)

__all__ = [
    "GangAssignment", "GangInfo", "GangNode", "GangOptions", "GangPlan",
    "GangPlanner", "GangProblem", "GreedyGangPlanner", "PodGroup",
    "ResilientGangPlanner", "SliceTable", "encode_gangs",
    "enumerate_placements", "gang_plan_defects", "parse_slice_shape",
    "slice_table",
]
