"""Chance-constrained stochastic packing — the fifth solver plane.

Per "Solving the Batch Stochastic Bin Packing Problem in Cloud: A
Chance-constrained Optimization Approach" (PAPERS.md), pod usage is a
DISTRIBUTION, not a scalar: each pod carries a per-resource
(mean, variance) pair (``apis/pod.UsageDistribution``) and each NodePool
a violation-probability bound epsilon (``NodePool.overcommit``).  A node
is chance-feasible when, per resource dimension,

    sum(mean) + z(eps) * sqrt(sum(variance)) <= capacity

— the Gaussian deterministic equivalent, evaluated as ONE vectorized
quantile check inside the existing solve dispatch (stochastic/kernel.py
rides the packed-buffer suffix trick the explain plane established:
the per-group mean/variance tensors travel as a small extra leaf, the
result buffer layout is unchanged).  Pooled variance is the density
win: sqrt(sum var) grows like sqrt(n) while budgeting each pod its own
z*sqrt(var) grows like n, so nodes legally hold 10-30% more mean demand
at the same violation bound.

Plane layout (the established encode/kernel/greedy-parity/degraded/
validate pattern of preempt/, gang/, and repack/):

- ``stochastic/encode.py``  — groups -> mean/var tensors + the packed
  suffix; ``solver/encode.py`` attaches them when the pool overcommits;
- ``stochastic/kernel.py``  — the chance-constrained FFD scan (jitted,
  donated per GL006, prof-sampled) sharing the packed result wire;
- ``stochastic/greedy.py``  — the bit-identical numpy parity oracle
  (same fixed-point binary search, same float32 op order);
- ``stochastic/degraded.py``— deterministic-requests fallback when the
  stochastic kernel fails (ResilientSolver convention);
- ``stochastic/validate.py``— independent chance-constraint validator +
  the measured-violation-rate probe the chaos invariant consumes;
- ``stochastic/risk.py``    — per-(type, zone) spot-interruption rate
  learned from the ledger's labeled lifecycle records, priced into
  offering ranking, persisted via recovery-journal state records.

Every numeric constant the device kernel and the host oracle share
lives HERE — change one side, change both is prevented by having only
one side to change.
"""

from __future__ import annotations

import math

import numpy as np

# Binary-search bounds of the vectorized quantile check: the fit count
# is clamped to CHANCE_FIT_MAX and resolved in CHANCE_ITERS fixed
# iterations, so the device scan and the numpy oracle run the
# IDENTICAL op sequence — the parity contract is structural, not
# numerical luck.  2047 pods per node per group is far above any real
# offering's pod-slot allocatable, and every iteration is a full
# [*, R] tensor pass — the cap is the direct knob on quantile-check
# cost (12 = ceil(log2(2047 + 2)) iterations resolve the range
# exactly).
CHANCE_FIT_MAX = 2047
CHANCE_ITERS = 12

# epsilon floor: z(eps) explodes as eps -> 0; bounds below this clamp
# (a 1e-9 bound would demand ~6 sigma of buffer and pack worse than
# deterministic requests for any realistic variance)
EPS_MIN = 1e-6


def z_value(eps: float) -> float:
    """One-sided standard-normal quantile z with P(X > z) = eps — the
    chance-constraint multiplier.  Acklam-free: derived from the exact
    inverse error function via ``sqrt(2) * erfinv(1 - 2*eps)`` computed
    with a deterministic rational approximation (Giles 2010 single-
    precision-grade polynomial evaluated in float64), accurate to ~1e-7
    over the clamped epsilon range — far below the basis-point
    quantization the kernel consumes."""
    eps = min(max(float(eps), EPS_MIN), 0.5)
    # inverse normal CDF at q = 1 - eps via the Beasley-Springer-Moro
    # style central/tail split (deterministic, stdlib-only)
    q = 1.0 - eps
    if q == 0.5:
        return 0.0
    # tail form: z = t - poly(t)/poly(t), t = sqrt(-2 ln(eps))
    t = math.sqrt(-2.0 * math.log(eps))
    z = t - ((2.515517 + 0.802853 * t + 0.010328 * t * t)
             / (1.0 + 1.432788 * t + 0.189269 * t * t
                + 0.001308 * t * t * t))
    # one Newton step against the exact normal tail tightens the
    # classic Hastings approximation from ~4.5e-4 to <1e-7 absolute
    pdf = math.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)
    cdf_tail = 0.5 * math.erfc(z / math.sqrt(2.0))
    if pdf > 0:
        z -= (eps - cdf_tail) / pdf
    return z


def z_bp_for(eps: float) -> int:
    """z(eps) quantized to basis points (z * 10000, int) — the STATIC
    kernel argument, so a handful of distinct epsilons per process
    means a handful of compiled executables, never a recompile per
    float wiggle."""
    return int(round(z_value(eps) * 10000.0))


def zsq_value(z_bp: int) -> float:
    """The squared z constant both the device kernel and the numpy
    oracle consume, materialized ONCE on the host in float32 so the
    two sides compare against bit-identical values: the quantile check
    is ``zsq * sum(var) <= (cap - sum(mean))^2`` — square-compare form,
    no sqrt on the hot path."""
    zf = np.float32(np.float32(z_bp) * np.float32(1e-4))
    return float(np.float32(zf * zf))


def stochastic_enabled(problem) -> bool:
    """Does this encoded problem carry the stochastic plane?  True when
    the encoder attached mean/variance tensors (pool overcommit > 0).
    The strict-superset gate: every dispatch path checks this before
    routing to the chance-constrained kernel."""
    return getattr(problem, "group_var", None) is not None
