"""Host oracle for the chance-constrained scan — the parity twin.

Recomputes, with numpy on the host, exactly what
``stochastic/kernel.solve_packed_stochastic`` computes on device:
node_off / assign / unplaced bit-identical, explain words bit-identical
(base words via the established ``explain/greedy`` oracle, the
overcommit_risk bit via the same fixed-iteration grid search), cost
equal up to float-reduction order.

Bit-identity holds STRUCTURALLY, not by luck: every float op in the
quantile check is a single IEEE-rounded elementwise float32
mul/add/compare in the identical order as the kernel (the shared
``zsq_value`` constant, the shared ``CHANCE_ITERS`` trip count, the
square-compare form with no sqrt and no float reductions).  Change one
side, change both — docs/design/stochastic.md "parity contract".
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.solver.types import FIT_BIG as _BIG
from karpenter_tpu.stochastic import CHANCE_FIT_MAX, CHANCE_ITERS, zsq_value


def _fit_counts_np(resid: np.ndarray, req: np.ndarray) -> np.ndarray:
    per_dim = np.where(req[None, :] > 0,
                       resid // np.maximum(req[None, :], 1), _BIG)
    return per_dim.min(axis=1).astype(np.int32)


def chance_fit_np(resid: np.ndarray, var_sum: np.ndarray, mean: np.ndarray,
                  var_f: np.ndarray, zsq: np.float32,
                  hi: np.ndarray) -> np.ndarray:
    """numpy mirror of kernel._chance_fit — same fixed iteration count,
    same float32 op order."""
    lo = np.zeros_like(hi)
    hi = hi.copy()
    for _ in range(CHANCE_ITERS):
        mid = (lo + hi + 1) // 2
        diff = resid - mid[:, None] * mean[None, :]
        diff_f = diff.astype(np.float32)
        lhs = zsq * (var_sum + mid[:, None].astype(np.float32)
                     * var_f[None, :])
        feas = (lhs <= diff_f * diff_f).all(axis=1)
        lo = np.where(feas, mid, lo)
        hi = np.where(feas, hi, mid - 1)
    return lo.astype(np.int32)


def _chance_fit_grid_np(alloc: np.ndarray, mean: np.ndarray,
                        var_f: np.ndarray, zsq: np.float32,
                        kd: np.ndarray) -> np.ndarray:
    """numpy mirror of kernel._chance_fit_grid (closed form in
    sqrt-space + the 4-point monotone correction window, identical
    float32 op order)."""
    A = alloc[None, :, :].astype(np.float32)
    m = mean[:, None, :].astype(np.float32)
    bv = zsq * var_f[:, None, :]
    den = np.sqrt(bv + np.float32(4.0) * m * A) + np.sqrt(bv)
    with np.errstate(divide="ignore", invalid="ignore"):
        s = np.where(den > 0, (np.float32(2.0) * A) / den,
                     np.float32(0.0))
    k_dim = np.where(mean[:, None, :] > 0, np.floor(s * s),
                     np.float32(CHANCE_FIT_MAX))
    k_hat = np.clip(k_dim.min(axis=2).astype(np.int32), 0, kd)
    k0 = np.maximum(k_hat - 2, 0)
    k = k0.copy()
    for j in range(1, 5):
        mid = k0 + j
        diff = alloc[None, :, :] - mid[:, :, None] * mean[:, None, :]
        diff_f = diff.astype(np.float32)
        lhs = zsq * (mid[:, :, None].astype(np.float32)
                     * var_f[:, None, :])
        feas = (mid <= kd) & (lhs <= diff_f * diff_f).all(axis=2)
        k = k + feas.astype(np.int32)
    return k


def risk_words_np(mean: np.ndarray, var: np.ndarray, count: np.ndarray,
                  unplaced: np.ndarray, compat: np.ndarray,
                  off_alloc: np.ndarray, z_bp: int) -> np.ndarray:
    """int32 [G] with only the overcommit_risk bit — the host mirror of
    kernel._risk_words."""
    from karpenter_tpu.explain import BIT

    G = mean.shape[0]
    if G == 0 or off_alloc.shape[0] == 0:
        return np.zeros(G, dtype=np.int32)
    zsq = np.float32(zsq_value(z_bp))
    var_f = var.astype(np.float32)
    per_dim = np.where(mean[:, None, :] > 0,
                       off_alloc[None, :, :]
                       // np.maximum(mean[:, None, :], 1), _BIG)
    kd = np.minimum(per_dim.min(axis=2), CHANCE_FIT_MAX).astype(np.int32)
    kc = _chance_fit_grid_np(off_alloc, mean, var_f, zsq, kd)
    has_var = (var > 0).any(axis=1)
    hit = (compat & (kc < kd)).any(axis=1) & has_var \
        & (np.asarray(count) > 0) & (np.asarray(unplaced) > 0)
    return np.where(hit, np.int32(1 << BIT["overcommit_risk"]),
                    np.int32(0)).astype(np.int32)


def binding_mask_np(mean: np.ndarray, var: np.ndarray,
                    compat: np.ndarray, off_alloc: np.ndarray,
                    z_bp: int) -> np.ndarray:
    """bool [G]: groups whose chance constraint BINDS — the variance
    term shrinks the per-node fit below the deterministic bound
    (kc < kd) on at least one compatible offering, and the group
    carries variance at all.  Host twin of the kernel's telemetry
    binding mask (``kernel.py``: same kd/kc grids that feed the risk
    words), counted into SLOT_BINDING_GROUPS by the telemetry oracle."""
    G = mean.shape[0]
    if G == 0 or off_alloc.shape[0] == 0:
        return np.zeros(G, dtype=bool)
    zsq = np.float32(zsq_value(z_bp))
    per_dim = np.where(mean[:, None, :] > 0,
                       off_alloc[None, :, :]
                       // np.maximum(mean[:, None, :], 1), _BIG)
    kd = np.minimum(per_dim.min(axis=2), CHANCE_FIT_MAX).astype(np.int32)
    kc = _chance_fit_grid_np(off_alloc, mean, var.astype(np.float32),
                             zsq, kd)
    return (compat & (kc < kd)).any(axis=1) & (var > 0).any(axis=1)


def solve_stochastic_host(problem, N: int, z_bp: int,
                          right_size: bool = True):
    """Run the chance-constrained FFD on the host.

    Returns ``(node_off [N], assign [G, N], unplaced [G], cost, words
    [G])`` — the first four bit-identical to the device kernel's packed
    result (cost up to reduction order), the words identical to the
    device's appended reason words.  ``problem`` is an EncodedProblem
    with the stochastic tensors attached (group_mean / group_var)."""
    G = problem.num_groups
    catalog = problem.catalog
    off_alloc = catalog.offering_alloc().astype(np.int32)
    off_price = catalog.off_price.astype(np.float32)
    off_rank = catalog.offering_rank_price().astype(np.float32)
    zsq = np.float32(zsq_value(z_bp))
    compat = np.ascontiguousarray(problem.compat, dtype=bool)
    mean_g = problem.group_mean.astype(np.int32)
    var_g = problem.group_var.astype(np.int32)
    count_g = problem.group_count.astype(np.int32)
    cap_g = problem.group_cap.astype(np.int32)

    R = off_alloc.shape[1]
    # the empty-offering fit grids, once per solve — the mirror of
    # kernel._empty_fit_grids (kc feeds the new-node branch, kd/kc
    # together feed the risk words)
    per_dim = np.where(mean_g[:, None, :] > 0,
                       off_alloc[None, :, :]
                       // np.maximum(mean_g[:, None, :], 1), _BIG)
    kd_grid = np.minimum(per_dim.min(axis=2),
                         CHANCE_FIT_MAX).astype(np.int32)
    kc_grid = _chance_fit_grid_np(off_alloc, mean_g,
                                  var_g.astype(np.float32), zsq, kd_grid)
    node_off = np.full(N, -1, dtype=np.int32)
    node_resid = np.zeros((N, R), dtype=np.int32)
    node_var = np.zeros((N, R), dtype=np.float32)
    ptr = 0
    assign = np.zeros((G, N), dtype=np.int32)
    unplaced = np.zeros(G, dtype=np.int32)

    for gi in range(G):
        mean = mean_g[gi]
        var_f = var_g[gi].astype(np.float32)
        count = int(count_g[gi])
        cap = int(cap_g[gi])
        compat_g = compat[gi]

        is_open = node_off >= 0
        node_compat = np.where(is_open, compat_g[np.clip(node_off, 0, None)],
                               False)
        hi = np.minimum(_fit_counts_np(node_resid, mean),
                        np.int32(CHANCE_FIT_MAX))
        fit = chance_fit_np(node_resid, node_var, mean, var_f, zsq, hi)
        fit = np.where(node_compat, fit, 0)
        fit = np.minimum(fit, cap)
        cumfit = np.cumsum(fit) - fit
        take = np.clip(count - cumfit, 0, fit).astype(np.int32)
        placed = int(take.sum())
        node_resid = node_resid - take[:, None] * mean[None, :]
        node_var = node_var + take[:, None].astype(np.float32) \
            * var_f[None, :]
        rem = count - placed

        fit_empty = np.where(compat_g, kc_grid[gi], 0)
        fit_empty = np.minimum(fit_empty, cap)
        fit_empty = np.minimum(fit_empty, rem)
        with np.errstate(divide="ignore", invalid="ignore"):
            cpp = np.where(fit_empty > 0,
                           off_rank / fit_empty.astype(np.float32), np.inf)
        best = int(np.argmin(cpp))
        bf = int(fit_empty[best])

        n_new = -(-rem // max(bf, 1)) if bf > 0 else 0
        n_new = min(n_new, N - ptr)
        new_pos = np.arange(N, dtype=np.int32) - ptr
        is_new = (new_pos >= 0) & (new_pos < n_new)
        pods_new = np.where(is_new, np.clip(rem - new_pos * bf, 0, bf),
                            0).astype(np.int32)
        opened = is_new & (pods_new > 0)
        node_off = np.where(opened, best, node_off).astype(np.int32)
        node_resid = np.where(opened[:, None],
                              off_alloc[best][None, :]
                              - pods_new[:, None] * mean[None, :],
                              node_resid)
        node_var = np.where(opened[:, None],
                            pods_new[:, None].astype(np.float32)
                            * var_f[None, :],
                            node_var)
        ptr += int(opened.sum())
        unplaced[gi] = rem - int(pods_new.sum())
        assign[gi] = take + pods_new

    if right_size and G:
        load_mean = off_alloc[np.clip(node_off, 0, None)] - node_resid
        node_off = _right_size_np(node_off, load_mean, node_var, assign,
                                  compat, off_alloc, off_rank, zsq)
    is_open = node_off >= 0
    # cost word: excluded from bit-parity up to reduction order (see
    # docs/design/parity.md) — the one sanctioned float reduction
    cost = float(np.where(  # graftlint: disable=GL202 (cost word)
        is_open, off_price[np.clip(node_off, 0, None)],
        np.float32(0.0)).sum())
    from karpenter_tpu.explain.greedy import reason_words

    # reason_words already folds the overcommit_risk bit for stochastic
    # problems (via risk_words_np) — no second grid build here
    words = reason_words(problem, unplaced)
    return node_off, assign, unplaced, cost, words


def _right_size_np(node_off, load_mean, load_var, assign, compat,
                   off_alloc, off_rank, zsq):
    """numpy mirror of kernel._right_size_stochastic."""
    N = node_off.shape[0]
    is_open = node_off >= 0
    safe_off = np.clip(node_off, 0, None)
    present = (assign > 0).astype(np.float32)
    incompat = (~compat).astype(np.float32)
    incompat_count = np.einsum("gn,go->no", present, incompat)
    all_compat = incompat_count < 0.5
    diff = off_alloc[None, :, :] - load_mean[:, None, :]
    diff_f = diff.astype(np.float32)
    chance_ok = ((diff >= 0)
                 & (zsq * load_var[:, None, :] <= diff_f * diff_f)
                 ).all(axis=2)
    candidate = all_compat & chance_ok & is_open[:, None]
    rank_eff = np.broadcast_to(off_rank[None, :], (N, off_rank.shape[0]))
    cand_price = np.where(candidate, rank_eff, np.inf)
    best = cand_price.argmin(axis=1).astype(np.int32)
    best_price = cand_price.min(axis=1)
    cur_price = np.take_along_axis(rank_eff, safe_off[:, None],
                                   axis=1)[:, 0]
    improve = is_open & (best_price < cur_price - np.float32(1e-9))
    return np.where(improve, best, node_off).astype(np.int32)
