"""Device kernel: the chance-constrained FFD scan.

Same shape as ``solver/jax_backend.solve_packed`` — one donated packed
problem buffer in, one packed result buffer (node_off / unplaced / cost
/ assign tail / explain words) out — plus the small donated stochastic
suffix leaf (``stochastic/encode.pack_stochastic``).  The ONLY semantic
change vs the deterministic scan is the fit count: capacity is consumed
by MEAN, and every fit is resolved through the vectorized quantile
check

    zsq * (node_var + k * var) <= (resid_mean - k * mean)^2   per dim

via a fixed ``CHANCE_ITERS``-step integer binary search (monotone
predicate; ``feas(0)`` is a loop invariant of the packing, so the
search is exact).  The square-compare form keeps sqrt off the hot path
and — with the shared float32 ``zsq`` constant and the identical op
order — makes the numpy oracle (stochastic/greedy.py) bit-identical:
every float op is a single IEEE-rounded elementwise mul/add/compare,
never a reassociable reduction.

Deterministic degenerate case: var == 0 collapses the predicate to
``0 <= diff^2`` — the chance fit EQUALS the integer mean fit, so a
window of request-mean/zero-variance pods packs exactly as the
deterministic scan would (the strict-superset contract, asserted in
tests/test_stochastic.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from karpenter_tpu.solver.types import FIT_BIG as _BIG
from karpenter_tpu.stochastic import CHANCE_FIT_MAX, CHANCE_ITERS, zsq_value


def _fit_counts(resid, req):
    """[X,R] // [R] -> [X]; dims with req==0 are unconstrained (mirror
    of jax_backend._fit_counts, local so the kernel module has no
    import-time dependency on the 2k-line backend)."""
    per_dim = jnp.where(req[None, :] > 0,
                        resid // jnp.maximum(req[None, :], 1), _BIG)
    return jnp.min(per_dim, axis=1)


def _chance_fit(resid, var_sum, mean, var_f, zsq, hi):
    """Max k per row of ``resid`` [X,R] with accumulated variance
    ``var_sum`` [X,R] such that every dimension passes the quantile
    check — ``hi`` [X] is the integer mean-fit bound (so k*mean never
    overflows int32 and feas(hi') only tightens).  Fixed-iteration
    binary search: identical trip count on device and oracle."""
    lo = jnp.zeros_like(hi)
    for _ in range(CHANCE_ITERS):
        mid = (lo + hi + 1) // 2
        diff = resid - mid[:, None] * mean[None, :]            # int32 >= 0
        diff_f = diff.astype(jnp.float32)
        lhs = zsq * (var_sum + mid[:, None].astype(jnp.float32)
                     * var_f[None, :])
        feas = jnp.all(lhs <= diff_f * diff_f, axis=1)
        lo = jnp.where(feas, mid, lo)
        hi = jnp.where(feas, hi, mid - 1)
    return lo


def _chance_fit_grid(alloc, mean, var_f, zsq, kd):
    """Empty-node chance fit over the [G, O] grid: max k of group g on
    an EMPTY offering o.  With zero accumulated variance the constraint
    SEPARATES per dimension, so the boundary has a closed form in
    sqrt-space — per dim, ``k*m + z*sqrt(k*v) <= A`` gives
    ``sqrt(k) <= 2A / (sqrt(z^2 v + 4mA) + sqrt(z^2 v))`` (the
    cancellation-free arrangement) — refined by a 4-point monotone
    correction window under the EXACT predicate, so float rounding in
    the closed form can never change the result.  ~3x fewer tensor
    passes than the fixed binary search the fill path uses (this grid
    is the quantile check's dominant cost at real offering counts)."""
    A = alloc[None, :, :].astype(jnp.float32)              # [1, O, R]
    m = mean[:, None, :].astype(jnp.float32)               # [G, 1, R]
    bv = zsq * var_f[:, None, :]                           # z^2 v
    den = jnp.sqrt(bv + 4.0 * m * A) + jnp.sqrt(bv)
    s = jnp.where(den > 0, (2.0 * A) / den, 0.0)
    k_dim = jnp.where(mean[:, None, :] > 0, jnp.floor(s * s),
                      jnp.float32(CHANCE_FIT_MAX))
    k_hat = jnp.clip(jnp.min(k_dim, axis=2).astype(jnp.int32), 0, kd)
    k = jnp.maximum(k_hat - 2, 0)
    k0 = k
    for j in range(1, 5):
        mid = k0 + j
        diff = alloc[None, :, :] - mid[:, :, None] * mean[:, None, :]
        diff_f = diff.astype(jnp.float32)
        lhs = zsq * (mid[:, :, None].astype(jnp.float32)
                     * var_f[:, None, :])
        feas = (mid <= kd) & jnp.all(lhs <= diff_f * diff_f, axis=2)
        k = k + feas.astype(jnp.int32)
    return k


def _ffd_step_stochastic(off_alloc, off_rank, zsq, state, inputs):
    """One group through the chance-constrained scan.  Mirrors
    jax_backend._ffd_step line for line; the mean replaces the request
    in every capacity term, the open-node fill routes through the
    quantile check against the node's accumulated variance, and the
    empty-node fit arrives PRECOMPUTED (``kc_g``, one vectorized grid
    search before the scan — per-step it would re-search the whole
    offering axis per group, the dominant quantile-check cost)."""
    node_off, node_resid, node_var, ptr = state
    mean, var, count, cap, compat_g, kc_g = inputs
    var_f = var.astype(jnp.float32)

    N = node_off.shape[0]
    is_open = node_off >= 0
    node_compat = jnp.where(is_open,
                            compat_g[jnp.clip(node_off, 0, None)], False)

    # ---- fill open nodes, first-fit in age order -------------------------
    hi = jnp.minimum(_fit_counts(node_resid, mean), CHANCE_FIT_MAX)
    fit = _chance_fit(node_resid, node_var, mean, var_f, zsq, hi)
    fit = jnp.where(node_compat, fit, 0)
    fit = jnp.minimum(fit, cap)
    cumfit = jnp.cumsum(fit) - fit
    take = jnp.clip(count - cumfit, 0, fit)
    placed = jnp.sum(take)
    node_resid = node_resid - take[:, None] * mean[None, :]
    node_var = node_var + take[:, None].astype(jnp.float32) * var_f[None, :]
    rem = count - placed

    # ---- open new nodes with the cheapest-per-pod offering ---------------
    fit_empty = jnp.where(compat_g, kc_g, 0)
    fit_empty = jnp.minimum(fit_empty, cap)
    fit_empty = jnp.minimum(fit_empty, rem)
    cpp = jnp.where(fit_empty > 0, off_rank / fit_empty.astype(jnp.float32),
                    jnp.inf)
    best = jnp.argmin(cpp).astype(jnp.int32)
    bf = fit_empty[best]

    n_new = jnp.where(bf > 0, -(-rem // jnp.maximum(bf, 1)), 0)
    n_new = jnp.minimum(n_new, N - ptr)
    idx = jnp.arange(N, dtype=jnp.int32)
    new_pos = idx - ptr
    is_new = (new_pos >= 0) & (new_pos < n_new)
    pods_new = jnp.where(is_new, jnp.clip(rem - new_pos * bf, 0, bf), 0)
    opened = is_new & (pods_new > 0)
    node_off = jnp.where(opened, best, node_off)
    node_resid = jnp.where(
        opened[:, None],
        off_alloc[best][None, :] - pods_new[:, None] * mean[None, :],
        node_resid)
    node_var = jnp.where(
        opened[:, None],
        pods_new[:, None].astype(jnp.float32) * var_f[None, :],
        node_var)
    ptr = ptr + jnp.sum(opened.astype(jnp.int32))
    placed_new = jnp.sum(pods_new)
    unplaced_g = rem - placed_new
    assign_g = take + pods_new
    return (node_off, node_resid, node_var, ptr), (assign_g, unplaced_g)


def _right_size_stochastic(node_off, load_mean, load_var, assign, compat,
                           off_alloc, off_rank, zsq):
    """Per-node cheapest compatible offering whose capacity passes the
    quantile check for the node's FINAL (mean, variance) load.  Same
    structure as jax_backend._right_size; the fit test gains the
    variance term (elementwise square-compare — no float reductions,
    so the oracle mirrors bit-exactly)."""
    N = node_off.shape[0]
    is_open = node_off >= 0
    safe_off = jnp.clip(node_off, 0, None)
    present = (assign > 0).astype(jnp.float32)               # [G, N]
    incompat = (~compat).astype(jnp.float32)                 # [G, O]
    incompat_count = jnp.einsum("gn,go->no", present, incompat,
                                preferred_element_type=jnp.float32)
    all_compat = incompat_count < 0.5                        # [N, O]
    diff = off_alloc[None, :, :] - load_mean[:, None, :]     # [N, O, R]
    diff_f = diff.astype(jnp.float32)
    chance_ok = jnp.all((diff >= 0)
                        & (zsq * load_var[:, None, :] <= diff_f * diff_f),
                        axis=2)                              # [N, O]
    candidate = all_compat & chance_ok & is_open[:, None]
    rank_eff = jnp.broadcast_to(off_rank[None, :], (N, off_rank.shape[0]))
    cand_price = jnp.where(candidate, rank_eff, jnp.inf)
    best = jnp.argmin(cand_price, axis=1).astype(jnp.int32)
    best_price = jnp.min(cand_price, axis=1)
    cur_price = jnp.take_along_axis(rank_eff, safe_off[:, None],
                                    axis=1)[:, 0]
    improve = is_open & (best_price < cur_price - 1e-9)
    return jnp.where(improve, best, node_off)


def _empty_fit_grids(mean, var, off_alloc, zsq):
    """(kd [G, O], kc [G, O]): the deterministic mean fit and the
    chance-constrained fit of each group on each EMPTY offering.  Pure
    per-problem constants (mean, var, catalog, epsilon) — computed ONCE
    per problem by :func:`build_fit_grids` and kept device-resident in
    the prepared-dispatch template (the device-catalog pattern), so the
    warm solve loop re-dispatches them as inputs instead of recomputing
    the [G, O, R] grid every window."""
    var_f = var.astype(jnp.float32)
    per_dim = jnp.where(mean[:, None, :] > 0,
                        off_alloc[None, :, :]
                        // jnp.maximum(mean[:, None, :], 1), _BIG)
    kd = jnp.minimum(jnp.min(per_dim, axis=2), CHANCE_FIT_MAX)   # [G, O]
    kc = _chance_fit_grid(off_alloc, mean, var_f, zsq, kd)
    return kd, kc


@functools.partial(jax.jit, static_argnames=("G", "z_bp"))
def build_fit_grids(sto, off_alloc, *, G: int, z_bp: int):
    """Device-side grid build from the packed stochastic suffix — one
    call per (problem, catalog) at first dispatch; the returned device
    arrays ride every later solve of the window as plain inputs."""
    from karpenter_tpu.apis.pod import NUM_RESOURCES

    half = G * NUM_RESOURCES
    mean = sto[:half].reshape(G, NUM_RESOURCES)
    var = sto[half:2 * half].reshape(G, NUM_RESOURCES)
    return _empty_fit_grids(mean, var, off_alloc,
                            jnp.float32(zsq_value(z_bp)))


def _risk_words(var, count, unplaced, compat, kd, kc):
    """int32 [G] with ONLY the overcommit_risk bit: set for a live
    unplaced group carrying variance whose chance fit on some compatible
    offering is STRICTLY below its deterministic mean fit — the
    variance buffer, not the mean, is what blocked density there.
    Mirrored in explain/greedy.reason_words (the parity contract)."""
    from karpenter_tpu.explain import BIT

    has_var = jnp.any(var > 0, axis=1)
    hit = jnp.any(compat & (kc < kd), axis=1) & has_var \
        & (count > 0) & (unplaced > 0)
    return jnp.where(hit, jnp.int32(1 << BIT["overcommit_risk"]),
                     0).astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("G", "O", "U", "N", "z_bp",
                                    "right_size", "compact", "dense16",
                                    "coo16"),
                   donate_argnames=("packed", "sto"))
def solve_packed_stochastic(packed, sto, kd, kc, off_alloc, off_price,
                            off_rank, *,
                            G: int, O: int, U: int, N: int, z_bp: int,
                            right_size: bool = True, compact: int = 0,
                            dense16: bool = False, coo16: bool = False):
    """Packed-I/O chance-constrained solve.  Buffer contract identical
    to ``solve_packed`` (the deterministic fallback re-dispatches the
    same ``packed`` buffer), plus the donated stochastic suffix ``sto``
    (mean/var rows, stochastic/encode.py) and the per-problem
    device-resident fit grids ``kd``/``kc`` (:func:`build_fit_grids` —
    NOT donated, they ride every warm solve of the window).  ``z_bp``
    is z(eps) in basis points — static, so epsilon changes recompile
    per distinct bound, never per solve."""
    from karpenter_tpu.apis.pod import NUM_RESOURCES
    from karpenter_tpu.solver.jax_backend import (
        _explain_words, _pack_result, _telemetry_words, _unpack_problem,
    )

    zsq = jnp.float32(zsq_value(z_bp))
    meta, compat_i, rows_g = _unpack_problem(packed, off_alloc, G, O, U)
    half = G * NUM_RESOURCES
    mean = sto[:half].reshape(G, NUM_RESOURCES)
    var = sto[half:2 * half].reshape(G, NUM_RESOURCES)
    compat = compat_i > 0
    count, cap = meta[:, 4], meta[:, 5]

    node_off0 = jnp.full((N,), -1, dtype=jnp.int32)
    node_resid0 = jnp.zeros((N, NUM_RESOURCES), dtype=jnp.int32)
    node_var0 = jnp.zeros((N, NUM_RESOURCES), dtype=jnp.float32)
    step = functools.partial(_ffd_step_stochastic, off_alloc, off_rank, zsq)
    (node_off, node_resid, node_var, _ptr), (assign, unplaced) = lax.scan(
        step, (node_off0, node_resid0, node_var0, jnp.int32(0)),
        (mean, var, count, cap, compat, kc))
    if right_size:
        load_mean = off_alloc[jnp.clip(node_off, 0, None)] - node_resid
        node_off = _right_size_stochastic(node_off, load_mean, node_var,
                                          assign, compat, off_alloc,
                                          off_rank, zsq)
    is_open = node_off >= 0
    # cost word: excluded from bit-parity up to reduction order (see
    # docs/design/parity.md) — the one sanctioned float reduction
    cost = jnp.sum(  # graftlint: disable=GL202 (cost word)
        jnp.where(is_open, off_price[jnp.clip(node_off, 0, None)], 0.0))
    out = _pack_result(node_off, assign, unplaced, cost, compact, dense16,
                       coo16)
    words = _explain_words(meta, rows_g, compat_i,
                           unplaced.astype(jnp.int32), off_alloc)
    words = words | _risk_words(var, count, unplaced.astype(jnp.int32),
                                compat, kd, kc)
    # chance-constraint binding mask for the telemetry block: groups
    # carrying variance whose chance fit is strictly below the
    # deterministic fit somewhere compatible — regardless of placement
    # outcome (the oracle twin: stochastic/greedy.binding_mask_np)
    binding = jnp.any(compat & (kc < kd), axis=1) \
        & jnp.any(var > 0, axis=1)
    tele = _telemetry_words(meta, node_off, assign, unplaced, off_alloc,
                            binding=binding)
    return jnp.concatenate([out, words, tele])
