"""Independent chance-constraint validation + measured violation rate.

Two no-shared-code-path oracles:

- :func:`node_chance_violations` re-derives, from raw pods + catalog +
  epsilon (NEVER from solver tensors), whether every planned node
  satisfies ``sum(mean) + z(eps) * sqrt(sum(var)) <= allocatable`` per
  dimension.  ``solver/validate.py`` routes its per-node capacity check
  here when the pool overcommits — float64 with a small relative slack,
  deliberately NOT the kernel's float32 arithmetic (an independent
  check that mirrored the kernel's rounding would inherit its bugs).

- :func:`measured_violation_rate` draws actual usage from each pod's
  distribution (seeded Gaussian, truncated at zero) and measures the
  node-overload frequency — the chaos ``violation-rate-under-bound``
  invariant's probe: the EMPIRICAL rate, not the model's promise, must
  stay at or under epsilon (plus finite-sample slack).
"""

from __future__ import annotations

import math

import numpy as np

from karpenter_tpu.apis.pod import NUM_RESOURCES, PodSpec
from karpenter_tpu.stochastic import z_value

# float comparison slack for the validator: the kernel certifies the
# constraint in float32 square-compare form, which can admit a node a
# hair past the exact real-arithmetic bound — the independent check
# must not flag that rounding as a capacity violation
_REL_SLACK = 1e-5
_ABS_SLACK = 1e-3


def pod_mean_var(pod: PodSpec) -> tuple[np.ndarray, np.ndarray]:
    """(mean [R], var [R]) float64 — requests/0 when no distribution."""
    if pod.usage is None:
        return (np.asarray(pod.requests.as_tuple(), dtype=np.float64),
                np.zeros(NUM_RESOURCES, dtype=np.float64))
    return (np.asarray(pod.usage.mean.as_tuple(), dtype=np.float64),
            np.asarray(pod.usage.var, dtype=np.float64))


def node_chance_violations(node_pods: list[PodSpec], alloc,
                           eps: float, label: str = "node") -> list[str]:
    """Violation strings for ONE node's chance constraint."""
    z = z_value(eps)
    mean = np.zeros(NUM_RESOURCES, dtype=np.float64)
    var = np.zeros(NUM_RESOURCES, dtype=np.float64)
    for pod in node_pods:
        m, v = pod_mean_var(pod)
        mean += m
        var += v
    out: list[str] = []
    for r in range(NUM_RESOURCES):
        demand = mean[r] + z * math.sqrt(var[r])
        bound = float(alloc[r]) * (1.0 + _REL_SLACK) + _ABS_SLACK
        if demand > bound:
            out.append(
                f"{label}: chance constraint violated on axis {r}: "
                f"mean {mean[r]:.1f} + z({eps:g})*sqrt(var) "
                f"= {demand:.1f} > allocatable {float(alloc[r]):.1f}")
    return out


def measured_violation_rate(nodes: list[tuple[list[PodSpec], np.ndarray]],
                            trials: int = 256,
                            seed: int = 0) -> tuple[float, int]:
    """Empirical overload frequency over seeded usage draws.

    ``nodes`` is [(pods on node, allocatable [R])]; each trial draws
    every pod's usage from N(mean, var) truncated at 0.  One SAMPLE is
    a (node, trial, dimension) triple over dimensions that carry any
    variance — the unit the per-dimension chance constraint actually
    bounds at epsilon (counting "any dimension over" would union-bound
    to R*epsilon and flag correct packers).  Returns (rate, samples).
    Deterministic per seed — the chaos determinism contract."""
    rng = np.random.RandomState(seed)
    samples = 0
    overloads = 0
    for pods, alloc in nodes:
        if not pods:
            continue
        means = np.stack([pod_mean_var(p)[0] for p in pods])   # [P, R]
        stds = np.sqrt(np.stack([pod_mean_var(p)[1] for p in pods]))
        active = np.nonzero(stds.sum(axis=0) > 0)[0]
        if active.size == 0:
            continue
        draws = rng.normal(means[None, :, :], stds[None, :, :],
                           size=(trials,) + means.shape)
        draws = np.maximum(draws, 0.0)
        totals = draws.sum(axis=1)                             # [T, R]
        alloc_f = np.asarray(alloc, dtype=np.float64)
        over = totals[:, active] > alloc_f[None, active]       # [T, A]
        overloads += int(over.sum())
        samples += trials * int(active.size)
    return (overloads / samples if samples else 0.0), samples


def violation_bound(eps: float, samples: int) -> float:
    """The pass bar for a finite-sample measured rate: eps plus three
    binomial standard errors (a correct packer still shows sampling
    noise; a broken one blows far past this)."""
    if samples <= 0:
        return eps
    return eps + 3.0 * math.sqrt(max(eps * (1.0 - eps), 1e-9) / samples) \
        + 1e-9
