"""Stochastic lowering: pod usage distributions -> dense group tensors.

The host half of the chance-constrained plane.  ``solver/encode.py``
calls :func:`usage_rows` per signature group while it builds the other
group columns, so the mean/var tensors ride the SAME grouping, FFD
sort, and spread-split the deterministic columns do — a stochastic
group row is always aligned with its ``group_req`` row.

Wire format: the per-window packed buffer the solve dispatch uploads is
UNCHANGED (the deterministic fallback must be able to re-dispatch the
identical buffer); the stochastic tensors travel as one extra int32
suffix leaf built by :func:`pack_stochastic` —

    [0,   G*4)   group mean  [G, R]  (int32, request units)
    [G*4, G*8)   group var   [G, R]  (int32, request units squared)

— small (64 B per group) and donated with the dispatch (GL006).
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.apis.pod import NUM_RESOURCES, PodSpec


def usage_rows(pod: PodSpec) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """(mean row, var row) for one representative pod.

    Defaults make the plane a strict superset: no distribution ->
    (requests, 0).  The pods axis is floored at 1 exactly as the
    deterministic ``req_row`` is — every pod occupies a slot, so the
    chance-fit binary search always has a finite per-node bound."""
    req = pod.requests.as_tuple()
    if pod.usage is None:
        mean = (req[0], req[1], req[2], max(req[3], 1))
        return mean, (0, 0, 0, 0)
    m = pod.usage.mean.as_tuple()
    return (m[0], m[1], m[2], max(m[3], 1)), tuple(pod.usage.var)


def stack_usage(g_mean: list, g_var: list) -> tuple[np.ndarray, np.ndarray]:
    """Group rows -> the int32 [G, R] tensors the kernel consumes."""
    G = len(g_mean)
    mean = np.asarray(g_mean, dtype=np.int32).reshape(G, NUM_RESOURCES)
    var = np.asarray(g_var, dtype=np.int32).reshape(G, NUM_RESOURCES)
    return mean, var


def pack_stochastic(group_mean: np.ndarray, group_var: np.ndarray,
                    G_pad: int) -> np.ndarray:
    """The int32 suffix leaf: mean rows then var rows, zero-padded to
    the group bucket (padding groups carry mean 0 / var 0 and place
    nothing — the scan's count column is already 0 for them)."""
    G = group_mean.shape[0]
    buf = np.zeros(G_pad * 2 * NUM_RESOURCES, dtype=np.int32)
    buf[:G * NUM_RESOURCES] = group_mean.reshape(-1)
    half = G_pad * NUM_RESOURCES
    buf[half:half + G * NUM_RESOURCES] = group_var.reshape(-1)
    return buf


def unpack_stochastic(buf: np.ndarray, G_pad: int
                      ) -> tuple[np.ndarray, np.ndarray]:
    """Host-side inverse of :func:`pack_stochastic` (tests, oracle)."""
    half = G_pad * NUM_RESOURCES
    mean = np.asarray(buf[:half]).reshape(G_pad, NUM_RESOURCES)
    var = np.asarray(buf[half:2 * half]).reshape(G_pad, NUM_RESOURCES)
    return mean, var
