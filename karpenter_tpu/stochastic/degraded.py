"""Degraded mode: deterministic-requests fallback.

The chance-constrained kernel can fail the same ways any device kernel
can (dead tunnel, Mosaic/XLA fault, a poisoned donated buffer).  None
of those may fail a solve window — the ``ResilientSolver`` convention:
the dispatch strips the stochastic suffix and re-runs the IDENTICAL
packed buffer through the deterministic scan (packing by request, zero
overcommit), with an ``ERRORS`` breadcrumb and the
``karpenter_tpu_overcommit_solves_total{mode="degraded"}`` counter so
dashboards see every degradation.  Semantics of the fallback are the
strict-superset guarantee in reverse: requests upper-bound usage, so a
deterministic plan is always chance-feasible at ANY epsilon.
"""

from __future__ import annotations

from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("stochastic.degraded")


def strip_stochastic(prep) -> None:
    """Disarm the stochastic route on a prepared dispatch IN PLACE: the
    next ``_dispatch`` of this prep (and of its cached template — a
    broken kernel must not re-break every later window of the same
    shape) runs the deterministic scan on the unchanged base buffer."""
    prep.sto = None
    tmpl = getattr(prep, "tmpl", None)
    if tmpl is not None:
        tmpl.sto = None


def note_degraded(prep, error: Exception) -> None:
    """One degradation breadcrumb: log + metric, then strip."""
    log.warning("stochastic kernel failed; deterministic-requests "
                "fallback engaged", error=str(error)[:300],
                G=prep.G_pad, O=prep.O_pad, N=prep.N)
    metrics.ERRORS.labels("solver", "stochastic_fallback").inc()
    metrics.OVERCOMMIT_SOLVES.labels("degraded").inc()
    strip_stochastic(prep)


def deterministic_problem(problem):
    """Problem-level fallback (host paths): the same window with the
    stochastic tensors dropped — packing reverts to requests."""
    if getattr(problem, "group_var", None) is None:
        return problem
    return problem.replace(group_mean=None, group_var=None,
                           overcommit_eps=0.0)
