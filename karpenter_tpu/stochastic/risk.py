"""Spot-interruption risk model: per-(type, zone) rates from the ledger.

The PR-6 placement ledger now keeps LABELED lifecycle history for spot
capacity (obs/ledger.py ``node_seen`` / ``interruption``): every spot
scan round counts one exposure per live spot instance, every observed
spot preemption counts one interruption — both stamped by the
production ``SpotPreemptionController`` from ground-truth cloud state,
so chaos spot-storm / overload / oversubscribe runs generate exactly
the histories production would.

The model is deliberately a COUNT-REPRODUCING estimator, not a fitted
curve: ``rate = interruptions / max(exposures, 1)``, clamped to [0, 1].
That is what makes the chaos ``risk-model-consistent`` invariant sharp
— the priced rates must equal the ledger's observed counts EXACTLY, so
any drift between what the solver prices and what the fleet actually
experienced is a violation, not a tolerance.  An empty ledger degrades
to the zero-risk prior: every rate is exactly 0.0, no NaN z-scores, no
division by zero (tests/test_stochastic.py pins both).

Pricing: expected eviction cost enters offering RANKING (the choice
tensor), never real cost accounting — a spot offering with observed
interruption rate r ranks as ``rank * (1 + RISK_LAMBDA * r)``, so
cost-comparable placements prefer capacity that historically survives.
The model persists across restarts through the recovery journal's
keyed state records (``spot_risk/<type>/<zone>``), the same channel
nominations and gang admissions ride.
"""

from __future__ import annotations

import threading

from karpenter_tpu.utils import metrics

# ranking penalty weight: a spot offering observed interrupted on every
# exposure ranks as (1 + RISK_LAMBDA)x its price — strong enough to
# lose ties against clean zones, never a hard mask (availability
# blackouts own the hard path)
RISK_LAMBDA = 1.0

STATE_PREFIX = "spot_risk/"


class SpotRiskModel:
    """Per-(instance type, zone) spot-interruption rates (see module
    docstring).  Thread-safe; counts are plain integers so snapshots
    and the consistency invariant compare exactly."""

    def __init__(self):
        self._lock = threading.Lock()
        self._interrupted: dict[tuple[str, str], int] = {}
        self._exposure: dict[tuple[str, str], int] = {}
        self.generation = 0

    # -- learning ----------------------------------------------------------

    @classmethod
    def from_ledger(cls, ledger) -> "SpotRiskModel":
        """Rebuild from the ledger's labeled lifecycle history — the
        canonical constructor (chaos re-derives through this same path
        for the consistency invariant)."""
        model = cls()
        hist = ledger.interruption_history()
        with model._lock:
            model._interrupted = dict(hist.get("interrupted", {}))
            model._exposure = dict(hist.get("exposure", {}))
            model.generation += 1
        return model

    def observe(self, itype: str, zone: str, *, interrupted: int = 0,
                exposure: int = 0) -> None:
        with self._lock:
            key = (itype, zone)
            if interrupted:
                self._interrupted[key] = \
                    self._interrupted.get(key, 0) + interrupted
            if exposure:
                self._exposure[key] = \
                    self._exposure.get(key, 0) + exposure
            self.generation += 1

    # -- readout -----------------------------------------------------------

    def rate(self, itype: str, zone: str) -> float:
        """Observed interruption rate in [0, 1]; 0.0 (zero-risk prior)
        when the pair was never exposed — never NaN, never a division
        by zero."""
        with self._lock:
            key = (itype, zone)
            n = self._interrupted.get(key, 0)
            d = self._exposure.get(key, 0)
        if d <= 0:
            # interruptions with no recorded exposure (history trimmed,
            # partial journal) still price as fully risky, not as safe
            return 1.0 if n > 0 else 0.0
        return min(1.0, n / d)

    def counts(self) -> dict[tuple[str, str], tuple[int, int]]:
        """(interrupted, exposure) per pair — the invariant's exact
        comparison surface."""
        with self._lock:
            keys = set(self._interrupted) | set(self._exposure)
            return {k: (self._interrupted.get(k, 0),
                        self._exposure.get(k, 0)) for k in sorted(keys)}

    def snapshot(self) -> dict:
        """The /debug/risk payload."""
        rows = []
        for (itype, zone), (n, d) in self.counts().items():
            rows.append({"instance_type": itype, "zone": zone,
                         "interrupted": n, "exposure": d,
                         "rate": round(self.rate(itype, zone), 6)})
        return {"risk_lambda": RISK_LAMBDA, "generation": self.generation,
                "pairs": rows}

    def update_metrics(self) -> None:
        """Refresh ``karpenter_tpu_spot_risk_rate{instance_type, zone}``
        for every observed pair (cardinality bounded by the catalog:
        types x zones)."""
        for (itype, zone), _ in self.counts().items():
            metrics.SPOT_RISK_RATE.labels(itype, zone).set(
                self.rate(itype, zone))

    # -- pricing -----------------------------------------------------------

    def risk_column(self, catalog, lam: float = RISK_LAMBDA):
        """Pure form of the pricing: the float32 [O] expected-eviction
        column this model implies for ``catalog`` — spot offerings get
        ``lam * rate``, on-demand stays 0.  The chaos consistency
        invariant re-derives this column independently and compares it
        to what the catalog actually carries."""
        import numpy as np

        from karpenter_tpu.catalog.arrays import CAPACITY_TYPES

        spot_idx = CAPACITY_TYPES.index("spot")
        risk = np.zeros(catalog.num_offerings, dtype=np.float32)
        for o in range(catalog.num_offerings):
            if int(catalog.off_cap[o]) != spot_idx:
                continue
            itype, zone, _cap = catalog.describe_offering(o)
            r = self.rate(itype, zone)
            if r > 0.0:
                risk[o] = np.float32(lam * r)
        return risk

    def price_catalog(self, catalog, lam: float = RISK_LAMBDA) -> None:
        """Attach expected-eviction-cost ranking to a catalog: spot
        offerings gain ``off_risk = lam * rate`` (on-demand stays 0),
        and the catalog's risk generation bumps so device-resident rank
        tensors re-upload (solver keys on it).  Idempotent for an
        unchanged model: the generation bumps only when the column
        actually changed."""
        import numpy as np

        risk = self.risk_column(catalog, lam)
        prev = getattr(catalog, "off_risk", None)
        if prev is not None and np.array_equal(prev, risk):
            return
        catalog.off_risk = risk
        catalog.risk_generation = getattr(catalog, "risk_generation", 0) + 1

    # -- persistence (recovery journal state records) ----------------------

    def save(self, journal) -> None:
        """One keyed state record per observed pair — newest-wins, so a
        restart rebuilds the exact counts (recovery/journal.py)."""
        for (itype, zone), (n, d) in self.counts().items():
            journal.state(f"{STATE_PREFIX}{itype}/{zone}",
                          {"interrupted": n, "exposure": d})

    @classmethod
    def load(cls, journal) -> "SpotRiskModel":
        model = cls()
        for key, value in journal.state_map().items():
            if not key.startswith(STATE_PREFIX) or not isinstance(value,
                                                                  dict):
                continue
            rest = key[len(STATE_PREFIX):]
            parts = rest.rsplit("/", 1)
            if len(parts) != 2:
                continue
            itype, zone = parts
            model.observe(itype, zone,
                          interrupted=int(value.get("interrupted", 0)),
                          exposure=int(value.get("exposure", 0)))
        return model


_MODEL = SpotRiskModel()


def get_risk_model() -> SpotRiskModel:
    return _MODEL


def refresh_from_ledger(ledger) -> SpotRiskModel:
    """Rebuild the process model from the ledger history and refresh
    its metric family — the /debug/risk and chaos pump entry point."""
    global _MODEL
    _MODEL = SpotRiskModel.from_ledger(ledger)
    _MODEL.update_metrics()
    return _MODEL
