from karpenter_tpu.parallel.mesh import fleet_mesh, solver_mesh
from karpenter_tpu.parallel.fleet import (
    FleetProblem, fleet_device_catalog, fleet_solve, fleet_solve_pallas,
    fleet_solve_sharded_offerings,
)

__all__ = ["fleet_mesh", "solver_mesh", "FleetProblem",
           "fleet_device_catalog", "fleet_solve", "fleet_solve_pallas",
           "fleet_solve_sharded_offerings"]
