from karpenter_tpu.parallel.mesh import fleet_mesh, shard_mesh, solver_mesh
from karpenter_tpu.parallel.fleet import (
    CooCapacity, FleetProblem, fleet_device_catalog, fleet_pack_inputs,
    fleet_parse_outputs, fleet_solve, fleet_solve_pallas,
    fleet_solve_pallas_sharded, fleet_solve_sharded_offerings,
)

__all__ = ["fleet_mesh", "shard_mesh", "solver_mesh", "CooCapacity",
           "FleetProblem", "fleet_device_catalog", "fleet_pack_inputs",
           "fleet_parse_outputs", "fleet_solve", "fleet_solve_pallas",
           "fleet_solve_pallas_sharded", "fleet_solve_sharded_offerings"]
