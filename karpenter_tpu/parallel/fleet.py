"""Fleet-scale solves: SPMD over the device mesh.

Two composable parallel dimensions (SURVEY.md §5.7, BASELINE config #5):

- **fleet axis** (data parallel): C independent cluster problems stacked on
  a leading axis, shard_map'd over ``fleet`` — each device solves its
  clusters with the plain ``solve_core``.  Embarrassingly parallel; no
  collectives (quota coupling is modeled as per-shard caps first, per
  SURVEY.md §7.4).

- **offer axis** (model parallel): ONE cluster's offering catalog sharded
  across ``offer`` devices.  Node state (which offering each node runs,
  residual capacity) is replicated; each FFD step computes its local
  shard's fit/cost-per-pod, then the winner is combined with
  ``lax.pmin`` and the winner's capacity row is broadcast with
  ``lax.psum`` — the collectives ride ICI, never the host.  Useful when
  the catalog axis outgrows one chip's VMEM-friendly tile or when
  offering-mask construction dominates.

Both entry points take numpy inputs padded by the caller (same bucketing
as JaxSolver) and return stacked numpy results bit-identical to running
``solve_kernel`` per cluster (tests assert this).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# jax >=0.8 renamed check_rep -> check_vma; disable either way (outputs are
# replicated over the offer axis by construction via psum/pmin).
_CHECK_KW = ("check_vma" if "check_vma" in inspect.signature(_shard_map).parameters
             else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check_rep})

from karpenter_tpu.faulttol import device_guard, device_ids
from karpenter_tpu.obs.devtel import get_devtel
from karpenter_tpu.obs.prof import get_profiler
from karpenter_tpu.parallel.mesh import FLEET_AXIS, OFFER_AXIS
from karpenter_tpu.solver.jax_backend import _fit_counts, _right_size, solve_core

_BIG_I32 = jnp.int32(2 ** 31 - 1)


@dataclass
class FleetProblem:
    """Stacked multi-cluster problem: leading axis = cluster."""

    group_req: np.ndarray      # [C, G, R] int32
    group_count: np.ndarray    # [C, G] int32
    group_cap: np.ndarray      # [C, G] int32
    compat: np.ndarray         # [C, G, O] bool
    off_alloc: np.ndarray      # [C, O, R] int32
    off_price: np.ndarray      # [C, O] float32
    off_rank: np.ndarray       # [C, O] float32

    @property
    def num_clusters(self) -> int:
        return self.group_req.shape[0]


# ---------------------------------------------------------------------------
# Fleet axis: clusters data-parallel
# ---------------------------------------------------------------------------

def fleet_device_catalog(problem: FleetProblem):
    """Device-resident per-cluster catalog tensors for the pallas fleet
    path — upload ONCE, reuse across solve windows (catalogs are static
    between refreshes; only the per-window problem buffer should move)."""
    from karpenter_tpu.solver.pallas_kernel import pack_catalog

    C = problem.num_clusters
    alloc8, rank = [], []
    for c in range(C):
        a8, rr = pack_catalog(problem.off_alloc[c], problem.off_rank[c])
        alloc8.append(a8)
        rank.append(rr)
    return (jax.device_put(np.stack(alloc8)),
            jax.device_put(np.stack(rank)),
            jax.device_put(problem.off_price.astype(np.float32)))


@functools.partial(jax.jit, static_argnames=("C", "G", "O", "U", "N",
                                             "right_size", "interpret",
                                             "compact"))
def fleet_packed_pallas(big, alloc8_all, rank_all, price_all, *, C: int,
                        G: int, O: int, U: int, N: int,
                        right_size: bool = True, interpret: bool = False,
                        compact: int = 0):
    """The whole fleet as ONE device program: vmapped packed-input
    unpacking, ONE Mosaic launch over the (C, G//Gb) grid
    (ffd_scan_pallas_fleet), vmapped right-sizing + result packing.
    [C, Li] packed problems in, [C, Lo] packed results out — one H2D,
    one dispatch, one D2H for the entire fleet (round 3 paid C
    sequential Mosaic dispatches here, 173 ms for C=8)."""
    from karpenter_tpu.solver.jax_backend import (
        _pack_result, _unpack_problem, finish_pallas_solve,
    )
    from karpenter_tpu.solver.pallas_kernel import ffd_scan_pallas_fleet

    off_alloc_all = alloc8_all[:, :4].transpose(0, 2, 1)      # [C,O,R]
    # the fleet wire keeps the bare _pack_result layout (no explain
    # suffix): its parser is fleet_parse_outputs, not unpack_result, and
    # repack consumers re-derive reasons host-side when they need them
    metas, compats, _rows = jax.vmap(
        lambda p, a: _unpack_problem(p, a, G, O, U))(big, off_alloc_all)
    node_off, assign, unplaced = ffd_scan_pallas_fleet(
        metas, compats, alloc8_all, rank_all, C=C, G=G, O=O, N=N,
        interpret=interpret)

    def finish_one(meta, compat_i, node_off_c, assign_c, unplaced_c,
                   alloc8, rank_row, price):
        # the shared post-kernel tail (jax_backend.finish_pallas_solve):
        # right-sizing + cost must not fork between single and fleet
        node_off_c, cost = finish_pallas_solve(
            meta, compat_i, node_off_c, assign_c, alloc8, rank_row, price,
            right_size)
        return _pack_result(node_off_c, assign_c, unplaced_c, cost, compact)

    return jax.vmap(finish_one)(metas, compats, node_off, assign, unplaced,
                                alloc8_all, rank_all, price_all)


def fleet_pack_inputs(problem: FleetProblem):
    """Stacked packed per-cluster buffers [C, Li] + the common label-row
    bucket (one compiled executable across clusters)."""
    from karpenter_tpu.solver.jax_backend import _pad2, dedup_rows, pack_input
    from karpenter_tpu.solver.types import LABELROW_BUCKETS, bucket

    C, G, O = problem.compat.shape
    factored = [dedup_rows(problem.compat[c]) for c in range(C)]
    U_pad = bucket(max(max(r.shape[0] for _, r in factored), 1),
                   LABELROW_BUCKETS)
    ins = np.stack([pack_input(problem.group_req[c], problem.group_count[c],
                               problem.group_cap[c], factored[c][0],
                               _pad2(factored[c][1], U_pad, O))
                    for c in range(C)])
    return ins, U_pad


def fleet_parse_outputs(out_np: np.ndarray, C: int, G: int, N: int, K: int):
    from karpenter_tpu.solver.jax_backend import unpack_result

    node_off = np.empty((C, N), np.int32)
    assign = np.empty((C, G, N), np.int32)
    unplaced = np.empty((C, G), np.int32)
    cost = np.empty(C, np.float32)
    for c in range(C):
        node_off[c], assign[c], unplaced[c], cost[c] = unpack_result(
            out_np[c], G, N, K)
    return node_off, assign, unplaced, cost


class CooCapacity:
    """COO fetch capacity shared across solve windows: starts small (D2H
    bytes are tunnel latency), grows on the overflow signal, and STAYS
    grown — without persistence every subsequent window of an nnz-heavy
    workload would re-pay the double dispatch + extra blocking round
    trip the shrink exists to remove."""

    __slots__ = ("k", "cap")

    def __init__(self, initial: int, cap: int):
        self.k = min(initial, cap)
        self.cap = cap


def fleet_solve_pallas(problem: FleetProblem, *, num_nodes: int,
                       right_size: bool = True, interpret: bool = False,
                       device_catalog=None, compact: int = 0,
                       compact_cap: int | None = None,
                       coo_state: CooCapacity | None = None,
                       packed_inputs=None, async_only: bool = False,
                       resident_buf=None):
    """Single-dispatch fleet solve through the Mosaic fleet grid.
    ``device_catalog`` (from :func:`fleet_device_catalog`) keeps the
    catalog upload out of the per-window path; ``packed_inputs`` (from
    :func:`fleet_pack_inputs`) hoists host packing out of a timing
    loop; ``async_only`` returns a zero-arg finalizer (the result copy
    is already in flight) for pipelined window streams.  ``compact``
    may start below the nnz bound ``compact_cap`` — D2H payload is
    latency through the tunnel — and the finalizer re-dispatches at 4x
    on the sound full-buffer overflow signal (jax_backend.coo_buffer_
    full).  ``resident_buf`` (a resident.store.ResidentBuffer) keeps the
    stacked input DEVICE-RESIDENT across windows: an unchanged window
    reuses the buffer outright and a churned one moves only the padded
    word delta through the donated update kernel — the fleet-path arm
    of ROADMAP-1 (per-window H2D bounded by the delta, not C x Li)."""
    from karpenter_tpu.solver.jax_backend import coo_buffer_full, grow_coo

    C, G, O = problem.compat.shape
    N = max(num_nodes, 128)
    ins, U_pad = packed_inputs or fleet_pack_inputs(problem)
    if device_catalog is None:
        device_catalog = fleet_device_catalog(problem)
    alloc8_all, rank_all, price_all = device_catalog
    if coo_state is None:
        coo_state = CooCapacity(
            min(compact, G * N),
            min(compact_cap if compact_cap is not None else compact, G * N))
    dispatch_ins = ins
    if resident_buf is not None:
        # the buffer accounts its own telemetry (delta vs rebuild bytes,
        # donated update dispatch); the solve dispatch below then sees a
        # device-resident input (no H2D, no donation miss).  Safe to
        # reuse across retries: fleet_packed_pallas does not donate it.
        dispatch_ins, _ = resident_buf.update(ins, kernel="fleet-resident")

    def dispatch(K):
        # device telemetry at DISPATCH level (never inside the traced
        # kernel — GL107): a host-numpy input is an H2D upload and a
        # donation miss; a new (C,G,O,U,N,K) signature is a recompile
        host_input = isinstance(dispatch_ins, np.ndarray)
        get_devtel().note_dispatch(
            "fleet-pallas", (C, G, O, U_pad, N, K, right_size),
            h2d_bytes=int(ins.nbytes) if host_input else 0,
            donated=not host_input)
        with device_guard("fleet-pallas"):
            with get_profiler().sampled("fleet-pallas") as probe:
                out_dev = fleet_packed_pallas(
                    dispatch_ins, alloc8_all, rank_all, price_all,
                    C=C, G=G, O=O, U=U_pad, N=N, right_size=right_size,
                    interpret=interpret, compact=K)
                probe.dispatched(out_dev)
        try:
            out_dev.copy_to_host_async()
        except Exception:  # noqa: BLE001 — cpu arrays
            pass
        return out_dev

    K0 = coo_state.k
    out_dev = dispatch(K0)

    def finalize():
        K, dev = K0, out_dev
        while True:
            with device_guard("fleet-pallas") as guard:
                out_np = guard.fetch(dev)
            get_devtel().note_d2h(int(out_np.nbytes))
            if K > 0 and K < coo_state.cap and any(
                    coo_buffer_full(out_np[c], G, N, K) for c in range(C)):
                K = grow_coo(K, coo_state.cap)
                coo_state.k = max(coo_state.k, K)   # persist across windows
                dev = dispatch(K)
                continue
            return fleet_parse_outputs(out_np, C, G, N, K)

    return finalize if async_only else finalize()


@functools.lru_cache(maxsize=64)
def _fleet_pallas_sharded_jit(mesh: Mesh, C_local: int, G: int, O: int,
                              U: int, N: int, right_size: bool,
                              interpret: bool, compact: int):
    """Cached jit of the sharded pallas fleet grid: shard_map + jit were
    previously rebuilt per solve call, so every window paid a fresh
    trace + XLA compile (GL003).  Keyed on the mesh and every static
    shape/option; COO escalation (`compact` growth) lands on its own
    cache line."""
    def inner(big_l, alloc8_l, rank_l, price_l):
        return fleet_packed_pallas(
            big_l, alloc8_l, rank_l, price_l,
            C=C_local, G=G, O=O, U=U, N=N, right_size=right_size,
            interpret=interpret, compact=compact)

    spec = P(FLEET_AXIS)
    return jax.jit(shard_map(inner, mesh=mesh, in_specs=(spec,) * 4,
                             out_specs=spec, check_rep=False))


def fleet_solve_pallas_sharded(problem: FleetProblem, mesh: Mesh, *,
                               num_nodes: int, right_size: bool = True,
                               interpret: bool = False, compact: int = 0,
                               compact_cap: int | None = None):
    """Fleet axis sharded over the mesh, each shard running the Mosaic
    fleet grid on its local clusters — the pallas fast path under
    shard_map (round 3 gap: only solve_core had a sharded variant).
    C % fleet-axis == 0 required; bit-identical to the single-chip
    fleet path per cluster.  An undersized ``compact`` escalates on the
    same full-buffer overflow signal as the single-chip path."""
    from karpenter_tpu.solver.jax_backend import coo_buffer_full, grow_coo

    n = mesh.shape[FLEET_AXIS]
    C, G, O = problem.compat.shape
    if C % n:
        raise ValueError(f"clusters {C} not divisible by fleet axis {n}")
    N = max(num_nodes, 128)
    ins, U_pad = fleet_pack_inputs(problem)
    alloc8_all, rank_all, price_all = fleet_device_catalog(problem)
    K = min(compact, G * N)
    K_cap = min(compact_cap if compact_cap is not None else compact, G * N)

    while True:
        f = _fleet_pallas_sharded_jit(mesh, C // n, G, O, U_pad, N,
                                      right_size, interpret, K)
        get_devtel().note_dispatch(
            "fleet-pallas-sharded", (n, C, G, O, U_pad, N, K, right_size),
            h2d_bytes=int(ins.nbytes), donated=False)
        with device_guard("fleet-pallas-sharded",
                          devices=device_ids(mesh.devices.flat)) as guard:
            with get_profiler().sampled("fleet-pallas-sharded") as probe:
                out_dev = f(jnp.asarray(ins), alloc8_all, rank_all, price_all)
                probe.dispatched(out_dev)
            out_np = guard.fetch(out_dev)
        get_devtel().note_d2h(int(out_np.nbytes))
        if K > 0 and K < K_cap and any(
                coo_buffer_full(out_np[c], G, N, K) for c in range(C)):
            K = grow_coo(K, K_cap)
            continue
        return fleet_parse_outputs(out_np, C, G, N, K)


def fleet_solve(problem: FleetProblem, mesh: Mesh, *, num_nodes: int,
                right_size: bool = True):
    """Solve C cluster problems across the mesh's fleet axis.

    C must be divisible by the fleet-axis size.  Returns stacked
    (node_off [C,N], assign [C,G,N], unplaced [C,G], cost [C]).
    """
    f = _fleet_solve_jit(mesh, num_nodes, right_size)
    h2d = sum(int(a.nbytes) for a in (
        problem.group_req, problem.group_count, problem.group_cap,
        problem.compat, problem.off_alloc, problem.off_price,
        problem.off_rank) if isinstance(a, np.ndarray))
    get_devtel().note_dispatch(
        "fleet-scan", problem.compat.shape + (num_nodes, right_size),
        h2d_bytes=h2d, donated=h2d == 0)
    with device_guard("fleet-scan",
                      devices=device_ids(mesh.devices.flat)) as guard:
        with get_profiler().sampled("fleet-scan") as probe:
            out = f(problem.group_req, problem.group_count, problem.group_cap,
                    problem.compat, problem.off_alloc, problem.off_price,
                    problem.off_rank)
            probe.dispatched(out)
        res = guard.fetch(out)
    get_devtel().note_d2h(sum(int(o.nbytes) for o in res))
    return res


@functools.lru_cache(maxsize=64)
def _fleet_solve_jit(mesh: Mesh, num_nodes: int, right_size: bool):
    """Cached jit of the fleet-axis vmapped solve (per-call shard_map +
    jit rebuild recompiled every invocation — GL003)."""
    vsolve = jax.vmap(functools.partial(
        solve_core, num_nodes=num_nodes, right_size=right_size))
    spec = P(FLEET_AXIS)
    return jax.jit(shard_map(vsolve, mesh=mesh,
                             in_specs=(spec,) * 7, out_specs=(spec,) * 4,
                             check_rep=False))


# ---------------------------------------------------------------------------
# Offer axis: catalog model-parallel with pmin/psum collectives
# ---------------------------------------------------------------------------

def _gather_global(values_local, global_idx, my_base, axis_name):
    """Fetch values at global offering indices from a sharded [O_l, ...]
    array: each shard contributes its in-range entries, psum combines."""
    O_l = values_local.shape[0]
    pos = jnp.clip(global_idx - my_base, 0, O_l - 1)
    in_range = (global_idx >= my_base) & (global_idx < my_base + O_l)
    local = jnp.where(
        in_range.reshape(in_range.shape + (1,) * (values_local.ndim - 1)),
        values_local[pos], 0)
    return lax.psum(local, axis_name)


def _ffd_step_sharded(axis_name, off_alloc_l, off_rank_l, state, inputs):
    """One FFD step with the offering axis sharded across ``axis_name``.

    Node state is replicated; the cheapest-per-pod offering is chosen with
    a two-stage pmin (min cost, then min global index among ties) and the
    winner's allocatable row is psum-broadcast."""
    node_off, node_resid, ptr = state
    req, count, cap, compat_l = inputs

    N = node_off.shape[0]
    O_l = off_rank_l.shape[0]
    my_base = lax.axis_index(axis_name).astype(jnp.int32) * O_l
    is_open = node_off >= 0

    # group-vs-open-node compatibility: gather compat at global node_off
    compat_i32 = compat_l.astype(jnp.int32)
    node_compat = _gather_global(compat_i32, node_off, my_base, axis_name) > 0
    node_compat = node_compat & is_open

    fit = _fit_counts(node_resid, req)
    fit = jnp.where(node_compat, fit, 0)
    fit = jnp.minimum(fit, cap)
    cumfit = jnp.cumsum(fit) - fit
    take = jnp.clip(count - cumfit, 0, fit)
    placed = jnp.sum(take)
    node_resid = node_resid - take[:, None] * req[None, :]
    rem = count - placed

    # local cheapest-per-pod, then global combine (fit capped by the pods
    # remaining, matching _ffd_step — parity with the unsharded kernel)
    fit_empty = _fit_counts(off_alloc_l, req)
    fit_empty = jnp.where(compat_l, fit_empty, 0)
    fit_empty = jnp.minimum(jnp.minimum(fit_empty, cap), rem)
    cpp = jnp.where(fit_empty > 0, off_rank_l / fit_empty.astype(jnp.float32),
                    jnp.inf)
    local_arg = jnp.argmin(cpp).astype(jnp.int32)
    local_min = cpp[local_arg]
    global_min = lax.pmin(local_min, axis_name)
    # tie-break: lowest global index among shards achieving the min
    cand = jnp.where(local_min == global_min, my_base + local_arg, _BIG_I32)
    best = lax.pmin(cand, axis_name)
    have_best = jnp.isfinite(global_min)
    # winner's fit + alloc row, broadcast
    mine = (best >= my_base) & (best < my_base + O_l)
    bf = lax.psum(jnp.where(mine, fit_empty[jnp.clip(best - my_base, 0, O_l - 1)], 0),
                  axis_name)
    bf = jnp.where(have_best, bf, 0)
    best_alloc = lax.psum(
        jnp.where(mine, off_alloc_l[jnp.clip(best - my_base, 0, O_l - 1)],
                  jnp.zeros_like(off_alloc_l[0])), axis_name)

    n_new = jnp.where(bf > 0, -(-rem // jnp.maximum(bf, 1)), 0)
    n_new = jnp.minimum(n_new, N - ptr)
    idx = jnp.arange(N, dtype=jnp.int32)
    new_pos = idx - ptr
    is_new = (new_pos >= 0) & (new_pos < n_new)
    pods_new = jnp.where(is_new, jnp.clip(rem - new_pos * bf, 0, bf), 0)
    opened = is_new & (pods_new > 0)
    node_off = jnp.where(opened, best, node_off)
    node_resid = jnp.where(opened[:, None],
                           best_alloc[None, :] - pods_new[:, None] * req[None, :],
                           node_resid)
    ptr = ptr + jnp.sum(opened.astype(jnp.int32))
    unplaced_g = rem - jnp.sum(pods_new)
    assign_g = take + pods_new
    return (node_off, node_resid, ptr), (assign_g, unplaced_g)


def _right_size_sharded(axis_name, node_off, node_resid, assign,
                        compat_l, off_alloc_l, off_rank_l):
    """Sharded right-sizing: each shard proposes its best local candidate
    per node; pmin picks the global winner."""
    O_l = off_rank_l.shape[0]
    my_base = lax.axis_index(axis_name).astype(jnp.int32) * O_l
    is_open = node_off >= 0
    alloc_at = _gather_global(off_alloc_l, node_off, my_base, axis_name)
    load = alloc_at - node_resid

    present = (assign > 0).astype(jnp.float32)
    incompat = (~compat_l).astype(jnp.float32)
    incompat_count = jnp.einsum("gn,go->no", present, incompat,
                                preferred_element_type=jnp.float32)
    all_compat = incompat_count < 0.5
    fits = jnp.all(off_alloc_l[None, :, :] >= load[:, None, :], axis=2)
    candidate = all_compat & fits & is_open[:, None]
    cand_price = jnp.where(candidate, off_rank_l[None, :], jnp.inf)
    local_arg = jnp.argmin(cand_price, axis=1).astype(jnp.int32)
    local_min = jnp.take_along_axis(cand_price, local_arg[:, None], axis=1)[:, 0]
    global_min = lax.pmin(local_min, axis_name)
    cand_idx = jnp.where(local_min == global_min, my_base + local_arg, _BIG_I32)
    best = lax.pmin(cand_idx, axis_name)

    cur_rank_local = jnp.where(
        (node_off >= my_base) & (node_off < my_base + O_l),
        off_rank_l[jnp.clip(node_off - my_base, 0, O_l - 1)], 0.0)
    cur_rank = lax.psum(cur_rank_local, axis_name)
    improve = is_open & jnp.isfinite(global_min) & (global_min < cur_rank - 1e-9)
    new_off = jnp.where(improve, best, node_off)
    new_alloc = _gather_global(off_alloc_l, new_off, my_base, axis_name)
    new_resid = jnp.where(improve[:, None], new_alloc - load, node_resid)
    return new_off, new_resid


def sharded_solve_core(axis_name, group_req, group_count, group_cap, compat_l,
                       off_alloc_l, off_price_l, off_rank_l, *, num_nodes: int,
                       right_size: bool = True):
    """Offerings-sharded solve body (runs inside shard_map)."""
    N = num_nodes
    R = group_req.shape[1]
    O_l = off_rank_l.shape[0]
    node_off0 = jnp.full((N,), -1, dtype=jnp.int32)
    node_resid0 = jnp.zeros((N, R), dtype=jnp.int32)
    step = functools.partial(_ffd_step_sharded, axis_name, off_alloc_l, off_rank_l)
    (node_off, node_resid, ptr), (assign, unplaced) = lax.scan(
        step, (node_off0, node_resid0, jnp.int32(0)),
        (group_req, group_count, group_cap, compat_l))
    if right_size:
        node_off, node_resid = _right_size_sharded(
            axis_name, node_off, node_resid, assign, compat_l, off_alloc_l,
            off_rank_l)
    my_base = lax.axis_index(axis_name).astype(jnp.int32) * O_l
    is_open = node_off >= 0
    price_local = jnp.where(
        is_open & (node_off >= my_base) & (node_off < my_base + O_l),
        off_price_l[jnp.clip(node_off - my_base, 0, O_l - 1)], 0.0)
    cost = lax.psum(jnp.sum(price_local), axis_name)
    return node_off, assign, unplaced, cost


def fleet_solve_sharded_offerings(problem: FleetProblem, mesh: Mesh, *,
                                  num_nodes: int, right_size: bool = True):
    """2D solve: clusters over FLEET_AXIS, offerings over OFFER_AXIS.

    C % fleet == 0 and O % offer == 0 required.  Results are bit-identical
    to the unsharded kernel (tie-breaks preserved by the index-pmin)."""
    n_offer = mesh.shape[OFFER_AXIS]
    O = problem.off_rank.shape[1]
    if O % n_offer:
        raise ValueError(f"offerings {O} not divisible by offer axis {n_offer}")

    f = _fleet_sharded_offerings_jit(mesh, num_nodes, right_size)
    out = f(problem.group_req, problem.group_count, problem.group_cap,
            problem.compat, problem.off_alloc, problem.off_price,
            problem.off_rank)
    return tuple(np.asarray(o) for o in out)


@functools.lru_cache(maxsize=64)
def _fleet_sharded_offerings_jit(mesh: Mesh, num_nodes: int,
                                 right_size: bool):
    """Cached jit of the 2D (fleet x offer) sharded solve (per-call
    shard_map + jit rebuild recompiled every invocation — GL003)."""
    vsolve = jax.vmap(functools.partial(
        sharded_solve_core, OFFER_AXIS, num_nodes=num_nodes,
        right_size=right_size))

    in_specs = (
        P(FLEET_AXIS), P(FLEET_AXIS), P(FLEET_AXIS),
        P(FLEET_AXIS, None, OFFER_AXIS),     # compat [C, G, O]
        P(FLEET_AXIS, OFFER_AXIS, None),     # off_alloc [C, O, R]
        P(FLEET_AXIS, OFFER_AXIS),           # off_price [C, O]
        P(FLEET_AXIS, OFFER_AXIS),           # off_rank [C, O]
    )
    out_specs = (P(FLEET_AXIS), P(FLEET_AXIS), P(FLEET_AXIS), P(FLEET_AXIS))
    return jax.jit(shard_map(vsolve, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False))
