"""Device-mesh helpers.

SURVEY.md §5.7/§2.8: the fleet axis ("clusters") is this project's
data-parallel dimension; the offerings axis is the model-parallel one
(catalog sharded across devices, combined with psum/pmin collectives over
ICI).  Meshes are plain ``jax.sharding.Mesh`` so everything composes with
pjit/shard_map.
"""

from __future__ import annotations

from collections.abc import Sequence

import numpy as np

import jax
from jax.sharding import Mesh

FLEET_AXIS = "fleet"
OFFER_AXIS = "offer"
SHARD_AXIS = "shard"


def fleet_mesh(n_devices: int | None = None,
               devices: Sequence | None = None) -> Mesh:
    """1D mesh over clusters (the v5e-8 fleet config of BASELINE.json #5)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)[:n_devices] if n_devices else list(devices)
    return Mesh(np.array(devices), (FLEET_AXIS,))


def shard_mesh(num_shards: int, devices: Sequence | None = None) -> Mesh:
    """1D mesh for the sharded continuous-solve service
    (karpenter_tpu/sharded): ``num_shards`` logical shards mapped onto
    up to ``num_shards`` devices.

    Degradation is explicit, never an error: when the host has fewer
    devices than shards (the 1-device CPU case included), the mesh spans
    the LARGEST divisor of ``num_shards`` that fits the device count and
    each device carries ``num_shards / mesh_size`` shards via the vmap
    inside the shard_map body — shard semantics (and the per-shard plan
    bits) are identical either way, only the parallel width changes.
    A 2-shard "virtual mesh" on a 1-device CPU host is exactly this
    degenerate case, pinned by tests/test_parallel.py.
    """
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    devices = list(devices) if devices is not None else jax.devices()
    width = 1
    for d in range(min(num_shards, len(devices)), 0, -1):
        if num_shards % d == 0:
            width = d
            break
    return Mesh(np.array(devices[:width]), (SHARD_AXIS,))


def solver_mesh(fleet: int, offer: int, devices: Sequence | None = None) -> Mesh:
    """2D mesh: fleet (cluster data-parallel) x offer (catalog
    model-parallel)."""
    devices = list(devices) if devices is not None else jax.devices()
    if fleet * offer > len(devices):
        raise ValueError(f"mesh {fleet}x{offer} needs {fleet * offer} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:fleet * offer]).reshape(fleet, offer)
    return Mesh(arr, (FLEET_AXIS, OFFER_AXIS))
