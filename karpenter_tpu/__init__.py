"""karpenter_tpu — a TPU-native autonomous node provisioner.

A ground-up rebuild of the capability surface of
``kubernetes-sigs/karpenter-provider-ibm-cloud`` (a Karpenter cloud-provider
operator, see ``/root/reference``) re-centered on one idea: the provisioning
scheduler's placement core (greedy bin-packing over pending pods x instance
offerings) is a **pure function over dense arrays, jitted on TPU** via
JAX/XLA.  Everything else — catalog refresh, actuation, drift, interruption,
circuit breaking — is thin host-side orchestration around that solve.

Package map (reference parity cited per-module; see SURVEY.md):

- ``apis``        — NodeClass / NodeClaim / Pod typed objects + validation
                    (ref: pkg/apis/v1alpha1/ibmnodeclass_types.go)
- ``catalog``     — instance-type + pricing + offering catalog as dense
                    device-resident arrays (ref: pkg/providers/common/{instancetype,pricing})
- ``cloud``       — cloud client layer: error taxonomy, retry, fake cloud,
                    subnet scoring, image resolution (ref: pkg/cloudprovider/ibm, pkg/providers/vpc)
- ``solver``      — the placement core: host greedy oracle + jax backend
                    (ref: karpenter-core Scheduler.Solve reframed per BASELINE.json north star)
- ``ops``         — low-level device ops (segment reductions, pallas kernels)
- ``parallel``    — mesh / fleet-scale sharded solve (pjit / shard_map)
- ``core``        — provisioner loop, solve-window coalescer, actuator,
                    circuit breaker, drift, disruption (ref: pkg/batcher, pkg/cloudprovider)
- ``controllers`` — the 16-controller reconcile plane (ref: pkg/controllers)
- ``utils``       — TTL cache, generic batcher, metrics, logging (ref: pkg/cache, pkg/batcher, pkg/metrics)
- ``models``      — solver formulations (FFD, right-sizing LP refinement, repack)
"""

__version__ = "0.1.0"

from karpenter_tpu.apis import (  # noqa: F401
    NodeClass,
    NodeClaim,
    PodSpec,
)
