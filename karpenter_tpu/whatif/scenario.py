"""Scenario algebra: composable perturbations of the packed baseline.

A scenario is the resident baseline plus a composition of perturbations
— forecasted arrival waves, chaos-profile-derived disruptions, and
candidate capacity actions — and its ONLY lowered form is a word delta
against the packed baseline buffer (the PR-8 delta path:
``resident/delta.diff_words`` + ``pad_delta``).  K scenarios therefore
ship to the device as one stacked ``[K, D]`` (indices, values) pair on
top of ONE baseline buffer — never K full encodes.

Why word deltas are sufficient: the packed buffer is a content-addressed
lowering of the solve problem (docs/design/packed-io.md), so every
solve-visible perturbation is a handful of word edits —

- **arrival wave**  -> the group's meta count word (``g*8 + 4``);
- **spot storm / zone blackout / capacity quota** (reused declaratively
  from :class:`ChaosProfile` knobs) -> label-row bit words (masking the
  affected offerings out of every row; ``_unpack_problem`` re-ANDs fit
  on device, so a cleared bit removes the offering exactly as an
  availability blackout would);
- **pool shrink / quota clamp** -> the group's meta cap word
  (``g*8 + 5``).

Capacity ACTIONS that add capacity (:class:`PreProvision`) do not change
the solve problem at all — the solver already answers "what would we
create"; pre-provisioned nodes are sunk cost, applied as a decode-side
cost discount in the planner.  That keeps the solve words of a scenario
independent of its action, which is exactly what the validator's
fresh-solve equality check requires.

Perturbations are deliberately NOT sanitized here: a broken forecaster's
garbage counts flow through to the scenario buffer, where
``validate_whatif`` rejects them — the validator is load-bearing, proven
by the broken-forecast falsifiability test.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from karpenter_tpu.resident.delta import DELTA_BUCKETS, diff_words, pad_delta

_I32_MIN = -(2 ** 31)
_I32_MAX = 2 ** 31 - 1

# meta-row columns (pack_input layout; docs/design/packed-io.md)
_COL_COUNT = 4
_COL_CAP = 5


@dataclass(frozen=True)
class WhatIfBaseline:
    """The packed baseline every scenario perturbs: one encoded pending
    window at its bucketed pads (``resident/delta.pack_window``'s exact
    lowering, so the buffer is word-identical to what the production
    solver would dispatch)."""

    problem: object                 # EncodedProblem
    packed: np.ndarray              # int32 [L]
    G_pad: int
    O_pad: int
    U_pad: int
    catalog: object
    pods: int = 0

    @property
    def L(self) -> int:
        return int(self.packed.size)

    def base_counts(self) -> np.ndarray:
        return self.packed[:self.G_pad * 8].reshape(
            self.G_pad, 8)[:, _COL_COUNT].copy()

    def group_signature(self, gi: int) -> str:
        """The encoder group's constraint-signature key — the arrival
        table's (and shard router's) grouping key, so forecasted waves
        land on exactly the solve group their history came from."""
        return self.problem.groups[gi].representative.signature_key()


# ---------------------------------------------------------------------------
# Perturbations (solve-visible: lowered to word edits)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ArrivalWave:
    """Extra pending pods per baseline group: ``waves`` is a tuple of
    (group_index, extra_pods).  The forecast's lowered form."""

    waves: tuple[tuple[int, int], ...]

    def apply(self, buf: np.ndarray, baseline: WhatIfBaseline) -> None:
        for gi, extra in self.waves:
            if not 0 <= gi < baseline.G_pad:
                continue
            w = gi * 8 + _COL_COUNT
            buf[w] = np.int32(np.clip(int(buf[w]) + int(extra),
                                      _I32_MIN, _I32_MAX))


@dataclass(frozen=True)
class OfferingMask:
    """Remove a set of offerings from every label row — the lowered form
    of a chaos disruption (spot storm, zone blackout, (type, zone)
    capacity quota)."""

    label: str
    offerings: tuple[int, ...]

    def apply(self, buf: np.ndarray, baseline: WhatIfBaseline) -> None:
        O_pad, U_pad, G_pad = baseline.O_pad, baseline.U_pad, baseline.G_pad
        if not self.offerings:
            return
        bits = np.zeros(O_pad, dtype=np.uint8)
        offs = [o for o in self.offerings if 0 <= o < O_pad]
        bits[offs] = 1
        # the exact packbits transform pack_input applies per label row
        mask = np.packbits(bits.reshape(O_pad // 32, 32), axis=-1,
                           bitorder="little").reshape(-1).view(np.int32)
        rows = buf[G_pad * 8:].reshape(U_pad, O_pad // 32)
        rows &= ~mask[None, :]


@dataclass(frozen=True)
class CapClamp:
    """Clamp per-group pod caps — the lowered form of a pool shrink or
    an instance-quota perturbation: ``caps`` is (group_index, new_cap)."""

    caps: tuple[tuple[int, int], ...]

    def apply(self, buf: np.ndarray, baseline: WhatIfBaseline) -> None:
        for gi, cap in self.caps:
            if not 0 <= gi < baseline.G_pad:
                continue
            w = gi * 8 + _COL_CAP
            buf[w] = np.int32(np.clip(int(cap), _I32_MIN, _I32_MAX))


# ---------------------------------------------------------------------------
# Capacity actions (decode-side: sunk-cost discount, never a word edit)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PreProvision:
    """Pre-provision ``count`` nodes of ``offering`` ahead of the
    horizon.  Solve-invisible by design (the solver already opens the
    nodes the demand needs); the planner discounts up to ``count``
    opened nodes of this offering as already-paid capacity and prices
    the action at ``count * off_price[offering]`` per hour."""

    offering: int
    count: int

    def describe(self, catalog) -> dict:
        itype, zone, cap = catalog.describe_offering(self.offering)
        return {"kind": "pre_provision", "offering": int(self.offering),
                "instance_type": itype, "zone": zone, "capacity_type": cap,
                "count": int(self.count),
                "cost_per_hour": round(
                    float(catalog.off_price[self.offering])
                    * int(self.count), 6)}


@dataclass(frozen=True)
class Scenario:
    """One named future: a perturbation composition + optional capacity
    action.  ``key()`` is the canonical form the determinism digest and
    the audit registry use."""

    name: str
    perturbations: tuple = ()
    action: PreProvision | None = None

    def key(self) -> str:
        return repr((self.name, self.perturbations, self.action))


# ---------------------------------------------------------------------------
# Declarative perturbation builders
# ---------------------------------------------------------------------------

def spot_storm_mask(catalog, frac: float = 1.0, rng=None) -> OfferingMask:
    """Every spot offering interrupted (the chaos spot-storm knob,
    ``preempt_storm_frac`` < 1 thins the set through the seeded rng)."""
    from karpenter_tpu.catalog.arrays import CAPACITY_TYPES

    spot_idx = CAPACITY_TYPES.index("spot")
    offs = [int(o) for o in np.nonzero(
        np.asarray(catalog.off_cap) == spot_idx)[0]]
    if frac < 1.0 and rng is not None:
        offs = [o for o in offs if rng.random() < frac]
    return OfferingMask(label="spot-storm", offerings=tuple(offs))


def zone_blackout_mask(catalog, zone: str) -> OfferingMask:
    """Every offering in ``zone`` gone (the chaos capacity-blackout
    knob, widened to the whole zone)."""
    try:
        zi = catalog.zones.index(zone)
    except ValueError:
        return OfferingMask(label=f"zone-blackout:{zone}", offerings=())
    offs = [int(o) for o in np.nonzero(
        np.asarray(catalog.off_zone) == zi)[0]]
    return OfferingMask(label=f"zone-blackout:{zone}", offerings=tuple(offs))


def quota_clamp(baseline: WhatIfBaseline, quota: int) -> CapClamp:
    """Clamp every live group's per-node pod cap to ``quota`` — the
    declarative form of the chaos ``instance_quota`` knob at the
    solve-problem level."""
    meta = baseline.packed[:baseline.G_pad * 8].reshape(baseline.G_pad, 8)
    caps = tuple((int(g), int(min(int(meta[g, _COL_CAP]), int(quota))))
                 for g in range(baseline.problem.num_groups))
    return CapClamp(caps=caps)


def perturbations_from_profile(profile, catalog,
                               baseline: WhatIfBaseline, rng) -> tuple:
    """Reuse a :class:`ChaosProfile` declaratively: map its storm /
    blackout / quota knobs onto scenario perturbations (the same fault
    surface `make chaos` injects, as a planning hypothetical).  ``rng``
    is the scenario seed's stream — a profile + seed fully determines
    the perturbation set, exactly like the chaos harness."""
    out: list = []
    if profile.preempt_storm_rate > 0.0:
        out.append(spot_storm_mask(catalog, profile.preempt_storm_frac,
                                   rng))
    if profile.capacity_blackout_rate > 0.0 and catalog.zones:
        zone = catalog.zones[rng.randrange(len(catalog.zones))]
        out.append(zone_blackout_mask(catalog, zone))
    if profile.instance_quota:
        out.append(quota_clamp(baseline, profile.instance_quota))
    return tuple(out)


def wave_from_forecast(baseline: WhatIfBaseline,
                       expected: dict[str, int],
                       scale: float = 1.0) -> ArrivalWave:
    """Match forecasted per-signature arrivals onto baseline groups.
    Signatures absent from the baseline are dropped (the solve can only
    perturb demand shapes it knows about — the standing menu re-derives
    every tick, so a new shape appears as soon as a real pod does).
    Counts are passed through UNSANITIZED — garbage rates must reach
    ``validate_whatif``, not be silently repaired here."""
    by_sig: dict[str, int] = {}
    for gi in range(baseline.problem.num_groups):
        sig = baseline.group_signature(gi)
        if sig not in by_sig:
            by_sig[sig] = gi
    waves = []
    for sig, n in sorted(expected.items()):
        gi = by_sig.get(sig)
        if gi is not None:
            waves.append((gi, int(round(n * scale))))
    return ArrivalWave(waves=tuple(waves))


# ---------------------------------------------------------------------------
# Lowering: scenarios -> one stacked delta pair
# ---------------------------------------------------------------------------

@dataclass
class StackedScenarios:
    """K scenarios lowered against one baseline: the stacked ``[K, D]``
    delta pair the kernel consumes, plus the host-derived per-scenario
    meta the decoder needs (counts, caps, totals)."""

    scenarios: list[Scenario]
    didx: np.ndarray                # int32 [K, D]
    dval: np.ndarray                # int32 [K, D]
    counts: np.ndarray              # int32/int64 [K, G_pad]
    caps: np.ndarray                # [K, G_pad]
    delta_words: list[int]
    D: int

    @property
    def K(self) -> int:
        return len(self.scenarios)


def perturbed_buffer(baseline: WhatIfBaseline,
                     scenario: Scenario) -> np.ndarray:
    """The scenario's full perturbed buffer (host scratch): baseline
    copy + perturbations applied in composition order.  The lowering,
    the oracle, and the validator all derive the scenario state through
    this one function, so 'the perturbed state' cannot fork."""
    buf = baseline.packed.copy()
    for p in scenario.perturbations:
        p.apply(buf, baseline)
    return buf


def lower_scenarios(baseline: WhatIfBaseline,
                    scenarios: list[Scenario]) -> StackedScenarios:
    """Lower K scenarios to ONE stacked delta pair at a shared bucket
    rung (the dispatch shape must be rectangular, like the sharded
    plane's stacked deltas).  Padding rows carry the drop index (L, one
    past the buffer) so the device-side ``.at[].set(mode="drop")``
    ignores them."""
    L = baseline.L
    G_pad = baseline.G_pad
    idxs: list[np.ndarray] = []
    vals: list[np.ndarray] = []
    counts = np.zeros((len(scenarios), G_pad), dtype=np.int64)
    caps = np.zeros((len(scenarios), G_pad), dtype=np.int64)
    for k, s in enumerate(scenarios):
        buf = perturbed_buffer(baseline, s)
        idx = diff_words(baseline.packed, buf)
        idxs.append(idx)
        vals.append(buf[idx])
        meta = buf[:G_pad * 8].reshape(G_pad, 8)
        counts[k] = meta[:, _COL_COUNT]
        caps[k] = meta[:, _COL_CAP]
    from karpenter_tpu.solver.types import bucket

    d_max = max([int(i.size) for i in idxs] or [1])
    rung = (bucket(max(d_max, 1), DELTA_BUCKETS),)
    pairs = [pad_delta(i, v, L, rung) for i, v in zip(idxs, vals)]
    return StackedScenarios(
        scenarios=list(scenarios),
        didx=np.stack([p[0] for p in pairs]),
        dval=np.stack([p[1] for p in pairs]),
        counts=counts, caps=caps,
        delta_words=[int(i.size) for i in idxs],
        D=int(rung[0]))
