"""Host oracle for the stacked scenario solve — the parity twin.

Recomputes, with numpy on the host, exactly what ``kernels.
solve_scenarios`` computes on device for each scenario: delta-apply,
``_unpack_problem``, the deterministic FFD scan, right-sizing, and the
packed result buffer INCLUDING the appended explain reason words — all
bit-identical except the single float32 cost word, which matches up to
reduction order (the same carve-out the stochastic oracle documents).

Bit-identity holds structurally, the way stochastic/greedy.py's does:
integer ops mirror the kernel's integer ops, float comparisons use the
same single IEEE-rounded float32 operations in the same order (the
ranking division, the 1e-9 right-size hysteresis), argmin tie-breaks
are first-index on both sides.  Change one side, change both
(docs/design/whatif.md "parity contract").
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.explain import (
    BIT, DEFICIT_CLIP, DEFICIT_MASKED, RESOURCE_BITS,
)

# shared with the device side (solver/jax_backend.py) via one home
# module — the fit sentinel is part of the parity contract (GL201/GL203)
from karpenter_tpu.solver.types import FIT_BIG as _BIG


def unpack_problem_np(packed: np.ndarray, off_alloc: np.ndarray,
                      G: int, O: int, U: int):
    """numpy mirror of jax_backend._unpack_problem: (meta [G,8], compat
    [G,O] 0/1, label rows_g [G,O] 0/1)."""
    meta = packed[:G * 8].reshape(G, 8)
    cw = packed[G * 8:].reshape(U, O // 32)
    b = (cw[:, :, None] >> np.arange(32, dtype=np.int32)[None, None, :]) & 1
    rows = b.reshape(U, O).astype(np.int32)
    rows_g = rows[np.clip(meta[:, 6], 0, U - 1)]
    fit = (off_alloc[None, :, :] >= meta[:, None, :4]).all(axis=2)
    return meta, rows_g * fit.astype(np.int32), rows_g


def _fit_counts_np(resid: np.ndarray, req: np.ndarray) -> np.ndarray:
    per_dim = np.where(req[None, :] > 0,
                       resid // np.maximum(req[None, :], 1), _BIG)
    return per_dim.min(axis=1).astype(np.int32)


def solve_core_np(meta: np.ndarray, compat_i: np.ndarray,
                  off_alloc: np.ndarray, off_price: np.ndarray,
                  off_rank: np.ndarray, N: int,
                  right_size: bool = True):
    """numpy mirror of the deterministic ``solve_core`` (the scan over
    ``_ffd_step`` + ``_right_size``): returns ``(node_off [N], assign
    [G,N], unplaced [G], cost)`` with the first three integer-exact."""
    G = meta.shape[0]
    R = 4
    compat = compat_i > 0
    node_off = np.full(N, -1, dtype=np.int32)
    node_resid = np.zeros((N, R), dtype=np.int32)
    ptr = 0
    assign = np.zeros((G, N), dtype=np.int32)
    unplaced = np.zeros(G, dtype=np.int32)
    idx_n = np.arange(N, dtype=np.int32)
    for gi in range(G):
        req = meta[gi, :4]
        count = int(meta[gi, 4])
        cap = int(meta[gi, 5])
        compat_g = compat[gi]

        is_open = node_off >= 0
        node_compat = np.where(is_open,
                               compat_g[np.clip(node_off, 0, None)], False)
        fit = _fit_counts_np(node_resid, req)
        fit = np.where(node_compat, fit, 0)
        fit = np.minimum(fit, cap)
        cumfit = np.cumsum(fit) - fit
        take = np.clip(count - cumfit, 0, fit).astype(np.int32)
        placed = int(take.sum())
        node_resid = node_resid - take[:, None] * req[None, :]
        rem = count - placed

        fit_empty = _fit_counts_np(off_alloc, req)
        fit_empty = np.where(compat_g, fit_empty, 0)
        fit_empty = np.minimum(fit_empty, cap)
        fit_empty = np.minimum(fit_empty, rem)
        with np.errstate(divide="ignore", invalid="ignore"):
            cpp = np.where(fit_empty > 0,
                           off_rank / fit_empty.astype(np.float32),
                           np.float32(np.inf))
        best = int(np.argmin(cpp))
        bf = int(fit_empty[best])

        n_new = -(-rem // max(bf, 1)) if bf > 0 else 0
        n_new = min(n_new, N - ptr)
        new_pos = idx_n - ptr
        is_new = (new_pos >= 0) & (new_pos < n_new)
        pods_new = np.where(is_new, np.clip(rem - new_pos * bf, 0, bf),
                            0).astype(np.int32)
        opened = is_new & (pods_new > 0)
        node_off = np.where(opened, best, node_off).astype(np.int32)
        node_resid = np.where(
            opened[:, None],
            off_alloc[best][None, :] - pods_new[:, None] * req[None, :],
            node_resid)
        ptr += int(opened.sum())
        unplaced[gi] = rem - int(pods_new.sum())
        assign[gi] = take + pods_new

    if right_size and G:
        node_off = _right_size_np(node_off, node_resid, assign, compat,
                                  off_alloc, off_rank)
    is_open = node_off >= 0
    # cost word: excluded from bit-parity up to reduction order (see
    # docs/design/parity.md) — the one sanctioned float reduction
    cost = float(np.where(  # graftlint: disable=GL202 (cost word)
        is_open, off_price[np.clip(node_off, 0, None)],
        np.float32(0.0)).sum())
    return node_off, assign, unplaced, cost


def _right_size_np(node_off, node_resid, assign, compat, off_alloc,
                   off_rank):
    """numpy mirror of jax_backend._right_size (deterministic form):
    cheapest compatible offering that fits each node's final load.  The
    einsum is integer-valued float32 math (0/1 presence counts), so
    reduction order cannot change the result."""
    N = node_off.shape[0]
    is_open = node_off >= 0
    safe_off = np.clip(node_off, 0, None)
    load = off_alloc[safe_off] - node_resid
    present = (assign > 0).astype(np.float32)
    incompat = (~compat).astype(np.float32)
    incompat_count = np.einsum("gn,go->no", present, incompat)
    all_compat = incompat_count < 0.5
    fits = (off_alloc[None, :, :] >= load[:, None, :]).all(axis=2)
    candidate = all_compat & fits & is_open[:, None]
    rank_eff = np.broadcast_to(off_rank[None, :], (N, off_rank.shape[0]))
    cand_price = np.where(candidate, rank_eff, np.float32(np.inf))
    best = cand_price.argmin(axis=1).astype(np.int32)
    best_price = cand_price.min(axis=1)
    cur_price = np.take_along_axis(rank_eff, safe_off[:, None],
                                   axis=1)[:, 0]
    improve = is_open & (best_price < cur_price - np.float32(1e-9))
    return np.where(improve, best, node_off).astype(np.int32)


def explain_words_np(meta, rows_g, compat_i, unplaced, off_alloc):
    """numpy mirror of jax_backend._explain_words at the WORD level
    (the packed-buffer form; explain/greedy.reason_words is the
    EncodedProblem form of the same reduction)."""
    G = meta.shape[0]
    req = meta[:, :4]
    count = meta[:, 4]
    prio = meta[:, 7]
    lbl = rows_g > 0
    compat = compat_i > 0
    has_label = lbl.any(axis=1)
    has_fit = compat.any(axis=1)
    per_dim = np.minimum(
        np.maximum(req[:, None, :] - off_alloc[None, :, :], 0),
        DEFICIT_CLIP)
    deficit = per_dim.sum(axis=2, dtype=np.int32)
    masked = np.where(lbl, deficit, DEFICIT_MASKED)
    nearest = masked.argmin(axis=1)
    near_alloc = off_alloc[nearest]
    insufficient = has_label & ~has_fit
    bits = np.zeros(G, dtype=np.int32)
    for r, bit_name in enumerate(RESOURCE_BITS):
        hit = insufficient & (req[:, r] > near_alloc[:, r])
        bits = bits | np.where(hit, np.int32(1 << BIT[bit_name]),
                               np.int32(0))
    bits = bits | np.where(~has_label,
                           np.int32(1 << BIT["requirements"]), np.int32(0))
    bits = bits | np.where(has_fit,
                           np.int32(1 << BIT["capacity_exhausted"]),
                           np.int32(0))
    placed = (count - unplaced) > 0
    int_min = np.iinfo(np.int32).min
    max_placed_prio = np.where(compat & placed[:, None], prio[:, None],
                               int_min).max(axis=0)
    cap_hp = (compat & (max_placed_prio[None, :] > prio[:, None])
              ).any(axis=1) & has_fit
    bits = bits | np.where(cap_hp,
                           np.int32(1 << BIT["capacity_higher_prio"]),
                           np.int32(0))
    live_un = (count > 0) & (unplaced > 0)
    return np.where(live_un, bits, 0).astype(np.int32)


def compact_assign_np(assign: np.ndarray, K: int):
    """numpy mirror of jax_backend._compact_assign (n-major COO)."""
    flat = assign.T.reshape(-1)
    mask = flat > 0
    pos = np.cumsum(mask.astype(np.int32)) - 1
    tgt = np.where(mask, pos, K)
    src = np.arange(flat.shape[0], dtype=np.int32)
    idx = np.zeros(K, dtype=np.int32)
    cnt = np.zeros(K, dtype=np.int32)
    valid = tgt < K
    idx[tgt[valid]] = src[valid]
    cnt[tgt[valid]] = flat[valid]
    return idx, cnt


def pack_result_np(node_off, assign, unplaced, cost, words, telemetry,
                   K: int, dense16: bool = False,
                   coo16: bool = False) -> np.ndarray:
    """numpy mirror of _pack_result + the appended reason words (the
    dense16 pair packing mirrors jax_backend.pack16_pairs) + the
    telemetry block (obs/telemetry_words.telemetry_words_np, the full
    magic-word-led block) — every oracle buffer carries the identical
    v1 suffix the device finisher emits (solver/result_layout.py)."""
    cost_i = np.asarray([cost], dtype=np.float32).view(np.int32)
    if K > 0:
        idx, cnt = compact_assign_np(assign.astype(np.int32), K)
        tail = [(idx << 16) | cnt] if coo16 else [idx, cnt]
    elif dense16:
        pairs = assign.astype(np.int32).reshape(-1, 2)
        tail = [(pairs[:, 0] & 0xFFFF) | (pairs[:, 1] << 16)]
    else:
        tail = [assign.astype(np.int32).reshape(-1)]
    return np.concatenate([node_off.astype(np.int32),
                           unplaced.astype(np.int32), cost_i]
                          + tail + [words.astype(np.int32),
                                    telemetry.astype(np.int32)])


def solve_packed_np(packed: np.ndarray, off_alloc, off_price, off_rank, *,
                    G: int, O: int, U: int, N: int,
                    right_size: bool = True, compact: int = 0,
                    dense16: bool = False, coo16: bool = False
                    ) -> np.ndarray:
    """One scenario's full packed result buffer, from the host — the
    scenario-at-a-time body of the oracle AND the degraded fallback."""
    off_alloc = np.asarray(off_alloc, dtype=np.int32)
    off_price = np.asarray(off_price, dtype=np.float32)
    off_rank = np.asarray(off_rank, dtype=np.float32)
    meta, compat_i, rows_g = unpack_problem_np(packed, off_alloc, G, O, U)
    node_off, assign, unplaced, cost = solve_core_np(
        meta, compat_i, off_alloc, off_price, off_rank, N,
        right_size=right_size)
    words = explain_words_np(meta, rows_g, compat_i,
                             unplaced.astype(np.int32), off_alloc)
    from karpenter_tpu.obs.telemetry_words import telemetry_words_np

    telemetry = telemetry_words_np(meta, node_off, assign,
                                   unplaced.astype(np.int32), off_alloc)
    return pack_result_np(node_off, assign, unplaced, cost, words,
                          telemetry, compact, dense16, coo16)


def solve_scenarios_np(baseline, stacked, *, N: int,
                       right_size: bool = True, compact: int = 0,
                       dense16: bool = False, coo16: bool = False
                       ) -> np.ndarray:
    """The stacked oracle: apply each scenario's padded delta to a host
    copy of the baseline (drop-index rows ignored, exactly like the
    device scatter) and solve scenario-at-a-time.  Returns [K, Lo]."""
    from karpenter_tpu.solver.jax_backend import _pad1, _pad2

    catalog = baseline.catalog
    alloc = _pad2(catalog.offering_alloc().astype(np.int32),
                  baseline.O_pad)
    price = _pad1(catalog.off_price.astype(np.float32), baseline.O_pad)
    rank = _pad1(catalog.offering_rank_price(), baseline.O_pad)
    outs = []
    L = baseline.L
    for k in range(stacked.K):
        buf = baseline.packed.copy()
        live = stacked.didx[k] < L
        buf[stacked.didx[k][live]] = stacked.dval[k][live]
        outs.append(solve_packed_np(
            buf, alloc, price, rank,
            G=baseline.G_pad, O=baseline.O_pad, U=baseline.U_pad, N=N,
            right_size=right_size, compact=compact, dense16=dense16,
            coo16=coo16))
    return np.stack(outs)


def cost_word_index(G: int, N: int) -> int:
    """Offset of the single float32 cost word in a packed result — the
    one word the oracle matches only up to reduction order."""
    return N + G


def words_equal_except_cost(a: np.ndarray, b: np.ndarray, G: int, N: int,
                            rtol: float = 1e-5) -> bool:
    """Bit-equality on every word but the cost word; the cost floats
    must still agree to ``rtol``."""
    ci = cost_word_index(G, N)
    if a.shape != b.shape:
        return False
    mask = np.ones(a.shape[0], dtype=bool)
    mask[ci] = False
    if not np.array_equal(a[mask], b[mask]):
        return False
    ca = float(a[ci:ci + 1].view(np.float32)[0])
    cb = float(b[ci:ci + 1].view(np.float32)[0])
    return bool(np.isclose(ca, cb, rtol=rtol, atol=1e-4))
