"""The operator-resident planning service (opt-in KARPENTER_ENABLE_WHATIF).

Periodically (and on demand via ``GET /debug/whatif``) evaluates a
STANDING SCENARIO MENU against the live pending window:

- scenario 0 is always the baseline (the live solve problem, untouched);
- the forecast peak (expected arrivals per signature group over the
  horizon, from the ledger-learned forecaster);
- threat scenarios — spot storm, a seeded zone blackout — each composed
  WITH the forecast wave (the question is "tonight's peak during a spot
  storm", not either alone);
- the pool-shrink capacity scenario (cap clamps ARE solve-visible).

All K scenarios ride ONE stacked device dispatch (planner).
Pre-provision capacity actions are solve-INVISIBLE (scenario.py), so
they cost zero extra scenarios: ``_rank`` derives each threat's
candidate from the threat's own decoded outcome (the offering it opens
most nodes of) and scores it by (SLO-risk averted per dollar), where
risk = weighted unplaced + boot-exposed pods and the action averts the
boot exposure of pods landing on its pre-provisioned nodes.
Positive-averted actions are recorded into a bounded audit registry
with the before-outcome and the projected after-state, the forecast
generation, and the plan backend, so ``/debug/whatif`` can always
answer "why did you recommend pre-provisioning 2 of type X".

Determinism: the menu derives from (ledger arrival table, seed,
baseline); the digest over the recommendation set is byte-stable across
reruns — the `make whatif-determinism` CI check runs the whole cycle
twice and compares digests, the same discipline the chaos matrix
enforces on event traces.
"""

from __future__ import annotations

import hashlib
import json
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from karpenter_tpu.controllers.runtime import PollController, Result
from karpenter_tpu.obs.trace import now
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger
from karpenter_tpu.whatif.forecast import ArrivalForecaster
from karpenter_tpu.whatif.scenario import (
    PreProvision, Scenario, spot_storm_mask, wave_from_forecast,
    zone_blackout_mask,
)

log = get_logger("whatif.service")


@dataclass
class Recommendation:
    """One ranked capacity action: the audit-registry row."""

    scenario: str
    action: dict
    risk_before: int
    risk_after: int
    averted: int
    cost_per_hour: float
    score: float
    horizon_hours: int
    forecast_generation: int
    backend: str
    created_at: float = 0.0
    outcome_before: dict = field(default_factory=dict)
    outcome_after: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "scenario": self.scenario,
            "action": self.action,
            "risk_before": self.risk_before,
            "risk_after": self.risk_after,
            "risk_averted": self.averted,
            "cost_per_hour": round(self.cost_per_hour, 6),
            "score": round(self.score, 6),
            "horizon_hours": self.horizon_hours,
            "forecast_generation": self.forecast_generation,
            "backend": self.backend,
            "created_at": round(self.created_at, 3),
            "outcome_before": self.outcome_before,
            "outcome_after": self.outcome_after,
        }


class PlanningService:
    """Forecast -> standing menu -> stacked plan -> ranked
    recommendations (see module docstring)."""

    def __init__(self, cluster, provisioner=None, *, catalog_fn=None,
                 nodepool_fn=None, seed: int = 17,
                 horizon_hours: int | None = None, planner=None,
                 journal=None, registry_cap: int = 256,
                 validate: bool = False):
        from karpenter_tpu.whatif import WHATIF_HORIZON_HOURS
        from karpenter_tpu.whatif.degraded import ResilientPlanner

        self.cluster = cluster
        self.provisioner = provisioner
        self._catalog_fn = catalog_fn
        self._nodepool_fn = nodepool_fn
        self.seed = seed
        self.horizon_hours = horizon_hours if horizon_hours is not None \
            else WHATIF_HORIZON_HOURS
        self.planner = planner or ResilientPlanner()
        self.journal = journal
        self.validate = validate
        self.forecaster = ArrivalForecaster()
        self._registry: deque[Recommendation] = deque(maxlen=registry_cap)
        self._flight = threading.Lock()
        self._lock = threading.Lock()
        self.ticks = 0
        self.evaluations = 0
        self.busy_rejections = 0
        self.last_payload: dict | None = None
        self.last_error = ""
        self.validation_failures = 0
        self._saved_forecast_generation = -1
        self._risk_labels: set[str] = set()
        # restart warm-start: the journal's forecast snapshot merges
        # (elementwise max — same bounded ring, never double-counted)
        # into every rebuilt forecaster until the live ring catches up
        self._persisted: ArrivalForecaster | None = None
        if journal is not None:
            loaded = ArrivalForecaster.load(journal)
            if loaded.rates():
                self._persisted = loaded

    # -- inputs ------------------------------------------------------------

    def _resolve_catalog(self):
        if self._catalog_fn is not None:
            return self._catalog_fn()
        if self.provisioner is None:
            return None
        pools = self.cluster.list("nodepools")
        pool = pools[0] if pools else None
        wanted = pool.nodeclass_name if pool and pool.nodeclass_name \
            else "default"
        nodeclass = self.cluster.get_nodeclass(wanted)
        if nodeclass is None:
            return None
        return self.provisioner._catalog_for(nodeclass)

    def _resolve_nodepool(self):
        if self._nodepool_fn is not None:
            return self._nodepool_fn()
        pools = self.cluster.list("nodepools")
        return pools[0] if pools else None

    def _pending(self) -> list:
        return [p.spec for p in self.cluster.pending_pods()]

    # -- the standing menu -------------------------------------------------

    def build_menu(self, baseline, expected: dict[str, int],
                   rng: random.Random) -> list[Scenario]:
        """Baseline + forecast peak + chaos-derived threats + the
        pool-shrink capacity action — every scenario a pure function of
        (baseline, forecast table, seed).  Pre-provision actions are
        solve-INVISIBLE (scenario.py), so the menu carries only
        solve-distinct futures and ``_rank`` derives the pre-provision
        recommendation for each threat from its own decoded outcome —
        the action axis costs zero extra scenarios and zero extra
        dispatches."""
        catalog = baseline.catalog
        wave = wave_from_forecast(baseline, expected)
        threat_base = (wave,) if wave.waves else ()
        menu: list[Scenario] = [Scenario("baseline")]
        if wave.waves:
            menu.append(Scenario("forecast-peak", (wave,)))
        storm = spot_storm_mask(catalog)
        if storm.offerings:
            menu.append(Scenario("spot-storm", threat_base + (storm,)))
        if catalog.zones:
            zone = catalog.zones[rng.randrange(len(catalog.zones))]
            blackout = zone_blackout_mask(catalog, zone)
            if blackout.offerings:
                menu.append(Scenario(f"zone-blackout:{zone}",
                                     threat_base + (blackout,)))
        # "what if this NodePool shrinks": per-node pod caps clamped
        # hard under the forecast peak — the disruption-budget question
        # from the ROADMAP, as a standing capacity-action scenario (its
        # answer is the risk row in /debug/whatif)
        from karpenter_tpu.whatif.scenario import quota_clamp

        shrink = quota_clamp(baseline, 2)
        if shrink.caps:
            menu.append(Scenario("pool-shrink", threat_base + (shrink,)))
        return menu

    # -- evaluation --------------------------------------------------------

    def evaluate(self, horizon_hours: int | None = None,
                 scenario_names: list[str] | None = None,
                 record: bool = False,
                 hour: int | None = None) -> dict | None:
        """One planning pass.  SINGLE-FLIGHT like /debug/profile: a
        concurrent call returns None (the endpoint maps it to 429) —
        a stacked K-scenario dispatch is exactly the kind of work two
        callers must not double-launch.  ``hour`` pins the virtual
        hour-of-day (the determinism check's knob; default = derived
        from the ambient clock)."""
        if not self._flight.acquire(blocking=False):
            with self._lock:
                self.busy_rejections += 1
            return None
        try:
            return self._evaluate_locked(horizon_hours, scenario_names,
                                         record, hour)
        finally:
            self._flight.release()

    def _evaluate_locked(self, horizon_hours, scenario_names,
                         record, hour=None) -> dict:
        from karpenter_tpu import obs
        from karpenter_tpu.whatif.planner import build_baseline
        from karpenter_tpu.whatif.validate import validate_whatif

        from karpenter_tpu.whatif import WHATIF_MAX_HORIZON_HOURS

        t0 = time.perf_counter()
        horizon = int(horizon_hours) if horizon_hours is not None \
            else self.horizon_hours
        # clamped like /debug/profile's ?duration_s=: an unbounded
        # horizon would run an O(horizon) forecast loop under the
        # single-flight lock and scale waves into an OOM-sized stack
        horizon = max(0, min(horizon, WHATIF_MAX_HORIZON_HOURS))
        catalog = self._resolve_catalog()
        if catalog is None:
            payload = {"error": "no catalog resolvable (no nodeclass?)",
                       "scenarios": []}
            with self._lock:
                self.last_error = payload["error"]
            return payload
        pods = self._pending()
        baseline = build_baseline(pods, catalog, self._resolve_nodepool())
        self.forecaster = ArrivalForecaster.from_ledger(obs.get_ledger())
        if self._persisted is not None:
            # the warm-start snapshot EXPIRES once the live ring has
            # re-observed as much demand as the snapshot held —
            # otherwise a max-merge would forecast decommissioned
            # workloads forever (the ring can age demand out, a
            # never-cleared snapshot cannot)
            live = sum(sum(r) for r in
                       obs.get_ledger().arrival_history().values())
            kept = sum(sum(r) for r in
                       self._persisted._counts.values())
            if live >= kept:
                self._persisted = None
            else:
                self.forecaster = \
                    self.forecaster.merged_with(self._persisted)
        # journal persistence only on RECORDING passes (the periodic
        # tick) and only when the table actually changed — a read-only
        # /debug/whatif GET must never append to the recovery journal
        if record and self.journal is not None:
            gen = self.forecaster.generation
            if gen != self._saved_forecast_generation:
                self.forecaster.save(self.journal)
                self._saved_forecast_generation = gen
        if hour is None:
            hour = int(now() // 3600.0) % 24
        expected = self.forecaster.expected_arrivals(horizon, hour)
        rng = random.Random((self.seed, horizon, baseline.G_pad).__repr__())
        menu = self.build_menu(baseline, expected, rng)
        if scenario_names:
            wanted = set(scenario_names)
            menu = [s for s in menu if s.name in wanted] or menu[:1]
        plan = self.planner.plan(baseline, menu)
        # the cheap well-formedness layer ALWAYS runs (a garbage
        # forecast must never reach the registry, validate flag or
        # not); the full fresh-solve replay is the opt-in half
        violations = validate_whatif(plan, replay=self.validate)
        if violations:
            with self._lock:
                self.validation_failures += 1
        recs = self._rank(plan, horizon)
        horizon_risk = max((o.unplaced for o in plan.outcomes
                            if o.action is None), default=0)
        if record and not violations:
            with self._lock:
                for r in recs:
                    self._registry.append(r)
            # refresh the horizon-risk gauge over THIS pass's standing
            # names and clear rows the menu no longer carries (the
            # seeded blackout zone rotates with the baseline shape; a
            # stale row would report a risk no pass maintains — the
            # series-hygiene rule every gauge here follows)
            fresh = {o.name for o in plan.outcomes if o.action is None}
            with self._lock:
                stale = self._risk_labels - fresh
                self._risk_labels = fresh
            for name in stale:
                metrics.WHATIF_HORIZON_RISK.remove(name)
            for o in plan.outcomes:
                if o.action is None:
                    metrics.WHATIF_HORIZON_RISK.labels(o.name).set(
                        float(o.unplaced))
        mode = "device" if plan.backend == "device" else "host"
        metrics.WHATIF_SCENARIOS.labels(mode).inc(len(menu))
        metrics.WHATIF_PLAN_DURATION.labels(mode).observe(
            plan.plan_seconds)
        with self._lock:
            metrics.WHATIF_RECOMMENDATIONS.set(float(len(self._registry)))
            self.evaluations += 1
            self.last_error = ""
        payload = {
            "horizon_hours": horizon,
            "virtual_hour": hour,
            "pending_pods": len(pods),
            "backend": plan.backend,
            "dispatches": plan.dispatches,
            "plan_seconds": round(plan.plan_seconds, 6),
            "horizon_risk": horizon_risk,
            "forecast": self.forecaster.snapshot(),
            "scenarios": [o.to_dict() for o in plan.outcomes],
            "recommendations": [r.to_dict() for r in recs],
            "validation": {"checked": bool(self.validate),
                           "violations": violations},
            "wall_seconds": round(time.perf_counter() - t0, 6),
        }
        with self._lock:
            self.last_payload = {k: payload[k] for k in
                                 ("horizon_hours", "backend", "dispatches",
                                  "plan_seconds", "horizon_risk",
                                  "pending_pods")}
        return payload

    # an unplaced pod outweighs a boot-waiting pod in the risk metric:
    # unplaced = SLO burn for the whole horizon, boot-wait = one
    # create+boot latency
    RISK_UNPLACED_WEIGHT = 10
    # pre-provision at most this many nodes per recommendation
    MAX_PREPROVISION = 2

    @classmethod
    def scenario_risk(cls, outcome) -> int:
        """SLO risk of one future: weighted unplaced pods + boot-exposed
        pods (every placed pod lands on a node the scenario would have
        to create and boot)."""
        return cls.RISK_UNPLACED_WEIGHT * max(outcome.unplaced, 0) \
            + max(outcome.placed, 0)

    def _rank(self, plan, horizon: int) -> list[Recommendation]:
        """(SLO-risk averted per dollar): for every non-baseline,
        action-free scenario, derive the pre-provision candidate from
        its OWN decoded outcome — the offering the scenario opens most
        nodes of — and score the action by the boot exposure (plus any
        unplaced delta, for explicitly actioned scenarios) it averts
        per dollar of pre-provisioned capacity.  Pre-provision is
        solve-invisible, so this costs zero extra scenarios and zero
        extra dispatches."""
        import numpy as np

        price = np.asarray(plan.baseline.catalog.off_price,
                           dtype=np.float64)
        recs: list[Recommendation] = []
        for o, s in zip(plan.outcomes, plan.stacked.scenarios):
            if s.name == "baseline" or s.action is not None:
                continue
            if not o.offering_node_pods:
                continue
            # most-opened offering, lowest index on ties — deterministic
            off, (n_nodes, pods_list) = max(
                o.offering_node_pods.items(),
                key=lambda kv: (kv[1][0], -kv[0]))
            count = min(self.MAX_PREPROVISION, n_nodes)
            if count <= 0 or off >= price.shape[0]:
                continue
            covered = sum(pods_list[:count])
            averted = covered
            cost = float(price[off]) * count
            if averted <= 0 or cost <= 0:
                continue
            action = PreProvision(offering=int(off), count=int(count))
            risk_before = self.scenario_risk(o)
            # the projected after-state: the same solve with the
            # action's sunk capacity applied — covered pods lose their
            # boot exposure, the action's price becomes standing spend
            after = {
                "scenario": s.name,
                "risk": risk_before - averted,
                "boot_exposed_pods": max(o.placed, 0) - covered,
                "covered_pods": covered,
                "unplaced": o.unplaced,
                "cost_per_hour": round(o.cost, 6),
                "standing_action_cost_per_hour": round(cost, 6),
            }
            recs.append(Recommendation(
                scenario=s.name,
                action=action.describe(plan.baseline.catalog),
                risk_before=risk_before,
                risk_after=risk_before - averted,
                averted=averted, cost_per_hour=cost,
                score=averted / cost, horizon_hours=horizon,
                forecast_generation=self.forecaster.generation,
                backend=plan.backend, created_at=now(),
                outcome_before=o.to_dict(),
                outcome_after=after))
        recs.sort(key=lambda r: (-r.score, r.scenario))
        return recs

    # -- periodic tick -----------------------------------------------------

    def tick(self) -> dict | None:
        payload = self.evaluate(record=True)
        if payload is not None:
            with self._lock:
                self.ticks += 1
        return payload

    # -- readout -----------------------------------------------------------

    def recommendations(self, n: int | None = None) -> list[dict]:
        with self._lock:
            rows = [r.to_dict() for r in self._registry]
        rows.reverse()                      # newest first
        return rows if n is None else rows[:n]

    def digest(self) -> str:
        """SHA-256 over the canonical recommendation set (action +
        risk numbers; timestamps excluded) — the determinism check's
        comparison surface.  Built from the dataclass fields directly:
        a read-only digest must never mutate the shared registry rows
        the audit surface serves."""
        with self._lock:
            rows = [{
                "scenario": r.scenario, "action": r.action,
                "risk_before": r.risk_before, "risk_after": r.risk_after,
                "averted": r.averted,
                "cost_per_hour": round(r.cost_per_hour, 6),
                "score": round(r.score, 6),
                "horizon_hours": r.horizon_hours,
                "forecast_generation": r.forecast_generation,
                "backend": r.backend,
            } for r in self._registry]
        blob = json.dumps(rows, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()

    def snapshot(self) -> dict:
        """The /statusz whatif block."""
        with self._lock:
            return {
                "ticks": self.ticks,
                "evaluations": self.evaluations,
                "busy_rejections": self.busy_rejections,
                "recommendations": len(self._registry),
                "validation_failures": self.validation_failures,
                "horizon_hours": self.horizon_hours,
                "last": dict(self.last_payload or {}),
                "last_error": self.last_error,
                "forecast_generation": self.forecaster.generation,
                "degraded_plans": getattr(self.planner, "degraded_plans",
                                          0),
            }


class WhatIfController(PollController):
    """The operator's periodic planning tick (docs/design/whatif.md):
    registered only under KARPENTER_ENABLE_WHATIF, like every other
    opt-in plane."""

    name = "whatif.planning"

    def __init__(self, service: PlanningService,
                 interval: float | None = None):
        from karpenter_tpu.whatif import WHATIF_INTERVAL_S

        self.service = service
        self.interval = interval if interval is not None \
            else WHATIF_INTERVAL_S

    def reconcile(self) -> Result:
        try:
            self.service.tick()
        except Exception as e:  # noqa: BLE001 — a planning failure must
            # never crash the controller plane; the breadcrumb + statusz
            # carry the cause
            metrics.ERRORS.labels("whatif", type(e).__name__).inc()
            with self.service._lock:
                self.service.last_error = str(e)[:200]
            log.warning("whatif tick failed", error=str(e)[:200])
        return Result()
