"""The K-scenario planner: one stacked dispatch, per-scenario outcomes.

``build_baseline`` lowers the live pending window through the ordinary
``solver/encode`` path and ``resident/delta.pack_window`` (the shared
packing — the baseline buffer is word-identical to what the production
solver dispatches), ``WhatIfPlanner.plan`` ships K scenario deltas as
one stacked pair into ``kernels.solve_scenarios`` (ONE device dispatch
for K <= WHATIF_MAX_K; larger menus fall back to chunked dispatches
instead of one OOM-sized buffer), and decodes each scenario's packed
result words into a :class:`ScenarioOutcome`:

- placed / unplaced totals and the explain reason histogram (the same
  15+1-reason taxonomy, folded per group from the appended words);
- open-node count and $/h cost, with the scenario's capacity action
  applied as a sunk-cost discount (pre-provisioned nodes are already
  paid for);
- gang park risk — the unplaced fraction of gang-group demand;
- a p99-staleness estimate: retry windows needed to drain the unplaced
  backlog at the scenario's observed placement rate, in virtual
  seconds (WHATIF_RETRY_S per window — a documented heuristic, not a
  measurement).

``plan_host`` is the same decode over the numpy oracle — the degraded
fallback's body and the parity reference the tests differentiate
against.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.whatif.scenario import (
    Scenario, StackedScenarios, WhatIfBaseline, lower_scenarios,
)

# staleness-estimate cap (virtual seconds): an unplaced backlog with no
# placement progress reads as "stuck for the whole day", not infinity
_STALENESS_CAP_S = 86400.0


def build_baseline(pods, catalog, nodepool=None) -> WhatIfBaseline:
    """Encode the pending window and pack it at its bucketed pads — the
    exact production lowering (encode memo included), so scenario zero
    (no perturbations) IS the live solve problem."""
    from karpenter_tpu.resident.delta import pack_window
    from karpenter_tpu.solver.encode import encode

    pods = list(pods)
    problem = encode(pods, catalog, nodepool)
    packed, (G_pad, O_pad, U_pad) = pack_window(problem)
    return WhatIfBaseline(problem=problem, packed=packed, G_pad=G_pad,
                          O_pad=O_pad, U_pad=U_pad, catalog=catalog,
                          pods=len(pods))


def _estimate_nodes_for(baseline: WhatIfBaseline,
                        stacked: StackedScenarios) -> int:
    """Static node-axis size covering the LARGEST scenario: the shared
    ``estimate_nodes`` bound evaluated at the max per-group counts AND
    the min per-group caps over K (every scenario shares one N — the
    dispatch shape is static).  The cap side matters as much as the
    count side: a cap-clamping scenario (pool-shrink, quota) needs
    ceil(count/cap) nodes, far more than the baseline caps imply — an
    undersized N would exhaust the node array and report phantom
    unplaced pods."""
    from types import SimpleNamespace

    from karpenter_tpu.solver.encode import estimate_nodes
    from karpenter_tpu.solver.types import NODE_BUCKETS, bucket

    problem = baseline.problem
    G = problem.num_groups
    if stacked.K:
        counts = np.maximum(stacked.counts[:, :G], 0).max(axis=0)
        caps = np.maximum(stacked.caps[:, :G], 0).min(axis=0)
    else:
        counts = np.asarray(problem.group_count)
        caps = np.asarray(problem.group_cap)
    proxy = SimpleNamespace(
        group_req=problem.group_req,
        group_count=counts.astype(np.int64),
        group_cap=caps.astype(np.int64),
        catalog=baseline.catalog)
    # hard-capped at the production node-bucket ladder's top rung: a
    # garbage forecast (validator-rejected, but only AFTER the solve)
    # must not size a multi-GB node axis; absurd demand simply reads
    # as unplaced at the biggest supported shape
    n_cap = min(bucket(max(int(np.maximum(counts, 0).sum()), 1),
                       NODE_BUCKETS),
                NODE_BUCKETS[-1])
    return estimate_nodes(proxy, n_cap, NODE_BUCKETS)


@dataclass
class ScenarioOutcome:
    """One future, decoded."""

    name: str
    pods: int
    placed: int
    unplaced: int
    cost: float
    net_cost: float
    nodes_open: int
    reasons: dict[str, int]
    gang_demand: int
    gang_unplaced: int
    staleness_est_s: float
    delta_words: int
    action: dict | None = None
    action_cost_per_hour: float = 0.0
    # pods the scenario's capacity action would shield from node-boot
    # wait: pods landing on up to action.count opened nodes of the
    # pre-provisioned offering (capacity already up = no create+boot in
    # their placement latency) — the boot-exposure half of SLO risk
    action_covered_pods: int = 0
    # per-offering (opened-node count, first-8 per-node pod counts in
    # open order) — the material the service's recommendation ranking
    # derives pre-provision actions from without a second dispatch
    # (excluded from to_dict: internal, not payload)
    offering_node_pods: dict[int, tuple[int, list[int]]] = \
        field(default_factory=dict)

    @property
    def gang_park_risk(self) -> float:
        return self.gang_unplaced / max(self.gang_demand, 1)

    def to_dict(self) -> dict:
        return {
            "scenario": self.name,
            "pods": self.pods,
            "placed": self.placed,
            "unplaced": self.unplaced,
            "cost_per_hour": round(self.cost, 6),
            "net_cost_per_hour": round(self.net_cost, 6),
            "nodes_open": self.nodes_open,
            "reasons": dict(self.reasons),
            "gang_demand": self.gang_demand,
            "gang_unplaced": self.gang_unplaced,
            "gang_park_risk": round(self.gang_park_risk, 4),
            "p99_staleness_est_s": round(self.staleness_est_s, 3),
            "delta_words": self.delta_words,
            "action": self.action,
            "action_cost_per_hour": round(self.action_cost_per_hour, 6),
            "action_covered_pods": self.action_covered_pods,
        }


@dataclass
class WhatIfPlan:
    """One planning pass: K outcomes + the raw material the validator
    replays (stacked deltas, result words, dispatch shapes)."""

    baseline: WhatIfBaseline
    stacked: StackedScenarios
    outcomes: list[ScenarioOutcome]
    raw: np.ndarray                 # int32 [K, Lo]
    N: int
    K_coo: int
    coo16: bool
    backend: str
    dispatches: int
    plan_seconds: float = 0.0
    right_size: bool = True
    errors: list[str] = field(default_factory=list)


class WhatIfPlanner:
    """Stacked scenario solves against a transient baseline (nothing
    stays device-resident between plans — the baseline re-derives from
    the live pending window every tick)."""

    def __init__(self, max_k: int | None = None, right_size: bool = True):
        from karpenter_tpu.whatif import WHATIF_MAX_K

        self.max_k = max_k if max_k is not None else WHATIF_MAX_K
        self.right_size = right_size
        self._device_catalog: dict[tuple, tuple] = {}
        self.plans = 0
        self.chunked_plans = 0

    # -- catalog tensors (device-resident, generation-keyed) ---------------

    def _catalog_tensors(self, catalog, O_pad: int):
        import jax

        from karpenter_tpu.obs.devtel import get_devtel
        from karpenter_tpu.solver.jax_backend import _pad1, _pad2

        key = (catalog.uid, catalog.generation,
               catalog.availability_generation, O_pad,
               getattr(catalog, "risk_generation", 0))
        cached = self._device_catalog.get(key)
        if cached is None:
            for k in [k for k in self._device_catalog
                      if k[0] == catalog.uid and k != key]:
                self._device_catalog.pop(k)
            while len(self._device_catalog) >= 4:
                self._device_catalog.pop(next(iter(self._device_catalog)))
            off_alloc = _pad2(catalog.offering_alloc().astype(np.int32),
                              O_pad)
            off_price = _pad1(catalog.off_price.astype(np.float32), O_pad)
            off_rank = _pad1(catalog.offering_rank_price(), O_pad)
            cached = (jax.device_put(off_alloc),
                      jax.device_put(off_price),
                      jax.device_put(off_rank))
            self._device_catalog[key] = cached
            get_devtel().note_catalog_upload(
                int(off_alloc.nbytes + off_price.nbytes + off_rank.nbytes))
        return cached

    # -- output shape ------------------------------------------------------

    @staticmethod
    def _output_opts(baseline: WhatIfBaseline, stacked: StackedScenarios,
                     N: int) -> tuple[int, bool, bool]:
        """(K_coo, dense16, coo16) for the dispatch: the COO tail is
        sized from the LARGEST scenario's pod total (nnz <= placed pods
        <= that total, so the compacted fetch can never drop entries —
        the same bound the production dispatch relies on)."""
        from karpenter_tpu.solver.jax_backend import clamp_output_opts
        from karpenter_tpu.solver.types import COO_BUCKETS, bucket

        max_pods = int(np.maximum(stacked.counts, 0).sum(axis=1).max()) \
            if stacked.K else 1
        K0 = bucket(max(max_pods, 1), COO_BUCKETS)
        return clamp_output_opts(K0, False, baseline.G_pad, N)

    # -- the stacked solve -------------------------------------------------

    def plan(self, baseline: WhatIfBaseline,
             scenarios: list[Scenario]) -> WhatIfPlan:
        """Lower -> ONE stacked dispatch (chunked above ``max_k``) ->
        decode.  The device path; ``ResilientPlanner`` wraps it with the
        scenario-at-a-time host fallback."""
        import jax

        from karpenter_tpu import obs
        from karpenter_tpu.faulttol import device_guard
        from karpenter_tpu.obs.devtel import get_devtel
        from karpenter_tpu.obs.prof import get_profiler
        from karpenter_tpu.whatif.kernels import solve_scenarios

        t0 = time.perf_counter()
        with obs.span("whatif.plan", backend="device",
                      scenarios=len(scenarios)) as sp:
            stacked = lower_scenarios(baseline, scenarios)
            N = _estimate_nodes_for(baseline, stacked)
            K_coo, dense16, coo16 = self._output_opts(baseline, stacked, N)
            ct = self._catalog_tensors(baseline.catalog, baseline.O_pad)
            K = stacked.K
            outs: list[np.ndarray] = []
            dispatches = 0
            for lo in range(0, K, self.max_k):
                hi = min(lo + self.max_k, K)
                didx = stacked.didx[lo:hi]
                dval = stacked.dval[lo:hi]
                get_devtel().note_dispatch(
                    "whatif",
                    (hi - lo, stacked.D, baseline.G_pad, baseline.O_pad,
                     baseline.U_pad, N, K_coo, coo16, self.right_size),
                    h2d_bytes=int(baseline.packed.nbytes + didx.nbytes
                                  + dval.nbytes),
                    donated=True)
                with device_guard("whatif") as guard:
                    with get_profiler().sampled("whatif") as probe:
                        out_dev = solve_scenarios(
                            jax.device_put(baseline.packed), didx, dval, *ct,
                            G=baseline.G_pad, O=baseline.O_pad,
                            U=baseline.U_pad, N=N,
                            right_size=self.right_size, compact=K_coo,
                            dense16=dense16, coo16=coo16)
                        probe.dispatched(out_dev)
                    out_np = guard.fetch(out_dev)
                get_devtel().note_d2h(int(out_np.nbytes))
                outs.append(out_np)
                dispatches += 1
            raw = np.concatenate(outs) if len(outs) > 1 else outs[0]
            plan = self._decode(baseline, stacked, raw, N, K_coo, coo16,
                                backend="device", dispatches=dispatches)
            sp.set("dispatches", dispatches)
            sp.set("delta_rung", stacked.D)
        plan.plan_seconds = time.perf_counter() - t0
        self.plans += 1
        if dispatches > 1:
            self.chunked_plans += 1
        return plan

    def plan_host(self, baseline: WhatIfBaseline,
                  scenarios: list[Scenario]) -> WhatIfPlan:
        """Scenario-at-a-time numpy oracle — the degraded fallback's
        body and the parity reference (bit-identical to the device path
        except the float cost word)."""
        from karpenter_tpu import obs
        from karpenter_tpu.whatif.oracle import solve_scenarios_np

        t0 = time.perf_counter()
        with obs.span("whatif.plan", backend="host",
                      scenarios=len(scenarios)):
            stacked = lower_scenarios(baseline, scenarios)
            N = _estimate_nodes_for(baseline, stacked)
            K_coo, dense16, coo16 = self._output_opts(baseline, stacked, N)
            raw = solve_scenarios_np(baseline, stacked, N=N,
                                     right_size=self.right_size,
                                     compact=K_coo, dense16=dense16,
                                     coo16=coo16)
            plan = self._decode(baseline, stacked, raw, N, K_coo, coo16,
                                backend="host", dispatches=0)
        plan.plan_seconds = time.perf_counter() - t0
        self.plans += 1
        return plan

    # -- decode ------------------------------------------------------------

    def _decode(self, baseline: WhatIfBaseline, stacked: StackedScenarios,
                raw: np.ndarray, N: int, K_coo: int, coo16: bool,
                backend: str, dispatches: int) -> WhatIfPlan:
        from karpenter_tpu.explain import fold_reason
        from karpenter_tpu.obs import telemetry_words
        from karpenter_tpu.obs.devtel import get_devtel
        from karpenter_tpu.solver.jax_backend import unpack_result
        from karpenter_tpu.solver.result_layout import (
            TELEMETRY_LEN_BYTES, unpack_reason_words,
        )
        from karpenter_tpu.whatif import WHATIF_RETRY_S

        G = baseline.G_pad
        G_real = baseline.problem.num_groups
        gang_mask = np.asarray(baseline.problem.group_gang) >= 0
        price = np.asarray(baseline.catalog.off_price, dtype=np.float64)
        outcomes: list[ScenarioOutcome] = []
        if backend == "device":
            get_devtel().note_telemetry_d2h(
                len(stacked.scenarios) * TELEMETRY_LEN_BYTES)
        for k, scenario in enumerate(stacked.scenarios):
            node_off, assign, unp, cost = unpack_result(
                raw[k], G, N, K_coo, coo16=coo16)
            words = unpack_reason_words(raw[k], G, N, K_coo, coo16=coo16)
            if backend == "device":
                telemetry_words.decode_and_record(
                    raw[k], G, N, K_coo, coo16=coo16, plane="whatif",
                    delta_words=int(stacked.delta_words[k]))
            counts = stacked.counts[k][:G_real]
            unp_r = unp[:G_real].astype(np.int64)
            pods = int(counts.sum())
            unplaced = int(unp_r.sum())
            placed = int((counts - unp_r).sum())
            reasons: dict[str, int] = {}
            if words is not None:
                for gi in np.nonzero(unp_r > 0)[0]:
                    name = fold_reason(int(words[gi])) or "unknown"
                    reasons[name] = reasons.get(name, 0) \
                        + int(unp_r[gi])
            open_off = node_off[node_off >= 0]
            nodes_open = int(open_off.size)
            # per-offering (opened-node count, first-8 per-node pod
            # counts in open order) — vectorized: the stable sort keeps
            # open order within each offering
            offering_node_pods: dict[int, tuple[int, list[int]]] = {}
            if nodes_open:
                open_idx = np.nonzero(node_off >= 0)[0]
                pods_n = assign[:, open_idx].sum(axis=0)
                order = np.argsort(open_off, kind="stable")
                uoff, starts = np.unique(open_off[order],
                                         return_index=True)
                for i, off in enumerate(uoff):
                    hi = starts[i + 1] if i + 1 < len(uoff) else None
                    seg = pods_n[order[starts[i]:hi]]
                    offering_node_pods[int(off)] = \
                        (int(seg.size), [int(x) for x in seg[:8]])
            cost = float(cost)
            net_cost = cost
            action_dict = None
            action_cost = 0.0
            action_covered = 0
            if scenario.action is not None:
                a = scenario.action
                opened = int((open_off == a.offering).sum())
                if 0 <= a.offering < price.shape[0]:
                    unit = float(price[a.offering])
                    net_cost = cost - min(opened, int(a.count)) * unit
                    action_cost = unit * int(a.count)
                # pods shielded from boot wait: those landing on the
                # first count opened nodes of the action's offering
                # (node order is the solve's deterministic open order)
                _n, pods_list = offering_node_pods.get(
                    int(a.offering), (0, []))
                action_covered = sum(pods_list[:int(a.count)])
                action_dict = a.describe(baseline.catalog)
            gang_demand = int(counts[gang_mask].sum()) \
                if gang_mask.any() else 0
            gang_unplaced = int(unp_r[gang_mask].sum()) \
                if gang_mask.any() else 0
            if unplaced <= 0:
                staleness = 0.0
            elif placed <= 0:
                staleness = _STALENESS_CAP_S
            else:
                staleness = min(
                    WHATIF_RETRY_S * (1.0 + unplaced / placed),
                    _STALENESS_CAP_S)
            outcomes.append(ScenarioOutcome(
                name=scenario.name, pods=pods, placed=placed,
                unplaced=unplaced, cost=cost, net_cost=net_cost,
                nodes_open=nodes_open, reasons=reasons,
                gang_demand=gang_demand, gang_unplaced=gang_unplaced,
                staleness_est_s=staleness,
                delta_words=stacked.delta_words[k],
                action=action_dict, action_cost_per_hour=action_cost,
                action_covered_pods=action_covered,
                offering_node_pods=offering_node_pods))
        return WhatIfPlan(baseline=baseline, stacked=stacked,
                          outcomes=outcomes, raw=raw, N=N, K_coo=K_coo,
                          coo16=coo16, backend=backend,
                          dispatches=dispatches,
                          right_size=self.right_size)
