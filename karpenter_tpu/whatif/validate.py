"""Independent whatif validator — the load-bearing check.

Two layers, both independent of the planner's own code paths:

1. **Perturbed-state well-formedness.**  Each scenario's delta is
   replayed onto a fresh baseline copy and the resulting buffer checked
   as a solve problem: delta indices in range, group counts and caps
   non-negative and int32-bounded.  This is where a broken forecaster's
   garbage rates die: scenario lowering deliberately does NOT sanitize
   (scenario.py), so a negative or absurd forecast count lands in the
   meta words and is REJECTED here — proven by the broken-forecast
   falsifiability test, the same way the chaos fixture profiles prove
   their invariants can fire.

2. **Fresh-solve equality.**  Every scenario's result words must equal
   a fresh SINGLE-scenario solve of the perturbed state — by the
   device's own ``solve_packed`` when a device is available (full word
   equality including the cost word: same kernel pipeline, same
   reductions), else by the numpy oracle (equality on every word but
   the float cost, which must still agree to tolerance).  A stacked
   kernel that cross-contaminates scenarios, a delta that lands on the
   wrong words, or a decode reading the wrong lane all surface as a
   word mismatch.
"""

from __future__ import annotations

import numpy as np

_I32_MAX = 2 ** 31 - 1

# sanity ceiling on a single group's pod count (~134M): the lowering
# saturates huge garbage at int32 max (the buffer is int32), so a pure
# "> int32" check could never fire — anything at or above this ceiling
# is a broken forecast, not a supported workload (the biggest bench
# regime is 4 orders of magnitude below it)
COUNT_CEILING = 1 << 27


def validate_whatif(plan, *, use_device: bool | None = None,
                    max_scenarios: int | None = None,
                    replay: bool = True) -> list[str]:
    """Validate a :class:`WhatIfPlan`.  Returns a list of violation
    strings (empty = valid).  ``use_device=None`` auto-detects jax;
    ``max_scenarios`` bounds the fresh-solve replay for large menus
    (well-formedness always checks every scenario); ``replay=False``
    runs ONLY the cheap well-formedness layer — the always-on guard
    the planning service applies even when full validation is off.

    Word comparison is exact (cost included) only when BOTH sides are
    device-produced; a host/degraded plan's cost word is a numpy
    reduction that matches the device only up to reduction order, so
    it compares through ``words_equal_except_cost``."""
    baseline = plan.baseline
    stacked = plan.stacked
    L = baseline.L
    G = baseline.G_pad
    violations: list[str] = []

    if use_device is None:
        try:
            import jax  # noqa: F401
            use_device = True
        except Exception:  # noqa: BLE001 — no device, oracle reference
            use_device = False

    bufs: list[np.ndarray | None] = []
    for k, scenario in enumerate(stacked.scenarios):
        name = scenario.name
        didx, dval = stacked.didx[k], stacked.dval[k]
        bad_idx = (didx < 0) | (didx > L)
        if bad_idx.any():
            violations.append(
                f"scenario {name!r}: delta index out of range "
                f"(min={int(didx.min())}, max={int(didx.max())}, L={L})")
            bufs.append(None)
            continue
        buf = baseline.packed.copy()
        live = didx < L
        buf[didx[live]] = dval[live]
        meta = buf[:G * 8].reshape(G, 8).astype(np.int64)
        counts = meta[:, 4]
        caps = meta[:, 5]
        if (counts < 0).any():
            violations.append(
                f"scenario {name!r}: negative group count "
                f"(min={int(counts.min())}) — garbage forecast or "
                f"corrupt delta")
            bufs.append(None)
            continue
        if (counts >= COUNT_CEILING).any():
            violations.append(
                f"scenario {name!r}: absurd group count "
                f"(max={int(counts.max())} >= {COUNT_CEILING}) — "
                f"garbage forecast (huge rates saturate at int32 in "
                f"the lowering)")
            bufs.append(None)
            continue
        if (caps < 0).any():
            violations.append(
                f"scenario {name!r}: negative group cap")
            bufs.append(None)
            continue
        bufs.append(buf)

    if not replay:
        return violations
    n_replay = len(stacked.scenarios) if max_scenarios is None \
        else min(max_scenarios, len(stacked.scenarios))
    # exact equality (cost word included) only holds device-vs-device;
    # a host/degraded plan's float cost differs by reduction order
    exact = use_device and plan.backend == "device"
    # padded catalog tensors hoisted out of the replay loop — identical
    # for every scenario
    catalog = baseline.catalog
    tensors = (_pad_host(catalog.offering_alloc().astype(np.int32),
                         baseline.O_pad),
               _pad_host(catalog.off_price.astype(np.float32),
                         baseline.O_pad),
               _pad_host(catalog.offering_rank_price(), baseline.O_pad))
    for k in range(n_replay):
        if bufs[k] is None:
            continue
        name = stacked.scenarios[k].name
        ref = _reference_solve(baseline, bufs[k], plan, use_device,
                               tensors)
        got = plan.raw[k]
        if exact:
            ok = got.shape == ref.shape and np.array_equal(got, ref)
        else:
            from karpenter_tpu.whatif.oracle import words_equal_except_cost

            ok = words_equal_except_cost(got, ref, G, plan.N)
        if not ok:
            diff = int(np.sum(got != ref)) if got.shape == ref.shape \
                else -1
            violations.append(
                f"scenario {name!r}: result words differ from a fresh "
                f"single-scenario solve ({diff} word(s); "
                f"reference={'device' if use_device else 'oracle'})")
    return violations


def _reference_solve(baseline, buf: np.ndarray, plan,
                     use_device: bool, tensors) -> np.ndarray:
    """One fresh single-scenario solve of the perturbed buffer at the
    plan's exact dispatch shapes.  ``tensors`` is the (alloc, price,
    rank) triple the caller padded once for the whole replay."""
    alloc, price, rank = tensors
    if use_device:
        import jax.numpy as jnp

        from karpenter_tpu.solver.jax_backend import solve_packed

        out = solve_packed(
            jnp.asarray(buf), jnp.asarray(alloc), jnp.asarray(price),
            jnp.asarray(rank),
            G=baseline.G_pad, O=baseline.O_pad, U=baseline.U_pad,
            N=plan.N, right_size=plan.right_size, compact=plan.K_coo,
            dense16=False, coo16=plan.coo16)
        return np.asarray(out)
    from karpenter_tpu.whatif.oracle import solve_packed_np

    return solve_packed_np(
        buf, alloc, price, rank,
        G=baseline.G_pad, O=baseline.O_pad, U=baseline.U_pad, N=plan.N,
        right_size=plan.right_size, compact=plan.K_coo, dense16=False,
        coo16=plan.coo16)


def _pad_host(a: np.ndarray, n: int) -> np.ndarray:
    from karpenter_tpu.solver.jax_backend import _pad1, _pad2

    return _pad2(a, n) if a.ndim == 2 else _pad1(a, n)
