"""Whatif CLI (`make whatif-determinism`).

    python -m karpenter_tpu.whatif --determinism [--seeds N]
    python -m karpenter_tpu.whatif --demo

The determinism check is the chaos-matrix discipline applied to the
planning plane: one seeded cycle (seeded arrival ledger -> forecast ->
standing menu -> stacked plan -> recommendation registry) run TWICE in
one process, digest-compared — same ledger + seed must produce a
byte-identical recommendation set, or the planner is consuming ambient
state it must not.  Exit 1 on any digest mismatch or validator
rejection.
"""

from __future__ import annotations

import argparse
import os
import sys
from types import SimpleNamespace

# the check never needs an accelerator; force CPU before jax can
# initialize a backend through any transitive import
os.environ.setdefault("JAX_PLATFORMS", "cpu")


class _StubCluster:
    """Just enough cluster for PlanningService: a fixed pending set."""

    def __init__(self, pods):
        self._pods = list(pods)

    def pending_pods(self):
        return [SimpleNamespace(spec=p) for p in self._pods]

    def list(self, kind, predicate=None):
        return []

    def get_nodeclass(self, name):
        return None


def _seeded_world(seed: int):
    """(cluster, catalog, ledger-seeding fn): a deterministic pending
    backlog + arrival history keyed only by ``seed``."""
    import random

    from karpenter_tpu.apis.pod import PodSpec, ResourceRequests
    from karpenter_tpu.catalog import (
        CatalogArrays, InstanceTypeProvider, PricingProvider,
    )
    from karpenter_tpu.cloud.fake import FakeCloud, generate_profiles

    rng = random.Random(seed)
    cloud = FakeCloud(profiles=generate_profiles(16))
    pricing = PricingProvider(cloud)
    catalog = CatalogArrays.build(InstanceTypeProvider(cloud,
                                                      pricing).list())
    pricing.close()
    menu = [(100 * rng.randint(1, 8), 256 * rng.randint(1, 8))
            for _ in range(6)]
    pods = [PodSpec(f"whatif-{i}",
                    requests=ResourceRequests(*menu[i % len(menu)], 0, 1))
            for i in range(48)]

    def seed_ledger(ledger):
        r = random.Random(seed * 31)
        for day_hour in range(24):
            for p in pods:
                for _ in range(r.randint(0, 2)):
                    ledger.arrival(p.signature_key(),
                                   t=day_hour * 3600.0)

    return _StubCluster(pods), catalog, seed_ledger


def _one_cycle(seed: int) -> tuple[str, list[str]]:
    """One full planning cycle on a FRESH ledger + service; returns the
    recommendation digest and any validator violations."""
    from karpenter_tpu import obs
    from karpenter_tpu.obs.ledger import PlacementLedger
    from karpenter_tpu.whatif.service import PlanningService

    cluster, catalog, seed_ledger = _seeded_world(seed)
    ledger = PlacementLedger()
    seed_ledger(ledger)
    with obs.use_ledger(ledger):
        svc = PlanningService(cluster, catalog_fn=lambda: catalog,
                              seed=seed, validate=True)
        payload = svc.evaluate(record=True, hour=9)
    violations = payload.get("validation", {}).get("violations", [])
    return svc.digest(), list(violations)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="karpenter_tpu.whatif")
    ap.add_argument("--determinism", action="store_true",
                    help="run each seeded planning cycle twice and "
                         "compare recommendation digests")
    ap.add_argument("--seeds", type=int, default=2,
                    help="seeds 1..N for --determinism (default 2)")
    ap.add_argument("--demo", action="store_true",
                    help="one seeded cycle, print the payload summary")
    args = ap.parse_args(argv)

    if args.demo:
        digest, violations = _one_cycle(1)
        print(f"whatif demo: digest={digest[:12]} "
              f"violations={len(violations)}")
        return 1 if violations else 0

    if not args.determinism:
        ap.print_help()
        return 2

    failures = 0
    for seed in range(1, args.seeds + 1):
        d1, v1 = _one_cycle(seed)
        d2, v2 = _one_cycle(seed)
        ok = d1 == d2 and not v1 and not v2
        status = "ok  " if ok else "FAIL"
        print(f"{status} whatif-determinism seed={seed} "
              f"digest={d1[:12]} rerun={d2[:12]} "
              f"violations={len(v1) + len(v2)}")
        if not ok:
            failures += 1
            for v in (v1 + v2)[:4]:
                print(f"     {v}")
    if failures:
        print(f"whatif determinism: {failures} seed(s) failed — replay "
              f"with: python -m karpenter_tpu.whatif --determinism "
              f"--seeds {args.seeds}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
