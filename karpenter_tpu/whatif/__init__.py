"""What-if scenario planning: forecast-driven proactive provisioning
as one extra batch dimension (docs/design/whatif.md).

Everything needed to answer "what happens at tonight's peak / during a
spot storm / if this NodePool shrinks" already exists in the codebase —
deterministic chaos profiles, the VirtualClock, the batched packed
solve, PR-8 resident deltas, the diurnal soak load model — this plane
turns them into a live product surface:

- :mod:`forecast` — deterministic arrival forecasting from the
  ledger's bounded per-(signature-group, virtual-hour) arrival table
  (rate EWMAs + a diurnal profile seeded from the soak load model),
  journal-persisted like the spot-risk model;
- :mod:`scenario` — scenario generation as composable perturbations of
  the packed baseline buffer (forecasted arrival waves x chaos-profile
  perturbations reused declaratively from :class:`ChaosProfile` x
  candidate capacity actions), each lowered to a word delta via the
  PR-8 delta path so K scenarios ship as ONE stacked ``[K, D]`` pair,
  never K full encodes;
- :mod:`kernels` + :mod:`planner` — one cached jitted dispatch vmapping
  delta-apply + ``solve_core`` + ``_pack_result_telemetry`` over the K
  axis (stacked inputs donated, prof-sampled ``"whatif"``), decoding
  per-scenario outcomes (placed/unplaced, explain reason histograms,
  cost, gang park risk, staleness estimate);
- :mod:`oracle` — the bit-identical numpy parity twin of the stacked
  device solve;
- :mod:`validate` — the independent validator: every scenario's result
  words must equal a fresh single-scenario solve of the perturbed
  state, and the perturbed state itself must be well-formed (a broken
  forecaster's garbage rates are REJECTED here, not served);
- :mod:`degraded` — :class:`ResilientPlanner`, the scenario-at-a-time
  host-loop fallback with an ``ERRORS{whatif,...}`` breadcrumb;
- :mod:`service` — the operator-resident :class:`PlanningService`
  (opt-in ``KARPENTER_ENABLE_WHATIF``): periodic standing-menu
  evaluation, (SLO-risk averted per dollar) recommendation ranking, a
  bounded audit registry, ``GET /debug/whatif`` and the
  ``karpenter_tpu_whatif_*`` metric families.
"""

from __future__ import annotations

import os

# scenarios per device dispatch: a K beyond this falls back to chunked
# dispatches (ceil(K / max) launches) instead of one giant stacked
# buffer that could OOM the device — tests pin the fallback
WHATIF_MAX_K = max(1, int(os.environ.get("KARPENTER_WHATIF_MAX_K", "128")))

# default planning horizon (virtual hours) and service cadence
WHATIF_HORIZON_HOURS = 4
WHATIF_INTERVAL_S = 60.0

# hard ceiling on the horizon an evaluation accepts (one week): the
# /debug/whatif ?horizon= knob must not drive an unbounded forecast
# loop or an OOM-sized scenario stack under the single-flight lock —
# the same clamp discipline /debug/profile applies to ?duration_s=
WHATIF_MAX_HORIZON_HOURS = 168

# staleness heuristic: expected seconds per retry window when estimating
# how long an unplaced backlog takes to drain (planner.ScenarioOutcome)
WHATIF_RETRY_S = 15.0

from karpenter_tpu.whatif.forecast import ArrivalForecaster  # noqa: E402
from karpenter_tpu.whatif.planner import (  # noqa: E402
    ScenarioOutcome, WhatIfBaseline, WhatIfPlan, WhatIfPlanner,
    build_baseline,
)
from karpenter_tpu.whatif.scenario import (  # noqa: E402
    ArrivalWave, CapClamp, OfferingMask, PreProvision, Scenario,
    lower_scenarios,
)
from karpenter_tpu.whatif.degraded import ResilientPlanner  # noqa: E402
from karpenter_tpu.whatif.service import PlanningService  # noqa: E402
from karpenter_tpu.whatif.validate import validate_whatif  # noqa: E402

__all__ = [
    "WHATIF_MAX_K", "WHATIF_HORIZON_HOURS", "WHATIF_INTERVAL_S",
    "WHATIF_RETRY_S", "ArrivalForecaster", "ArrivalWave", "CapClamp",
    "OfferingMask", "PreProvision", "Scenario", "lower_scenarios",
    "WhatIfBaseline", "WhatIfPlan", "WhatIfPlanner", "ScenarioOutcome",
    "build_baseline", "ResilientPlanner", "PlanningService",
    "validate_whatif",
]
