"""Device kernel for the stacked K-scenario solve.

One cached jit (GL003: per-call rebuilds would re-trace every plan)
vmapping delta-apply + ``_unpack_problem`` + ``solve_core`` +
``_pack_result_telemetry`` over the scenario axis: K futures solved in
ONE device dispatch against ONE baseline buffer.  Per scenario the body
traces exactly the ``solve_packed`` pipeline on the delta-applied
buffer, which is what makes each scenario's result words bit-identical
to a fresh single-scenario solve of the perturbed state — the parity
contract ``validate_whatif`` and the 8-seed differentials pin
(docs/design/whatif.md).

The baseline buffer and the stacked delta pair are DONATED (GL006):
all three are transient per plan — nothing whatif keeps device-resident
between plans, because the baseline re-derives from the live pending
window every tick.
"""

from __future__ import annotations

import functools

import jax

from karpenter_tpu.solver.jax_backend import (
    _pack_result_telemetry, _unpack_problem, solve_core,
)


@functools.lru_cache(maxsize=32)
def _solve_scenarios_jit(K: int, D: int, G: int, O: int, U: int, N: int,
                         right_size: bool, compact: int, dense16: bool,
                         coo16: bool):
    """Cached jit of the stacked scenario solve (delta-apply fused)."""

    def one(didx_row, dval_row, base, off_alloc, off_price, off_rank):
        buf = base.at[didx_row].set(dval_row, mode="drop")
        meta, compat_i, rows_g = _unpack_problem(buf, off_alloc, G, O, U)
        node_off, assign, unplaced, cost = solve_core(
            meta[:, :4], meta[:, 4], meta[:, 5], compat_i > 0,
            off_alloc, off_price, off_rank, num_nodes=N,
            right_size=right_size)
        return _pack_result_telemetry(
            meta, rows_g, compat_i, node_off, assign, unplaced, cost,
            off_alloc, compact, dense16, coo16)

    def stacked(base, didx, dval, off_alloc, off_price, off_rank):
        return jax.vmap(one, in_axes=(0, 0, None, None, None, None))(
            didx, dval, base, off_alloc, off_price, off_rank)

    return jax.jit(stacked, donate_argnums=(0, 1, 2))


def solve_scenarios(base, didx, dval, off_alloc, off_price, off_rank, *,
                    G: int, O: int, U: int, N: int,
                    right_size: bool = True, compact: int = 0,
                    dense16: bool = False, coo16: bool = False):
    """Dispatch the stacked scenario solve: ``base`` int32 [L] (the
    packed baseline) and ``didx``/``dval`` int32 [K, D] (per-scenario
    word deltas, drop-index padded) are all donated.  Returns the
    stacked result buffer [K, Lo], still on device — the caller owns
    fetch accounting."""
    K, D = int(didx.shape[0]), int(didx.shape[1])
    f = _solve_scenarios_jit(K, D, G, O, U, N, right_size, compact,
                             dense16, coo16)
    return f(base, didx, dval, off_alloc, off_price, off_rank)
