"""Degraded mode for the whatif plane: scenario-at-a-time host loop.

Same contract as every other plane's Resilient* wrapper (solver,
preempt, gang, repack, sharded): a device dispatch that raises — Mosaic
runtime fault, OOM on an oversized stack, backend gone — degrades to
the numpy oracle loop with an ``ERRORS{whatif, degraded_*}``
breadcrumb, never an exception into the planning service's tick.  The
host loop produces the SAME result words (modulo the float cost word),
so recommendations keep flowing at host speed while the device path is
sick.
"""

from __future__ import annotations

from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger
from karpenter_tpu.whatif.planner import WhatIfPlan, WhatIfPlanner

log = get_logger("whatif.degraded")


class ResilientPlanner:
    """Wraps :class:`WhatIfPlanner`: device plan, host fallback."""

    def __init__(self, planner: WhatIfPlanner | None = None,
                 device: bool = True):
        self.planner = planner or WhatIfPlanner()
        self.device = device
        self.degraded_plans = 0

    def plan(self, baseline, scenarios) -> WhatIfPlan:
        if self.device:
            try:
                return self.planner.plan(baseline, scenarios)
            except Exception as e:  # noqa: BLE001 — the degraded contract:
                # any device failure falls back to the host loop
                kind = type(e).__name__
                log.warning("whatif device plan failed; degrading to the "
                            "host loop", error=str(e)[:200], kind=kind)
                metrics.ERRORS.labels("whatif", f"degraded_{kind}").inc()
        plan = self.planner.plan_host(baseline, scenarios)
        self.degraded_plans += 1
        plan.backend = "host-degraded" if self.device else "host"
        return plan
