"""Deterministic arrival forecasting from the ledger's arrival table.

The PR-6 placement ledger now keeps a bounded per-(signature-group,
virtual-hour) arrival count table (obs/ledger.py ``arrival`` /
``arrival_history``), stamped at the one intake path every pod shares.
The forecaster turns it into expected arrivals per signature group over
a planning horizon:

- **base rate**: a true RECENCY EWMA over the group's per-absolute-hour
  counts in chronological ring order (the ring carries the absolute
  virtual hour, so "recent" means recent in time, not a position in
  the 0..23 hour-of-day walk — an hour-of-day EWMA would weight a
  group by WHICH clock hours its demand lands in, silently zeroing
  overnight workloads).  A journal-loaded table has no chronological
  series, so it falls back to the mean hourly rate (total / 24) — the
  ring's FIFO bound is itself the outer recency window either way;
- **diurnal profile**: the global per-hour multiplier, blended with a
  prior derived from the soak load model (chaos/soak.PRODUCTION_DAY's
  per-segment load factors stretched over 24 hours) — a cold ledger
  degrades to the prior alone, never to NaN.

Both are pure functions of the ledger state: same ledger => same
rates, no clocks, no randomness.

Like the spot-risk model the forecaster is deliberately COUNT-DERIVED,
not a fitted curve: rebuilding from the same ledger reproduces the same
forecast exactly, which is what makes the whatif determinism check
(same ledger + seed => byte-identical recommendation digest) sharp.  It
persists across restarts through the recovery journal's keyed state
records (``whatif_forecast/<digest>``), the same channel the spot-risk
model rides.
"""

from __future__ import annotations

import hashlib
import threading

import numpy as np

from karpenter_tpu.obs.trace import now

HOURS = 24

# EWMA recency weight over the CHRONOLOGICAL per-absolute-hour counts
# (alpha toward the most recent observed hour)
EWMA_ALPHA = 0.35

STATE_PREFIX = "whatif_forecast/"


def _table_fingerprint(counts: dict[str, list[int]]) -> int:
    """Content fingerprint of an arrival table (stable 31-bit int).
    Used as the rebuilt forecaster's ``generation``: the service builds
    a FRESH forecaster every pass, so a per-instance counter would read
    the same number forever, and a total-count generation saturates at
    the ring capacity while the ring keeps rotating — the audit fields
    and the journal-save gate need a number that changes exactly when
    the table content does, and reproduces for the determinism digest."""
    h = hashlib.blake2b(digest_size=4)
    for sig in sorted(counts):
        h.update(sig.encode())
        h.update(bytes(str(counts[sig]), "utf-8"))
    return int.from_bytes(h.digest(), "big") & 0x7FFFFFFF


def soak_diurnal_prior() -> np.ndarray:
    """float64 [24] multipliers (mean 1.0) derived from the soak load
    model: chaos/soak.PRODUCTION_DAY's segments, each stretched over its
    proportional share of the 24-hour day, carrying its load factor —
    the same diurnal shape `make soak` replays.  The cold-ledger prior:
    with no observed arrivals the forecaster still knows mornings ramp
    and midday peaks."""
    from karpenter_tpu.chaos.soak import PRODUCTION_DAY

    total_rounds = sum(seg.rounds for seg in PRODUCTION_DAY)
    prof = np.ones(HOURS, dtype=np.float64)
    hour = 0.0
    for seg in PRODUCTION_DAY:
        span = HOURS * seg.rounds / max(total_rounds, 1)
        lo, hi = int(hour), int(min(hour + span, HOURS))
        prof[lo:max(hi, lo + 1)] = seg.load
        hour += span
    mean = float(prof.mean())
    return prof / max(mean, 1e-9)


class ArrivalForecaster:
    """Per-signature-group arrival rates + diurnal profile (see module
    docstring).  Thread-safe; counts are plain integers so rebuilds and
    the determinism digest compare exactly."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[str, list[int]] = {}   # sig -> [24] counts
        # chronological (signature, absolute-hour) events when built
        # from a live ledger; None for journal-loaded/merged tables
        # (whose rates fall back to the mean hourly rate)
        self._series: list[tuple[str, int]] | None = None
        self.generation = 0
        self.built_at = 0.0

    # -- learning ----------------------------------------------------------

    @classmethod
    def from_ledger(cls, ledger) -> "ArrivalForecaster":
        """Rebuild from the ledger's arrival table — the canonical
        constructor (the determinism check re-derives through this same
        path).  The generation is the table's content fingerprint (see
        :func:`_table_fingerprint`)."""
        model = cls()
        table = ledger.arrival_history()
        series = ledger.arrival_series()
        with model._lock:
            model._counts = {sig: list(row) for sig, row in
                             sorted(table.items())}
            model._series = series
            model.generation = _table_fingerprint(model._counts)
            model.built_at = now()
        return model

    def merged_with(self, other: "ArrivalForecaster"
                    ) -> "ArrivalForecaster":
        """Elementwise-max merge — the restart warm-start: the journal
        snapshot is an earlier state of the SAME bounded ring, so max
        per (signature, hour) restores history the fresh ring hasn't
        re-observed yet without ever double-counting, and is idempotent
        (merging the snapshot twice changes nothing)."""
        with other._lock:
            theirs = {sig: list(row) for sig, row in other._counts.items()}
        out = ArrivalForecaster()
        with self._lock:
            mine = {sig: list(row) for sig, row in self._counts.items()}
        for sig in sorted(set(mine) | set(theirs)):
            a = mine.get(sig, [0] * HOURS)
            b = theirs.get(sig, [0] * HOURS)
            out._counts[sig] = [max(x, y) for x, y in zip(a, b)]
        out.generation = _table_fingerprint(out._counts)
        out.built_at = self.built_at
        return out

    # -- readout -----------------------------------------------------------

    def rates(self) -> dict[str, float]:
        """Per-signature base arrival rate (pods/hour).  With a live
        chronological series: the EWMA over the group's counts per
        ABSOLUTE hour, walked oldest -> newest over the span the ring
        covers (gap hours count as zero, so an idle stretch decays the
        rate).  Without one (journal-loaded/merged table): the mean
        hourly rate, total / 24.  Always finite and >= 0 — an empty
        table is an empty dict, never NaN."""
        with self._lock:
            counts = {sig: list(row) for sig, row in self._counts.items()}
            series = None if self._series is None else list(self._series)
        if series:
            lo = min(h for _, h in series)
            hi = max(h for _, h in series)
            span = min(hi - lo + 1, 24 * 14)     # bounded walk (2 weeks)
            lo = hi - span + 1
            per_hour: dict[str, dict[int, int]] = {}
            for sig, h in series:
                if h >= lo:
                    d = per_hour.setdefault(sig, {})
                    d[h] = d.get(h, 0) + 1
            out: dict[str, float] = {}
            for sig, buckets in sorted(per_hour.items()):
                ewma = 0.0
                for h in range(lo, hi + 1):
                    ewma = EWMA_ALPHA * float(buckets.get(h, 0)) \
                        + (1.0 - EWMA_ALPHA) * ewma
                if ewma > 0.0:
                    out[sig] = ewma
            return out
        out = {}
        for sig, row in counts.items():
            mean = sum(row) / float(HOURS)
            if mean > 0.0:
                out[sig] = mean
        return out

    def diurnal(self) -> np.ndarray:
        """float64 [24] hour-of-day multipliers, mean 1.0: the observed
        global profile when the table has enough mass, the soak prior
        otherwise (and a count-weighted blend in between) — guarded so
        a cold or garbage-free ledger can never produce NaN."""
        prior = soak_diurnal_prior()
        with self._lock:
            rows = list(self._counts.values())
        if not rows:
            return prior
        totals = np.zeros(HOURS, dtype=np.float64)
        for row in rows:
            totals += np.asarray(row, dtype=np.float64)
        mass = float(totals.sum())
        if mass <= 0.0:
            return prior
        observed = totals * (HOURS / mass)
        # blend weight ramps with observed mass: ~HOURS arrivals is
        # still mostly prior, hundreds are mostly observation
        w = min(1.0, mass / (HOURS * 8.0))
        prof = w * observed + (1.0 - w) * prior
        return prof / max(float(prof.mean()), 1e-9)

    def expected_arrivals(self, horizon_hours: int,
                          start_hour: int = 0) -> dict[str, int]:
        """Expected arrivals per signature group over the next
        ``horizon_hours`` virtual hours starting after ``start_hour`` —
        the forecasted wave the scenario generator lowers onto the
        baseline's matching solve groups.  Deterministic rounding; an
        empty table forecasts nothing (the cold-ledger degenerate case
        the baseline-only scenario covers)."""
        prof = self.diurnal()
        scale = sum(float(prof[(start_hour + 1 + i) % HOURS])
                    for i in range(max(int(horizon_hours), 0)))
        out: dict[str, int] = {}
        for sig, rate in self.rates().items():
            n = int(round(rate * scale))
            if n > 0:
                out[sig] = n
        return out

    def snapshot(self) -> dict:
        """The /debug/whatif + /statusz forecast payload."""
        rates = self.rates()
        with self._lock:
            groups = len(self._counts)
            total = sum(sum(row) for row in self._counts.values())
        return {
            "generation": self.generation,
            "built_at": round(self.built_at, 3),
            "signature_groups": groups,
            "arrivals_observed": total,
            "top_rates": [
                {"signature": sig[:120], "pods_per_hour": round(r, 4)}
                for sig, r in sorted(rates.items(),
                                     key=lambda kv: (-kv[1], kv[0]))[:8]],
            "diurnal": [round(float(x), 4) for x in self.diurnal()],
        }

    # -- persistence (recovery journal state records) ----------------------

    def save(self, journal) -> None:
        """One keyed state record per signature group — newest-wins, so
        a restart rebuilds the exact table (recovery/journal.py, the
        spot-risk model's channel)."""
        with self._lock:
            counts = {sig: list(row) for sig, row in self._counts.items()}
        for sig, row in counts.items():
            digest = hashlib.blake2b(sig.encode(), digest_size=10).hexdigest()
            journal.state(f"{STATE_PREFIX}{digest}",
                          {"signature": sig, "counts": row})

    @classmethod
    def load(cls, journal) -> "ArrivalForecaster":
        model = cls()
        for key, value in journal.state_map().items():
            if not key.startswith(STATE_PREFIX) or not isinstance(value,
                                                                  dict):
                continue
            sig = value.get("signature")
            row = value.get("counts")
            if not isinstance(sig, str) or not isinstance(row, list):
                continue
            with model._lock:
                model._counts[sig] = [int(c) for c in row[:HOURS]] \
                    + [0] * max(0, HOURS - len(row))
        with model._lock:
            model.generation = _table_fingerprint(model._counts)
        return model
