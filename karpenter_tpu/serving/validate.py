"""Independent parity checks for the serving plane.

The serving loop's whole claim is "the ring changes WHEN the solve
runs, never WHAT it computes" — so the checks here compare against the
classic single-shot path itself, not against serving-side arithmetic
(an oracle the loop can lie to proves nothing):

- :func:`ring_state_violations` — the loop's device state, host
  mirror, and the :class:`~karpenter_tpu.serving.oracle.RingOracle`
  replay of every admitted slot agree word-for-word (and, given the
  catalog, the generation stamp is current).
- :func:`raw_parity_violations` — the 8-seed churn differential at the
  WORD level: a ring-fed ``serve_window`` chain (delta slots against a
  persistent donated state) must produce packed result buffers
  bit-identical to per-window classic ``solve_packed`` dispatches of
  the freshly packed state, and the carried state must equal the host
  re-pack after every window.
- :func:`plan_parity_violations` — the same differential one level up:
  DECODED plans from a serving-enabled solver vs a classic solver over
  identical churned window streams (node set, pod placement, unplaced
  set, cost — the resident/bench parity key).
- :func:`sharded_parity_violations` — the 2-shard variant: the
  deferred-fetch :class:`~karpenter_tpu.serving.service.ShardedServingLoop`
  vs the same service's synchronous ``solve_window``.

All builders are seeded and deterministic; the checks run on any
backend (CPU included — bit-identity is a compilation contract, not a
hardware one).
"""

from __future__ import annotations

import numpy as np


def _churn_stream(num_pods: int, num_types: int, windows: int, seed: int):
    """Seeded pod-churn window sequence + catalog (arrivals and
    departures per window, the repack-loop shape)."""
    import random as _random

    from karpenter_tpu.apis.pod import PodSpec, ResourceRequests
    from karpenter_tpu.catalog import (
        CatalogArrays, InstanceTypeProvider, PricingProvider,
    )
    from karpenter_tpu.cloud.fake import FakeCloud, generate_profiles

    cloud = FakeCloud(profiles=generate_profiles(num_types))
    pricing = PricingProvider(cloud)
    catalog = CatalogArrays.build(InstanceTypeProvider(cloud, pricing).list())
    pricing.close()

    rng = _random.Random(f"serving-validate-{seed}")
    sizes = ((250, 512), (500, 1024), (1000, 4096), (2000, 8192))

    def mk(tag: str, i: int) -> PodSpec:
        cpu, mem = sizes[rng.randrange(len(sizes))]
        return PodSpec(f"{tag}-{i}", requests=ResourceRequests(cpu, mem, 0, 1))

    cur = [mk(f"s{seed}w0", i) for i in range(num_pods)]
    seqs = [list(cur)]
    for w in range(1, windows):
        for _ in range(rng.randrange(1, max(2, num_pods // 16))):
            cur.pop(rng.randrange(len(cur)))
        cur.extend(mk(f"s{seed}w{w}", i)
                   for i in range(rng.randrange(1, max(2, num_pods // 16))))
        seqs.append(list(cur))
    return seqs, catalog


def ring_state_violations(loop, catalog=None) -> list[str]:
    """Device state == host mirror == oracle replay, word-for-word."""
    snap = loop.snapshot_state()
    if snap is None:
        return []
    out: list[str] = []
    if catalog is not None:
        gen = (catalog.uid, catalog.generation,
               catalog.availability_generation)
        if snap["generation"] != gen:
            return [f"serving state generation {snap['generation']} != "
                    f"catalog generation {gen} (missed invalidation)"]
    mirror, device = snap["mirror"], snap["device"]
    if mirror.size != device.size:
        return [f"serving mirror size {mirror.size} != device state size "
                f"{device.size}"]
    diff = int(np.count_nonzero(mirror != device))
    if diff:
        out.append(f"serving host mirror diverged from device state "
                   f"({diff} words differ)")
    oracle = snap["oracle"]
    if oracle is None:
        out.append("serving oracle replay is cold while the ring is warm "
                   "— an admitted slot bypassed the replay")
    else:
        d = loop.oracle.diverges(device)
        if d:
            out.append(f"ring oracle replay diverged from device state "
                       f"({d} words differ after slot {snap['seq']})")
    return out


def raw_parity_violations(seeds: int = 8, num_pods: int = 48,
                          num_types: int = 6,
                          windows: int = 4) -> list[str]:
    """Word-level churn differential: ring-fed ``serve_window`` chain vs
    per-window classic ``solve_packed`` of the same freshly packed
    buffer — raw packed RESULT words and the carried state, both
    bit-identical, every window, every seed."""
    import jax

    from karpenter_tpu.resident.delta import DELTA_BUCKETS, pad_delta
    from karpenter_tpu.serving.kernels import serve_window
    from karpenter_tpu.serving.oracle import apply_ring_np
    from karpenter_tpu.solver import JaxSolver, encode
    from karpenter_tpu.solver.jax_backend import solve_packed
    from karpenter_tpu.solver.types import SolverOptions

    out: list[str] = []
    solver = JaxSolver(SolverOptions(backend="jax"))
    for seed in range(seeds):
        seqs, catalog = _churn_stream(num_pods, num_types, windows, seed)
        mirror = None
        state = None
        for w, pods in enumerate(seqs):
            prep = solver._prepare(encode(pods, catalog))
            flat = prep.packed.reshape(-1)
            G, O, U, N = prep.G_pad, prep.O_pad, prep.U_pad, prep.N
            rs = solver.options.right_size
            off_alloc, off_price, off_rank = solver._device_offerings(
                catalog, O)
            if mirror is None or mirror.shape != flat.shape:
                mirror = flat.copy()
                state = jax.device_put(flat)
                idx = np.empty(0, dtype=np.int64)
            else:
                idx = np.nonzero(mirror != flat)[0]
                mirror[idx] = flat[idx]
            didx, dval = pad_delta(idx, flat[idx], flat.size, DELTA_BUCKETS)
            state, ring_res = serve_window(
                state, jax.device_put(didx), jax.device_put(dval),
                off_alloc, off_price, off_rank, G=G, O=O, U=U, N=N,
                right_size=rs, compact=prep.K, dense16=prep.dense16,
                coo16=prep.coo16)
            classic_res = solve_packed(
                jax.device_put(flat), off_alloc, off_price, off_rank,
                G=G, O=O, U=U, N=N, right_size=rs, compact=prep.K,
                dense16=prep.dense16, coo16=prep.coo16)
            ring_np = np.asarray(ring_res)
            classic_np = np.asarray(classic_res)
            if not np.array_equal(ring_np, classic_np):
                d = int(np.count_nonzero(ring_np != classic_np))
                out.append(f"seed {seed} window {w}: ring-fed result "
                           f"differs from classic solve_packed "
                           f"({d} of {classic_np.size} words)")
            state_np = np.asarray(state)
            expect = apply_ring_np(mirror, didx, dval)
            if not np.array_equal(state_np, expect):
                d = int(np.count_nonzero(state_np != expect))
                out.append(f"seed {seed} window {w}: carried serving "
                           f"state diverged from the host re-pack "
                           f"({d} words)")
    return out


def _plan_key(plan):
    return ([(n.instance_type, n.zone, n.capacity_type,
              tuple(n.pod_names)) for n in plan.nodes],
            tuple(plan.unplaced_pods),
            round(plan.total_cost_per_hour, 9))


def plan_parity_violations(seeds: int = 8, num_pods: int = 48,
                           num_types: int = 6,
                           windows: int = 4) -> list[str]:
    """Decoded-plan churn differential: a serving-enabled solver's
    ``serve_stream`` vs a classic solver, identical window streams."""
    from karpenter_tpu.solver import JaxSolver, encode
    from karpenter_tpu.solver.types import SolverOptions

    out: list[str] = []
    for seed in range(seeds):
        seqs, catalog = _churn_stream(num_pods, num_types, windows, seed)
        on = JaxSolver(SolverOptions(backend="jax", serving="on"))
        off = JaxSolver(SolverOptions(backend="jax", serving="off"))
        problems = [encode(pods, catalog) for pods in seqs]
        served = list(on.serving.serve(iter(problems), depth=2))
        for w, (plan, problem) in enumerate(zip(served, problems)):
            classic = off.solve_encoded(problem)
            if _plan_key(plan) != _plan_key(classic):
                out.append(f"seed {seed} window {w}: serving plan "
                           f"differs from classic plan "
                           f"(mode history {on.serving.last_mode!r})")
        if on.serving.ring_windows == 0:
            out.append(f"seed {seed}: no window ever rode the ring — "
                       f"the differential exercised nothing")
        out.extend(ring_state_violations(on.serving, catalog))
    return out


def sharded_parity_violations(seeds: int = 4, num_pods: int = 64,
                              num_types: int = 6, windows: int = 3,
                              num_shards: int = 2) -> list[str]:
    """2-shard churn differential: deferred-fetch serving windows vs
    the same service class solving synchronously."""
    from karpenter_tpu.serving.service import ShardedServingLoop
    from karpenter_tpu.sharded.service import ShardedSolveService

    out: list[str] = []
    for seed in range(seeds):
        seqs, catalog = _churn_stream(num_pods, num_types, windows,
                                      1000 + seed)
        serving_svc = ShardedSolveService(num_shards=num_shards)
        classic_svc = ShardedSolveService(num_shards=num_shards)
        loop = ShardedServingLoop(serving_svc, capacity=2)
        handles = [loop.submit(catalog, pods=pods) for pods in seqs]
        plans = [h.result() for h in handles]
        for w, (pods, plan) in enumerate(zip(seqs, plans)):
            classic = classic_svc.solve_window(catalog, pods=pods)
            if _plan_key(plan.merged()) != _plan_key(classic.merged()):
                out.append(f"seed {seed} window {w}: 2-shard serving "
                           f"plan differs from synchronous solve")
    return out


def validate(seeds: int = 8) -> list[str]:
    """The full independent check (bench + CI entry point)."""
    out = raw_parity_violations(seeds=seeds)
    out.extend(plan_parity_violations(seeds=seeds))
    out.extend(sharded_parity_violations(seeds=max(2, seeds // 4)))
    return out


__all__ = ["ring_state_violations", "raw_parity_violations",
           "plan_parity_violations", "sharded_parity_violations",
           "validate"]
