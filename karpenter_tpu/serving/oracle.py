"""Numpy twins of the serving-loop kernels + the host ring replay.

The parity registry (tools/graftlint/pairs.py ``PairSpec("serving")``)
pins these to karpenter_tpu/serving/kernels.py: ``apply_ring_np`` must
implement the exact drop-scatter semantics of ``apply_ring`` and
``serve_window_np`` the exact slot-apply-then-solve decomposition of
``serve_window`` — same ``DELTA_BUCKETS`` wire format (shared from
karpenter_tpu/resident/delta.py, GL203), no re-derived literals.

``RingOracle`` is the host-side replay the ring-converges invariant
and the drain path compare against: feed it every ADMITTED slot in
sequence order and its mirror must equal the device state
word-for-word (and equal a fresh ClusterState re-encode — the chaos
``ring-converges`` check closes that triangle).
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.resident.delta import DELTA_BUCKETS


def apply_ring_np(state: np.ndarray, didx: np.ndarray,
                  dval: np.ndarray) -> np.ndarray:
    """Host twin of ``kernels.apply_ring``: scatter one padded ring
    slot into a copy of ``state``.  Padding entries carry an
    out-of-range index (one past the buffer end — the
    ``pad_delta`` drop_index convention) and are dropped, exactly the
    device ``mode="drop"`` semantics."""
    flat = np.asarray(state, dtype=np.int32).reshape(-1).copy()
    didx = np.asarray(didx, dtype=np.int64).reshape(-1)
    dval = np.asarray(dval, dtype=np.int32).reshape(-1)
    live = (didx >= 0) & (didx < flat.size)
    flat[didx[live]] = dval[live]
    return flat.reshape(np.asarray(state).shape)


def serve_window_np(state: np.ndarray, didx: np.ndarray,
                    dval: np.ndarray, solve_fn):
    """Host twin of ``kernels.serve_window``: one loop iteration is
    slot-apply THEN single-shot solve of the updated state — nothing
    else.  ``solve_fn`` is the classic packed solve of the caller's
    choosing (the validator passes the device ``solve_packed`` wrapper
    so word-level parity is literally ring-apply + classic solve).
    Returns ``(new_state, solve_fn(new_state))``."""
    new_state = apply_ring_np(state, didx, dval)
    return new_state, solve_fn(new_state)


class RingOracle:
    """Replay of every admitted ring slot, in sequence order.

    The oracle never sees backpressured/classic-fallback windows (they
    bypass the ring by definition) and never sees a slot twice — the
    ``seq`` monotonicity assert is the "exactly once" half of the
    no-window-lost-serving invariant, host-side."""

    __slots__ = ("mirror", "applied", "last_seq")

    def __init__(self):
        self.mirror: np.ndarray | None = None
        self.applied = 0
        self.last_seq = -1

    def reset(self) -> None:
        self.mirror = None
        self.applied = 0
        self.last_seq = -1

    def rebuild(self, seq: int, flat: np.ndarray) -> None:
        """A rebuild slot replaces the whole mirror (cold start,
        generation/shape bump, delta_too_large — the resident
        ladder)."""
        assert seq > self.last_seq, \
            f"ring slot {seq} replayed out of order (last {self.last_seq})"
        self.mirror = np.asarray(flat, dtype=np.int32).reshape(-1).copy()
        self.applied += 1
        self.last_seq = seq

    def apply(self, seq: int, didx: np.ndarray, dval: np.ndarray) -> None:
        assert self.mirror is not None, \
            "ring oracle saw a delta slot before any rebuild"
        assert seq > self.last_seq, \
            f"ring slot {seq} replayed out of order (last {self.last_seq})"
        assert np.asarray(didx).size in DELTA_BUCKETS, \
            f"ring slot {seq} width {np.asarray(didx).size} is not a " \
            f"DELTA_BUCKETS rung — off-wire-format payload"
        self.mirror = apply_ring_np(self.mirror, didx, dval)
        self.applied += 1
        self.last_seq = seq

    def diverges(self, device_state: np.ndarray) -> int:
        """Word diff count between the replayed mirror and a drained
        device state (0 = converged)."""
        if self.mirror is None:
            return -1
        dev = np.asarray(device_state, dtype=np.int32).reshape(-1)
        if dev.size != self.mirror.size:
            return max(dev.size, self.mirror.size)
        return int(np.count_nonzero(dev != self.mirror))


__all__ = ["DELTA_BUCKETS", "apply_ring_np", "serve_window_np",
           "RingOracle"]
