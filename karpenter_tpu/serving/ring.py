"""The serving rings: sequence-numbered slots, host-visible head/tail.

Two fixed-capacity rings make the serving loop's flow control explicit
instead of implicit in JAX's async dispatch queue:

- ``InputRing`` — admitted window deltas on their way to the device.
  ``push`` uploads the padded ``(word index, word value)`` pair (the
  resident ``DELTA_BUCKETS`` wire format, unchanged) at admission time
  — the H2D stream IS the ring fill — and returns the slot's sequence
  number, or ``None`` when the ring is full: the caller's explicit
  backpressure signal (ServingLoop falls back to classic per-window
  dispatch; the window is never dropped).
- ``OutputRing`` — in-flight packed results between kick and fetch.
  ``push`` starts the async D2H copy immediately, so by the time the
  consumer fetches slot N the NEXT window's compute has been kicked
  and the copy overlapped it (the double-buffer contract
  ``overlap_fraction`` measures).

Both rings index a fixed slot list by ``seq % capacity`` with
monotonic ``head`` (next unconsumed) / ``tail`` (next assigned)
counters — wrap-around is arithmetic, never reallocation, so a
long-running serving loop touches no allocator on the hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from karpenter_tpu.serving import RING_SLOTS


@dataclass(slots=True)
class InputSlot:
    """One admitted window delta: device-side padded pair + the host
    bookkeeping the kick and the replay oracle need."""

    seq: int
    mode: str                    # "delta" | "hit" | "rebuild"
    didx: Any                    # device int32 [D]
    dval: Any                    # device int32 [D]
    # host copies for the ring oracle replay (ring-converges) — the
    # exact words the device scatter will apply, kept verbatim
    host_didx: np.ndarray = field(default=None, repr=False)
    host_dval: np.ndarray = field(default=None, repr=False)
    words: int = 0               # live (unpadded) delta words
    h2d_bytes: int = 0
    reason: str = ""             # rebuild reason ("" for delta/hit)
    ctx: Any = None              # (problem, prep) carried to the kick


@dataclass(slots=True)
class OutputSlot:
    """One in-flight result: kicked, async-copying, not yet fetched."""

    seq: int
    dev: Any                     # device int32 packed result
    prep: Any                    # the _Prepared the decode chain needs
    problem: Any
    mode: str
    t_disp: float = 0.0
    t_issued: float = 0.0
    kick_seq: int = 0            # loop kick counter at creation time
    done: bool = False


class _Ring:
    """Shared fixed-capacity machinery: monotonic head/tail, slot list
    indexed ``seq % capacity``."""

    __slots__ = ("capacity", "head", "tail", "_slots")

    def __init__(self, capacity: int = RING_SLOTS):
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.head = 0            # next seq to consume
        self.tail = 0            # next seq to assign
        self._slots: list = [None] * capacity

    @property
    def occupancy(self) -> int:
        return self.tail - self.head

    @property
    def full(self) -> bool:
        return self.occupancy >= self.capacity

    def __len__(self) -> int:
        return self.occupancy

    def _store(self, slot) -> int:
        seq = self.tail
        self._slots[seq % self.capacity] = slot
        self.tail = seq + 1
        return seq

    def clear(self) -> list:
        """Drop every unconsumed slot (fault-drain path); returns them
        oldest-first so the caller can account for each one."""
        out = [self._slots[s % self.capacity]
               for s in range(self.head, self.tail)]
        for i in range(self.capacity):
            self._slots[i] = None
        self.head = self.tail
        return out


class InputRing(_Ring):
    def push(self, mode: str, didx: np.ndarray, dval: np.ndarray, *,
             words: int = 0, h2d_bytes: int = 0,
             reason: str = "") -> int | None:
        """Admit one window delta: upload the padded pair and take a
        slot.  Returns the slot's sequence number, or None when the
        ring is full — the caller's backpressure signal (nothing is
        uploaded on a refused push)."""
        if self.full:
            return None
        import jax

        host_didx = np.asarray(didx, dtype=np.int32)
        host_dval = np.asarray(dval, dtype=np.int32)
        slot = InputSlot(
            seq=self.tail, mode=mode,
            didx=jax.device_put(host_didx), dval=jax.device_put(host_dval),
            host_didx=host_didx, host_dval=host_dval,
            words=words, h2d_bytes=h2d_bytes, reason=reason)
        return self._store(slot)

    def pop(self) -> InputSlot | None:
        """Consume the oldest admitted slot (the kick path)."""
        if self.occupancy == 0:
            return None
        slot = self._slots[self.head % self.capacity]
        self._slots[self.head % self.capacity] = None
        self.head += 1
        return slot


class OutputRing(_Ring):
    def push(self, slot: OutputSlot) -> int | None:
        """Park one kicked result; starts its async D2H copy so the
        transfer overlaps the next window's compute.  None when full
        (the kick path must check BEFORE dispatching)."""
        if self.full:
            return None
        try:
            slot.dev.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass                 # host arrays / backends without async copy
        slot.seq = self.tail
        return self._store(slot)

    def take(self, seq: int) -> OutputSlot | None:
        """Claim slot ``seq`` for fetching (out-of-order safe: head
        advances over the contiguous fetched prefix)."""
        if not (self.head <= seq < self.tail):
            return None
        slot = self._slots[seq % self.capacity]
        if slot is None or slot.done:
            return None
        slot.done = True
        while self.head < self.tail:
            s = self._slots[self.head % self.capacity]
            if s is None or s.done:
                self._slots[self.head % self.capacity] = None
                self.head += 1
            else:
                break
        return slot

    def pending(self) -> list[OutputSlot]:
        """Unfetched slots oldest-first (the drain path)."""
        out = []
        for s in range(self.head, self.tail):
            slot = self._slots[s % self.capacity]
            if slot is not None and not slot.done:
                out.append(slot)
        return out


__all__ = ["RING_SLOTS", "InputSlot", "OutputSlot", "InputRing",
           "OutputRing"]
