"""The serving loop: persistent device-resident solve service.

``ServingLoop`` inverts the per-window dispatch control flow: the
packed problem state LIVES on device (one donated buffer, the resident
plane's mirror discipline) and each submitted window only streams its
padded word delta through the input ring.  One loop iteration is ONE
dispatch (``serving/kernels.serve_window``: ring-slot apply +
``solve_core`` + ``_pack_result_telemetry``), and the result parks in
the output ring whose async D2H overlaps the NEXT window's compute —
the consumer fetches with lag, so the tunnel round trip the single-shot
path serializes on is paid concurrently with useful work.

The fallback ladder (every rung falls to the next, no window is ever
lost or solved twice):

1. ring (hit / delta / rebuild) — eligible steady-state windows;
2. classic — ineligible windows (preference/stochastic/affinity/flat/
   empty) and BACKPRESSURED windows (either ring full): the unchanged
   ``solve_encoded_async`` path, mirror untouched so the next admitted
   delta re-absorbs the skipped churn;
3. host failover — a ``DeviceFaultError`` at kick or fetch invalidates
   the ring state (generation-tracked, the resident contract) and
   re-solves the window classically, which carries its own faulttol
   ladder down to the host oracle.

Every kick and fetch runs inside ``device_guard`` (prof sites
``serving-kick`` / ``serving-fetch``); parity with the classic path is
bit-level and independently checked (serving/validate.py).
"""

from __future__ import annotations

from collections import deque

import numpy as np

from karpenter_tpu import obs
from karpenter_tpu.faulttol import DeviceFaultError, device_guard
from karpenter_tpu.obs.devtel import get_devtel
from karpenter_tpu.obs.prof import get_profiler
from karpenter_tpu.resident.delta import DELTA_BUCKETS, pad_delta
from karpenter_tpu.resident.store import ResidentBuffer, plan_update
from karpenter_tpu.serving import RING_SLOTS
from karpenter_tpu.serving.oracle import RingOracle
from karpenter_tpu.serving.ring import InputRing, OutputRing, OutputSlot
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("serving.service")


class ServingPending:
    """Deferred handle for one ring-fed window: ``result()`` claims the
    output-ring slot, fetches, and rides the classic decode chain."""

    __slots__ = ("_loop", "_seq", "_done")

    def __init__(self, loop: "ServingLoop", seq: int):
        self._loop = loop
        self._seq = seq
        self._done = None

    def result(self):
        if self._done is None:
            self._done = self._loop._finish(self._seq)
        return self._done


class ServingLoop:
    """The solver-side serving service (one per JaxSolver when
    ``serving_enabled``)."""

    def __init__(self, solver, capacity: int = RING_SLOTS):
        self.solver = solver
        self.capacity = capacity
        self.input = InputRing(capacity)
        self.output = OutputRing(capacity)
        # the ring state IS a ResidentBuffer: same mirror discipline,
        # same generation-tracked invalidation, same plan_update ladder
        self.buf = ResidentBuffer("serving")
        self.oracle = RingOracle()
        self.windows = 0             # everything submitted
        self.ring_windows = 0        # admitted to the ring
        self.classic_windows = 0     # ineligible -> classic dispatch
        self.backpressured = 0       # ring full -> classic dispatch
        self.host_failovers = 0      # fault -> classic re-solve
        self.rebuilds = 0
        self.invalidations = 0
        self.kicks = 0               # dispatch counter (overlap clock)
        self.fetched = 0
        self.overlapped = 0          # fetched after a later kick issued
        self.last_mode = ""
        self.last_reason = ""

    # -- state ---------------------------------------------------------------

    def invalidate(self, reason: str = "") -> None:
        """Generation-tracked invalidation: the device state and mirror
        die together; in-flight OUTPUT slots stay fetchable (their
        windows were solved against then-valid state)."""
        self.buf.invalidate(reason)
        self.oracle.reset()
        self.input.clear()
        self.invalidations += 1
        self.last_reason = reason

    def track_generation(self, catalog) -> None:
        """Transparent catalog-bump invalidation for idle/classic
        stretches: a warm ring whose generation stamp no longer matches
        the catalog dies NOW, not at the next admit — the ring either
        serves from current state or holds none at all (eligible
        submits get the same treatment for free via plan_update)."""
        if self.buf.dev is None or self.buf.generation is None:
            return
        gen = (catalog.uid, catalog.generation,
               catalog.availability_generation)
        if self.buf.generation != gen:
            self.invalidate("generation")

    @property
    def overlap_fraction(self) -> float:
        return self.overlapped / self.fetched if self.fetched else 0.0

    def stats(self) -> dict:
        return {
            "windows": self.windows,
            "ring_windows": self.ring_windows,
            "classic_windows": self.classic_windows,
            "backpressured": self.backpressured,
            "host_failovers": self.host_failovers,
            "rebuilds": self.rebuilds,
            "invalidations": self.invalidations,
            "kicks": self.kicks,
            "fetched": self.fetched,
            "overlapped": self.overlapped,
            "overlap_fraction": self.overlap_fraction,
            "input_occupancy": self.input.occupancy,
            "output_occupancy": self.output.occupancy,
            "capacity": self.capacity,
            "last_mode": self.last_mode,
            "last_reason": self.last_reason,
        }

    def snapshot_state(self) -> dict | None:
        """Mirror + fetched device state + oracle replay — the
        ring-converges invariant's raw material.  None when cold."""
        if self.buf.mirror is None or self.buf.dev is None:
            return None
        return {
            "generation": self.buf.generation,
            "mirror": self.buf.mirror.copy(),
            "device": np.asarray(self.buf.dev).reshape(-1),
            "oracle": None if self.oracle.mirror is None
            else self.oracle.mirror.copy(),
            "seq": self.oracle.last_seq,
        }

    # -- submission ----------------------------------------------------------

    def submit(self, problem):
        """Route one window: ring when eligible and there is room,
        classic otherwise.  Returns a handle with ``result()``."""
        self.windows += 1
        if not self._eligible(problem):
            return self._classic(problem, "classic")
        prep = self.solver._prepare(problem)
        if prep.sto is not None or prep.aff is not None \
                or prep.pref_rows is not None \
                or not isinstance(prep.packed, np.ndarray):
            return self._classic(problem, "classic")
        if self.output.full or self.input.full:
            # explicit backpressure: the window falls back to classic
            # dispatch UNTOUCHED (mirror unchanged — the next admitted
            # delta re-absorbs this window's churn)
            self.backpressured += 1
            metrics.SERVING_BACKPRESSURE.inc()
            return self._classic(problem, "backpressure")
        return self._admit(problem, prep)

    def serve(self, problems, depth: int = 2):
        """Depth-bounded streaming iterator: yields Plans in submit
        order while keeping ``depth`` windows in flight, so every
        fetch overlaps a later window's compute."""
        pending = deque()
        for problem in problems:
            pending.append(self.submit(problem))
            while len(pending) >= max(1, depth):
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()

    def drain(self) -> dict:
        """Fetch every in-flight output slot (shutdown / fault ladder).
        Returns {seq: Plan}."""
        return {slot.seq: self._finish(slot.seq)
                for slot in self.output.pending()}

    # -- internals -----------------------------------------------------------

    def _eligible(self, problem) -> bool:
        from karpenter_tpu.solver.flat import flat_viable

        return (problem.num_groups > 0 and problem.pref_rows is None
                and problem.group_var is None
                and getattr(problem, "aff", None) is None
                and not flat_viable(problem, self.solver.options))

    def _classic(self, problem, mode: str):
        self.classic_windows += 1
        self.last_mode = mode
        metrics.SERVING_WINDOWS.labels(mode).inc()
        return self.solver.solve_encoded_async(problem)

    def _admit(self, problem, prep):
        """Delta-encode against the mirror, admit to the input ring,
        kick.  plan_update is THE shared ladder (resident/store): a
        reason means rebuild; empty idx means hit."""
        import jax

        catalog = prep.catalog
        generation = (catalog.uid, catalog.generation,
                      catalog.availability_generation)
        flat = prep.packed.reshape(-1)
        reason, idx = plan_update(self.buf, flat, generation)
        if reason:
            mode, words = "rebuild", int(flat.size)
            h2d = int(flat.nbytes)
            didx, dval = pad_delta(np.empty(0, dtype=np.int64),
                                   np.empty(0, dtype=np.int32),
                                   flat.size, DELTA_BUCKETS)
            self.buf.dev = jax.device_put(flat)
            self.buf.mirror = flat.copy()
            self.buf.generation = generation
            self.buf.pending_reason = ""
            self.buf.stats["rebuild"] += 1
            self.rebuilds += 1
            self.last_reason = reason
        elif idx.size == 0:
            mode, words, h2d = "hit", 0, 0
            didx, dval = pad_delta(idx, flat[idx], flat.size, DELTA_BUCKETS)
            self.buf.stats["hit"] += 1
        else:
            didx, dval = pad_delta(idx, flat[idx], flat.size, DELTA_BUCKETS)
            mode, words = "delta", int(idx.size)
            h2d = int(didx.nbytes + dval.nbytes)
            self.buf.mirror[idx] = flat[idx]
            self.buf.stats["delta"] += 1
        seq = self.input.push(mode, didx, dval, words=words,
                              h2d_bytes=h2d, reason=reason)
        assert seq is not None     # full rings were refused in submit
        slot = self.input._slots[seq % self.input.capacity]
        slot.ctx = (problem, prep)
        if mode == "rebuild":
            self.oracle.rebuild(seq, flat)
        else:
            self.oracle.apply(seq, slot.host_didx, slot.host_dval)
        self.ring_windows += 1
        self.last_mode = mode
        metrics.SERVING_WINDOWS.labels(mode).inc()
        try:
            return self._kick()
        except DeviceFaultError as e:
            # the donated state (and anything the ring held) can no
            # longer be trusted: drain bookkeeping, fail the window
            # over — classic dispatch carries its own faulttol ladder
            # down to the host oracle.  The window is never lost.
            self.invalidate(f"device_fault:{e.kind}")
            self.host_failovers += 1
            metrics.SERVING_WINDOWS.labels("host_failover").inc()
            log.warning("serving kick faulted; host failover engaged",
                        kind=e.kind, seq=seq)
            return self.solver.solve_encoded_async(problem)
        except Exception as e:  # noqa: BLE001 — degrade, never fail
            self.invalidate("dispatch_error")
            metrics.ERRORS.labels("solver", "serving_fallback").inc()
            log.warning("serving kick failed; classic fallback engaged",
                        error=str(e)[:300])
            return self.solver.solve_encoded_async(problem)

    def _kick(self) -> ServingPending:
        """Consume the oldest input slot, dispatch one fused loop
        iteration, park the result in the output ring."""
        from karpenter_tpu.serving.kernels import serve_window
        from karpenter_tpu.solver.jax_backend import clamp_output_opts

        slot = self.input.pop()
        problem, prep = slot.ctx
        G, O, U, N = prep.G_pad, prep.O_pad, prep.U_pad, prep.N
        prep.K, prep.dense16, prep.coo16 = clamp_output_opts(
            prep.K0, prep.dense16_ok, G, N)
        rs = self.solver.options.right_size if prep.right_size is None \
            else prep.right_size
        off_alloc, off_price, off_rank = self.solver._device_offerings(
            prep.catalog, O)
        get_devtel().note_dispatch(
            "serving-kick",
            (G, O, U, N, prep.K, prep.dense16, prep.coo16, rs,
             slot.mode == "rebuild"),
            h2d_bytes=slot.h2d_bytes, donated=slot.mode != "rebuild")
        t0 = obs.now()
        state, self.buf.dev = self.buf.dev, None    # donated
        with device_guard("serving-kick"):
            with get_profiler().sampled("serving-kick") as probe:
                new_state, out_dev = serve_window(
                    state, slot.didx, slot.dval,
                    off_alloc, off_price, off_rank,
                    G=G, O=O, U=U, N=N, right_size=rs,
                    compact=prep.K, dense16=prep.dense16,
                    coo16=prep.coo16)
                # fetch=False: the new state stays device-resident and
                # the RESULT's D2H is the overlapped serving-fetch
                # site's to account, not the kick's
                probe.dispatched(new_state, fetch=False)
        self.buf.dev = new_state
        oseq = self.output.push(OutputSlot(
            seq=slot.seq, dev=out_dev, prep=prep, problem=problem,
            mode=slot.mode, t_disp=t0, t_issued=obs.now(),
            kick_seq=self.kicks))
        assert oseq is not None    # full rings were refused in submit
        self.kicks += 1
        metrics.SERVING_RING_OCCUPANCY.set(float(self.output.occupancy))
        obs.instant("serving.kick", seq=slot.seq, mode=slot.mode,
                    words=slot.words, h2d_bytes=slot.h2d_bytes)
        return ServingPending(self, oseq)

    def _finish(self, seq: int):
        """Claim + fetch one output slot and decode through the classic
        chain (COO growth / node escalation re-dispatch classically —
        exactly what the single-shot path would have done)."""
        from karpenter_tpu.solver.jax_backend import PendingSolve

        slot = self.output.take(seq)
        if slot is None:
            raise KeyError(f"serving output slot {seq} already fetched "
                           f"or out of window")
        self.fetched += 1
        if self.kicks > slot.kick_seq + 1:
            # a later window's kick was issued before this fetch began:
            # its compute overlapped this slot's D2H
            self.overlapped += 1
        metrics.SERVING_OVERLAP.set(self.overlap_fraction)
        t0 = obs.now()
        try:
            with device_guard("serving-fetch") as guard:
                with get_profiler().sampled("serving-fetch") as probe:
                    probe.dispatched(slot.dev)
                out_np = guard.fetch(slot.dev)
        except DeviceFaultError as e:
            self.invalidate(f"device_fault:{e.kind}")
            self.host_failovers += 1
            metrics.SERVING_WINDOWS.labels("host_failover").inc()
            log.warning("serving fetch faulted; host failover engaged",
                        kind=e.kind, seq=seq)
            return self.solver.solve_encoded_async(slot.problem).result()
        metrics.SERVING_RING_OCCUPANCY.set(float(self.output.occupancy))
        obs.record("serving.fetch", t0, obs.now(), seq=slot.seq,
                   mode=slot.mode)
        pend = PendingSolve(self.solver, slot.problem, prep=slot.prep,
                            dev=out_np, path="serving",
                            t_disp=slot.t_disp, t_issued=slot.t_issued)
        return pend.result()


class ShardedServingPending:
    """Deferred handle for one sharded serving window."""

    __slots__ = ("_loop", "_kick", "_done")

    def __init__(self, loop: "ShardedServingLoop", kick, done=None):
        self._loop = loop
        self._kick = kick
        self._done = done

    def result(self):
        if self._done is None:
            self._done = self._loop._finish(self._kick)
        return self._done


class ShardedServingLoop:
    """Per-shard rings under the one ``jit(shard_map)`` window: kicks
    ride :meth:`ShardedSolveService._kick_window` (the stacked state
    advances at dispatch), fetches are deferred so window t's D2H
    overlaps window t+1's compute.  A fault at either phase fails the
    window over to the host oracle — never lost."""

    def __init__(self, service, capacity: int = 2):
        self.service = service
        self.capacity = max(1, capacity)
        self._inflight: deque = deque()
        self.windows = 0
        self.kicks = 0
        self.fetched = 0
        self.overlapped = 0
        self.host_failovers = 0

    @property
    def overlap_fraction(self) -> float:
        return self.overlapped / self.fetched if self.fetched else 0.0

    def submit(self, catalog, nodepool=None, pods=None):
        """Kick one sharded window; returns a deferred handle.  When
        ``capacity`` windows are already in flight the oldest is
        fetched first (bounded ring, implicit drain)."""
        from karpenter_tpu.sharded.types import ShardedPlan

        while len(self._inflight) >= self.capacity:
            self._inflight.popleft().result()
        self.windows += 1
        try:
            kick = self.service._kick_window(catalog, nodepool, pods)
        except DeviceFaultError:
            self.host_failovers += 1
            metrics.SERVING_WINDOWS.labels("host_failover").inc()
            plan = self.service.solve_window_host(catalog, nodepool, pods)
            return ShardedServingPending(self, None, done=plan)
        if isinstance(kick, ShardedPlan):
            # host-routed (pref/sto/aff) window: already decoded
            metrics.SERVING_WINDOWS.labels("classic").inc()
            return ShardedServingPending(self, None, done=kick)
        self.kicks += 1
        kick_seq = self.kicks
        pend = ShardedServingPending(self, (kick, kick_seq))
        self._inflight.append(pend)
        metrics.SERVING_WINDOWS.labels("delta" if kick.delta.mode == "delta"
                                       else kick.delta.mode).inc()
        metrics.SERVING_RING_OCCUPANCY.set(float(len(self._inflight)))
        return pend

    def drain(self):
        """Fetch everything still in flight (shutdown / end of stream)."""
        out = []
        while self._inflight:
            out.append(self._inflight.popleft().result())
        return out

    def _finish(self, kick_ctx):
        kick, kick_seq = kick_ctx
        try:
            self._inflight.remove(
                next(p for p in self._inflight if p._kick is kick_ctx))
        except StopIteration:
            pass
        self.fetched += 1
        if self.kicks > kick_seq:
            self.overlapped += 1
        metrics.SERVING_OVERLAP.set(self.overlap_fraction)
        try:
            plan = self.service._fetch_window(kick)
        except DeviceFaultError:
            self.host_failovers += 1
            metrics.SERVING_WINDOWS.labels("host_failover").inc()
            # re-solve the SAME window through the host oracle: the
            # routed/encoded window rides along, so ownership and
            # shard membership are identical — no window lost
            plan = self.service.solve_window_host(
                kick.catalog, kick.nodepool, window=kick.window)
        metrics.SERVING_RING_OCCUPANCY.set(float(len(self._inflight)))
        return plan

    def stats(self) -> dict:
        return {
            "windows": self.windows,
            "kicks": self.kicks,
            "fetched": self.fetched,
            "overlapped": self.overlapped,
            "overlap_fraction": self.overlap_fraction,
            "host_failovers": self.host_failovers,
            "inflight": len(self._inflight),
            "capacity": self.capacity,
        }


def serving_loop_of(solver):
    """The solver's attached ServingLoop, or None (the
    ``resident_store_of`` convention)."""
    return getattr(solver, "serving", None)


__all__ = ["ServingLoop", "ServingPending", "ShardedServingLoop",
           "ShardedServingPending", "serving_loop_of"]
