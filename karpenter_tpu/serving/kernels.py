"""Donated device kernels for the serving loop.

One loop iteration of the persistent solve service is ONE dispatch:
``serve_window`` fuses ring-slot delta-apply + ``solve_core`` +
``_pack_result_telemetry`` with the state buffer DONATED (graftlint
GL006: the transient state input must alias the output, never double
the device footprint).  The body is the ``solve_resident`` body — the
serving plane adds the ring *around* the kernel, never inside it — so
a ring-fed window on a bit-identical buffer is bit-identical to the
classic single-shot ``solve_packed`` path (the parity contract
docs/design/serving.md pins; karpenter_tpu/serving/validate.py is the
independent 8-seed check).

``apply_ring`` is the standalone scatter: the drain path uses it to
land already-admitted ring slots into device state without a solve
(the fault ladder's "every admitted delta lands exactly once" half).

The catalog tensors (off_alloc / off_price / off_rank) are the
device-RESIDENT cache JaxSolver keys by generation — they are never
donated (GL006's explicit carve-out).
"""

from __future__ import annotations

import functools

import jax

from karpenter_tpu.resident.delta import DELTA_BUCKETS
from karpenter_tpu.solver.jax_backend import (
    _pack_result_telemetry, _unpack_problem, solve_core,
)


@functools.partial(jax.jit, donate_argnames=("state",))
def apply_ring(state, didx, dval):
    """Scatter one admitted ring slot into the serving state buffer:
    padding entries carry an out-of-range index and drop.  The old
    buffer is donated — the update aliases in place on device."""
    # trace-time wire-format check: a slot not padded to a
    # DELTA_BUCKETS rung would silently fragment the executable cache
    assert didx.shape[0] in DELTA_BUCKETS, \
        f"ring slot width {didx.shape[0]} is not a DELTA_BUCKETS rung"
    flat = state.reshape(-1).at[didx].set(dval, mode="drop")
    return flat.reshape(state.shape)


@functools.partial(jax.jit,
                   static_argnames=("G", "O", "U", "N", "right_size",
                                    "compact", "dense16", "coo16"),
                   donate_argnames=("state",))
def serve_window(state, didx, dval, off_alloc, off_price, off_rank, *,
                 G: int, O: int, U: int, N: int,
                 right_size: bool = True, compact: int = 0,
                 dense16: bool = False, coo16: bool = False):
    """One serving-loop iteration: ring-slot apply + packed solve in
    one dispatch.

    Args: ``state`` int32 [L] device-resident packed buffer (donated);
    ``didx``/``dval`` int32 [D] padded ring-slot word delta (the
    ``DELTA_BUCKETS`` wire format); catalog tensors as in
    ``solve_packed``.  Returns ``(new_state, packed_result)`` — the
    new state stays on device for the next slot, the result buffer
    streams out through the output ring (top-k COO compressed via the
    ``compact`` suffix, so the overlapped D2H moves kilobytes).
    """
    assert didx.shape[0] in DELTA_BUCKETS, \
        f"ring slot width {didx.shape[0]} is not a DELTA_BUCKETS rung"
    state = state.at[didx].set(dval, mode="drop")
    meta, compat_i, rows_g = _unpack_problem(state, off_alloc, G, O, U)
    node_off, assign, unplaced, cost = solve_core(
        meta[:, :4], meta[:, 4], meta[:, 5], compat_i > 0,
        off_alloc, off_price, off_rank, num_nodes=N,
        right_size=right_size)
    return state, _pack_result_telemetry(meta, rows_g, compat_i, node_off,
                                         assign, unplaced, cost, off_alloc,
                                         compact, dense16, coo16)
