"""Persistent device-resident solve service: the serving loop.

ROADMAP item 2, the RTT-floor kill: BENCH_r04 measured a single-shot
solve at 76.7 ms wall of which 70.3 ms is dispatch/exec/fetch round
trip and only 1.2 ms is compute — the ``speedup_20x`` gate never
flipped because every window paid the full host->device->host tunnel.
CvxCluster (PAPERS.md) wins 100-1000x only when the end-to-end serving
path keeps up with the kernel, and "Priority Matters" frames the
scheduler as a continuous constraint-solving service, not a per-event
call.  This package inverts the control flow accordingly: the solver
state LIVES on device and the host only streams deltas.

- :mod:`karpenter_tpu.serving.ring` — the input/output rings:
  sequence-numbered slots with host-visible head/tail counters and
  explicit backpressure when full.  The input ring carries the
  PR-8/PR-14 ``DELTA_BUCKETS`` padded ``(word index, word value)``
  pairs (the resident wire format, unchanged); the output ring holds
  in-flight packed results whose D2H fetch overlaps the NEXT window's
  compute (double buffering).
- :mod:`karpenter_tpu.serving.kernels` — the donated loop-iteration
  kernel: ``serve_window`` fuses delta-apply + ``solve_core`` +
  ``_pack_result_telemetry`` in ONE dispatch, exactly the
  ``solve_resident`` body, so a ring-fed window is bit-identical to a
  classic single-shot ``solve_packed`` on the same state.
- :mod:`karpenter_tpu.serving.oracle` — the numpy twins
  (``apply_ring_np`` / ``serve_window_np``) and ``RingOracle``, the
  host replay the ring-converges invariant and the drain path compare
  against word-for-word.
- :mod:`karpenter_tpu.serving.service` — ``ServingLoop`` (the
  solver-side service: eligibility gate, plan_update-driven
  delta/rebuild ladder, backpressure -> classic-dispatch fallback,
  device-fault drain + host failover) and ``ServingPending`` (the
  deferred fetch handle that rides the classic decode chain).
- :mod:`karpenter_tpu.serving.validate` — the independent validator:
  ring-fed vs classic ``solve_packed`` over an 8-seed churn stream,
  raw packed words AND decoded plans (the PR-14 parity contract).

Every ring kick runs inside ``device_guard`` (faulttol): a fault
drains the ring and fails over without losing a window.  Opt-in via
``KARPENTER_ENABLE_SERVING`` (the resident/preempt convention) or
``SolverOptions.serving="on"``.  Design: docs/design/serving.md.
"""

from __future__ import annotations

import os

# Default ring capacity (slots): bounds in-flight un-fetched windows.
# Deep enough that a fetch-lagged stream never backpressures at the
# bench's depth-2 pipelining, small enough that a stalled consumer
# surfaces as explicit backpressure instead of unbounded device memory.
RING_SLOTS = 8


def serving_enabled(options=None, env=None) -> bool:
    """The one gate every wiring point shares: SolverOptions.serving
    "on"/"off" wins; "auto" defers to KARPENTER_ENABLE_SERVING."""
    mode = getattr(options, "serving", "auto") if options is not None \
        else "auto"
    if mode == "on":
        return True
    if mode == "off":
        return False
    raw = (os.environ if env is None else env).get(
        "KARPENTER_ENABLE_SERVING", "")
    return raw.lower() in ("1", "true", "yes", "on")


__all__ = ["RING_SLOTS", "serving_enabled"]
