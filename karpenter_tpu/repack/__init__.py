"""Fleet-wide consolidation as a batched LP-relaxed solve.

The repack plane (docs/design/repack.md) follows the established
encode / batched-planner / greedy-parity / degraded layout the
preemption (``preempt/``) and gang (``gang/``) planes share:

- :mod:`karpenter_tpu.repack.encode` — live fleet -> dense migration
  tensors, consumed straight off the resident occupancy substrate
  (``ResidentStore.occupancy_tensors``) when available;
- :mod:`karpenter_tpu.repack.planner` — one batched scoring grid per
  round (jitted device kernel or numpy, integer-exact both ways) + the
  deterministic integral rounding;
- :mod:`karpenter_tpu.repack.greedy` — the scalar host-loop oracle the
  batched path is differentially tested against, and the degraded-mode
  fallback;
- :mod:`karpenter_tpu.repack.degraded` — ``ResilientRepacker`` + the
  cheap structural gate;
- ``validate_repack_plan`` (solver/validate.py) — the independent
  feasibility oracle the disruption controller runs before actuation.
"""

from karpenter_tpu.repack.encode import (
    RepackProblem, encode_repack, parked_gang_shapes,
)
from karpenter_tpu.repack.degraded import ResilientRepacker, repack_plan_defects
from karpenter_tpu.repack.greedy import GreedyRepacker
from karpenter_tpu.repack.planner import RepackPlanner
from karpenter_tpu.repack.types import (
    KIND_DEFRAG, KIND_DRAIN, Migration, ReopenedSlice, RepackOptions,
    RepackPlan,
)

__all__ = [
    "KIND_DEFRAG", "KIND_DRAIN", "GreedyRepacker", "Migration",
    "ReopenedSlice", "RepackOptions", "RepackPlan", "RepackPlanner",
    "RepackProblem", "ResilientRepacker", "encode_repack",
    "parked_gang_shapes", "repack_plan_defects",
]
