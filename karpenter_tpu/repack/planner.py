"""Batched fleet repack: one LP-relaxed scoring grid per round.

The canonical algorithm (shared bit-for-bit with ``repack/greedy.py``,
the pure-python parity path — differential tests assert identical
plans):

1. **Score grid** — every live node is evaluated AT ONCE as a
   migration-source candidate (the CvxCluster move: relax the integral
   bin-packing of "evacuate node s into the rest of the fleet" to its
   fractional feasibility, which vectorizes):

   - *drain* (``KIND_DRAIN``): every occupant is movable, the node's
     total demand fits the fleet's aggregate positive residual
     excluding itself (the LP relaxation), its largest pod fits SOME
     other node whole (the rounding relax), and no parked gang shape is
     currently open on it (an open slice belongs to the gang plane, not
     the shredder).  Score = the node's price (milli-$/h saved).
   - *defrag* (``KIND_DEFRAG``): the node's movable chip-consuming
     singletons can relocate, and vacating their chips reopens >= 1
     parked gang shape on the node's torus — evaluated as one batched
     AND over the ``[shapes, nodes, placements]`` bitmask grid
     (``gang/topology.py`` SliceTables).  Score = reopened x price
     (each reopened slice stands in for the accelerator node the gang
     would otherwise force-create).  Defrag outranks drain on the same
     node: the freed torus must stay alive for the parked gang.

2. **Rounding** — candidates commit in score-DESC (ties: node index
   ASC) order: each source's movable pods first-fit into targets in
   tightest-first order, with chip-aware placement (lowest free chips;
   a move may never close a parked shape's currently-open placement on
   its target).  A source that fails rounding is skipped (residuals
   only shrink, so retrying later cannot help); a node that received a
   migration is locked as a target (never drained in the same plan).

The grid optionally runs as a jitted device kernel consuming the
resident occupancy rows DIRECTLY (``ResidentStore.occupancy_tensors``
— the delta-maintained device tensor, no per-tick re-encode), int32,
bucket-padded so recompiles stay bounded, per-tick scratch inputs
donated (GL006), dispatch prof-sampled.  Arithmetic is integer-exact
on both paths, so the backend choice never changes the plan.
"""

from __future__ import annotations

import functools
import time
from functools import lru_cache

import numpy as np

from karpenter_tpu.gang.topology import split_mask_words
from karpenter_tpu.repack.encode import RepackProblem, lowest_free_chips
from karpenter_tpu.repack.types import (
    KIND_DEFRAG, KIND_DRAIN, Migration, ReopenedSlice, RepackOptions,
    RepackPlan,
)
from karpenter_tpu.solver.types import NODE_BUCKETS, bucket

# bucket rungs for the device grid (recompile bound): parked shapes and
# placements per shape; nodes ride the resident store's NODE_BUCKETS so
# the occupancy rows tensor is consumed as-is
_SHAPE_PAD = (1, 2, 4, 8)
_PLACE_PAD = (2, 4, 8, 16, 32, 64)
# below this pairwise-grid size the jit dispatch overhead beats the win
_DEVICE_MIN_CELLS = 4096
_I32_MAX = int(np.iinfo(np.int32).max)

_ROLE_FREE, _ROLE_SOURCE, _ROLE_TARGET = 0, 1, 2


@lru_cache(maxsize=1)
def _device_score_grid():
    """Jitted per-node scoring kernel, or None when jax is unusable."""
    try:
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, donate_argnames=(
            "price_n", "movable", "maxpod", "sing_ok", "sing_demand",
            "sing_max", "occ_lo", "occ_hi", "sing_lo", "sing_hi", "real",
            "elig"))
        def score_grid(rows, alloc, price_n, movable, maxpod, sing_ok,
                       sing_demand, sing_max, occ_lo, occ_hi, sing_lo,
                       sing_hi, m_lo, m_hi, valid, tot_pos, real, elig):
            off = rows[:, 0]
            count = rows[:, 1]
            resid = rows[:, 2:]
            demand = alloc[off] - resid                     # [Nn, R]
            pos = jnp.where(real[:, None], jnp.maximum(resid, 0), 0)
            excl = tot_pos[None, :] - pos                   # [Nn, R]
            full_relax = (demand <= excl).all(axis=1)
            eye = jnp.eye(resid.shape[0], dtype=bool)
            tgt = (real & elig)[None, :] & ~eye             # [Nn, Nn]
            pair_full = ((resid[None, :, :] >= maxpod[:, None, :])
                         .all(axis=2) & tgt).any(axis=1)
            pair_sing = ((resid[None, :, :] >= sing_max[:, None, :])
                         .all(axis=2) & tgt).any(axis=1)
            # defrag term: [S, Nn, P] placement grids gathered by the
            # node's offering; chip-disjointness decomposes exactly over
            # the two 32-bit mask words
            nm_lo, nm_hi = m_lo[:, off, :], m_hi[:, off, :]
            nv = valid[:, off, :]
            a_lo, a_hi = occ_lo & ~sing_lo, occ_hi & ~sing_hi
            dis_b = ((nm_lo & occ_lo[None, :, None])
                     | (nm_hi & occ_hi[None, :, None])) == 0
            dis_a = ((nm_lo & a_lo[None, :, None])
                     | (nm_hi & a_hi[None, :, None])) == 0
            before = (nv & dis_b).any(axis=2)               # [S, Nn]
            after = (nv & dis_a).any(axis=2)
            open_parked = before.any(axis=0)
            reopened = (after & ~before).sum(axis=0).astype(jnp.int32)
            sing_relax = (sing_demand <= excl).all(axis=1)
            full_ok = real & elig & movable & (count > 0) & full_relax \
                & pair_full & ~open_parked
            defrag_ok = real & elig & sing_ok & (reopened > 0) \
                & sing_relax & pair_sing
            kind = jnp.where(defrag_ok, KIND_DEFRAG,
                             jnp.where(full_ok, KIND_DRAIN, 0))
            score = jnp.where(
                kind == KIND_DEFRAG, reopened * jnp.maximum(price_n, 1),
                jnp.where(kind == KIND_DRAIN, price_n, 0))
            return kind.astype(jnp.int32), score.astype(jnp.int32), reopened

        # force one trace so an unusable backend fails HERE, not mid-plan
        z = np.zeros(2, np.int32)
        score_grid(np.zeros((2, 6), np.int32), np.ones((1, 4), np.int32),
                   z.copy(), np.zeros(2, bool), np.zeros((2, 4), np.int32),
                   np.zeros(2, bool), np.zeros((2, 4), np.int32),
                   np.zeros((2, 4), np.int32), z.copy(), z.copy(),
                   z.copy(), z.copy(), np.zeros((1, 1, 2), np.int32),
                   np.zeros((1, 1, 2), np.int32),
                   np.zeros((1, 1, 2), bool), np.zeros(4, np.int32),
                   np.ones(2, bool), np.ones(2, bool))
        return score_grid
    except Exception:  # noqa: BLE001 — device is an optimization, not a dep
        return None


class RepackPlanner:
    """Pure function over an encoded repack problem."""

    def __init__(self, options: RepackOptions | None = None):
        self.options = options or RepackOptions()

    # -- grid step (the only backend-switched code) -----------------------

    def _score_grid(self, p: RepackProblem):
        """(kind, score, reopened) int64 [Nn] + the backend tag."""
        Nn = p.num_nodes
        tables = p.tables if self.options.defrag else []
        S = len(tables)
        use = self.options.use_device
        if use != "off" and (use == "on" or Nn * Nn >= _DEVICE_MIN_CELLS):
            dev = _device_score_grid()
            if dev is None and use == "on":
                # forced-on must never silently fall back to numpy — a
                # parity harness comparing "device" vs host would be
                # certifying a kernel that never ran
                raise RuntimeError(
                    "repack device kernel forced on (use_device='on') "
                    "but no usable jax backend is available")
            # int32 contract: overflow would silently diverge from the
            # host path, so any out-of-range tensor routes to numpy
            if dev is not None and self._i32_safe(p):
                from karpenter_tpu.faulttol import DeviceFaultError

                try:
                    return self._grid_device(dev, p, tables, S)
                except DeviceFaultError:
                    if use == "on":
                        # forced-on surfaces the fault (parity contract
                        # above); auto falls to the host oracle below
                        raise
        return (*self._grid_numpy(p, tables), "vector")

    @staticmethod
    def _i32_safe(p: RepackProblem) -> bool:
        alloc = p.catalog.offering_alloc()
        tot = np.clip(p.resid, 0, None).sum(axis=0)
        return all(int(np.abs(np.asarray(a)).max(initial=0)) < _I32_MAX
                   for a in (p.resid, p.maxpod, p.sing_demand, p.sing_max,
                             p.price_milli, alloc, tot))

    def _grid_device(self, dev, p: RepackProblem, tables, S):
        Nn = p.num_nodes
        if p.rows_host is not None:
            Np = p.rows_host.shape[0]
            rows = p.rows_dev if p.rows_dev is not None else p.rows_host
        else:
            Np = bucket(max(Nn, 1), NODE_BUCKETS)
            host_rows = np.zeros((Np, 2 + p.resid.shape[1]), np.int32)
            host_rows[:Nn, 0] = p.node_off
            host_rows[:Nn, 1] = p.pod_count
            host_rows[:Nn, 2:] = p.resid
            rows = host_rows
        R = p.resid.shape[1]
        alloc = p.catalog.offering_alloc().astype(np.int32)
        O = alloc.shape[0]
        Sp = bucket(max(S, 1), _SHAPE_PAD)
        Pmax = max((t.pmax for t in tables), default=1)
        Pp = bucket(max(Pmax, 1), _PLACE_PAD)
        m = np.zeros((Sp, O, Pp), np.uint64)
        v = np.zeros((Sp, O, Pp), bool)
        for i, t in enumerate(tables):
            m[i, :, :t.pmax] = t.masks
            v[i, :, :t.pmax] = t.valid
        m_lo, m_hi = split_mask_words(m)
        occ_lo, occ_hi = split_mask_words(p.occ_mask)
        sing_lo, sing_hi = split_mask_words(p.sing_mask)

        def padn(a, dtype):
            out = np.zeros((Np,) + a.shape[1:], dtype)
            out[:Nn] = a
            return out

        real = np.zeros(Np, bool)
        real[:Nn] = True
        tot_pos = np.clip(p.resid, 0, None).sum(axis=0).astype(np.int32)
        from karpenter_tpu.faulttol import device_guard
        from karpenter_tpu.obs.prof import get_profiler

        with device_guard("repack-grid") as guard:
            with get_profiler().sampled("repack-grid") as probe:
                kind, score, reopened = dev(
                    rows, alloc, padn(p.price_milli, np.int32),
                    padn(p.movable_all, bool), padn(p.maxpod, np.int32),
                    padn(p.sing_count > 0, bool),
                    padn(p.sing_demand, np.int32), padn(p.sing_max, np.int32),
                    padn(occ_lo, np.int32), padn(occ_hi, np.int32),
                    padn(sing_lo, np.int32), padn(sing_hi, np.int32),
                    m_lo, m_hi, v, tot_pos, real, padn(p.eligible, bool))
                probe.dispatched((kind, score, reopened))
            kind, score, reopened = guard.fetch((kind, score, reopened))
        return (np.asarray(kind)[:Nn].astype(np.int64),
                np.asarray(score)[:Nn].astype(np.int64),
                np.asarray(reopened)[:Nn].astype(np.int64), "device")

    def _grid_numpy(self, p: RepackProblem, tables):
        Nn = p.num_nodes
        resid = p.resid
        alloc = p.catalog.offering_alloc().astype(np.int64)
        demand = alloc[p.node_off] - resid
        pos = np.clip(resid, 0, None)
        tot_pos = pos.sum(axis=0)
        excl = tot_pos[None, :] - pos
        full_relax = (demand <= excl).all(axis=1)
        eye = np.eye(Nn, dtype=bool)
        tgt = p.eligible[None, :] & ~eye
        pair_full = ((resid[None, :, :] >= p.maxpod[:, None, :])
                     .all(axis=2) & tgt).any(axis=1)
        pair_sing = ((resid[None, :, :] >= p.sing_max[:, None, :])
                     .all(axis=2) & tgt).any(axis=1)
        before = np.zeros((len(tables), Nn), dtype=bool)
        after = np.zeros((len(tables), Nn), dtype=bool)
        occ = p.occ_mask
        vac = p.occ_mask & ~p.sing_mask
        for i, t in enumerate(tables):
            masks = t.masks[p.node_off]              # [Nn, P]
            valid = t.valid[p.node_off]
            before[i] = (valid & ((masks & occ[:, None]) == 0)).any(axis=1)
            after[i] = (valid & ((masks & vac[:, None]) == 0)).any(axis=1)
        open_parked = before.any(axis=0)
        reopened = (after & ~before).sum(axis=0).astype(np.int64)
        sing_relax = (p.sing_demand <= excl).all(axis=1)
        full_ok = p.eligible & p.movable_all & (p.pod_count > 0) \
            & full_relax & pair_full & ~open_parked
        defrag_ok = p.eligible & (p.sing_count > 0) & (reopened > 0) \
            & sing_relax & pair_sing
        kind = np.where(defrag_ok, KIND_DEFRAG,
                        np.where(full_ok, KIND_DRAIN, 0)).astype(np.int64)
        score = np.where(
            kind == KIND_DEFRAG, reopened * np.maximum(p.price_milli, 1),
            np.where(kind == KIND_DRAIN, p.price_milli, 0)).astype(np.int64)
        return kind, score, reopened

    # -- the plan ----------------------------------------------------------

    def plan(self, problem: RepackProblem) -> RepackPlan:
        t0 = time.perf_counter()
        out = RepackPlan(backend="vector")
        Nn = problem.num_nodes
        current = float(problem.price_milli.sum()) / 1000.0 if Nn else 0.0
        out.current_cost = out.proposed_cost = current
        if Nn < 2:
            out.plan_seconds = time.perf_counter() - t0
            return out
        kind, score, reopened, backend = self._score_grid(problem)
        out.backend = backend
        out.candidate_count = Nn
        round_plan(problem, kind, score, out,
                   max_migrations=self.options.max_migrations)
        out.plan_seconds = time.perf_counter() - t0
        return out


def target_order(problem: RepackProblem) -> list[int]:
    """Static tightest-first target order: ascending dominant free
    fraction (integer 0..1024 of the node's allocatable), index ASC —
    the deterministic first-fit order every planner path shares (packing
    into the fullest node first is the consolidation-friendly fill)."""
    alloc = problem.catalog.offering_alloc().astype(np.int64)[
        problem.node_off]
    frac = np.where(alloc > 0,
                    np.clip(problem.resid, 0, None) * 1024
                    // np.maximum(alloc, 1), 0).max(axis=1)
    return np.lexsort((np.arange(problem.num_nodes), frac)).tolist()


def closes_open_slice(problem: RepackProblem, t: int, occ_t: int,
                      chips: int) -> bool:
    """Would landing ``chips`` on node ``t`` close a parked shape's
    currently-open placement there?  The anti-ping-pong guard: defrag
    must never re-fragment its own targets."""
    off = int(problem.node_off[t])
    for table in problem.tables:
        masks = table.masks[off]
        valid = table.valid[off]
        open_before = (valid & ((masks & np.uint64(occ_t)) == 0)).any()
        if not open_before:
            continue
        open_after = (valid
                      & ((masks & np.uint64(occ_t | chips)) == 0)).any()
        if not open_after:
            return True
    return False


def _batch_target(problem: RepackProblem, s: int, refs, work, occ, role,
                  sig_node_ok, rank, rank_inf):
    """The whole-batch fast path's target: the min-rank node that hosts
    EVERY movable pod of source ``s`` at once (combined demand, every
    pod's compat/zone pin, combined chip count, closure guard), or None
    — then the per-pod scan decides.  Returns ``(t, per-pod chip
    masks)``; chips split lowest-first in pod order, exactly what the
    sequential per-pod assignment onto one node would produce."""
    total = refs[0].req.copy()
    gpu_total = refs[0].gpu
    sigs = {refs[0].sig}
    pinned = bool(problem.sig_zone_pinned[refs[0].sig])
    for ref in refs[1:]:
        total = total + ref.req
        gpu_total += ref.gpu
        sigs.add(ref.sig)
        pinned |= bool(problem.sig_zone_pinned[ref.sig])
    feas = (role != _ROLE_SOURCE) & problem.eligible \
        & (work >= total[None, :]).all(axis=1)
    for sig in sigs:
        feas &= sig_node_ok[sig]
    if pinned:
        feas &= problem.node_zone == problem.node_zone[s]
    feas[s] = False
    if not feas.any():
        return None
    if gpu_total == 0:
        t = int(np.argmin(np.where(feas, rank, rank_inf)))
        return t, [0] * len(refs)
    cand = np.nonzero(feas)[0]
    cand = cand[np.argsort(rank[cand], kind="stable")]
    for tc in cand.tolist():
        mask = lowest_free_chips(occ[tc], int(problem.n_chips[tc]),
                                 gpu_total)
        if mask.bit_count() < gpu_total:
            continue
        if closes_open_slice(problem, tc, occ[tc], mask):
            continue
        split = []
        remaining = mask
        for ref in refs:
            ch = 0
            taken = 0
            while taken < ref.gpu:
                low = remaining & -remaining
                ch |= low
                remaining &= ~low
                taken += 1
            split.append(ch)
        return tc, split
    return None


def round_plan(problem: RepackProblem, kind: np.ndarray, score: np.ndarray,
               out: RepackPlan, max_migrations: int = -1) -> None:
    """Integral rounding of the relaxed candidate scores (see module
    docstring) — shared host code: both backends feed it identical grid
    outputs, so plans stay bit-identical.  The per-pod target search is
    vectorized (min tightest-first rank over the feasibility mask —
    identical outcome to the oracle's ordered scan, pinned by the
    differential tests) so rounding stays sub-linear in python ops at
    the 2k-claim bench shape."""
    Nn = problem.num_nodes
    order = np.lexsort((np.arange(Nn), -score))
    torder = target_order(problem)
    rank = np.empty(Nn, dtype=np.int64)
    rank[np.asarray(torder, dtype=np.int64)] = np.arange(Nn)
    if problem.sig_rows.shape[0]:
        sig_node_ok = problem.sig_rows[:, problem.node_off] \
            & problem.taint_ok
    else:
        sig_node_ok = np.zeros((0, Nn), dtype=bool)
    work = problem.resid.astype(np.int64).copy()
    occ = [int(x) for x in problem.occ_mask]
    role = np.zeros(Nn, dtype=np.int8)
    budget = max_migrations if max_migrations >= 0 else (1 << 60)
    names = problem.claim_names
    _RANK_INF = np.int64(1) << 60

    for s in order.tolist():
        k = int(kind[s])
        if k == 0 or int(score[s]) <= 0 or role[s] != _ROLE_FREE:
            continue
        refs = [r for r in problem.pods[s]
                if (r.movable if k == KIND_DRAIN else r.single)]
        if not refs or len(refs) > budget:
            continue
        moves: list[tuple] = []
        journal: list[tuple] = []
        ok = True
        # whole-batch fast path: one target hosting the source's ENTIRE
        # movable set (the common drain shape) costs one vectorized
        # probe instead of one per pod; per-pod first-fit is the
        # fallback.  The oracle implements the identical two-phase rule.
        batch = _batch_target(problem, s, refs, work, occ, role,
                              sig_node_ok, rank, _RANK_INF)
        if batch is not None:
            t, chip_split = batch
            for ref, chips in zip(refs, chip_split):
                work[t] -= ref.req
                occ[t] |= chips
                journal.append((t, ref.req, chips))
                moves.append((ref, t, chips))
        else:
            for ref in refs:
                feas = (role != _ROLE_SOURCE) & problem.eligible \
                    & (work >= ref.req[None, :]).all(axis=1) \
                    & sig_node_ok[ref.sig]
                feas[s] = False
                if problem.sig_zone_pinned[ref.sig]:
                    feas &= problem.node_zone == problem.node_zone[s]
                chips = 0
                if ref.gpu > 0:
                    cand = np.nonzero(feas)[0]
                    cand = cand[np.argsort(rank[cand], kind="stable")]
                    t = -1
                    for tc in cand.tolist():
                        ch = lowest_free_chips(occ[tc],
                                               int(problem.n_chips[tc]),
                                               ref.gpu)
                        if ch.bit_count() < ref.gpu:
                            continue
                        if closes_open_slice(problem, tc, occ[tc], ch):
                            continue
                        t, chips = tc, ch
                        break
                    if t < 0:
                        ok = False
                        break
                else:
                    if not feas.any():
                        ok = False
                        break
                    t = int(np.argmin(np.where(feas, rank, _RANK_INF)))
                work[t] -= ref.req
                occ[t] |= chips
                journal.append((t, ref.req, chips))
                moves.append((ref, t, chips))
        if not ok:
            # residuals only shrink: retrying later cannot help.  Undo
            # the trial deltas (chips were free before the OR, so the
            # AND-NOT restores exactly).
            for t, req, chips in journal:
                work[t] += req
                occ[t] &= ~chips
            continue
        # commit (work/occ already applied by the trial)
        for ref, t, chips in moves:
            out.migrations.append(Migration(
                pod_key=ref.key, src_claim=names[s], dst_claim=names[t],
                kind=k))
            role[t] = _ROLE_TARGET
        role[s] = _ROLE_SOURCE
        budget -= len(moves)
        if k == KIND_DRAIN:
            out.drained.append(names[s])
            out.proposed_cost -= float(problem.price_milli[s]) / 1000.0
        else:
            pre = occ[s]
            post = pre & ~int(problem.sing_mask[s])
            occ[s] = post
            work[s] += problem.sing_demand[s]
            off = int(problem.node_off[s])
            for shape, table in zip(problem.parked_shapes, problem.tables):
                masks = table.masks[off]
                valid = table.valid[off]
                fit_pre = (valid
                           & ((masks & np.uint64(pre)) == 0)).any()
                fit_post = (valid
                            & ((masks & np.uint64(post)) == 0)).any()
                if fit_post and not fit_pre:
                    out.reopened.append(ReopenedSlice(
                        claim_name=names[s], offering=off, shape=shape,
                        pre_mask=pre, post_mask=post))
