"""Pure-python repack oracle: the differential-parity reference.

Implements the canonical repack algorithm (repack/planner.py module
docstring) with scalar host loops — no vectorized grids, no device —
so the batched planner has an independent implementation to be
bit-identical against (the same role ``preempt/greedy.py`` and
``gang/greedy.py`` play for their planes).  Also the degraded-mode
fallback ``ResilientRepacker`` rides when the batched path fails.
"""

from __future__ import annotations

import time

import numpy as np

from karpenter_tpu.repack.encode import RepackProblem, lowest_free_chips
from karpenter_tpu.repack.types import (
    KIND_DEFRAG, KIND_DRAIN, Migration, ReopenedSlice, RepackOptions,
    RepackPlan,
)


def _fits_any(table_masks, table_valid, occ: int) -> bool:
    for m, ok in zip(table_masks.tolist(), table_valid.tolist()):
        if ok and (int(m) & occ) == 0:
            return True
    return False


class GreedyRepacker:
    """Scalar-loop implementation of the canonical repack algorithm."""

    def __init__(self, options: RepackOptions | None = None):
        self.options = options or RepackOptions()

    # -- scoring (the loop twin of the batched grid) -----------------------

    def _score(self, p: RepackProblem):
        Nn = p.num_nodes
        alloc = p.catalog.offering_alloc().astype(np.int64)
        tables = p.tables if self.options.defrag else []
        kind = [0] * Nn
        score = [0] * Nn
        reopened = [0] * Nn
        tot_pos = [0] * p.resid.shape[1]
        for ni in range(Nn):
            for ri in range(len(tot_pos)):
                v = int(p.resid[ni, ri])
                if v > 0:
                    tot_pos[ri] += v
        for s in range(Nn):
            off = int(p.node_off[s])
            resid_s = p.resid[s]
            demand = [int(alloc[off, ri]) - int(resid_s[ri])
                      for ri in range(len(tot_pos))]
            excl = [tot_pos[ri] - max(int(resid_s[ri]), 0)
                    for ri in range(len(tot_pos))]
            full_relax = all(demand[ri] <= excl[ri]
                             for ri in range(len(excl)))
            sing_relax = all(int(p.sing_demand[s, ri]) <= excl[ri]
                             for ri in range(len(excl)))
            pair_full = pair_sing = False
            for t in range(Nn):
                if t == s or not bool(p.eligible[t]):
                    continue
                if all(int(p.resid[t, ri]) >= int(p.maxpod[s, ri])
                       for ri in range(len(excl))):
                    pair_full = True
                if all(int(p.resid[t, ri]) >= int(p.sing_max[s, ri])
                       for ri in range(len(excl))):
                    pair_sing = True
            occ = int(p.occ_mask[s])
            vac = occ & ~int(p.sing_mask[s])
            open_parked = False
            reopen = 0
            for table in tables:
                before = _fits_any(table.masks[off], table.valid[off], occ)
                after = _fits_any(table.masks[off], table.valid[off], vac)
                open_parked |= before
                if after and not before:
                    reopen += 1
            price = int(p.price_milli[s])
            if not bool(p.eligible[s]):
                pass
            elif int(p.sing_count[s]) > 0 and reopen > 0 and sing_relax \
                    and pair_sing:
                kind[s] = KIND_DEFRAG
                score[s] = reopen * max(price, 1)
            elif bool(p.movable_all[s]) and int(p.pod_count[s]) > 0 \
                    and full_relax and pair_full and not open_parked:
                kind[s] = KIND_DRAIN
                score[s] = price
            reopened[s] = reopen
        return kind, score, reopened

    # -- rounding (the loop twin of planner.round_plan) --------------------

    def plan(self, problem: RepackProblem) -> RepackPlan:
        t0 = time.perf_counter()
        out = RepackPlan(backend="greedy")
        Nn = problem.num_nodes
        current = sum(int(v) for v in problem.price_milli) / 1000.0
        out.current_cost = out.proposed_cost = current
        if Nn < 2:
            out.plan_seconds = time.perf_counter() - t0
            return out
        kind, score, _reopened = self._score(problem)
        out.candidate_count = Nn
        order = sorted(range(Nn), key=lambda i: (-score[i], i))
        alloc = problem.catalog.offering_alloc().astype(np.int64)
        frac = []
        for ni in range(Nn):
            a = alloc[int(problem.node_off[ni])]
            frac.append(max(
                (max(int(problem.resid[ni, ri]), 0) * 1024
                 // max(int(a[ri]), 1)) if int(a[ri]) > 0 else 0
                for ri in range(problem.resid.shape[1])))
        torder = sorted(range(Nn), key=lambda i: (frac[i], i))
        work = [[int(v) for v in problem.resid[ni]] for ni in range(Nn)]
        occ = [int(x) for x in problem.occ_mask]
        role = [0] * Nn
        budget = self.options.max_migrations \
            if self.options.max_migrations >= 0 else (1 << 60)
        names = problem.claim_names
        R = problem.resid.shape[1]

        for s in order:
            k = kind[s]
            if k == 0 or score[s] <= 0 or role[s] != 0:
                continue
            refs = [r for r in problem.pods[s]
                    if (r.movable if k == KIND_DRAIN else r.single)]
            if not refs or len(refs) > budget:
                continue
            trial_res: dict[int, list[int]] = {}
            trial_occ: dict[int, int] = {}
            moves: list[tuple] = []
            ok = True
            # whole-batch fast path first (the same two-phase rule the
            # batched planner's rounding applies): one target hosting
            # the ENTIRE movable set, min tightest-first rank
            batch = self._batch_target(problem, s, refs, work, occ,
                                       role, torder)
            if batch is not None:
                t, split = batch
                for ref, chips in zip(refs, split):
                    req = [int(v) for v in ref.req]
                    trial_res[t] = [a + b for a, b in zip(
                        trial_res.get(t, [0] * R), req)]
                    if chips:
                        trial_occ[t] = trial_occ.get(t, 0) | chips
                    moves.append((ref, t, chips, req))
                refs = []
            for ref in refs:
                placed = False
                req = [int(v) for v in ref.req]
                for t in torder:
                    if t == s or role[t] == 1 \
                            or not bool(problem.eligible[t]):
                        continue
                    if not bool(problem.sig_rows[ref.sig][
                            int(problem.node_off[t])]) \
                            or not bool(problem.taint_ok[ref.sig][t]):
                        continue
                    if bool(problem.sig_zone_pinned[ref.sig]) and \
                            int(problem.node_zone[t]) != \
                            int(problem.node_zone[s]):
                        continue
                    used = trial_res.get(t, [0] * R)
                    if any(work[t][ri] - used[ri] < req[ri]
                           for ri in range(R)):
                        continue
                    chips = 0
                    if ref.gpu > 0:
                        occ_t = occ[t] | trial_occ.get(t, 0)
                        chips = lowest_free_chips(
                            occ_t, int(problem.n_chips[t]), ref.gpu)
                        if chips.bit_count() < ref.gpu:
                            continue
                        if self._closes_open(problem, t, occ_t, chips):
                            continue
                    trial_res[t] = [used[ri] + req[ri] for ri in range(R)]
                    if chips:
                        trial_occ[t] = trial_occ.get(t, 0) | chips
                    moves.append((ref, t, chips, req))
                    placed = True
                    break
                if not placed:
                    ok = False
                    break
            if not ok:
                continue
            for ref, t, chips, req in moves:
                out.migrations.append(Migration(
                    pod_key=ref.key, src_claim=names[s],
                    dst_claim=names[t], kind=k))
                for ri in range(R):
                    work[t][ri] -= req[ri]
                occ[t] |= chips
                role[t] = 2
            role[s] = 1
            budget -= len(moves)
            if k == KIND_DRAIN:
                out.drained.append(names[s])
                out.proposed_cost -= int(problem.price_milli[s]) / 1000.0
            else:
                pre = occ[s]
                post = pre & ~int(problem.sing_mask[s])
                occ[s] = post
                for ri in range(R):
                    work[s][ri] += int(problem.sing_demand[s, ri])
                off = int(problem.node_off[s])
                for shape, table in zip(problem.parked_shapes,
                                        problem.tables):
                    fit_pre = _fits_any(table.masks[off],
                                        table.valid[off], pre)
                    fit_post = _fits_any(table.masks[off],
                                         table.valid[off], post)
                    if fit_post and not fit_pre:
                        out.reopened.append(ReopenedSlice(
                            claim_name=names[s], offering=off,
                            shape=shape, pre_mask=pre, post_mask=post))
        out.plan_seconds = time.perf_counter() - t0
        return out

    def _batch_target(self, problem: RepackProblem, s: int, refs,
                      work, occ, role, torder):
        """Scalar twin of ``planner._batch_target``: min-rank node that
        hosts every movable pod of ``s`` at once, or None."""
        R = problem.resid.shape[1]
        total = [0] * R
        gpu_total = 0
        sigs = set()
        pinned = False
        for ref in refs:
            for ri in range(R):
                total[ri] += int(ref.req[ri])
            gpu_total += ref.gpu
            sigs.add(ref.sig)
            pinned |= bool(problem.sig_zone_pinned[ref.sig])
        for t in torder:
            if t == s or role[t] == 1 or not bool(problem.eligible[t]):
                continue
            if any(work[t][ri] < total[ri] for ri in range(R)):
                continue
            if any(not bool(problem.sig_rows[sig][
                    int(problem.node_off[t])])
                   or not bool(problem.taint_ok[sig][t])
                   for sig in sigs):
                continue
            if pinned and int(problem.node_zone[t]) != \
                    int(problem.node_zone[s]):
                continue
            if gpu_total == 0:
                return t, [0] * len(refs)
            mask = lowest_free_chips(occ[t], int(problem.n_chips[t]),
                                     gpu_total)
            if mask.bit_count() < gpu_total:
                continue
            if self._closes_open(problem, t, occ[t], mask):
                continue
            split = []
            remaining = mask
            for ref in refs:
                ch = 0
                taken = 0
                while taken < ref.gpu:
                    low = remaining & -remaining
                    ch |= low
                    remaining &= ~low
                    taken += 1
                split.append(ch)
            return t, split
        return None

    @staticmethod
    def _closes_open(problem: RepackProblem, t: int, occ_t: int,
                     chips: int) -> bool:
        off = int(problem.node_off[t])
        for table in problem.tables:
            if not _fits_any(table.masks[off], table.valid[off], occ_t):
                continue
            if not _fits_any(table.masks[off], table.valid[off],
                             occ_t | chips):
                return True
        return False
