"""Repack-plane interface types: migration plan, options, records.

A :class:`RepackPlan` is the consolidation counterpart of the solver's
Plan and the preemption plane's PreemptionPlan: instead of *nodes to
create* or *pods to evict* it names *pods to migrate between existing
nodes* — fully evacuating nodes whose workload provably fits elsewhere
(the node is then drained and deleted: the savings), and moving chip-
consuming singletons off accelerator nodes when that reopens contiguous
torus slices for parked gangs (the defrag term — no savings, but a
parked gang stops starving).  Like the solver, the planner is a pure
function over explicit inputs (an encoded :class:`RepackProblem`) —
stateless, deterministic, differential-testable
(docs/design/repack.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field

# candidate kinds the scoring grid emits (shared by every backend and
# the rounding pass — the integer values ARE the wire contract)
KIND_NONE = 0          # not a candidate this round
KIND_DRAIN = 1         # full evacuation: node deleted, price saved
KIND_DEFRAG = 2        # singleton evacuation: node kept, slices reopened


@dataclass
class RepackOptions:
    """Gated planner config (mirrors PlannerOptions' env-style gating)."""

    # "auto": jitted scoring grid when a jax backend is importable,
    # numpy otherwise; "on"/"off" force.  Both paths share integer-exact
    # arithmetic, so the choice never changes the plan.
    use_device: str = "auto"
    # topology-aware slice defragmentation (scoring parked gang shapes
    # against per-node chip bitmasks); off = pure cost consolidation
    defrag: bool = True
    # max pod migrations this plan may spend. -1 = unbounded.
    max_migrations: int = -1


@dataclass(slots=True, frozen=True)
class Migration:
    """One pod moved from its current node to another live node."""

    pod_key: str                 # canonical 'namespace/name'
    src_claim: str
    dst_claim: str
    # why the pod moved: the source candidate's kind (KIND_DRAIN =
    # consolidation, KIND_DEFRAG = slice defragmentation)
    kind: int = KIND_DRAIN


@dataclass(slots=True, frozen=True)
class ReopenedSlice:
    """One parked gang shape that newly fits a node after its singleton
    chips were vacated — the defrag win, with the occupancy evidence the
    validator re-derives geometry against."""

    claim_name: str
    offering: int                # catalog offering index of the node
    shape: tuple[int, ...]       # the parked gang's slice shape
    pre_mask: int                # chip occupancy before the migration
    post_mask: int               # chip occupancy after (singletons gone)


@dataclass
class RepackPlan:
    """Migration set + the drains and slice reopenings it unlocks."""

    migrations: list[Migration] = field(default_factory=list)
    drained: list[str] = field(default_factory=list)    # claims deleted
    reopened: list[ReopenedSlice] = field(default_factory=list)
    current_cost: float = 0.0    # $/h of the live fleet at plan time
    proposed_cost: float = 0.0   # $/h after the drains
    candidate_count: int = 0     # nodes the scoring grid considered
    backend: str = ""
    plan_seconds: float = 0.0

    @property
    def savings(self) -> float:
        return self.current_cost - self.proposed_cost

    @property
    def savings_fraction(self) -> float:
        return self.savings / self.current_cost if self.current_cost > 0 \
            else 0.0

    @property
    def migration_count(self) -> int:
        return len(self.migrations)

    @property
    def slices_reopened(self) -> int:
        return len(self.reopened)

    @property
    def empty(self) -> bool:
        return not self.migrations and not self.drained

    def summary(self) -> dict[str, object]:
        return {
            "migrations": self.migration_count,
            "drained": len(self.drained),
            "slices_reopened": self.slices_reopened,
            "savings": round(self.savings, 4),
            "savings_fraction": round(self.savings_fraction, 4),
            "candidates": self.candidate_count,
            "backend": self.backend,
            "plan_seconds": round(self.plan_seconds, 6),
        }
