"""Repack degraded mode: host-loop fallback instead of a failed plan.

Mirrors ``preempt/degraded.py`` and ``gang/degraded.py``: the batched
planner can fail in ways the host loop cannot (a broken device kernel,
a shape bug in the grid padding).  None of those may stall the
disruption plane — ``ResilientRepacker`` degrades that one plan to the
``repack/greedy.py`` host loop with an ``ERRORS`` breadcrumb
(component="repack") and a ``degraded:`` backend tag.

The structural gate is deliberately cheap (O(migrations + nodes)); full
feasibility stays with ``validate_repack_plan`` (solver/validate.py),
which tests, the chaos harness, and the disruption controller's
choke point run on every plan before actuation.
"""

from __future__ import annotations

from karpenter_tpu.repack.encode import RepackProblem
from karpenter_tpu.repack.greedy import GreedyRepacker
from karpenter_tpu.repack.planner import RepackPlanner
from karpenter_tpu.repack.types import RepackOptions, RepackPlan
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("repack.degraded")


def repack_plan_defects(plan: RepackPlan,
                        problem: RepackProblem) -> list[str]:
    """Structural sanity of a repack plan (cheap; the full oracle is
    validate_repack_plan)."""
    if plan is None:
        return ["planner returned no plan"]
    defects: list[str] = []
    known = set(problem.claim_names)
    drained = set(plan.drained)
    on_node = {name: {r.key for r in refs}
               for name, refs in zip(problem.claim_names, problem.pods)}
    moved: dict[str, str] = {}
    for m in plan.migrations:
        if m.src_claim not in known:
            defects.append(f"migration of {m.pod_key} from unknown claim "
                           f"{m.src_claim}")
        elif m.pod_key not in on_node.get(m.src_claim, ()):
            defects.append(f"migration of {m.pod_key}: pod not on "
                           f"{m.src_claim}")
        if m.dst_claim not in known:
            defects.append(f"migration of {m.pod_key} onto unknown claim "
                           f"{m.dst_claim}")
        if m.dst_claim == m.src_claim:
            defects.append(f"migration of {m.pod_key} onto its own node")
        if m.dst_claim in drained:
            defects.append(f"migration of {m.pod_key} onto drained claim "
                           f"{m.dst_claim}")
        if m.pod_key in moved:
            defects.append(f"pod {m.pod_key} migrated twice")
        moved[m.pod_key] = m.dst_claim
    for name in plan.drained:
        if name not in known:
            defects.append(f"drain of unknown claim {name}")
            continue
        # the invariant the whole plane exists to uphold: a drained
        # node's occupants must ALL have somewhere to go — no pod dropped
        for key in on_node.get(name, ()):
            if key not in moved:
                defects.append(f"drained claim {name} still hosts "
                               f"{key} (pod dropped)")
    for r in plan.reopened:
        if r.claim_name not in known:
            defects.append(f"reopened slice on unknown claim "
                           f"{r.claim_name}")
        if r.claim_name in drained:
            defects.append(f"reopened slice on DRAINED claim "
                           f"{r.claim_name} (a deleted torus hosts "
                           f"nothing)")
    return defects


class ResilientRepacker:
    """Wraps the batched planner; degrades single plans to the host
    loop (the same plan the pre-batched repack tick computed)."""

    def __init__(self, primary: RepackPlanner | None = None,
                 options: RepackOptions | None = None):
        self.options = options or getattr(primary, "options", None) \
            or RepackOptions()
        self.primary = primary or RepackPlanner(self.options)
        self._fallback = None

    @property
    def fallback(self) -> GreedyRepacker:
        if self._fallback is None:
            self._fallback = GreedyRepacker(self.options)
        return self._fallback

    def plan(self, problem: RepackProblem) -> RepackPlan:
        try:
            plan = self.primary.plan(problem)
        except Exception as e:  # noqa: BLE001 — degrade, never fail the tick
            log.error("repack planner failed; degrading to host loop",
                      error=str(e)[:200])
            return self._degrade(problem, "backend_failure")
        defects = repack_plan_defects(plan, problem)
        if defects:
            log.error("repack planner produced invalid plan; degrading",
                      defects=defects[:3])
            return self._degrade(problem, "invalid_plan")
        return plan

    def _degrade(self, problem: RepackProblem, reason: str) -> RepackPlan:
        metrics.ERRORS.labels("repack", f"degraded_{reason}").inc()
        with obs.span("repack.plan.degraded", reason=reason):
            plan = self.fallback.plan(problem)
        plan.backend = f"degraded:{plan.backend}"
        return plan


__all__ = ["GreedyRepacker", "RepackPlanner", "ResilientRepacker",
           "repack_plan_defects"]
