"""Host-side repack encoding: live fleet -> dense migration tensors.

The placement solve answers "where do pending pods fit on NEW nodes",
the preemption planner "who must move off existing nodes for pending
high-priority pods" — the repack planner answers "which existing nodes
can be *emptied* so the fleet shrinks (consolidation), and which
accelerator nodes can shed their chip-consuming singletons so a parked
gang's contiguous slice reopens (defragmentation)".  Its inputs are
dense per-node tensors built from ground truth (cluster claims + bound
pods + catalog arrays), or — the production path — consumed straight
off the resident occupancy substrate:

- ``resid``       int64 [Nn, R]   residual allocatable per node, read
                                  from ``ResidentStore.occupancy_tensors``
                                  rows when a store is supplied (the
                                  delta-maintained device tensor; no
                                  per-tick re-encode + full upload);
- ``maxpod``      int64 [Nn, R]   componentwise max pod request per
                                  node (the rounding-feasibility relax);
- ``sing_*``                      the defrag-movable singleton slice of
                                  the same quantities;
- ``occ_mask`` / ``sing_mask``    uint64 chip bitmasks per node under
                                  the canonical chip model below.

**Canonical chip model** (shared by every planner backend AND the
independent validator): chips of an accelerator node are assigned
deterministically from its occupant list — placed gangs first (in
first-appearance order), each taking the lowest
``enumerate_placements`` mask disjoint from chips already assigned;
then every remaining accelerator-consuming pod in occupant order takes
its ``gpu``-count lowest free chips.  Pods carrying a gang are never
movable (atomic co-location is the gang plane's invariant, not ours to
break); hostname-anti-affinity pods are conservatively immovable.

Group->node compatibility deliberately IGNORES offering availability —
the target node already exists (same rationale as preempt/encode.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.apis.nodeclaim import NodePool
from karpenter_tpu.apis.pod import NUM_RESOURCES, pod_key, tolerates_all
from karpenter_tpu.catalog.arrays import CatalogArrays
from karpenter_tpu.gang.topology import SliceTable, enumerate_placements, slice_table
from karpenter_tpu.preempt.encode import (
    _label_row_no_avail, _pod_req_vec, claim_pods, occupancy_index,
)
from karpenter_tpu.solver.encode import (
    _has_hostname_anti_affinity, _has_zone_affinity, _zone_spread_constraints,
)


@dataclass(slots=True)
class PodRef:
    """One occupant pod's host-side record, in canonical move order."""

    key: str
    req: np.ndarray              # int64 [R]
    sig: int                     # index into sig_rows / sig_zone_pinned
    gpu: int
    movable: bool
    single: bool                 # defrag-movable chip consumer
    chip_mask: int = 0           # chips under the canonical model


@dataclass
class RepackProblem:
    """Dense consolidation/defrag input (see module docstring)."""

    claim_names: list[str]
    claims: list = field(default_factory=list)
    node_off: np.ndarray = None        # int32 [Nn]
    node_zone: np.ndarray = None       # int32 [Nn] catalog zone index
    resid: np.ndarray = None           # int64 [Nn, R]
    pod_count: np.ndarray = None       # int32 [Nn]
    # initialized, node-backed claims only: a launched-but-unready node
    # is neither a source (its pods are nominations in flight) nor a
    # target (unproven capacity) — but it stays a ROW so the node set
    # matches the resident occupancy tensor word-for-word
    eligible: np.ndarray = None        # bool [Nn]
    price_milli: np.ndarray = None     # int64 [Nn] claim $/h * 1000
    n_chips: np.ndarray = None         # int32 [Nn] torus chip count
    pods: list[list[PodRef]] = field(default_factory=list)
    movable_all: np.ndarray = None     # bool [Nn]
    maxpod: np.ndarray = None          # int64 [Nn, R]
    sing_demand: np.ndarray = None     # int64 [Nn, R]
    sing_max: np.ndarray = None        # int64 [Nn, R]
    sing_count: np.ndarray = None      # int32 [Nn]
    occ_mask: np.ndarray = None        # uint64 [Nn]
    sing_mask: np.ndarray = None       # uint64 [Nn]
    sig_rows: np.ndarray = None        # bool [Nsig, O]
    sig_zone_pinned: np.ndarray = None  # bool [Nsig]
    taint_ok: np.ndarray = None        # bool [Nsig, Nn]
    parked_shapes: list[tuple[int, ...]] = field(default_factory=list)
    tables: list[SliceTable] = field(default_factory=list)
    catalog: CatalogArrays = None
    # resident occupancy handoff: the delta-maintained device rows (the
    # kernel consumes these directly) + their host mirror; None when the
    # problem was encoded from a fresh ClusterState scan
    rows_dev: object = None
    rows_host: np.ndarray | None = None

    @property
    def num_nodes(self) -> int:
        return len(self.claim_names)


def lowest_free_chips(occ: int, n_chips: int, count: int) -> int:
    """The ``count`` lowest free chip bits under occupancy ``occ`` on an
    ``n_chips`` torus (clipped to availability) — THE deterministic chip
    assignment every path shares."""
    mask = 0
    taken = 0
    for c in range(n_chips):
        if taken >= count:
            break
        bit = 1 << c
        if not occ & bit:
            mask |= bit
            taken += 1
    return mask


def chip_layout(pods: list[PodRef], gang_shapes: list[tuple[str, tuple]],
                torus: tuple[int, ...]) -> tuple[int, int]:
    """Canonical per-node chip assignment -> ``(occ_mask, sing_mask)``.

    ``gang_shapes`` is [(gang name, slice shape)] in first-appearance
    order; each gang takes the lowest placement mask disjoint from chips
    already assigned.  Remaining accelerator consumers take lowest free
    chips in occupant order; the movable singletons among them form
    ``sing_mask`` (their ``chip_mask`` is stamped on the PodRef)."""
    n = 1
    for d in torus:
        n *= d
    if not torus:
        n = 0
    occ = 0
    for _name, shape in gang_shapes:
        placed = 0
        for m in enumerate_placements(torus, shape):
            if (m & occ) == 0:
                placed = m
                break
        occ |= placed
    sing = 0
    for ref in pods:
        if ref.gpu <= 0 or ref.chip_mask == -1:
            continue   # chip_mask -1 marks gang members (shape owns chips)
        mask = lowest_free_chips(occ, n, ref.gpu)
        ref.chip_mask = mask
        occ |= mask
        if ref.single:
            sing |= mask
    return occ, sing


def parked_gang_shapes(cluster) -> list[tuple[int, ...]]:
    """Distinct slice shapes of gangs currently parked (members pending,
    unbound, unnominated), ascending — the deterministic defrag demand
    set both planner paths and the validator score against."""
    shapes: set[tuple[int, ...]] = set()
    for p in cluster.pending_pods():
        if p.bound_node or p.nominated_node:
            continue
        g = p.spec.gang
        if g is not None and g.slice_shape:
            shapes.add(tuple(g.slice_shape))
    return sorted(shapes)


def _claim_pod_list(cluster, claim, snapshot, index):
    """Occupant PendingPods of ``claim`` in the canonical (collection)
    order — via the shared per-tick OccupancySnapshot when the resident
    path is on, via the per-call occupancy index otherwise.  Both
    reproduce ``preempt.encode.claim_pods`` exactly."""
    if snapshot is None:
        return claim_pods(cluster, claim, index=index)
    seen: set[str] = set()
    out = []
    for name in (claim.node_name, claim.name):
        if not name:
            continue
        for key in snapshot.pods_on(name):
            if key in seen:
                continue
            p = cluster.get("pods", key)
            if p is not None:
                seen.add(key)
                out.append(p)
    return out


def encode_repack(cluster, catalog: CatalogArrays,
                  nodepool: NodePool | None = None, *,
                  snapshot=None, store=None, claims=None,
                  parked: list[tuple[int, ...]] | None = None
                  ) -> RepackProblem:
    """Build the migration tensors from live claims.

    ``store`` (a ResidentStore) routes ``resid``/``pod_count`` through
    the delta-maintained occupancy rows — the device tensor the kernel
    consumes directly — instead of a fresh host rebuild; ``snapshot``
    (an OccupancySnapshot) replaces the per-call pod rescan.  Node order
    is cluster insertion order (the k8s list-order analogue every other
    encoder shares), so plans from either path are comparable
    bit-for-bit (tests/test_repack.py pins this across churn).
    """
    nodepool = nodepool or NodePool(name="default")
    if claims is None:
        claims = [c for c in cluster.nodeclaims()
                  if not c.deleted and c.launched]
    live = []
    for c in claims:
        if c.deleted or not c.launched:
            continue
        off = catalog.find_offering(c.instance_type, c.zone, c.capacity_type)
        if off is None:
            continue   # offering left the catalog: not a node we can size
        live.append((c, off))

    Nn = len(live)
    R = NUM_RESOURCES
    index = None if snapshot is not None else occupancy_index(cluster)
    alloc = catalog.offering_alloc().astype(np.int64)
    prob = RepackProblem(claim_names=[], catalog=catalog)
    prob.node_off = np.zeros(Nn, dtype=np.int32)
    prob.node_zone = np.zeros(Nn, dtype=np.int32)
    prob.resid = np.zeros((Nn, R), dtype=np.int64)
    prob.pod_count = np.zeros(Nn, dtype=np.int32)
    prob.eligible = np.zeros(Nn, dtype=bool)
    prob.price_milli = np.zeros(Nn, dtype=np.int64)
    prob.n_chips = np.zeros(Nn, dtype=np.int32)
    prob.movable_all = np.zeros(Nn, dtype=bool)
    prob.maxpod = np.zeros((Nn, R), dtype=np.int64)
    prob.sing_demand = np.zeros((Nn, R), dtype=np.int64)
    prob.sing_max = np.zeros((Nn, R), dtype=np.int64)
    prob.sing_count = np.zeros(Nn, dtype=np.int32)
    prob.occ_mask = np.zeros(Nn, dtype=np.uint64)
    prob.sing_mask = np.zeros(Nn, dtype=np.uint64)

    shapes = parked if parked is not None else parked_gang_shapes(cluster)
    prob.parked_shapes = [tuple(s) for s in shapes]
    prob.tables = [slice_table(catalog, s) for s in prob.parked_shapes]

    # per-signature offering compat (labels, availability ignored) +
    # zone-pin flag; taint verdicts per (signature, claim taint tuple)
    sig_index: dict[tuple, int] = {}
    sig_rows: list[np.ndarray] = []
    sig_pinned: list[bool] = []
    sig_reps: list = []
    mask_cache: dict = {}
    pool_taints = tuple(nodepool.taints)

    def _sig_of(spec) -> int:
        # requests/priority/gang (the first three signature slots) do
        # not affect label compat, taints, or zone pinning — keying on
        # them would lower one label row PER POD at the 4k-pod bench
        # shape instead of one per distinct constraint set
        key = spec.constraint_signature()[3:]
        hit = sig_index.get(key)
        if hit is not None:
            return hit
        idx = len(sig_rows)
        sig_index[key] = idx
        sig_rows.append(_label_row_no_avail(
            spec.scheduling_requirements(), None, catalog, mask_cache))
        sig_pinned.append(bool(_has_zone_affinity(spec)
                               or _zone_spread_constraints(spec)))
        sig_reps.append(spec)
        return idx

    for ni, (c, off) in enumerate(live):
        prob.claim_names.append(c.name)
        prob.claims.append(c)
        prob.node_off[ni] = off
        t = int(catalog.off_type[off])
        prob.node_zone[ni] = int(catalog.off_zone[off])
        torus = tuple(catalog.type_torus[t]) if t < len(
            catalog.type_torus) else ()
        n_chips = 1
        for d in torus:
            n_chips *= d
        prob.n_chips[ni] = n_chips if torus else 0
        prob.eligible[ni] = bool(c.initialized and c.node_name)
        prob.price_milli[ni] = int(round(c.hourly_price * 1000.0))
        resid = alloc[off].copy()
        refs: list[PodRef] = []
        gang_shapes: list[tuple[str, tuple]] = []
        gangs_seen: set[str] = set()
        all_movable = True
        for p in _claim_pod_list(cluster, c, snapshot, index):
            spec = p.spec
            req = _pod_req_vec(spec)
            resid -= req
            gpu = int(spec.requests.gpu)
            in_gang = spec.gang is not None
            movable = not in_gang and not _has_hostname_anti_affinity(spec) \
                and tolerates_all(spec.tolerations, pool_taints)
            single = movable and gpu > 0
            ref = PodRef(key=pod_key(spec), req=req, sig=_sig_of(spec),
                         gpu=gpu, movable=movable, single=single)
            if in_gang:
                if spec.gang.slice_shape \
                        and spec.gang.name not in gangs_seen:
                    gangs_seen.add(spec.gang.name)
                    gang_shapes.append((spec.gang.name,
                                        tuple(spec.gang.slice_shape)))
                if spec.gang.slice_shape:
                    ref.chip_mask = -1   # shape owns the chips
            refs.append(ref)
            all_movable &= movable
            np.maximum(prob.maxpod[ni], req, out=prob.maxpod[ni])
            if single:
                prob.sing_demand[ni] += req
                np.maximum(prob.sing_max[ni], req, out=prob.sing_max[ni])
                prob.sing_count[ni] += 1
        occ, sing = chip_layout(refs, gang_shapes, torus)
        prob.occ_mask[ni] = np.uint64(occ)
        prob.sing_mask[ni] = np.uint64(sing)
        prob.pods.append(refs)
        prob.movable_all[ni] = all_movable
        prob.pod_count[ni] = len(refs)
        prob.resid[ni] = resid

    # the resident occupancy handoff: resid/pod_count served from the
    # delta-maintained rows (device tensor + host mirror).  A store that
    # serves stale rows makes the plan diverge from the fresh encode —
    # exactly the failure the pinned handoff test exists to catch.
    if store is not None:
        names, dev, _delta = store.occupancy_tensors(cluster, catalog)
        if names == prob.claim_names:
            mirror = store.occupancy_rows()
            if mirror is not None and mirror.shape[0] >= Nn:
                prob.rows_dev = dev
                prob.rows_host = mirror
                prob.resid = mirror[:Nn, 2:2 + R].astype(np.int64)
                prob.pod_count = mirror[:Nn, 1].astype(np.int32)

    Nsig = len(sig_rows)
    O = catalog.num_offerings
    prob.sig_rows = (np.stack(sig_rows) if Nsig
                     else np.zeros((0, O), dtype=bool))
    prob.sig_zone_pinned = np.asarray(sig_pinned, dtype=bool)
    prob.taint_ok = np.ones((Nsig, Nn), dtype=bool)
    # claims sharing a taint tuple share one toleration verdict per sig
    taint_sets: dict[tuple, np.ndarray] = {}
    for ni, c in enumerate(prob.claims):
        taint_sets.setdefault(tuple(c.taints),
                              np.zeros(Nn, bool))[ni] = True
    for si, rep in enumerate(sig_reps):
        for taints, nmask in taint_sets.items():
            if taints and not tolerates_all(rep.tolerations, taints):
                prob.taint_ok[si] &= ~nmask
    return prob
