"""Load balancer provider: node registration in LB pools.

Capability parity with ``pkg/providers/loadbalancer/``:

- ``register_instance`` adds the node to each configured target pool
  (provider.go:69,137-178) and optionally waits for the member to report
  healthy by POLLING the member through the API (:246-274 — a 10s ticker
  against GetLoadBalancerPoolMember, not a local sleep);
- ``deregister_instance`` finds the member by instance id and skips
  silently when it is already gone (:180-207);
- the health-check manager DIFFS desired config against the pool and
  patches only the drifted fields (``build_health_check_patch``,
  healthcheck.go:77-145), leaving pools with no configured HC on their
  defaults (:44-49);
- ``validate_health_check`` / ``validate_integration`` mirror the
  reference's ranges (healthcheck.go:150-189, provider.go:277).

The fake LB state lives here too (the reference talks to VPC LB REST;
tests use pkg/fake).  Members carry the VPC member lifecycle:
``provisioning_status`` create_pending -> active (-> delete_pending) and
``health`` unknown -> ok | faulted.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from karpenter_tpu.apis.nodeclass import (
    HealthCheck, LoadBalancerIntegration, LoadBalancerTarget,
)
from karpenter_tpu.cloud.errors import CloudError, is_not_found, not_found
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.loadbalancer")

# reference defaults (healthcheck.go:80-100)
_HC_DEFAULT_PROTOCOL = "tcp"
_HC_DEFAULT_INTERVAL = 30
_HC_DEFAULT_TIMEOUT = 5
_HC_DEFAULT_RETRIES = 2


@dataclass
class PoolMember:
    id: str
    address: str
    port: int
    weight: int = 50
    instance_id: str = ""
    health: str = "unknown"               # unknown | ok | faulted
    provisioning_status: str = "create_pending"   # -> active -> delete_pending
    created_at: float = field(default_factory=time.time)


@dataclass
class PoolHealthMonitor:
    """The pool's applied health-monitor config (vpcv1
    LoadBalancerPoolHealthMonitor analogue: delay/max_retries/timeout/
    type/url_path)."""

    delay: int = _HC_DEFAULT_INTERVAL
    max_retries: int = _HC_DEFAULT_RETRIES
    timeout: int = _HC_DEFAULT_TIMEOUT
    type: str = _HC_DEFAULT_PROTOCOL
    url_path: str = ""


@dataclass
class FakePool:
    id: str
    lb_id: str
    name: str
    members: dict[str, PoolMember] = field(default_factory=dict)
    protocol: str = _HC_DEFAULT_PROTOCOL
    health_monitor: PoolHealthMonitor | None = None   # None = pool defaults


class FakeLoadBalancers:
    """In-memory LB API double (pool/member CRUD, ref vpc.go:516-669).

    ``settle_after`` models the VPC member lifecycle: a member stays
    create_pending/unknown for that long, then flips active/ok (or
    active/faulted when its address was marked via ``fault_address``).
    """

    def __init__(self, healthy_after: float = 0.0):
        self._lock = threading.RLock()
        self.pools: dict[tuple[str, str], FakePool] = {}   # (lb, pool name)
        self.known_lbs: set = set()
        self._seq = 0
        self.healthy_after = healthy_after   # member settle delay
        self._faulted_addresses: set = set()

    # -- LB / pool surface (ref vpc.go:516-588) ----------------------------

    def create_load_balancer(self, lb_id: str) -> None:
        with self._lock:
            self.known_lbs.add(lb_id)

    def get_load_balancer(self, lb_id: str) -> str:
        with self._lock:
            if self.known_lbs and lb_id not in self.known_lbs:
                raise not_found("load_balancer", lb_id)
            return lb_id

    def ensure_pool(self, lb_id: str, pool_name: str) -> FakePool:
        with self._lock:
            self.known_lbs.add(lb_id)
            key = (lb_id, pool_name)
            if key not in self.pools:
                self._seq += 1
                self.pools[key] = FakePool(id=f"lbpool-{self._seq}",
                                           lb_id=lb_id, name=pool_name)
            return self.pools[key]

    def get_pool(self, lb_id: str, pool_name: str) -> FakePool:
        with self._lock:
            pool = self.pools.get((lb_id, pool_name))
            if pool is None:
                raise not_found("lb_pool", f"{lb_id}/{pool_name}")
            return pool

    def update_pool(self, lb_id: str, pool_name: str, patch: dict) -> FakePool:
        """Apply a health-check patch map (ref UpdateLoadBalancerPool)."""
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            if "protocol" in patch:
                pool.protocol = patch["protocol"]
            hm = patch.get("health_monitor")
            if hm:
                pool.health_monitor = PoolHealthMonitor(
                    delay=int(hm.get("delay", _HC_DEFAULT_INTERVAL)),
                    max_retries=int(hm.get("max_retries",
                                           _HC_DEFAULT_RETRIES)),
                    timeout=int(hm.get("timeout", _HC_DEFAULT_TIMEOUT)),
                    type=hm.get("type", _HC_DEFAULT_PROTOCOL),
                    url_path=hm.get("url_path", ""))
            return pool

    # -- members (ref vpc.go:590-669) --------------------------------------

    def add_member(self, lb_id: str, pool_name: str, address: str, port: int,
                   weight: int, instance_id: str = "") -> PoolMember:
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            for m in pool.members.values():
                if m.address == address and m.port == port:
                    return m   # idempotent
            self._seq += 1
            member = PoolMember(id=f"member-{self._seq}", address=address,
                                port=port, weight=weight,
                                instance_id=instance_id)
            pool.members[member.id] = member
            return member

    def get_member(self, lb_id: str, pool_name: str,
                   member_id: str) -> PoolMember:
        """(ref GetLoadBalancerPoolMember — the wait-healthy poll target).
        Reads advance the simulated lifecycle."""
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            member = pool.members.get(member_id)
            if member is None:
                raise not_found("lb_member", member_id)
            self._advance(member)
            return member

    def find_member_by_instance(self, lb_id: str, pool_name: str,
                                instance_id: str) -> PoolMember | None:
        """(ref findMemberByInstanceID, provider.go:225)"""
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            for m in pool.members.values():
                if m.instance_id == instance_id:
                    return m
            return None

    def remove_member(self, lb_id: str, pool_name: str, address: str) -> int:
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            gone = [mid for mid, m in pool.members.items()
                    if m.address == address]
            for mid in gone:
                pool.members[mid].provisioning_status = "delete_pending"
                del pool.members[mid]
            return len(gone)

    def delete_member(self, lb_id: str, pool_name: str,
                      member_id: str) -> None:
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            if member_id not in pool.members:
                raise not_found("lb_member", member_id)
            pool.members[member_id].provisioning_status = "delete_pending"
            del pool.members[member_id]

    def member_health(self, member: PoolMember) -> str:
        with self._lock:
            self._advance(member)
            return member.health

    def fault_address(self, address: str) -> None:
        """Test hook: members at this address settle faulted, not ok."""
        with self._lock:
            self._faulted_addresses.add(address)

    def _advance(self, member: PoolMember) -> None:
        if member.provisioning_status == "create_pending" and \
                time.time() - member.created_at >= self.healthy_after:
            member.provisioning_status = "active"
            member.health = "faulted" \
                if member.address in self._faulted_addresses else "ok"


# ---------------------------------------------------------------------------
# Health-check manager (healthcheck.go:44-189)
# ---------------------------------------------------------------------------

def build_health_check_patch(desired: HealthCheck, pool: FakePool
                             ) -> tuple[bool, dict]:
    """Diff desired HC config against the pool's applied state; returns
    (needs_update, patch map).  Mirrors buildHealthCheckPatch
    (healthcheck.go:77-145): defaults tcp/30s/5s/2 retries; url_path only
    for http(s) with a path; untouched fields stay out of the patch."""
    patch: dict = {}
    protocol = desired.protocol or _HC_DEFAULT_PROTOCOL
    interval = desired.interval or _HC_DEFAULT_INTERVAL
    timeout = desired.timeout or _HC_DEFAULT_TIMEOUT
    retries = desired.retries or _HC_DEFAULT_RETRIES

    if pool.protocol != protocol:
        patch["protocol"] = protocol

    hm = pool.health_monitor
    needs_monitor = hm is None or (
        hm.delay != interval or hm.max_retries != retries
        or hm.timeout != timeout or hm.type != protocol
        or (protocol in ("http", "https") and desired.path
            and hm.url_path != desired.path))
    if needs_monitor:
        monitor: dict = {"delay": interval, "max_retries": retries,
                         "timeout": timeout, "type": protocol}
        if protocol in ("http", "https") and desired.path:
            monitor["url_path"] = desired.path
        patch["health_monitor"] = monitor
    return bool(patch), patch


def validate_health_check(hc: HealthCheck | None) -> list[str]:
    """(ref ValidateHealthCheck, healthcheck.go:150-189)"""
    if hc is None:
        return []
    errs: list[str] = []
    if hc.protocol not in ("", "tcp", "http", "https"):
        errs.append(f"invalid health check protocol: {hc.protocol}")
    if hc.protocol in ("http", "https") and not hc.path:
        errs.append("path is required for HTTP/HTTPS health checks")
    if hc.path and not hc.path.startswith("/"):
        errs.append(f"invalid health check path: {hc.path}")
    if hc.port and not (1 <= hc.port <= 65535):
        errs.append(f"health check port {hc.port} out of range")
    if hc.interval and not (5 <= hc.interval <= 300):
        errs.append("health check interval must be between 5 and 300 seconds")
    if hc.timeout and not (1 <= hc.timeout <= 60):
        errs.append("health check timeout must be between 1 and 60 seconds")
    if hc.retries and not (1 <= hc.retries <= 10):
        errs.append("health check retry count must be between 1 and 10")
    if hc.interval and hc.timeout and hc.timeout >= hc.interval:
        errs.append(f"health check timeout ({hc.timeout}) must be less "
                    f"than interval ({hc.interval})")
    return errs


def validate_integration(integration: LoadBalancerIntegration) -> list[str]:
    """Static spec validation (ref provider.go:277 + per-target HC rules)."""
    errs: list[str] = []
    if not integration.enabled:
        return errs
    if not integration.target_groups:
        errs.append("loadBalancerIntegration.enabled requires targetGroups")
    for i, tg in enumerate(integration.target_groups):
        prefix = f"targetGroups[{i}]"
        if not tg.load_balancer_id:
            errs.append(f"{prefix}.loadBalancerID is required")
        if not tg.pool_name:
            errs.append(f"{prefix}.poolName is required")
        if not (1 <= tg.port <= 65535):
            errs.append(f"{prefix}.port {tg.port} out of range")
        if not (0 <= tg.weight <= 100):
            errs.append(f"{prefix}.weight {tg.weight} out of range")
        errs.extend(f"{prefix}.healthCheck: {e}"
                    for e in validate_health_check(tg.health_check))
    return errs


class LoadBalancerProvider:
    def __init__(self, lbs: FakeLoadBalancers | None = None,
                 poll_interval: float = 0.05):
        self.lbs = lbs or FakeLoadBalancers()
        # the reference polls every 10s (provider.go:252); tests shrink it
        self.poll_interval = poll_interval

    # -- registration (provider.go:69,137-178) -----------------------------

    def register_instance(self, integration: LoadBalancerIntegration,
                          address: str, instance_id: str = "",
                          wait_healthy: bool = False,
                          timeout: float = 5.0) -> list[str]:
        """Adds the node to every target pool; returns member ids.  HC
        config is reconciled per pool through the diff-driven patch
        builder BEFORE the member lands, so a newly-registered node is
        probed with the desired settings from its first check."""
        errs = validate_integration(integration)
        if errs:
            raise CloudError("invalid loadBalancerIntegration: " +
                             "; ".join(errs), 400, retryable=False)
        member_ids: list[str] = []
        for tg in integration.target_groups:
            self.lbs.ensure_pool(tg.load_balancer_id, tg.pool_name)
            if tg.health_check is not None:
                self.configure_health_check(tg)
            member = self.lbs.add_member(tg.load_balancer_id, tg.pool_name,
                                         address, tg.port, tg.weight,
                                         instance_id=instance_id)
            member_ids.append(member.id)
            metrics.API_REQUESTS.labels("lb", "add_member", "ok").inc()
            if wait_healthy:
                self.wait_member_healthy(tg.load_balancer_id, tg.pool_name,
                                         member.id, timeout)
        return member_ids

    def configure_health_check(self, tg: LoadBalancerTarget) -> bool:
        """(ref ConfigureHealthCheck, healthcheck.go:44-75): no desired HC
        -> pool defaults untouched; otherwise patch only on drift.
        Returns whether a patch was applied."""
        if tg.health_check is None:
            return False
        pool = self.lbs.get_pool(tg.load_balancer_id, tg.pool_name)
        needs, patch = build_health_check_patch(tg.health_check, pool)
        if not needs:
            return False
        self.lbs.update_pool(tg.load_balancer_id, tg.pool_name, patch)
        metrics.API_REQUESTS.labels("lb", "update_pool", "ok").inc()
        log.info("health check patched", lb=tg.load_balancer_id,
                 pool=tg.pool_name, fields=sorted(patch))
        return True

    # -- deregistration (provider.go:98,180-207) ---------------------------

    def deregister_instance(self, integration: LoadBalancerIntegration,
                            address: str, instance_id: str = "") -> int:
        """Remove the node from each pool — by instance id when known
        (the reference's member lookup, provider.go:180-207), by address
        otherwise.  Continues past per-pool failures like the reference's
        per-target loop; the failure count is surfaced via
        :meth:`remove_targets` for callers that must retry."""
        removed, _ = self.remove_targets(integration.target_groups, address,
                                         instance_id=instance_id)
        return removed

    def remove_targets(self, targets, address: str,
                       instance_id: str = "") -> tuple[int, int]:
        """Remove the node from each target pool; returns
        (members_removed, failures).  Lookup by ``instance_id`` when
        given (members already gone are skipped silently,
        provider.go:195), by address otherwise.  A non-zero failure count
        means the caller must retry — the member may still be serving
        traffic."""
        removed = failures = 0
        for tg in targets:
            try:
                if instance_id:
                    member = self.lbs.find_member_by_instance(
                        tg.load_balancer_id, tg.pool_name, instance_id)
                    if member is None:
                        continue
                    self.lbs.delete_member(tg.load_balancer_id, tg.pool_name,
                                           member.id)
                    removed += 1
                else:
                    removed += self.lbs.remove_member(
                        tg.load_balancer_id, tg.pool_name, address)
                metrics.API_REQUESTS.labels("lb", "remove_member", "ok").inc()
            except CloudError as e:
                if is_not_found(e):
                    continue   # pool/member gone = nothing left to remove
                failures += 1
                metrics.API_REQUESTS.labels("lb", "remove_member", "error").inc()
                log.warning("deregister failed", lb=tg.load_balancer_id,
                            pool=tg.pool_name, error=str(e))
        return removed, failures

    # -- wait-healthy (provider.go:246-274) --------------------------------

    def wait_member_healthy(self, lb_id: str, pool_name: str, member_id: str,
                            timeout: float) -> None:
        """Poll the member THROUGH THE API until health == ok.  A member
        that settles faulted fails immediately (no point burning the
        whole timeout on a dead backend); transient get errors are
        retried like the reference's poll loop."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                member = self.lbs.get_member(lb_id, pool_name, member_id)
            except CloudError as e:
                if is_not_found(e):
                    raise
                time.sleep(self.poll_interval)
                continue
            if member.health == "ok":
                return
            if member.health == "faulted":
                raise CloudError(
                    f"member {member_id} faulted in pool {pool_name}",
                    503, code="member_faulted", retryable=True)
            time.sleep(self.poll_interval)
        raise CloudError(f"member {member_id} not healthy after {timeout}s",
                         408, code="timeout", retryable=True)

    # -- configuration validation against the live API (provider.go:277) ----

    def validate_configuration(self,
                               integration: LoadBalancerIntegration
                               ) -> list[str]:
        """Spec rules plus existence checks: LB reachable, pool present."""
        errs = validate_integration(integration)
        if errs or not integration.enabled:
            return errs
        for i, tg in enumerate(integration.target_groups):
            try:
                self.lbs.get_load_balancer(tg.load_balancer_id)
            except CloudError:
                errs.append(f"target group {i}: load balancer "
                            f"{tg.load_balancer_id} not found")
                continue
            try:
                self.lbs.get_pool(tg.load_balancer_id, tg.pool_name)
            except CloudError:
                errs.append(f"target group {i}: pool {tg.pool_name} "
                            f"not found")
        return errs
