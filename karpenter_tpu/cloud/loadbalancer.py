"""Load balancer provider: node registration in LB pools.

Capability parity with ``pkg/providers/loadbalancer/provider.go``:
``register_instance`` adds the node IP to each configured target pool
(:69) and waits for the member to report healthy (:246);
``deregister_instance`` removes it; health-check config validation mirrors
:277 and the patch builder ``healthcheck.go:44-145``.  The fake LB state
lives here too (the reference talks to VPC LB REST; tests use pkg/fake).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.apis.nodeclass import HealthCheck, LoadBalancerIntegration, LoadBalancerTarget
from karpenter_tpu.cloud.errors import CloudError, is_not_found, not_found
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.loadbalancer")


@dataclass
class PoolMember:
    id: str
    address: str
    port: int
    weight: int = 50
    health: str = "unknown"      # unknown | ok | faulted
    created_at: float = field(default_factory=time.time)


@dataclass
class FakePool:
    id: str
    lb_id: str
    name: str
    members: Dict[str, PoolMember] = field(default_factory=dict)
    health_check: Optional[HealthCheck] = None


class FakeLoadBalancers:
    """In-memory LB API double (pool/member CRUD, ref vpc.go:516-669)."""

    def __init__(self, healthy_after: float = 0.0):
        self._lock = threading.RLock()
        self.pools: Dict[Tuple[str, str], FakePool] = {}   # (lb, pool name)
        self._seq = 0
        self.healthy_after = healthy_after   # member health settle delay

    def ensure_pool(self, lb_id: str, pool_name: str) -> FakePool:
        with self._lock:
            key = (lb_id, pool_name)
            if key not in self.pools:
                self._seq += 1
                self.pools[key] = FakePool(id=f"lbpool-{self._seq}",
                                           lb_id=lb_id, name=pool_name)
            return self.pools[key]

    def get_pool(self, lb_id: str, pool_name: str) -> FakePool:
        with self._lock:
            pool = self.pools.get((lb_id, pool_name))
            if pool is None:
                raise not_found("lb_pool", f"{lb_id}/{pool_name}")
            return pool

    def add_member(self, lb_id: str, pool_name: str, address: str, port: int,
                   weight: int) -> PoolMember:
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            for m in pool.members.values():
                if m.address == address and m.port == port:
                    return m   # idempotent
            self._seq += 1
            member = PoolMember(id=f"member-{self._seq}", address=address,
                                port=port, weight=weight)
            pool.members[member.id] = member
            return member

    def remove_member(self, lb_id: str, pool_name: str, address: str) -> int:
        with self._lock:
            pool = self.get_pool(lb_id, pool_name)
            gone = [mid for mid, m in pool.members.items()
                    if m.address == address]
            for mid in gone:
                del pool.members[mid]
            return len(gone)

    def member_health(self, member: PoolMember) -> str:
        if member.health != "unknown":
            return member.health
        if time.time() - member.created_at >= self.healthy_after:
            member.health = "ok"
        return member.health

    def set_health_check(self, lb_id: str, pool_name: str,
                         hc: HealthCheck) -> None:
        with self._lock:
            self.get_pool(lb_id, pool_name).health_check = hc


def validate_integration(integration: LoadBalancerIntegration) -> List[str]:
    """(ref provider.go:277 config validation)"""
    errs: List[str] = []
    if not integration.enabled:
        return errs
    if not integration.target_groups:
        errs.append("loadBalancerIntegration.enabled requires targetGroups")
    for i, tg in enumerate(integration.target_groups):
        prefix = f"targetGroups[{i}]"
        if not tg.load_balancer_id:
            errs.append(f"{prefix}.loadBalancerID is required")
        if not tg.pool_name:
            errs.append(f"{prefix}.poolName is required")
        if not (1 <= tg.port <= 65535):
            errs.append(f"{prefix}.port {tg.port} out of range")
        if not (0 <= tg.weight <= 100):
            errs.append(f"{prefix}.weight {tg.weight} out of range")
        hc = tg.health_check
        if hc is not None:
            if hc.protocol not in ("tcp", "http", "https"):
                errs.append(f"{prefix}.healthCheck.protocol invalid")
            if hc.port and not (1 <= hc.port <= 65535):
                errs.append(f"{prefix}.healthCheck.port out of range")
            if hc.interval < 2 or hc.timeout < 1 or hc.timeout >= hc.interval:
                errs.append(f"{prefix}.healthCheck timing invalid "
                            "(timeout must be < interval, interval >= 2)")
    return errs


class LoadBalancerProvider:
    def __init__(self, lbs: Optional[FakeLoadBalancers] = None):
        self.lbs = lbs or FakeLoadBalancers()

    def register_instance(self, integration: LoadBalancerIntegration,
                          address: str, wait_healthy: bool = False,
                          timeout: float = 5.0) -> List[str]:
        """Adds the address to every target pool; returns member ids
        (ref RegisterInstance provider.go:69, wait-healthy :246)."""
        errs = validate_integration(integration)
        if errs:
            raise CloudError("invalid loadBalancerIntegration: " +
                             "; ".join(errs), 400, retryable=False)
        member_ids: List[str] = []
        for tg in integration.target_groups:
            pool = self.lbs.ensure_pool(tg.load_balancer_id, tg.pool_name)
            if tg.health_check is not None and \
                    pool.health_check != tg.health_check:
                self.lbs.set_health_check(tg.load_balancer_id, tg.pool_name,
                                          tg.health_check)
            member = self.lbs.add_member(tg.load_balancer_id, tg.pool_name,
                                         address, tg.port, tg.weight)
            member_ids.append(member.id)
            metrics.API_REQUESTS.labels("lb", "add_member", "ok").inc()
            if wait_healthy:
                self._wait_healthy(member, timeout)
        return member_ids

    def deregister_instance(self, integration: LoadBalancerIntegration,
                            address: str) -> int:
        removed, _ = self.remove_targets(integration.target_groups, address)
        return removed

    def remove_targets(self, targets, address: str) -> Tuple[int, int]:
        """Remove ``address`` from each target pool; returns
        (members_removed, failures).  A non-zero failure count means the
        caller must retry — the member may still be serving traffic."""
        removed = failures = 0
        for tg in targets:
            try:
                removed += self.lbs.remove_member(tg.load_balancer_id,
                                                  tg.pool_name, address)
                metrics.API_REQUESTS.labels("lb", "remove_member", "ok").inc()
            except CloudError as e:
                if is_not_found(e):
                    continue   # pool gone = nothing left to remove
                failures += 1
                metrics.API_REQUESTS.labels("lb", "remove_member", "error").inc()
                log.warning("deregister failed", lb=tg.load_balancer_id,
                            pool=tg.pool_name, error=str(e))
        return removed, failures

    def _wait_healthy(self, member: PoolMember, timeout: float) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.lbs.member_health(member) == "ok":
                return
            time.sleep(0.05)
        raise CloudError(f"member {member.id} not healthy after {timeout}s",
                         408, code="timeout", retryable=True)
