"""Stateful in-memory fake IKS (managed-cluster) API.

Parity with the reference's IKS double (``pkg/fake/iksapi.go:29-485``):
worker pools with per-zone sizing, **atomic** increment/decrement resize
(the race-free resize idiom of ``pkg/cloudprovider/ibm/iks.go:406``),
worker -> VPC instance mapping, call recording and error injection via the
shared :class:`~karpenter_tpu.cloud.fake.CallRecorder`.

Workers materialize real instances in the attached :class:`FakeCloud`, so
the node-join continuation and GC sweeps behave identically across VPC and
IKS actuation modes.
"""

from __future__ import annotations

import itertools
import threading

from karpenter_tpu.cloud.errors import CloudError, not_found
from karpenter_tpu.cloud.fake import CallRecorder, FakeCloud
from karpenter_tpu.cloud.resources import Worker, WorkerPool

# Historical names — DTOs live in cloud/resources.py, shared with the
# HTTP-backed IKS client.
FakeWorkerPool = WorkerPool
FakeWorker = Worker


class FakeIKS:
    """One fake IKS cluster backed by a FakeCloud for instances."""

    def __init__(self, cluster_id: str, cloud: FakeCloud,
                 kube_version: str = "1.32.3"):
        self.cluster_id = cluster_id
        self.cloud = cloud
        self.kube_version = kube_version
        self.recorder = CallRecorder()
        self.pools: dict[str, FakeWorkerPool] = {}
        self.workers: dict[str, FakeWorker] = {}
        self._lock = threading.RLock()
        self._pool_seq = itertools.count(1)
        self._worker_seq = itertools.count(1)

    # -- pool CRUD (ref iks.go:317-469, 559-633) ---------------------------

    def list_pools(self) -> list[FakeWorkerPool]:
        self.recorder.record("list_pools")
        self.recorder.maybe_raise("list_pools")
        with self._lock:
            return list(self.pools.values())

    def get_pool(self, pool_id: str) -> FakeWorkerPool:
        self.recorder.record("get_pool", pool_id)
        self.recorder.maybe_raise("get_pool")
        with self._lock:
            pool = self.pools.get(pool_id)
            if pool is None:
                raise not_found("worker_pool", pool_id)
            return pool

    def get_pool_by_name(self, name: str) -> FakeWorkerPool | None:
        with self._lock:
            for pool in self.pools.values():
                if pool.name == name:
                    return pool
        return None

    def create_pool(self, name: str, flavor: str, zones: list[str],
                    size_per_zone: int = 0, labels: dict[str, str] | None = None,
                    dynamic: bool = False) -> FakeWorkerPool:
        self.recorder.record("create_pool", name, flavor)
        self.recorder.maybe_raise("create_pool")
        with self._lock:
            if self.get_pool_by_name(name) is not None:
                raise CloudError(f"worker pool {name!r} already exists", 409,
                                 code="already_exists", retryable=False)
            pool = FakeWorkerPool(
                id=f"pool-{next(self._pool_seq)}", name=name, flavor=flavor,
                zones=list(zones), size_per_zone=size_per_zone,
                labels=dict(labels or {}), dynamic=dynamic)
            self.pools[pool.id] = pool
            for zone in pool.zones:
                for _ in range(size_per_zone):
                    self._add_worker_locked(pool, zone)
            return pool

    def delete_pool(self, pool_id: str) -> None:
        self.recorder.record("delete_pool", pool_id)
        self.recorder.maybe_raise("delete_pool")
        with self._lock:
            pool = self.pools.get(pool_id)
            if pool is None:
                raise not_found("worker_pool", pool_id)
            for worker in [w for w in self.workers.values()
                           if w.pool_id == pool_id]:
                self._remove_worker_locked(worker)
            del self.pools[pool_id]

    def add_pool_zone(self, pool_id: str, zone: str) -> None:
        """Idempotently extend a pool's zone list (locked — callers must
        not check-then-append on the pool object directly)."""
        self.recorder.record("add_pool_zone", pool_id, zone)
        self.recorder.maybe_raise("add_pool_zone")
        with self._lock:
            pool = self.pools.get(pool_id)
            if pool is None:
                raise not_found("worker_pool", pool_id)
            if zone not in pool.zones:
                pool.zones.append(zone)

    # -- atomic resize (ref iks.go:406 atomic increment) -------------------

    def increment_pool(self, pool_id: str, zone: str) -> FakeWorker:
        """Atomically grow the pool by one worker in ``zone`` and return the
        new worker — callers never read-modify-write a size field, so
        concurrent increments can't lose updates."""
        self.recorder.record("increment_pool", pool_id, zone)
        self.recorder.maybe_raise("increment_pool")
        with self._lock:
            pool = self.pools.get(pool_id)
            if pool is None:
                raise not_found("worker_pool", pool_id)
            if zone not in pool.zones:
                raise CloudError(f"pool {pool.name} has no zone {zone}", 400,
                                 code="bad_request", retryable=False)
            worker = self._add_worker_locked(pool, zone)
            pool.size_per_zone = max(
                len([w for w in self.workers.values()
                     if w.pool_id == pool_id and w.zone == z])
                for z in pool.zones)
            return worker

    def decrement_pool(self, pool_id: str, worker_id: str) -> None:
        """Atomically remove one specific worker."""
        self.recorder.record("decrement_pool", pool_id, worker_id)
        self.recorder.maybe_raise("decrement_pool")
        with self._lock:
            worker = self.workers.get(worker_id)
            if worker is None or worker.pool_id != pool_id:
                raise not_found("worker", worker_id)
            self._remove_worker_locked(worker)
            pool = self.pools.get(pool_id)
            if pool is not None and pool.zones:
                pool.size_per_zone = max(
                    len([w for w in self.workers.values()
                         if w.pool_id == pool_id and w.zone == z])
                    for z in pool.zones)

    # -- workers (ref iks.go:161-232) --------------------------------------

    def list_workers(self, pool_id: str | None = None) -> list[FakeWorker]:
        self.recorder.record("list_workers")
        self.recorder.maybe_raise("list_workers")
        with self._lock:
            return [w for w in self.workers.values()
                    if pool_id is None or w.pool_id == pool_id]

    def get_worker(self, worker_id: str) -> FakeWorker:
        self.recorder.record("get_worker", worker_id)
        self.recorder.maybe_raise("get_worker")
        with self._lock:
            worker = self.workers.get(worker_id)
            if worker is None:
                raise not_found("worker", worker_id)
            return worker

    def worker_instance_id(self, worker_id: str) -> str:
        """Worker -> VPC instance mapping (ref iks.go:195)."""
        return self.get_worker(worker_id).instance_id

    def register_worker(self, instance_id: str, pool_id: str = "") -> FakeWorker:
        """IKS-API bootstrap: register an EXISTING VPC instance as a
        cluster worker (ref AddWorkerToIKSCluster, iks_api.go:53) — the
        control plane joins the node, no cloud-init token dance."""
        self.recorder.record("register_worker", instance_id, pool_id)
        self.recorder.maybe_raise("register_worker")
        inst = self.cloud.get_instance(instance_id)
        with self._lock:
            if pool_id and pool_id not in self.pools:
                raise not_found("worker_pool", pool_id)
            worker = FakeWorker(id=f"worker-{inst.id}", pool_id=pool_id,
                                zone=inst.zone, instance_id=inst.id)
            self.workers[worker.id] = worker
            return worker

    def get_cluster_config(self) -> dict:
        """Cluster config for bootstrap decisions (ref iks.go:248)."""
        self.recorder.record("get_cluster_config")
        self.recorder.maybe_raise("get_cluster_config")
        return {"cluster_id": self.cluster_id,
                "kube_version": self.kube_version,
                "api_endpoint": f"https://{self.cluster_id}.cluster.local:6443",
                "ca_bundle": "fake-ca"}

    def deploy_worker(self, worker_id: str) -> None:
        """Test hook: worker finishes provisioning."""
        with self._lock:
            if worker_id in self.workers:
                self.workers[worker_id].state = "deployed"

    # -- internals ---------------------------------------------------------

    def _add_worker_locked(self, pool: FakeWorkerPool, zone: str) -> FakeWorker:
        # caller holds self._lock (RLock; the _locked contract)
        subnet = next((s for s in self.cloud.list_subnets() if s.zone == zone),
                      None)
        images = self.cloud.list_images()   # IKS-managed worker image
        inst = self.cloud.create_instance(
            name=f"iks-{pool.name}-{next(self._worker_seq)}",
            profile=pool.flavor, zone=zone,
            subnet_id=subnet.id if subnet else "",
            image_id=images[0].id if images else "",
            tags={"iks.io/cluster": self.cluster_id,
                  "iks.io/pool": pool.id})
        worker = FakeWorker(id=f"worker-{inst.id}", pool_id=pool.id,
                            zone=zone, instance_id=inst.id)
        self.workers[worker.id] = worker
        return worker

    def _remove_worker_locked(self, worker: FakeWorker) -> None:
        # caller holds self._lock (RLock; the _locked contract)
        try:
            self.cloud.delete_instance(worker.instance_id)
        except CloudError:
            pass
        self.workers.pop(worker.id, None)
