"""Cached cloud-client manager.

Parity with ``pkg/utils/vpcclient`` (manager.go:52-148): client
construction is expensive (auth handshake), so a TTL-cached instance is
shared, with explicit invalidation on auth failures and an error-logging
helper that classifies through the shared taxonomy.
"""

from __future__ import annotations

import threading
import time
from collections.abc import Callable
from typing import Generic, TypeVar

from karpenter_tpu.cloud.errors import is_auth, parse_error
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.client_manager")

C = TypeVar("C")


class ClientManager(Generic[C]):
    """TTL-cached client with invalidate-on-auth-failure."""

    def __init__(self, build: Callable[[], C], ttl: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        from karpenter_tpu.constants import DEFAULT_CLIENT_CACHE_TTL_SECONDS

        self._build = build
        self._ttl = float(DEFAULT_CLIENT_CACHE_TTL_SECONDS) if ttl is None \
            else ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._client: C | None = None
        self._built_at = -float("inf")

    def get(self) -> C:
        with self._lock:
            if self._client is None or \
                    self._clock() - self._built_at >= self._ttl:
                self._client = self._build()
                self._built_at = self._clock()
            return self._client

    def invalidate(self) -> None:
        with self._lock:
            self._client = None
            self._built_at = -float("inf")

    def call(self, op: Callable[[C], object], operation: str = "call"):
        """Run ``op(client)``; on auth errors the cached client is dropped
        so the next call re-authenticates (manager.go invalidation +
        HandleVPCError logging semantics)."""
        try:
            return op(self.get())
        except Exception as e:
            err = parse_error(e, operation)
            if is_auth(err):
                log.warning("auth failure; invalidating cached client",
                            operation=operation, error=str(err))
                self.invalidate()
            raise
