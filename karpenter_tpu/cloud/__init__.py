from karpenter_tpu.cloud.errors import (
    CloudError, is_not_found, is_rate_limit, is_retryable, is_timeout,
)
from karpenter_tpu.cloud.retry import retry_with_backoff, RetryConfig
from karpenter_tpu.cloud.fake import FakeCloud, FakeInstance, FakeSubnet, FakeImage

__all__ = [
    "CloudError", "is_not_found", "is_rate_limit", "is_retryable", "is_timeout",
    "retry_with_backoff", "RetryConfig",
    "FakeCloud", "FakeInstance", "FakeSubnet", "FakeImage",
]
