"""Cloud resource DTOs shared by every cloud-client implementation.

The provider layer (actuator, subnet/image providers, controllers) is
written against these plain dataclasses; the in-memory fake
(``cloud/fake.py``) and the HTTP-backed clients (``cloud/vpc.py``,
``cloud/iks.py``) both return them, so the two implementations are
interchangeable behind the same seam (ref ``pkg/cloudprovider/ibm/vpc.go:70``
wraps the SDK types the same way for its consumers).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class Instance:
    id: str
    name: str
    profile: str
    zone: str
    subnet_id: str
    image_id: str
    capacity_type: str = "on-demand"   # availability policy analogue
    status: str = "running"            # pending|running|stopped|deleting
    status_reason: str = ""
    health_state: str = "ok"           # ok|degraded|faulted (metadata svc)
    tags: dict[str, str] = field(default_factory=dict)
    security_group_ids: tuple[str, ...] = ()
    vni_id: str = ""
    volume_ids: tuple[str, ...] = ()
    user_data: str = ""
    created_at: float = field(default_factory=time.time)
    ip_address: str = ""


@dataclass
class Subnet:
    id: str
    zone: str
    total_ips: int = 256
    available_ips: int = 256
    state: str = "available"
    tags: dict[str, str] = field(default_factory=dict)
    vpc_id: str = "vpc-1"


@dataclass
class Image:
    id: str
    name: str                          # e.g. "ubuntu-24-04-amd64"
    os: str = "ubuntu"
    architecture: str = "amd64"
    status: str = "available"
    visibility: str = "public"
    created_at: float = 0.0


@dataclass
class VNI:
    id: str
    subnet_id: str


@dataclass
class Volume:
    id: str
    capacity_gb: int
    profile: str


@dataclass
class WorkerPool:
    id: str
    name: str
    flavor: str                  # instance profile name
    zones: list[str]
    size_per_zone: int
    state: str = "normal"        # normal | resizing | deleting
    labels: dict[str, str] = field(default_factory=dict)
    dynamic: bool = False        # created by karpenter (eligible for cleanup)
    created_at: float = field(default_factory=time.time)


@dataclass
class Worker:
    id: str
    pool_id: str
    zone: str
    instance_id: str             # backing VPC instance
    state: str = "provisioning"  # provisioning | deployed | deleting
