"""Cloud error taxonomy.

Parity with ``pkg/cloudprovider/ibm/errors.go``: a typed error carrying
status code / error code / retryability (errors.go:54), parseable from
loose sources (:134-296), with the predicate set the rest of the system
branches on (:298-331).
"""

from __future__ import annotations


# Error codes (the cloud-API-level taxonomy).
CODE_NOT_FOUND = "not_found"
CODE_RATE_LIMIT = "rate_limited"
CODE_TIMEOUT = "timeout"
CODE_QUOTA_EXCEEDED = "quota_exceeded"
CODE_CAPACITY = "insufficient_capacity"
CODE_AUTH = "unauthorized"
CODE_CONFLICT = "conflict"
CODE_INVALID = "invalid_request"
CODE_INTERNAL = "internal_error"
CODE_UNAVAILABLE = "service_unavailable"

_RETRYABLE_CODES = {CODE_RATE_LIMIT, CODE_TIMEOUT, CODE_INTERNAL, CODE_UNAVAILABLE}
_RETRYABLE_STATUS = {408, 429, 500, 502, 503, 504}


class CloudError(Exception):
    """Typed cloud API error (ref IBMError, errors.go:54)."""

    def __init__(self, message: str, status_code: int = 0, code: str = "",
                 retryable: bool | None = None, retry_after: float = 0.0,
                 operation: str = ""):
        super().__init__(message)
        self.message = message
        self.status_code = status_code
        self.code = code or _code_from_status(status_code)
        self.retry_after = retry_after
        self.operation = operation
        if retryable is None:
            retryable = (self.code in _RETRYABLE_CODES
                         or status_code in _RETRYABLE_STATUS)
        self.retryable = retryable

    def __repr__(self):
        return (f"CloudError(code={self.code!r}, status={self.status_code}, "
                f"retryable={self.retryable}, msg={self.message!r})")


def _code_from_status(status: int) -> str:
    return {
        404: CODE_NOT_FOUND, 429: CODE_RATE_LIMIT, 408: CODE_TIMEOUT,
        401: CODE_AUTH, 403: CODE_AUTH, 409: CODE_CONFLICT,
        400: CODE_INVALID, 500: CODE_INTERNAL, 502: CODE_UNAVAILABLE,
        503: CODE_UNAVAILABLE, 504: CODE_TIMEOUT,
    }.get(status, "")


def not_found(resource: str, ident: str) -> CloudError:
    return CloudError(f"{resource} {ident!r} not found", status_code=404)


def parse_error(err: Exception, operation: str = "") -> CloudError:
    """Normalize any exception into a CloudError (ref ParseError,
    errors.go:134-296): typed errors pass through; strings are classified
    by substring heuristics."""
    if isinstance(err, CloudError):
        return err
    msg = str(err)
    lower = msg.lower()
    if "not found" in lower or "no such" in lower:
        return CloudError(msg, 404, operation=operation)
    if "rate limit" in lower or "too many requests" in lower:
        return CloudError(msg, 429, operation=operation)
    if "timeout" in lower or "timed out" in lower or "deadline" in lower:
        return CloudError(msg, 408, operation=operation)
    if "quota" in lower:
        return CloudError(msg, 403, code=CODE_QUOTA_EXCEEDED,
                          retryable=False, operation=operation)
    if "capacity" in lower or "out of stock" in lower:
        return CloudError(msg, 503, code=CODE_CAPACITY, retryable=False,
                          operation=operation)
    if "unauthorized" in lower or "forbidden" in lower or "invalid token" in lower:
        return CloudError(msg, 401, operation=operation)
    return CloudError(msg, 500, operation=operation)


# Predicates (errors.go:298-331).

def is_not_found(err: Exception) -> bool:
    return isinstance(err, CloudError) and err.code == CODE_NOT_FOUND


def is_rate_limit(err: Exception) -> bool:
    return isinstance(err, CloudError) and err.code == CODE_RATE_LIMIT


def is_timeout(err: Exception) -> bool:
    return isinstance(err, CloudError) and err.code == CODE_TIMEOUT


def is_retryable(err: Exception) -> bool:
    if isinstance(err, CloudError):
        return err.retryable
    return parse_error(err).retryable


def is_capacity(err: Exception) -> bool:
    return isinstance(err, CloudError) and err.code == CODE_CAPACITY


def is_quota(err: Exception) -> bool:
    return isinstance(err, CloudError) and err.code == CODE_QUOTA_EXCEEDED


def is_auth(err: Exception) -> bool:
    return isinstance(err, CloudError) and err.status_code in (401, 403)


class NodeClaimNotFoundError(Exception):
    """Signals the core lifecycle to release the finalizer — the instance is
    verifiably gone (ref contract at vpc/instance/provider.go:1041-1046)."""

    def __init__(self, claim_name: str):
        super().__init__(f"nodeclaim {claim_name!r}: instance not found")
        self.claim_name = claim_name
