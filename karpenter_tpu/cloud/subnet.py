"""Subnet provider: listing, scoring, placement-strategy selection.

Parity with ``pkg/providers/vpc/subnet/provider.go``:
- 5-minute list cache (:73-80, :346-414);
- score = available-IP ratio x100 - fragmentation x50 (:95-111);
- cluster-awareness bonus: +50 for subnets already hosting cluster nodes,
  +10 per node (capped), -5 for non-cluster subnets when cluster subnets
  exist (:327-344);
- zone distribution: Balanced = best per zone, AvailabilityFirst = all,
  CostOptimized = top 2 zones (:181-210).
"""

from __future__ import annotations

from dataclasses import dataclass
from collections.abc import Callable

from karpenter_tpu.apis.nodeclass import PlacementStrategy, SubnetSelectionCriteria
from karpenter_tpu.cloud.fake import FakeSubnet
from karpenter_tpu.utils.cache import TTLCache
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.subnet")


def subnet_score(subnet: FakeSubnet) -> float:
    """Higher is better (subnet/provider.go:95-111)."""
    if subnet.total_ips == 0:
        return 0.0
    capacity_ratio = subnet.available_ips / subnet.total_ips
    fragmentation = (subnet.total_ips - subnet.available_ips) / subnet.total_ips
    return capacity_ratio * 100.0 - fragmentation * 50.0


def apply_cluster_awareness(subnet: FakeSubnet, base: float,
                            cluster_subnets: dict[str, int]) -> float:
    """(subnet/provider.go:327-344)"""
    if not cluster_subnets:
        return base
    nodes = cluster_subnets.get(subnet.id, 0)
    if nodes > 0:
        return base + 50.0 + min(nodes * 10.0, 50.0)
    return base - 5.0


class SubnetProvider:
    CACHE_TTL = 300.0  # 5 min (:73-80)

    def __init__(self, client, cluster_subnets_fn: Callable[[], dict[str, int]] | None = None,
                 clock=None):
        """``cluster_subnets_fn`` returns {subnet_id: node_count} for nodes
        already in the cluster (ref walks providerID -> GetInstance,
        :247-310; here the cluster state supplies it directly)."""
        self._client = client
        self._cluster_subnets_fn = cluster_subnets_fn or (lambda: {})
        self._cache = TTLCache(default_ttl=self.CACHE_TTL,
                               **({"clock": clock} if clock else {}))

    def list_subnets(self) -> list[FakeSubnet]:
        return self._cache.get_or_set("subnets", self._client.list_subnets)

    def get_subnet(self, subnet_id: str) -> FakeSubnet:
        return self._client.get_subnet(subnet_id)

    def invalidate(self) -> None:
        self._cache.delete("subnets")

    def select_subnets(self, strategy: PlacementStrategy | None) -> list[FakeSubnet]:
        """Filter -> score -> zone-distribute (:114-217)."""
        strategy = strategy or PlacementStrategy()
        criteria = strategy.subnet_selection or SubnetSelectionCriteria()
        eligible = []
        for s in self.list_subnets():
            if s.state != "available":
                continue
            if criteria.minimum_available_ips > 0 and \
                    s.available_ips < criteria.minimum_available_ips:
                continue
            if criteria.required_tags:
                if any(s.tags.get(k) != v for k, v in criteria.required_tags):
                    continue
            eligible.append(s)
        if not eligible:
            raise ValueError("no eligible subnets found")

        cluster_subnets = self._cluster_subnets_fn()
        scored = sorted(
            eligible,
            key=lambda s: apply_cluster_awareness(s, subnet_score(s), cluster_subnets),
            reverse=True)

        selected: list[FakeSubnet] = []
        seen_zones = set()
        if strategy.zone_balance == "Balanced":
            for s in scored:
                if s.zone not in seen_zones:
                    selected.append(s)
                    seen_zones.add(s.zone)
        elif strategy.zone_balance == "AvailabilityFirst":
            selected = scored
        elif strategy.zone_balance == "CostOptimized":
            for s in scored:
                if len(selected) >= 2:
                    break
                if s.zone not in seen_zones:
                    selected.append(s)
                    seen_zones.add(s.zone)
        else:
            raise ValueError(f"unknown zone balance {strategy.zone_balance!r}")
        if not selected:
            raise ValueError("no subnets selected after applying placement strategy")
        return selected

    def best_subnet_in_zone(self, zone: str) -> FakeSubnet | None:
        """Most-free-IPs subnet in a zone (ref create-path fallback,
        vpc/instance/provider.go:243-329)."""
        candidates = [s for s in self.list_subnets()
                      if s.zone == zone and s.state == "available" and s.available_ips > 0]
        return max(candidates, key=lambda s: s.available_ips, default=None)
