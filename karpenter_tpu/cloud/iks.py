"""HTTP-backed IKS (managed-cluster) client — real counterpart to FakeIKS.

Capability parity with ``pkg/cloudprovider/ibm/iks.go:56`` (worker
details :161, worker->VPC instance mapping :195, cluster kubeconfig :248,
pool list/resize with **atomic increment/decrement** :317-469, pool
create/delete :559-633) and the IKS-API bootstrap flow of
``pkg/providers/iks/bootstrap/iks_api.go:53`` (``AddWorkerToIKSCluster`` +
cluster-config retrieval).

Same provider-facing surface as :class:`~karpenter_tpu.cloud.fake_iks.FakeIKS`
(minus the ``deploy_worker`` test hook), so the worker-pool actuator runs
unmodified against either implementation.

Wire protocol (v2-flavored; the stub server in ``cloud/stub.py`` speaks it):

=====================================================  ======================
``GET    /v2/clusters/{c}/workerpools``                list pools
``POST   /v2/clusters/{c}/workerpools``                create pool
``GET    /v2/clusters/{c}/workerpools/{p}``            get pool
``DELETE /v2/clusters/{c}/workerpools/{p}``            delete pool
``POST   /v2/clusters/{c}/workerpools/{p}/zones``      add zone
``POST   /v2/clusters/{c}/workerpools/{p}/increment``  atomic +1 -> worker
``POST   /v2/clusters/{c}/workerpools/{p}/decrement``  atomic -1 (by worker)
``GET    /v2/clusters/{c}/workers[?pool=]``            list workers
``GET    /v2/clusters/{c}/workers/{id}``               get worker
``POST   /v2/clusters/{c}/workers``                    register an existing
                                                       VPC instance as a
                                                       worker (iks_api.go:53)
``GET    /v2/clusters/{c}/config``                     cluster config (API
                                                       endpoint, CA, version)
=====================================================  ======================
"""

from __future__ import annotations


from karpenter_tpu.cloud.http import HTTPClient, TokenSource
from karpenter_tpu.cloud.resources import Worker, WorkerPool


def pool_to_json(p: WorkerPool) -> dict:
    return {"id": p.id, "name": p.name, "flavor": p.flavor,
            "zones": list(p.zones), "size_per_zone": p.size_per_zone,
            "state": p.state, "labels": dict(p.labels),
            "dynamic": p.dynamic, "created_at": p.created_at}


def pool_from_json(d: dict) -> WorkerPool:
    return WorkerPool(
        id=d["id"], name=d.get("name", ""), flavor=d.get("flavor", ""),
        zones=list(d.get("zones") or []),
        size_per_zone=int(d.get("size_per_zone", 0)),
        state=d.get("state", "normal"), labels=dict(d.get("labels") or {}),
        dynamic=bool(d.get("dynamic", False)),
        created_at=float(d.get("created_at", 0.0)))


def worker_to_json(w: Worker) -> dict:
    return {"id": w.id, "pool_id": w.pool_id, "zone": w.zone,
            "instance_id": w.instance_id, "state": w.state}


def worker_from_json(d: dict) -> Worker:
    return Worker(id=d["id"], pool_id=d.get("pool_id", ""),
                  zone=d.get("zone", ""),
                  instance_id=d.get("instance_id", ""),
                  state=d.get("state", "provisioning"))


class IKSClient:
    """Provider-facing IKS client speaking the REST protocol above."""

    def __init__(self, endpoint: str, cluster_id: str, api_key: str = "",
                 token_source: TokenSource | None = None,
                 timeout: float = 30.0, opener=None, sleep=None):
        self.cluster_id = cluster_id
        kw = {}
        if opener is not None:
            kw["opener"] = opener
        if sleep is not None:
            kw["sleep"] = sleep
        tokens = token_source
        if tokens is None and api_key:
            iam = HTTPClient(endpoint, "iam", timeout=timeout, **kw)
            tokens = TokenSource(lambda: iam.post(
                "/identity/token", {"apikey": api_key}, operation="token"))
        self.http = HTTPClient(endpoint, "iks", token_source=tokens,
                               timeout=timeout, **kw)
        self._base = f"/v2/clusters/{cluster_id}"

    # -- pool CRUD (ref iks.go:317-469, 559-633) ---------------------------

    def list_pools(self) -> list[WorkerPool]:
        data = self.http.get(f"{self._base}/workerpools", "list_pools")
        return [pool_from_json(p) for p in data.get("workerpools", [])]

    def get_pool(self, pool_id: str) -> WorkerPool:
        return pool_from_json(self.http.get(
            f"{self._base}/workerpools/{pool_id}", "get_pool"))

    def get_pool_by_name(self, name: str) -> WorkerPool | None:
        for pool in self.list_pools():
            if pool.name == name:
                return pool
        return None

    def create_pool(self, name: str, flavor: str, zones: list[str],
                    size_per_zone: int = 0,
                    labels: dict[str, str] | None = None,
                    dynamic: bool = False) -> WorkerPool:
        body = {"name": name, "flavor": flavor, "zones": list(zones),
                "size_per_zone": size_per_zone, "labels": dict(labels or {}),
                "dynamic": dynamic}
        return pool_from_json(self.http.post(
            f"{self._base}/workerpools", body, "create_pool"))

    def delete_pool(self, pool_id: str) -> None:
        self.http.delete(f"{self._base}/workerpools/{pool_id}", "delete_pool")

    def add_pool_zone(self, pool_id: str, zone: str) -> None:
        self.http.post(f"{self._base}/workerpools/{pool_id}/zones",
                       {"zone": zone}, "add_pool_zone")

    # -- atomic resize (ref iks.go:406) ------------------------------------

    def increment_pool(self, pool_id: str, zone: str) -> Worker:
        """Server-side atomic +1: callers never read-modify-write a size
        field, so concurrent increments cannot lose updates."""
        return worker_from_json(self.http.post(
            f"{self._base}/workerpools/{pool_id}/increment",
            {"zone": zone}, "increment_pool"))

    def decrement_pool(self, pool_id: str, worker_id: str) -> None:
        self.http.post(f"{self._base}/workerpools/{pool_id}/decrement",
                       {"worker_id": worker_id}, "decrement_pool")

    # -- workers (ref iks.go:161-232) --------------------------------------

    def list_workers(self, pool_id: str | None = None) -> list[Worker]:
        path = f"{self._base}/workers"
        if pool_id:
            path += f"?pool={pool_id}"
        data = self.http.get(path, "list_workers")
        return [worker_from_json(w) for w in data.get("workers", [])]

    def get_worker(self, worker_id: str) -> Worker:
        return worker_from_json(self.http.get(
            f"{self._base}/workers/{worker_id}", "get_worker"))

    def worker_instance_id(self, worker_id: str) -> str:
        """Worker -> VPC instance mapping (ref iks.go:195)."""
        return self.get_worker(worker_id).instance_id

    # -- IKS-API bootstrap (ref iks_api.go:53) -----------------------------

    def register_worker(self, instance_id: str,
                        pool_id: str = "") -> Worker:
        """Register an existing VPC instance as a cluster worker — the
        ``AddWorkerToIKSCluster`` flow: the IKS control plane installs the
        kubelet and joins the node, no cloud-init token dance required."""
        body = {"instance_id": instance_id}
        if pool_id:
            body["pool_id"] = pool_id
        return worker_from_json(self.http.post(
            f"{self._base}/workers", body, "register_worker"))

    def get_cluster_config(self) -> dict:
        """Cluster config for bootstrap decisions (ref iks.go:248 cluster
        kubeconfig retrieval): API endpoint, CA bundle, kube version."""
        return self.http.get(f"{self._base}/config", "get_cluster_config")
