"""Raw cloud instance profile (the VPC instance-profile analogue).

Lives at the bottom of the cloud layer so both the fake cloud and the
catalog can import it without cycles.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class InstanceProfile:
    name: str                    # e.g. "bx2-4x16"
    cpu: int                     # vCPUs
    memory_gib: int
    architecture: str = "amd64"
    gpu: int = 0
    gpu_model: str = ""
    supports_spot: bool = True
    bandwidth_gbps: int = 16
