"""Stateful in-memory fake cloud.

Parity with the reference's ``pkg/fake`` doubles (fake/vpcapi.go:32-56,
108-461; atomic.go:26-96): per-method call recording, injectable
``next_error``, atomic state slices — the layer every provisioning test
runs against instead of a real cloud.

Also ships a deterministic synthetic catalog generator
(:func:`generate_profiles`) producing IBM-VPC-shaped profile ladders
(bx2 1:4, cx2 1:2, mx2 1:8, gx3 gpu) with a price model, so benchmarks can
scale the catalog to 500+ types (BASELINE.json configs).
"""

from __future__ import annotations

import itertools
import threading
from collections import defaultdict

from karpenter_tpu.cloud.errors import CloudError, not_found
from karpenter_tpu.cloud.profile import InstanceProfile
from karpenter_tpu.cloud.resources import VNI, Image, Instance, Subnet, Volume

# Historical names — the DTOs moved to cloud/resources.py so the HTTP
# clients share them; existing imports keep working.
FakeInstance = Instance
FakeSubnet = Subnet
FakeImage = Image
FakeVNI = VNI
FakeVolume = Volume


def _snap(obj):
    """Deep-enough copy of a fake resource: mutable containers are copied so
    snapshots handed to callers are isolated from live fake-cloud state."""
    import dataclasses
    kw = {}
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if isinstance(v, dict):
            v = dict(v)
        elif isinstance(v, list):
            v = list(v)
        kw[f.name] = v
    return type(obj)(**kw)


class CallRecorder:
    """Per-method call capture + one-shot error injection (ref
    MockedFunction, fake/atomic.go:26-96)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.calls: dict[str, list[tuple]] = defaultdict(list)
        self._next_errors: dict[str, list[Exception]] = defaultdict(list)
        self._persistent_errors: dict[str, Exception] = {}

    def record(self, method: str, *args) -> None:
        with self._lock:
            self.calls[method].append(args)

    def call_count(self, method: str) -> int:
        with self._lock:
            return len(self.calls[method])

    def inject_error(self, method: str, err: Exception, times: int = 1) -> None:
        with self._lock:
            self._next_errors[method].extend([err] * times)

    def set_persistent_error(self, method: str, err: Exception | None) -> None:
        with self._lock:
            if err is None:
                self._persistent_errors.pop(method, None)
            else:
                self._persistent_errors[method] = err

    def maybe_raise(self, method: str) -> None:
        with self._lock:
            queue = self._next_errors[method]
            err = queue.pop(0) if queue else self._persistent_errors.get(method)
        if err is not None:
            raise err

    def reset(self) -> None:
        with self._lock:
            self.calls.clear()
            self._next_errors.clear()
            self._persistent_errors.clear()


_FAMILIES = {
    # family -> (mem_per_cpu_gib, gpu_per_8cpu, base price per cpu-hour)
    "bx2": (4, 0, 0.0475),
    "cx2": (2, 0, 0.0415),
    "mx2": (8, 0, 0.0555),
    "ux2d": (28, 0, 0.1320),
    "gx3": (8, 1, 0.4200),
}
_CPU_LADDER = (2, 4, 8, 16, 24, 32, 48, 64, 96, 128)


def generate_profiles(count: int = 20, families: tuple[str, ...] = ("bx2", "cx2", "mx2"),
                      arch: str = "amd64") -> list[InstanceProfile]:
    """Deterministic IBM-shaped profile ladder of ``count`` types."""
    out: list[InstanceProfile] = []
    for family, cpu in itertools.product(families, _CPU_LADDER):
        if len(out) >= count:
            break
        mem_ratio, gpu_per8, _ = _FAMILIES[family]
        gpu = (cpu // 8) * gpu_per8
        name = f"{family}-{cpu}x{cpu * mem_ratio}"
        out.append(InstanceProfile(name=name, cpu=cpu, memory_gib=cpu * mem_ratio,
                                   architecture=arch, gpu=gpu,
                                   supports_spot=(family != "ux2d")))
    # widen with synthetic variant suffixes when count exceeds the real ladder
    variant = 2
    while len(out) < count:
        for family, cpu in itertools.product(families, _CPU_LADDER):
            if len(out) >= count:
                break
            mem_ratio, gpu_per8, _ = _FAMILIES[family]
            name = f"{family}v{variant}-{cpu}x{cpu * mem_ratio}"
            out.append(InstanceProfile(name=name, cpu=cpu,
                                       memory_gib=cpu * mem_ratio,
                                       architecture=arch,
                                       gpu=(cpu // 8) * gpu_per8))
        variant += 1
    return out[:count]


def profile_price(profile: InstanceProfile) -> float:
    """Deterministic on-demand $/h for a synthetic profile."""
    fam = next((f for f in _FAMILIES if profile.name.startswith(f)), "bx2")
    per_cpu = _FAMILIES[fam][2]
    price = profile.cpu * per_cpu + profile.gpu * 0.95
    # mild variant premium so duplicated ladders aren't price-identical
    if "v2-" in profile.name:
        price *= 1.07
    elif "v3-" in profile.name:
        price *= 1.15
    return round(price, 4)


class FakeCloud:
    """In-memory cloud: instances, subnets, images, profiles, pricing.

    Thread-safe; every mutator records its call and honors injected errors.
    """

    def __init__(self, region: str = "us-south", zones: list[str] | None = None,
                 profiles: list[InstanceProfile] | None = None,
                 subnets_per_zone: int = 2, subnet_capacity: int = 256,
                 instance_quota: int = 100000):
        self.region = region
        self.zone_names = (zones if zones is not None
                           else [f"{region}-{i}" for i in (1, 2, 3)])
        self.recorder = CallRecorder()
        self._lock = threading.RLock()
        self._seq = itertools.count(1)
        self.profiles: list[InstanceProfile] = profiles or generate_profiles(20)
        self.instances: dict[str, FakeInstance] = {}
        self.subnets: dict[str, FakeSubnet] = {}
        self.images: dict[str, FakeImage] = {}
        self.vnis: dict[str, FakeVNI] = {}
        self.volumes: dict[str, FakeVolume] = {}
        self.security_groups: dict[str, str] = {"sg-default": "default"}
        self.default_security_group = "sg-default"
        self.vpcs: dict[str, str] = {"vpc-1": region}   # id -> region
        self.ssh_keys: dict[str, str] = {"key-1": "rsa"}  # id -> type
        self.instance_quota = instance_quota
        self.capacity_limits: dict[tuple[str, str], int] = {}  # (profile, zone) -> max
        # idempotency-key ledger (docs/design/recovery.md): a create
        # replayed with the same key returns the EXISTING resource —
        # the server-side contract the crash-recovery journal's
        # deterministic keys rely on (real clouds expect client tokens
        # the same way, e.g. IBM VPC's transaction ids)
        self.idempotency: dict[str, str] = {}
        for zi, zone in enumerate(self.zone_names):
            for si in range(subnets_per_zone):
                sid = f"subnet-{zi + 1}{si + 1}"
                self.subnets[sid] = FakeSubnet(id=sid, zone=zone,
                                               total_ips=subnet_capacity,
                                               available_ips=subnet_capacity)
        for i, (name, osname, arch, ts) in enumerate([
                ("ubuntu-24-04-amd64", "ubuntu", "amd64", 400.0),
                ("ubuntu-22-04-amd64", "ubuntu", "amd64", 300.0),
                ("ubuntu-22-04-arm64", "ubuntu", "arm64", 300.0),
                ("rhel-9-4-amd64", "rhel", "amd64", 350.0),
                ("debian-12-5-amd64", "debian", "amd64", 320.0)]):
            iid = f"img-{i + 1}"
            self.images[iid] = FakeImage(id=iid, name=name, os=osname,
                                         architecture=arch, created_at=ts)

    # -- catalog side ------------------------------------------------------

    def list_zones(self) -> list[str]:
        self.recorder.record("list_zones")
        self.recorder.maybe_raise("list_zones")
        return list(self.zone_names)

    def list_instance_profiles(self) -> list[InstanceProfile]:
        self.recorder.record("list_instance_profiles")
        self.recorder.maybe_raise("list_instance_profiles")
        return list(self.profiles)

    def get_pricing(self, profile_name: str) -> float:
        self.recorder.record("get_pricing", profile_name)
        self.recorder.maybe_raise("get_pricing")
        for p in self.profiles:
            if p.name == profile_name:
                return profile_price(p)
        raise not_found("profile", profile_name)

    # -- subnets / images / SGs -------------------------------------------

    def list_subnets(self) -> list[FakeSubnet]:
        self.recorder.record("list_subnets")
        self.recorder.maybe_raise("list_subnets")
        with self._lock:
            return [_snap(s) for s in self.subnets.values()]

    def get_subnet(self, subnet_id: str) -> FakeSubnet:
        self.recorder.record("get_subnet", subnet_id)
        self.recorder.maybe_raise("get_subnet")
        with self._lock:
            s = self.subnets.get(subnet_id)
            if s is None:
                raise not_found("subnet", subnet_id)
            return _snap(s)

    def list_images(self) -> list[FakeImage]:
        self.recorder.record("list_images")
        self.recorder.maybe_raise("list_images")
        with self._lock:
            return list(self.images.values())

    def get_default_security_group(self) -> str:
        self.recorder.record("get_default_security_group")
        self.recorder.maybe_raise("get_default_security_group")
        return self.default_security_group

    def list_security_groups(self) -> list[str]:
        """SG ids in the VPC (ref vpc.go:268-414 SG surface; consumed by
        the status controller's existence checks)."""
        self.recorder.record("list_security_groups")
        self.recorder.maybe_raise("list_security_groups")
        with self._lock:
            return list(self.security_groups)

    def list_vpcs(self) -> list[str]:
        """VPC ids visible in this region (ref status/controller.go:471
        VPC-in-region validation)."""
        self.recorder.record("list_vpcs")
        self.recorder.maybe_raise("list_vpcs")
        with self._lock:
            return [v for v, r in self.vpcs.items() if r == self.region]

    def list_ssh_keys(self) -> list[str]:
        """SSH key ids (ref status/controller.go:796 key validation)."""
        self.recorder.record("list_ssh_keys")
        self.recorder.maybe_raise("list_ssh_keys")
        with self._lock:
            return list(self.ssh_keys)

    # -- network interfaces / volumes (staged allocation) ------------------

    def _idem_hit(self, key: str, collection: dict):
        """Existing resource for a replayed idempotency key, or None.
        Caller holds the lock.  A stale entry (resource since deleted)
        falls through to a fresh create."""
        if not key:
            return None
        rid = self.idempotency.get(key)
        return collection.get(rid) if rid else None

    def find_by_idempotency(self, key: str) -> str | None:
        """Resource id previously created under ``key`` (recovery's
        fence path uses this to learn a leaked id)."""
        with self._lock:
            return self.idempotency.get(key)

    def create_vni(self, subnet_id: str,
                   idempotency_key: str = "") -> FakeVNI:
        """Standalone VNI allocation — the first stage of the reference's
        staged create (vpc/instance/provider.go:333-401); a later instance
        create attaches it, a failed create must clean it up."""
        self.recorder.record("create_vni", subnet_id)
        self.recorder.maybe_raise("create_vni")
        with self._lock:
            hit = self._idem_hit(idempotency_key, self.vnis)
            if hit is not None:
                return hit
            subnet = self.subnets.get(subnet_id)
            if subnet is None:
                raise not_found("subnet", subnet_id)
            if subnet.available_ips <= 0:
                raise CloudError(f"subnet {subnet_id} has no available IPs",
                                 409, retryable=False)
            vni = FakeVNI(id=f"vni-{next(self._seq)}", subnet_id=subnet_id)
            self.vnis[vni.id] = vni
            if idempotency_key:
                self.idempotency[idempotency_key] = vni.id
            return vni

    def create_volume(self, capacity_gb: int = 100,
                      profile: str = "general-purpose",
                      volume_id: str = "",
                      idempotency_key: str = "") -> FakeVolume:
        """Standalone volume allocation (second stage of staged create)."""
        self.recorder.record("create_volume", volume_id or capacity_gb)
        self.recorder.maybe_raise("create_volume")
        with self._lock:
            hit = self._idem_hit(idempotency_key, self.volumes)
            if hit is not None:
                return hit
            vol = FakeVolume(id=volume_id or f"vol-{next(self._seq)}",
                             capacity_gb=capacity_gb, profile=profile)
            self.volumes[vol.id] = vol
            if idempotency_key:
                self.idempotency[idempotency_key] = vol.id
            return vol

    # -- instance lifecycle ------------------------------------------------

    def create_instance(self, name: str, profile: str, zone: str, subnet_id: str,
                        image_id: str, capacity_type: str = "on-demand",
                        security_group_ids: tuple[str, ...] = (),
                        user_data: str = "", tags: dict[str, str] | None = None,
                        volumes: tuple[FakeVolume, ...] = (),
                        vni_id: str = "",
                        volume_ids: tuple[str, ...] = (),
                        idempotency_key: str = "") -> FakeInstance:
        """Create an instance.  With ``vni_id``/``volume_ids`` it ATTACHES
        pre-allocated resources (staged create); otherwise it allocates
        them implicitly (legacy one-shot path).  A replayed
        ``idempotency_key`` returns the existing instance — quota and
        validation are skipped, the work already happened."""
        self.recorder.record("create_instance", name, profile, zone, capacity_type)
        self.recorder.maybe_raise("create_instance")
        with self._lock:
            hit = self._idem_hit(idempotency_key, self.instances)
            if hit is not None:
                return _snap(hit)
            if not any(p.name == profile for p in self.profiles):
                raise CloudError(f"profile {profile!r} not found", 404)
            if zone not in self.zone_names:
                raise CloudError(f"zone {zone!r} not found", 404)
            subnet = self.subnets.get(subnet_id)
            if subnet is None:
                raise not_found("subnet", subnet_id)
            if subnet.zone != zone:
                raise CloudError(
                    f"subnet {subnet_id} is in {subnet.zone}, not {zone}", 400)
            if subnet.available_ips <= 0:
                raise CloudError(f"subnet {subnet_id} has no available IPs", 409,
                                 retryable=False)
            if image_id not in self.images:
                raise not_found("image", image_id)
            if vni_id and vni_id not in self.vnis:
                raise not_found("vni", vni_id)
            for vid in volume_ids:
                if vid not in self.volumes:
                    raise not_found("volume", vid)
            live = sum(1 for i in self.instances.values()
                       if i.status not in ("deleting",))
            if live >= self.instance_quota:
                raise CloudError("instance quota exceeded", 403,
                                 code="quota_exceeded", retryable=False)
            limit = self.capacity_limits.get((profile, zone))
            if limit is not None:
                used = sum(1 for i in self.instances.values()
                           if i.profile == profile and i.zone == zone
                           and i.status != "deleting")
                if used >= limit:
                    raise CloudError(
                        f"insufficient capacity for {profile} in {zone}", 503,
                        code="insufficient_capacity", retryable=False)
            n = next(self._seq)
            if vni_id:
                vni = self.vnis[vni_id]
            else:
                vni = FakeVNI(id=f"vni-{n}", subnet_id=subnet_id)
                self.vnis[vni.id] = vni
            if volume_ids:
                vol_ids = tuple(volume_ids)
            else:
                vols = tuple(volumes) or (FakeVolume(id=f"vol-{n}",
                                                     capacity_gb=100,
                                                     profile="general-purpose"),)
                for v in vols:
                    self.volumes[v.id] = v
                vol_ids = tuple(v.id for v in vols)
            inst = FakeInstance(
                id=f"inst-{n:06d}", name=name, profile=profile, zone=zone,
                subnet_id=subnet_id, image_id=image_id,
                capacity_type=capacity_type,
                security_group_ids=tuple(security_group_ids) or (self.default_security_group,),
                vni_id=vni.id, volume_ids=vol_ids,
                user_data=user_data, tags=dict(tags or {}),
                ip_address=f"10.0.{len(self.instances) // 250}.{len(self.instances) % 250 + 4}")
            self.instances[inst.id] = inst
            subnet.available_ips -= 1
            if idempotency_key:
                self.idempotency[idempotency_key] = inst.id
            return _snap(inst)

    def get_instance(self, instance_id: str) -> FakeInstance:
        self.recorder.record("get_instance", instance_id)
        self.recorder.maybe_raise("get_instance")
        with self._lock:
            inst = self.instances.get(instance_id)
            if inst is None:
                raise not_found("instance", instance_id)
            return _snap(inst)

    def list_instances(self) -> list[FakeInstance]:
        self.recorder.record("list_instances")
        self.recorder.maybe_raise("list_instances")
        with self._lock:
            return [_snap(i) for i in self.instances.values()]

    def delete_instance(self, instance_id: str) -> None:
        self.recorder.record("delete_instance", instance_id)
        self.recorder.maybe_raise("delete_instance")
        with self._lock:
            inst = self.instances.pop(instance_id, None)
            if inst is None:
                raise not_found("instance", instance_id)
            self.vnis.pop(inst.vni_id, None)
            for vid in inst.volume_ids:
                self.volumes.pop(vid, None)
            subnet = self.subnets.get(inst.subnet_id)
            if subnet is not None:
                subnet.available_ips = min(subnet.total_ips, subnet.available_ips + 1)

    def update_tags(self, instance_id: str, tags: dict[str, str]) -> None:
        self.recorder.record("update_tags", instance_id)
        self.recorder.maybe_raise("update_tags")
        with self._lock:
            inst = self.instances.get(instance_id)
            if inst is None:
                raise not_found("instance", instance_id)
            inst.tags.update(tags)

    def delete_vni(self, vni_id: str) -> None:
        self.recorder.record("delete_vni", vni_id)
        self.recorder.maybe_raise("delete_vni")
        with self._lock:
            self.vnis.pop(vni_id, None)

    def delete_volume(self, volume_id: str) -> None:
        self.recorder.record("delete_volume", volume_id)
        self.recorder.maybe_raise("delete_volume")
        with self._lock:
            self.volumes.pop(volume_id, None)

    # -- spot / fault simulation ------------------------------------------

    def list_spot_instances(self) -> list[FakeInstance]:
        self.recorder.record("list_spot_instances")
        self.recorder.maybe_raise("list_spot_instances")
        with self._lock:
            return [_snap(i) for i in self.instances.values()
                    if i.capacity_type == "spot"]

    def preempt_spot_instance(self, instance_id: str) -> None:
        """Test hook: simulate a spot preemption (ref marker
        'stopped_by_preemption', spot/preemption/controller.go:97)."""
        with self._lock:
            inst = self.instances.get(instance_id)
            if inst is None:
                raise not_found("instance", instance_id)
            inst.status = "stopped"
            inst.status_reason = "stopped_by_preemption"

    def fail_instance(self, instance_id: str, reason: str = "failed") -> None:
        """Test hook: mark an instance unhealthy for interruption tests."""
        with self._lock:
            inst = self.instances.get(instance_id)
            if inst is None:
                raise not_found("instance", instance_id)
            inst.status = "stopped"
            inst.status_reason = reason

    def degrade_instance(self, instance_id: str,
                         state: str = "degraded") -> None:
        """Test hook: the metadata-service health signal (ref
        interruption/controller.go:304-325) — instance still runs but its
        health_state reads degraded/faulted."""
        with self._lock:
            inst = self.instances.get(instance_id)
            if inst is None:
                raise not_found("instance", instance_id)
            inst.health_state = state

    # -- introspection -----------------------------------------------------

    def quota_status(self) -> tuple[int, int]:
        """(live instances, quota limit) — the reference introspects VPC
        quotas per resource (vpc/instance/provider.go:905-991); the fake
        exposes the single instance quota it enforces."""
        with self._lock:
            live = sum(1 for i in self.instances.values()
                       if i.status != "deleting")
            return live, self.instance_quota

    def instance_count(self) -> int:
        with self._lock:
            return len(self.instances)
