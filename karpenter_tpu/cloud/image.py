"""Image resolver: by-ID, by-name, and semantic selector resolution.

Parity with ``pkg/providers/common/image/resolver.go``: direct id/name
lookup (:49-126) and semantic selection — parse ``os-major-minor-arch
[-variant]`` image names, filter by selector fields, pick the newest
(:134-432).
"""

from __future__ import annotations

import re

from karpenter_tpu.apis.nodeclass import ImageSelector
from karpenter_tpu.cloud.errors import not_found
from karpenter_tpu.cloud.fake import FakeImage

_NAME_RE = re.compile(
    r"^(?P<os>[a-z]+)-(?P<major>\d+)(?:-(?P<minor>\d+))?-(?P<arch>amd64|arm64|s390x)"
    r"(?:-(?P<variant>[a-z0-9]+))?$")


def parse_image_name(name: str):
    m = _NAME_RE.match(name)
    if not m:
        return None
    return m.groupdict()


class ImageResolver:
    def __init__(self, client):
        self._client = client

    def resolve(self, image: str = "", selector: ImageSelector | None = None) -> str:
        """-> image id."""
        if image:
            return self._resolve_direct(image)
        if selector is not None:
            return self._resolve_selector(selector)
        raise ValueError("image or image selector required")

    def _resolve_direct(self, image: str) -> str:
        images = self._client.list_images()
        for img in images:
            if img.id == image:
                return img.id
        for img in images:
            if img.name == image:
                return img.id
        raise not_found("image", image)

    def _resolve_selector(self, sel: ImageSelector) -> str:
        candidates: list[FakeImage] = []
        for img in self._client.list_images():
            if img.status != "available":
                continue
            parsed = parse_image_name(img.name)
            if parsed is None:
                continue
            if sel.os and parsed["os"] != sel.os:
                continue
            if sel.major_version and parsed["major"] != sel.major_version:
                continue
            if sel.minor_version and (parsed["minor"] or "") != sel.minor_version:
                continue
            if sel.architecture and parsed["arch"] != sel.architecture:
                continue
            if sel.variant and (parsed["variant"] or "") != sel.variant:
                continue
            candidates.append(img)
        if not candidates:
            raise not_found(
                "image matching selector",
                f"{sel.os}-{sel.major_version}-{sel.minor_version}-{sel.architecture}")
        # newest first: by (major, minor) then creation time (:134-432)
        def version_key(img: FakeImage):
            p = parse_image_name(img.name)
            return (int(p["major"]), int(p["minor"] or 0), img.created_at)
        return max(candidates, key=version_key).id
