"""Local stub cloud REST server: the wire-level test double.

Serves the exact protocol :class:`~karpenter_tpu.cloud.vpc.VPCCloudClient`
and :class:`~karpenter_tpu.cloud.iks.IKSClient` speak, delegating every
operation to a backing :class:`FakeCloud` / :class:`FakeIKS` — so the
HTTP clients are contract-tested against the same semantics (quota,
capacity limits, zone validation, atomic pool resize, injected errors)
the in-memory fakes enforce, without a real cloud account (the reference
tests its client layer the same way: in-memory API doubles behind the SDK
interface, ``pkg/fake/vpcapi.go:32``).

Auth: ``POST /identity/token`` exchanges the configured api key for a
bearer token; every other route requires it.  401s from bad/expired
tokens exercise the client's invalidate-and-refresh path.

Error mapping: :class:`CloudError` -> HTTP status + IBM-style envelope
``{"errors": [{"message", "code"}]}``; rate-limit errors carry
``Retry-After`` so the 429 retry contract is testable end-to-end.
"""

from __future__ import annotations

import json
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from karpenter_tpu.cloud.errors import CloudError
from karpenter_tpu.cloud.fake import FakeCloud
from karpenter_tpu.cloud.fake_iks import FakeIKS
from karpenter_tpu.cloud.resources import Volume
from karpenter_tpu.cloud.iks import pool_to_json, worker_to_json
from karpenter_tpu.cloud.vpc import (
    image_to_json, instance_to_json, profile_to_json, subnet_to_json,
)
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.stub")


class StubCloudServer:
    """HTTP facade over a FakeCloud (+ optional FakeIKS)."""

    def __init__(self, cloud: FakeCloud | None = None,
                 iks: FakeIKS | None = None,
                 api_key: str = "test-key", host: str = "127.0.0.1",
                 port: int = 0, token_ttl: float = 3600.0):
        self.cloud = cloud or FakeCloud()
        self.iks = iks
        self.api_key = api_key
        self.token_ttl = token_ttl
        self._tokens: dict[str, bool] = {}
        self._lock = threading.Lock()
        handler = _make_handler(self)
        self._server = ThreadingHTTPServer((host, port), handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "StubCloudServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- auth --------------------------------------------------------------

    def issue_token(self, apikey: str) -> dict:
        if apikey != self.api_key:
            raise CloudError("invalid api key", 401, retryable=False)
        token = secrets.token_hex(16)
        with self._lock:
            self._tokens[token] = True
        return {"access_token": token, "expires_in": self.token_ttl}

    def check_token(self, header: str) -> bool:
        if not header.startswith("Bearer "):
            return False
        with self._lock:
            return self._tokens.get(header[len("Bearer "):], False)

    def revoke_all_tokens(self) -> None:
        """Test hook: simulate server-side token expiry -> clients must
        re-auth on the 401."""
        with self._lock:
            self._tokens.clear()

    # -- routing -----------------------------------------------------------

    def handle(self, method: str, path: str, query: dict, body: dict) -> dict:
        """Dispatch a request to the backing fakes.  Returns the JSON
        response dict; raises CloudError for API-level failures."""
        parts = [p for p in path.split("/") if p]
        cloud = self.cloud

        # ---- VPC plane ----
        if path == "/v1/zones":
            return {"zones": cloud.list_zones()}
        if path == "/v1/instance/profiles":
            return {"profiles": [profile_to_json(p)
                                 for p in cloud.list_instance_profiles()]}
        if len(parts) == 3 and parts[:2] == ["v1", "pricing"]:
            return {"price": cloud.get_pricing(parts[2])}
        if path == "/v1/subnets":
            return {"subnets": [subnet_to_json(s)
                                for s in cloud.list_subnets()]}
        if len(parts) == 3 and parts[:2] == ["v1", "subnets"]:
            return subnet_to_json(cloud.get_subnet(parts[2]))
        if path == "/v1/images":
            return {"images": [image_to_json(m) for m in cloud.list_images()]}
        if path == "/v1/vpcs/default/security_group":
            return {"id": cloud.get_default_security_group()}
        if path == "/v1/security_groups":
            return {"security_groups": cloud.list_security_groups()}
        if path == "/v1/vpcs":
            return {"vpcs": cloud.list_vpcs()}
        if path == "/v1/keys":
            return {"keys": cloud.list_ssh_keys()}
        if path == "/v1/virtual_network_interfaces" and method == "POST":
            vni = cloud.create_vni(
                body.get("subnet_id", ""),
                idempotency_key=body.get("idempotency_key", ""))
            return {"id": vni.id, "subnet_id": vni.subnet_id}
        if path == "/v1/volumes" and method == "POST":
            vol = cloud.create_volume(
                capacity_gb=int(body.get("capacity_gb", 100)),
                profile=body.get("profile", "general-purpose"),
                volume_id=body.get("volume_id", ""),
                idempotency_key=body.get("idempotency_key", ""))
            return {"id": vol.id, "capacity_gb": vol.capacity_gb,
                    "profile": vol.profile}
        if path == "/v1/instances" and method == "POST":
            vols = tuple(Volume(id=v.get("id", ""),
                                capacity_gb=int(v.get("capacity_gb", 100)),
                                profile=v.get("profile", "general-purpose"))
                         for v in body.get("volumes") or ())
            inst = cloud.create_instance(
                name=body.get("name", ""), profile=body.get("profile", ""),
                zone=body.get("zone", ""),
                subnet_id=body.get("subnet_id", ""),
                image_id=body.get("image_id", ""),
                capacity_type=body.get("capacity_type", "on-demand"),
                security_group_ids=tuple(body.get("security_group_ids") or ()),
                user_data=body.get("user_data", ""),
                tags=body.get("tags") or {}, volumes=vols,
                vni_id=body.get("vni_id", ""),
                volume_ids=tuple(body.get("volume_ids") or ()),
                idempotency_key=body.get("idempotency_key", ""))
            return instance_to_json(inst)
        if path == "/v1/instances" and method == "GET":
            if query.get("availability") == ["spot"]:
                return {"instances": [instance_to_json(i)
                                      for i in cloud.list_spot_instances()]}
            return {"instances": [instance_to_json(i)
                                  for i in cloud.list_instances()]}
        if len(parts) == 4 and parts[:2] == ["v1", "instances"] \
                and parts[3] == "tags" and method == "POST":
            cloud.update_tags(parts[2], body.get("tags") or {})
            return {}
        if len(parts) == 3 and parts[:2] == ["v1", "instances"]:
            if method == "GET":
                return instance_to_json(cloud.get_instance(parts[2]))
            if method == "DELETE":
                cloud.delete_instance(parts[2])
                return {}
        if len(parts) == 3 and parts[:2] == ["v1",
                                             "virtual_network_interfaces"] \
                and method == "DELETE":
            cloud.delete_vni(parts[2])
            return {}
        if len(parts) == 3 and parts[:2] == ["v1", "volumes"] \
                and method == "DELETE":
            cloud.delete_volume(parts[2])
            return {}
        if path == "/v1/quota":
            live, limit = cloud.quota_status()
            return {"live": live, "limit": limit}

        # ---- IKS plane ----
        if len(parts) >= 3 and parts[0] == "v2" and parts[1] == "clusters":
            return self._handle_iks(method, parts[2], parts[3:], query, body)

        raise CloudError(f"no route for {method} {path}", 404,
                         retryable=False)

    def _handle_iks(self, method: str, cluster_id: str, rest, query: dict,
                    body: dict) -> dict:
        iks = self.iks
        if iks is None or cluster_id != iks.cluster_id:
            raise CloudError(f"cluster {cluster_id!r} not found", 404,
                             retryable=False)
        if rest == ["workerpools"]:
            if method == "POST":
                return pool_to_json(iks.create_pool(
                    name=body.get("name", ""), flavor=body.get("flavor", ""),
                    zones=list(body.get("zones") or []),
                    size_per_zone=int(body.get("size_per_zone", 0)),
                    labels=body.get("labels") or {},
                    dynamic=bool(body.get("dynamic", False))))
            return {"workerpools": [pool_to_json(p)
                                    for p in iks.list_pools()]}
        if len(rest) == 2 and rest[0] == "workerpools":
            if method == "GET":
                return pool_to_json(iks.get_pool(rest[1]))
            if method == "DELETE":
                iks.delete_pool(rest[1])
                return {}
        if len(rest) == 3 and rest[0] == "workerpools":
            pool_id, action = rest[1], rest[2]
            if action == "zones" and method == "POST":
                iks.add_pool_zone(pool_id, body.get("zone", ""))
                return {}
            if action == "increment" and method == "POST":
                return worker_to_json(
                    iks.increment_pool(pool_id, body.get("zone", "")))
            if action == "decrement" and method == "POST":
                iks.decrement_pool(pool_id, body.get("worker_id", ""))
                return {}
        if rest == ["workers"]:
            if method == "POST":
                return worker_to_json(self._register_worker(body))
            pool = (query.get("pool") or [None])[0]
            return {"workers": [worker_to_json(w)
                                for w in iks.list_workers(pool)]}
        if len(rest) == 2 and rest[0] == "workers" and method == "GET":
            return worker_to_json(iks.get_worker(rest[1]))
        if rest == ["config"]:
            return iks.get_cluster_config()
        raise CloudError(f"no IKS route for {method} /{'/'.join(rest)}", 404,
                         retryable=False)

    def _register_worker(self, body: dict):
        """AddWorkerToIKSCluster analogue: attach an existing VPC instance
        to the cluster as a worker (ref iks_api.go:53)."""
        return self.iks.register_worker(body.get("instance_id", ""),
                                        body.get("pool_id", ""))


def _make_handler(stub: StubCloudServer):
    class Handler(BaseHTTPRequestHandler):
        # silence per-request logging
        def log_message(self, *args):
            pass

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if not length:
                return {}
            try:
                return json.loads(self.rfile.read(length))
            except json.JSONDecodeError:
                return {}

        def _send(self, status: int, payload: dict,
                  headers: dict | None = None) -> None:
            data = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _dispatch(self, method: str) -> None:
            parsed = urlparse(self.path)
            body = self._read_body()
            # token issuance is the one unauthenticated route
            if parsed.path == "/identity/token" and method == "POST":
                try:
                    self._send(200, stub.issue_token(body.get("apikey", "")))
                except CloudError as e:
                    self._send_error(e)
                return
            if not stub.check_token(self.headers.get("Authorization", "")):
                self._send(401, {"errors": [
                    {"message": "invalid or expired token",
                     "code": "unauthorized"}]})
                return
            try:
                self._send(200, stub.handle(method, parsed.path,
                                            parse_qs(parsed.query), body))
            except CloudError as e:
                self._send_error(e)
            except Exception as e:   # stub bug -> visible 500
                log.error("stub handler crashed", method=method,
                          path=parsed.path, error=str(e))
                self._send(500, {"errors": [{"message": str(e),
                                             "code": "internal_error"}]})

        def _send_error(self, e: CloudError) -> None:
            headers = {}
            if e.retry_after:
                headers["Retry-After"] = str(int(e.retry_after))
            elif e.status_code == 429:
                headers["Retry-After"] = "1"
            self._send(e.status_code or 500,
                       {"errors": [{"message": e.message, "code": e.code}]},
                       headers)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def do_DELETE(self):
            self._dispatch("DELETE")

    return Handler
