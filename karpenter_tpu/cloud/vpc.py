"""HTTP-backed VPC cloud client — the real L5 counterpart to FakeCloud.

Capability parity with the reference's concrete client layer
(``pkg/cloudprovider/ibm/client.go:31`` credential bootstrap + lazy
sub-clients; ``vpc.go:70`` VPCClient instance/subnet/image/VNI/volume ops;
``catalog.go:36`` pricing; ``iam.go:55`` token fetch/refresh): a REST
client over :class:`~karpenter_tpu.cloud.http.HTTPClient` that exposes the
EXACT provider-facing surface :class:`~karpenter_tpu.cloud.fake.FakeCloud`
defines, so the actuator / catalog / subnet / image providers run
unmodified against either implementation (contract tests drive both, the
real one against the local stub server in ``cloud/stub.py``).

Wire protocol (VPC-flavored JSON REST; the stub server speaks the same):

==========================================  =================================
``POST /identity/token``                    api-key -> bearer token (IAM)
``GET  /v1/zones``                          region zones
``GET  /v1/instance/profiles``              catalog profiles
``GET  /v1/pricing/{profile}``              $/h (GlobalCatalog stand-in)
``GET  /v1/subnets[/{id}]``                 subnet list/get
``GET  /v1/images``                         image list
``GET  /v1/vpcs/default/security_group``    default SG id
``POST /v1/instances``                      create (VNI+volumes server-side)
``GET  /v1/instances[/{id}]``               list/get (``?availability=spot``)
``DELETE /v1/instances/{id}``               delete
``POST /v1/instances/{id}/tags``            tag merge (Global Tagging
                                            stand-in)
``DELETE /v1/virtual_network_interfaces/{id}``  orphan VNI cleanup
``DELETE /v1/volumes/{id}``                 orphan volume cleanup
``GET  /v1/quota``                          live count + limit
==========================================  =================================

Error envelope: ``{"errors": [{"message", "code"}]}`` + status code,
parsed into the shared taxonomy by the HTTP layer (429 honors
``Retry-After`` — ``ratelimit_retry.go:39`` contract via cloud/retry.py).
"""

from __future__ import annotations


from karpenter_tpu.cloud.http import HTTPClient, TokenSource
from karpenter_tpu.cloud.profile import InstanceProfile
from karpenter_tpu.cloud.resources import VNI, Image, Instance, Subnet, Volume


def instance_to_json(i: Instance) -> dict:
    return {
        "id": i.id, "name": i.name, "profile": i.profile, "zone": i.zone,
        "subnet_id": i.subnet_id, "image_id": i.image_id,
        "capacity_type": i.capacity_type, "status": i.status,
        "status_reason": i.status_reason,
        "health_state": i.health_state, "tags": dict(i.tags),
        "security_group_ids": list(i.security_group_ids),
        "vni_id": i.vni_id, "volume_ids": list(i.volume_ids),
        "user_data": i.user_data, "created_at": i.created_at,
        "ip_address": i.ip_address,
    }


def instance_from_json(d: dict) -> Instance:
    return Instance(
        id=d["id"], name=d.get("name", ""), profile=d.get("profile", ""),
        zone=d.get("zone", ""), subnet_id=d.get("subnet_id", ""),
        image_id=d.get("image_id", ""),
        capacity_type=d.get("capacity_type", "on-demand"),
        status=d.get("status", "running"),
        status_reason=d.get("status_reason", ""),
        health_state=d.get("health_state", "ok"),
        tags=dict(d.get("tags") or {}),
        security_group_ids=tuple(d.get("security_group_ids") or ()),
        vni_id=d.get("vni_id", ""),
        volume_ids=tuple(d.get("volume_ids") or ()),
        user_data=d.get("user_data", ""),
        created_at=float(d.get("created_at", 0.0)),
        ip_address=d.get("ip_address", ""))


def subnet_to_json(s: Subnet) -> dict:
    return {"id": s.id, "zone": s.zone, "total_ips": s.total_ips,
            "available_ips": s.available_ips, "state": s.state,
            "tags": dict(s.tags), "vpc_id": s.vpc_id}


def subnet_from_json(d: dict) -> Subnet:
    return Subnet(id=d["id"], zone=d.get("zone", ""),
                  total_ips=int(d.get("total_ips", 256)),
                  available_ips=int(d.get("available_ips", 256)),
                  state=d.get("state", "available"),
                  tags=dict(d.get("tags") or {}),
                  vpc_id=d.get("vpc_id", "vpc-1"))


def image_to_json(m: Image) -> dict:
    return {"id": m.id, "name": m.name, "os": m.os,
            "architecture": m.architecture, "status": m.status,
            "visibility": m.visibility, "created_at": m.created_at}


def image_from_json(d: dict) -> Image:
    return Image(id=d["id"], name=d.get("name", ""), os=d.get("os", ""),
                 architecture=d.get("architecture", "amd64"),
                 status=d.get("status", "available"),
                 visibility=d.get("visibility", "public"),
                 created_at=float(d.get("created_at", 0.0)))


def profile_to_json(p: InstanceProfile) -> dict:
    return {"name": p.name, "cpu": p.cpu, "memory_gib": p.memory_gib,
            "architecture": p.architecture, "gpu": p.gpu,
            "gpu_model": p.gpu_model, "supports_spot": p.supports_spot,
            "bandwidth_gbps": p.bandwidth_gbps}


def profile_from_json(d: dict) -> InstanceProfile:
    return InstanceProfile(
        name=d["name"], cpu=int(d.get("cpu", 0)),
        memory_gib=int(d.get("memory_gib", 0)),
        architecture=d.get("architecture", "amd64"),
        gpu=int(d.get("gpu", 0)), gpu_model=d.get("gpu_model", ""),
        supports_spot=bool(d.get("supports_spot", True)),
        bandwidth_gbps=int(d.get("bandwidth_gbps", 16)))


class VPCCloudClient:
    """Provider-facing cloud client speaking the REST protocol above.

    Same surface as :class:`FakeCloud` minus the test-only hooks
    (``preempt_spot_instance`` / ``fail_instance`` / ``capacity_limits``
    are fault injectors, not cloud API).
    """

    def __init__(self, endpoint: str, api_key: str, region: str = "",
                 timeout: float = 30.0, opener=None, sleep=None):
        self.region = region
        kw = {}
        if opener is not None:
            kw["opener"] = opener
        if sleep is not None:
            kw["sleep"] = sleep
        # the token endpoint authenticates with the api key itself —
        # no bearer token yet (ref iam.go:76)
        self._iam = HTTPClient(endpoint, "iam", timeout=timeout, **kw)
        self._api_key = api_key
        self.tokens = TokenSource(self._fetch_token)
        self.http = HTTPClient(endpoint, "vpc", token_source=self.tokens,
                               timeout=timeout, **kw)

    def _fetch_token(self) -> dict:
        return self._iam.post("/identity/token", {"apikey": self._api_key},
                              operation="token")

    # -- catalog side (ref catalog.go:84-114, vpc.go:489-514) --------------

    def list_zones(self) -> list[str]:
        return list(self.http.get("/v1/zones", "list_zones").get("zones", []))

    def list_instance_profiles(self) -> list[InstanceProfile]:
        data = self.http.get("/v1/instance/profiles", "list_profiles")
        return [profile_from_json(p) for p in data.get("profiles", [])]

    def get_pricing(self, profile_name: str) -> float:
        data = self.http.get(f"/v1/pricing/{profile_name}", "get_pricing")
        return float(data["price"])

    # -- subnets / images / SGs (ref vpc.go:234-414) -----------------------

    def list_subnets(self) -> list[Subnet]:
        data = self.http.get("/v1/subnets", "list_subnets")
        return [subnet_from_json(s) for s in data.get("subnets", [])]

    def get_subnet(self, subnet_id: str) -> Subnet:
        return subnet_from_json(
            self.http.get(f"/v1/subnets/{subnet_id}", "get_subnet"))

    def list_images(self) -> list[Image]:
        data = self.http.get("/v1/images", "list_images")
        return [image_from_json(m) for m in data.get("images", [])]

    def get_default_security_group(self) -> str:
        return self.http.get("/v1/vpcs/default/security_group",
                             "get_default_sg")["id"]

    def list_security_groups(self) -> list[str]:
        return list(self.http.get("/v1/security_groups",
                                  "list_security_groups")
                    .get("security_groups", []))

    def list_vpcs(self) -> list[str]:
        return list(self.http.get("/v1/vpcs", "list_vpcs").get("vpcs", []))

    def list_ssh_keys(self) -> list[str]:
        return list(self.http.get("/v1/keys", "list_ssh_keys")
                    .get("keys", []))

    # -- staged allocation (ref vpc.go:448-478 VNIs, :416-446 volumes) -----

    def create_vni(self, subnet_id: str, idempotency_key: str = "") -> VNI:
        data = self.http.post("/v1/virtual_network_interfaces",
                              {"subnet_id": subnet_id,
                               "idempotency_key": idempotency_key},
                              "create_vni")
        return VNI(id=data["id"], subnet_id=data.get("subnet_id", subnet_id))

    def create_volume(self, capacity_gb: int = 100,
                      profile: str = "general-purpose",
                      volume_id: str = "",
                      idempotency_key: str = "") -> Volume:
        data = self.http.post("/v1/volumes",
                              {"capacity_gb": capacity_gb, "profile": profile,
                               "volume_id": volume_id,
                               "idempotency_key": idempotency_key},
                              "create_volume")
        return Volume(id=data["id"],
                      capacity_gb=int(data.get("capacity_gb", capacity_gb)),
                      profile=data.get("profile", profile))

    # -- instance lifecycle (ref vpc.go:125-232) ---------------------------

    def create_instance(self, name: str, profile: str, zone: str,
                        subnet_id: str, image_id: str,
                        capacity_type: str = "on-demand",
                        security_group_ids: tuple[str, ...] = (),
                        user_data: str = "",
                        tags: dict[str, str] | None = None,
                        volumes: tuple[Volume, ...] = (),
                        vni_id: str = "",
                        volume_ids: tuple[str, ...] = (),
                        idempotency_key: str = "") -> Instance:
        body = {
            "name": name, "profile": profile, "zone": zone,
            "subnet_id": subnet_id, "image_id": image_id,
            "capacity_type": capacity_type,
            "security_group_ids": list(security_group_ids),
            "user_data": user_data, "tags": dict(tags or {}),
            "volumes": [{"id": v.id, "capacity_gb": v.capacity_gb,
                         "profile": v.profile} for v in volumes],
            "vni_id": vni_id, "volume_ids": list(volume_ids),
            "idempotency_key": idempotency_key,
        }
        return instance_from_json(
            self.http.post("/v1/instances", body, "create_instance"))

    def get_instance(self, instance_id: str) -> Instance:
        return instance_from_json(
            self.http.get(f"/v1/instances/{instance_id}", "get_instance"))

    def list_instances(self) -> list[Instance]:
        data = self.http.get("/v1/instances", "list_instances")
        return [instance_from_json(i) for i in data.get("instances", [])]

    def delete_instance(self, instance_id: str) -> None:
        self.http.delete(f"/v1/instances/{instance_id}", "delete_instance")

    def update_tags(self, instance_id: str, tags: dict[str, str]) -> None:
        self.http.post(f"/v1/instances/{instance_id}/tags", {"tags": tags},
                       "update_tags")

    def delete_vni(self, vni_id: str) -> None:
        self.http.delete(f"/v1/virtual_network_interfaces/{vni_id}",
                         "delete_vni")

    def delete_volume(self, volume_id: str) -> None:
        self.http.delete(f"/v1/volumes/{volume_id}", "delete_volume")

    # -- spot (ref vpc.go:191) ---------------------------------------------

    def list_spot_instances(self) -> list[Instance]:
        data = self.http.get("/v1/instances?availability=spot",
                             "list_spot_instances")
        return [instance_from_json(i) for i in data.get("instances", [])]

    # -- introspection (ref vpc/instance/provider.go:905-991) --------------

    def quota_status(self) -> tuple[int, int]:
        data = self.http.get("/v1/quota", "quota_status")
        return int(data["live"]), int(data["limit"])

    def instance_count(self) -> int:
        return len(self.list_instances())
