"""Retry stack: exponential backoff + decorrelated jitter + 429
Retry-After honoring.

Parity with the reference's two retry layers:
- exponential backoff 1s -> 15s cap, 10 steps for catalog listing
  (instancetype.go:440-446);
- generic rate-limit retry that honors Retry-After
  (ratelimit_retry.go:39).

On top of parity: **decorrelated jitter** (the AWS architecture-blog
schedule: ``wait = min(cap, uniform(initial, prev_wait * 3))``).  A pure
exponential schedule synchronizes retry storms — every controller that
failed in the same cloud brownout retries in the same instant, forever.
Jitter decorrelates the fleet while keeping the same bounds
(``min(initial, cap) <= wait <= cap``).  Pass a seeded ``random.Random``
as ``rng`` for a deterministic schedule (tests, the chaos harness);
``jitter=False`` pins the exact geometric ramp.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from collections.abc import Callable
from typing import TypeVar

from karpenter_tpu.cloud.errors import is_rate_limit, is_retryable, parse_error
from karpenter_tpu import obs
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.retry")

T = TypeVar("T")


@dataclass
class RetryConfig:
    initial: float = 1.0
    factor: float = 2.0
    cap: float = 15.0
    steps: int = 10
    honor_retry_after: bool = True
    # decorrelated jitter on every backoff wait; False pins the pure
    # geometric ramp (pinned-schedule tests, lockstep simulations)
    jitter: bool = True


def retry_with_backoff(fn: Callable[[], T], config: RetryConfig = None,
                       sleep: Callable[[float], None] | None = None,
                       operation: str = "",
                       rng: random.Random | None = None,
                       budget: float | None = None) -> T:
    """Call ``fn`` with exponential backoff on retryable errors.

    Non-retryable errors raise immediately; the last error raises after
    ``steps`` attempts.  With ``config.jitter`` each wait is drawn
    decorrelated from the previous one (bounded by ``initial``/``cap``);
    a server Retry-After always overrides the drawn wait verbatim.

    ``budget`` is an overall wall-clock deadline in seconds that retries
    AND Retry-After sleeps must respect: a wait that would end at or
    past ``now + budget`` is never started — the last error raises
    instead (sleeping a clamped remainder would waste the whole wait,
    and retrying before a server-mandated Retry-After elapses would
    violate it).  A retry loop must never outlive its caller's requeue
    interval; the controller re-enters on its own schedule.
    """
    cfg = config or RetryConfig()
    if sleep is None:
        # resolved at call time, NOT bound as a default at import — the
        # chaos VirtualClock patches time.sleep so injected Retry-After
        # waits cost scenario time, and an import-time default would
        # capture the real sleep before the patch
        sleep = time.sleep
    # deadline resolved at call time for the same VirtualClock reason
    deadline = (time.monotonic() + budget) if budget is not None else None
    draw = (rng or random).uniform if cfg.jitter else None
    # the cap bounds EVERY wait, including the first (a misconfigured
    # initial > cap must not produce one over-cap sleep)
    floor = min(cfg.initial, cfg.cap)
    delay = floor
    last: Exception = RuntimeError("retry_with_backoff: no attempts")
    for attempt in range(cfg.steps):
        try:
            # one span per attempt: retried RPCs show up in a dumped
            # trace as N sibling spans with the backoff decisions as
            # events on the enclosing span
            with obs.span("rpc.attempt", operation=operation or "call",
                          attempt=attempt + 1):
                return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            err = parse_error(e, operation)
            if not is_retryable(err):
                raise
            last = e
            wait = delay
            if cfg.honor_retry_after and is_rate_limit(err) and err.retry_after > 0:
                wait = err.retry_after
            if attempt < cfg.steps - 1:
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    # boundary clamp: wait == remaining is already too
                    # late (the post-sleep attempt would start at the
                    # deadline), so >= stops the loop here
                    if wait >= remaining:
                        obs.event("backoff.budget_exhausted",
                                  operation=operation, attempt=attempt + 1,
                                  wait=round(wait, 4),
                                  remaining=round(max(remaining, 0.0), 4))
                        raise last
                log.debug("retrying after error", operation=operation,
                          attempt=attempt + 1, wait=wait, error=str(e))
                obs.event("backoff", operation=operation,
                          attempt=attempt + 1, wait=round(wait, 4),
                          retry_after=err.retry_after > 0,
                          error=str(e)[:120])
                sleep(wait)
                if draw is not None:
                    # decorrelated: next draw ranges off the PREVIOUS
                    # drawn delay (not the server hint), clamped to
                    # [floor, cap]
                    delay = min(cfg.cap, max(floor, draw(floor, delay * 3)))
                else:
                    delay = min(delay * cfg.factor, cfg.cap)
    raise last
