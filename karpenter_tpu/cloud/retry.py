"""Retry stack: exponential backoff + 429 Retry-After honoring.

Parity with the reference's two retry layers:
- exponential backoff 1s -> 15s cap, 10 steps for catalog listing
  (instancetype.go:440-446);
- generic rate-limit retry that honors Retry-After
  (ratelimit_retry.go:39).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from collections.abc import Callable
from typing import TypeVar

from karpenter_tpu.cloud.errors import is_rate_limit, is_retryable, parse_error
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.retry")

T = TypeVar("T")


@dataclass
class RetryConfig:
    initial: float = 1.0
    factor: float = 2.0
    cap: float = 15.0
    steps: int = 10
    honor_retry_after: bool = True


def retry_with_backoff(fn: Callable[[], T], config: RetryConfig = None,
                       sleep: Callable[[float], None] = time.sleep,
                       operation: str = "") -> T:
    """Call ``fn`` with exponential backoff on retryable errors.

    Non-retryable errors raise immediately; the last error raises after
    ``steps`` attempts.
    """
    cfg = config or RetryConfig()
    # the cap bounds EVERY wait, including the first (a misconfigured
    # initial > cap must not produce one over-cap sleep)
    delay = min(cfg.initial, cfg.cap)
    last: Exception = RuntimeError("retry_with_backoff: no attempts")
    for attempt in range(cfg.steps):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            err = parse_error(e, operation)
            if not is_retryable(err):
                raise
            last = e
            wait = delay
            if cfg.honor_retry_after and is_rate_limit(err) and err.retry_after > 0:
                wait = err.retry_after
            if attempt < cfg.steps - 1:
                log.debug("retrying after error", operation=operation,
                          attempt=attempt + 1, wait=wait, error=str(e))
                sleep(wait)
                delay = min(delay * cfg.factor, cfg.cap)
    raise last
