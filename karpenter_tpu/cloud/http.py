"""HTTP client layer for real cloud backends.

Parity with ``pkg/httpclient`` (typed IBMCloudError parsing,
client.go:55-224) and the IAM token handling of
``pkg/cloudprovider/ibm/iam.go``: a minimal, dependency-free REST helper
(urllib) with

- bearer-token auth + refresh-before-expiry,
- typed :class:`~karpenter_tpu.cloud.errors.CloudError` parsing from
  JSON error envelopes,
- 429 Retry-After honoring + exponential backoff for retryable statuses
  (the ratelimit_retry.go:39 contract, via cloud/retry.py),
- request metrics per (service, operation, status).

The fake cloud remains the default in tests/sim; this layer is the seam
a production backend plugs into (the FakeCloud and an HTTP-backed client
expose the same provider-facing surface).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from collections.abc import Callable

from karpenter_tpu.cloud.errors import CloudError, parse_error
from karpenter_tpu.cloud.retry import retry_with_backoff
from karpenter_tpu import obs
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("cloud.http")


class TokenSource:
    """IAM-style bearer token with refresh-before-expiry
    (ref iam.go:76: fetch, cache, refresh when <5m left)."""

    REFRESH_MARGIN = 300.0

    def __init__(self, fetch: Callable[[], dict],
                 clock: Callable[[], float] = time.monotonic):
        """``fetch() -> {"access_token": str, "expires_in": seconds}``"""
        self._fetch = fetch
        self._clock = clock
        self._lock = threading.Lock()
        self._token = ""
        self._expires_at = -float("inf")

    def token(self) -> str:
        with self._lock:
            if self._clock() >= self._expires_at - self.REFRESH_MARGIN:
                data = self._fetch()
                self._token = data["access_token"]
                self._expires_at = self._clock() + float(
                    data.get("expires_in", 3600))
            return self._token

    def invalidate(self) -> None:
        with self._lock:
            self._expires_at = -float("inf")


class HTTPClient:
    """Thin JSON REST client with typed errors and retry."""

    def __init__(self, base_url: str, service: str,
                 token_source: TokenSource | None = None,
                 timeout: float = 30.0,
                 opener: Callable | None = None,
                 sleep: Callable[[float], None] = time.sleep,
                 budget: float | None = None):
        self.base_url = base_url.rstrip("/")
        self.service = service
        self.tokens = token_source
        self.timeout = timeout
        # overall wall-clock budget per request (retries + Retry-After
        # sleeps included; cloud/retry.py deadline propagation) — a
        # controller-owned client caps every call below its requeue
        # interval so the retry loop can never outlive the reconcile
        self.budget = budget
        # injectable transport/sleep for tests
        self._open = opener or urllib.request.urlopen
        self._sleep = sleep

    # -- verbs -------------------------------------------------------------

    def get(self, path: str, operation: str = "get",
            budget: float | None = None) -> dict:
        return self.request("GET", path, operation=operation, budget=budget)

    def post(self, path: str, body: dict, operation: str = "post",
             budget: float | None = None) -> dict:
        return self.request("POST", path, body=body, operation=operation,
                            budget=budget)

    def delete(self, path: str, operation: str = "delete",
               budget: float | None = None) -> dict:
        return self.request("DELETE", path, operation=operation,
                            budget=budget)

    def request(self, method: str, path: str, body: dict | None = None,
                operation: str = "request",
                budget: float | None = None) -> dict:
        def attempt():
            return self._do(method, path, body, operation)

        return retry_with_backoff(
            attempt, operation=operation, sleep=self._sleep,
            budget=budget if budget is not None else self.budget)

    # -- internals ---------------------------------------------------------

    def _do(self, method: str, path: str, body: dict | None,
            operation: str) -> dict:
        url = f"{self.base_url}{path}"
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.tokens is not None:
            req.add_header("Authorization", f"Bearer {self.tokens.token()}")
        # one span per wire attempt — retries are SEPARATE spans, so a
        # dumped trace shows each round trip with its own status
        with obs.span(f"rpc.{self.service}.{operation}", method=method,
                      path=path) as sp:
            try:
                with self._open(req, timeout=self.timeout) as resp:
                    status = getattr(resp, "status", 200)
                    sp.set("status", status)
                    metrics.API_REQUESTS.labels(self.service, operation,
                                                str(status)).inc()
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                sp.set("status", e.code)
                metrics.API_REQUESTS.labels(self.service, operation,
                                            str(e.code)).inc()
                if e.code in (401, 403) and self.tokens is not None:
                    self.tokens.invalidate()  # force re-auth on next attempt
                raise self._typed_error(e, operation)
            except urllib.error.URLError as e:
                sp.set("status", "network")
                metrics.API_REQUESTS.labels(self.service, operation,
                                            "network").inc()
                raise CloudError(f"{operation}: {e.reason}", status_code=0,
                                 code="network", retryable=True)

    @staticmethod
    def _typed_error(e: "urllib.error.HTTPError", operation: str) -> CloudError:
        """Parse the JSON error envelope into the shared taxonomy
        (ref httpclient/client.go:55-224 IBMCloudError parsing)."""
        retry_after = 0.0
        try:
            retry_after = float(e.headers.get("Retry-After", 0))
        except (TypeError, ValueError):
            pass
        message, code = str(e.reason), ""
        try:
            envelope = json.loads(e.read())
            errs = envelope.get("errors") or []
            if errs:
                message = errs[0].get("message", message)
                code = errs[0].get("code", "")
            else:
                message = envelope.get("message", message)
                code = envelope.get("code", "")
        except Exception as parse_exc:
            # a malformed error envelope still yields a typed CloudError
            # from the HTTP status; record why the body was unusable
            log.debug("unparseable error envelope", operation=operation,
                      status=e.code, error=str(parse_exc))
        err = parse_error(
            CloudError(f"{operation}: {message}", status_code=e.code,
                       code=code),
            operation=operation)
        if retry_after > 0:
            err.retry_after = retry_after
        return err
