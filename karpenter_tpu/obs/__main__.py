"""obs CLI: export traces as Chrome trace-event JSON or JSONL.

    python -m karpenter_tpu.obs export --format chrome            # demo run
    python -m karpenter_tpu.obs export --input spans.jsonl -o out.json
    python -m karpenter_tpu.obs export --format jsonl

Without ``--input`` the command runs a small self-contained provisioning
cycle (fake cloud, greedy solver) and exports ITS trace — a one-command
way to produce a Perfetto-loadable file showing the pod-event -> batch
-> solve -> actuation -> RPC chain.  With ``--input`` it converts a span
dump produced by the chaos harness or ``/debug/traces`` tooling.

Exit codes: 0 ok, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# the demo cycle never needs an accelerator; pin CPU before any
# transitive jax import can initialize a backend
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _run_demo():
    """One provisioning cycle on the fakes, traced into a fresh recorder."""
    from karpenter_tpu import obs
    from karpenter_tpu.apis.nodeclass import (
        InstanceRequirements, NodeClass, NodeClassSpec, PlacementStrategy,
    )
    from karpenter_tpu.apis.pod import ResourceRequests, make_pods, pod_key
    from karpenter_tpu.catalog.instancetype import InstanceTypeProvider
    from karpenter_tpu.catalog.pricing import PricingProvider
    from karpenter_tpu.cloud.fake import FakeCloud
    from karpenter_tpu.core.actuator import Actuator
    from karpenter_tpu.core.cluster import ClusterState
    from karpenter_tpu.core.provisioner import Provisioner, ProvisionerOptions
    from karpenter_tpu.solver.types import SolverOptions

    recorder = obs.FlightRecorder()
    with obs.use(obs.Tracer(recorder)):
        cloud = FakeCloud(region="us-south")
        pricing = PricingProvider(cloud)
        try:
            cluster = ClusterState()
            nc = NodeClass(name="default", spec=NodeClassSpec(
                region="us-south", image="img-1", vpc="vpc-1",
                instance_requirements=InstanceRequirements(min_cpu=2),
                placement_strategy=PlacementStrategy()))
            nc.status.resolved_image_id = "img-1"
            nc.status.set_condition("Ready", "True", "ObsDemo")
            cluster.add_nodeclass(nc)
            provisioner = Provisioner(
                cluster, InstanceTypeProvider(cloud, pricing),
                Actuator(cloud, cluster),
                ProvisionerOptions(solver=SolverOptions(backend="greedy")))
            for pod in make_pods(12, name_prefix="demo",
                                 requests=ResourceRequests(500, 1024, 0, 1)):
                cluster.add_pod(pod)
                obs.instant("pod.event", pod=pod_key(pod))
            provisioner.provision_once()
        finally:
            pricing.close()
    return recorder


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="karpenter_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    exp = sub.add_parser("export", help="export traces")
    exp.add_argument("--format", choices=("chrome", "jsonl"),
                     default="chrome")
    exp.add_argument("--input", help="span-dump JSONL (chaos artifact); "
                                     "default: run a traced demo cycle")
    exp.add_argument("-o", "--output", default="-",
                     help="output path (default stdout)")
    args = ap.parse_args(argv)

    from karpenter_tpu.obs import export as ex

    if args.input:
        span_dicts = ex.load_jsonl(args.input)
    else:
        span_dicts = ex.recorder_to_dicts(_run_demo())

    if args.format == "chrome":
        text = json.dumps(ex.dicts_to_chrome(span_dicts), indent=1,
                          default=str)
    else:
        text = "\n".join(json.dumps(d, sort_keys=True, default=str)
                         for d in span_dicts)
    if args.output == "-":
        print(text)
    else:
        from pathlib import Path

        p = Path(args.output)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text + "\n")
        print(f"wrote {len(span_dicts)} spans -> {p}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
