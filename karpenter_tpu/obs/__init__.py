"""karpenter_tpu.obs — dependency-free tracing + flight recorder.

Module-level helpers route through one process-wide :class:`Tracer` so
call sites stay one-liners::

    from karpenter_tpu import obs

    with obs.span("actuate.create", zone=zone) as sp:
        ...
        sp.set("claim", claim.name)

    obs.record("solve.h2d", t0, t1)        # retroactive phase span
    obs.instant("pod.event", pod=key)      # zero-duration marker

The chaos harness swaps in a scenario-scoped tracer with :func:`use`
(fresh deterministic id counter per seeded run); bench resets the
recorder between measurement sections with :func:`reset_recorder`.
See docs/design/observability.md.
"""

from __future__ import annotations

from contextlib import contextmanager

from karpenter_tpu.obs.trace import (  # noqa: F401 (public API re-exports)
    FlightRecorder, Span, Tracer, current_span, now,
)
# AFTER trace: importing the ledger pulls in utils.metrics, whose
# package __init__ imports the batcher, which imports this package —
# the batcher's class body reads ``obs.now``, so the trace re-exports
# must already be bound when that re-entrant import observes us.
from karpenter_tpu.obs.ledger import PlacementLedger  # noqa: F401,E402

_tracer = Tracer()
_ledger = PlacementLedger()


def get_tracer() -> Tracer:
    return _tracer


def get_recorder() -> FlightRecorder:
    return _tracer.recorder


def get_ledger() -> PlacementLedger:
    return _ledger


def span(name: str, **kwargs) -> Span:
    return _tracer.span(name, **kwargs)


def record(name: str, start: float, end: float, **kwargs) -> Span:
    return _tracer.record(name, start, end, **kwargs)


def instant(name: str, **attrs) -> None:
    _tracer.instant(name, **attrs)


def event(name: str, **fields) -> None:
    """Attach an event to the active span; dropped when none is open."""
    cur = current_span()
    if cur is not None:
        cur.event(name, **fields)


def reset_recorder(capacity: int = 64, error_capacity: int = 32) -> None:
    """Swap the default tracer onto a fresh recorder (bench measurement
    sections; test isolation)."""
    _tracer.recorder = FlightRecorder(capacity=capacity,
                                      error_capacity=error_capacity)


@contextmanager
def use(tracer: Tracer):
    """Route the module-level helpers through ``tracer`` for the block —
    the chaos harness's per-scenario isolation (deterministic ids)."""
    global _tracer
    prev = _tracer
    _tracer = tracer
    try:
        yield tracer
    finally:
        _tracer = prev


@contextmanager
def use_ledger(ledger: PlacementLedger):
    """Route ledger stamps through ``ledger`` for the block — the soak
    harness installs a fresh one so a production-day run's accounting
    never mixes with ambient process state (mirrors :func:`use`)."""
    global _ledger
    prev = _ledger
    _ledger = ledger
    try:
        yield ledger
    finally:
        _ledger = prev


def phase_durations(prefix: str = "solve.") -> dict[str, list[float]]:
    """name -> [duration_s] of every retained span under ``prefix`` —
    bench's per-phase breakdown source (same spans the recorder serves,
    not a parallel set of perf_counter pairs)."""
    out: dict[str, list[float]] = {}
    for _tid, _status, _root, spans in _tracer.recorder.traces():
        for sp in spans:
            if sp.name.startswith(prefix):
                out.setdefault(sp.name, []).append(sp.duration_s)
    return out


def last_solve_breakdown() -> dict[str, float]:
    """{phase: ms} of the newest trace containing solve phase spans —
    the /statusz "last solve" readout."""
    for _tid, _status, _root, spans in _tracer.recorder.traces():
        phases = {sp.name.removeprefix("solve."):
                  round(sp.duration_s * 1000.0, 3)
                  for sp in spans if sp.name.startswith("solve.")}
        if phases:
            return phases
    return {}
