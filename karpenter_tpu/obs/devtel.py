"""Device telemetry: recompiles, transfer bytes, donation misses.

BENCH_r05 shows the solve path is >98% transfer/dispatch overhead
(exec_fetch ~70 ms vs ~1.2 ms compute; encode_cold ~105-117 ms), but
those numbers were inferred from bench tails — nothing measured them
continuously on the LIVE solve path.  This module is the direct
instrumentation the device-resident-state refactor (ROADMAP item 1)
optimizes against:

- **Recompile events** — every dispatch carries a static-shape
  signature (kernel path + padded G/O/U/N + output layout); a signature
  this process has never dispatched implies an XLA trace+compile (the
  jit cache is keyed by exactly these static args).  Counted per kernel
  and per constraint-signature bucket in
  ``karpenter_tpu_jit_recompiles_total{kernel,bucket}``.
- **Executable-cache hit ratio** — hits/(hits+misses) over the same
  signatures (``karpenter_tpu_executable_cache_events_total{event}``),
  surfaced on ``/statusz`` and ``/debug/slo``.
- **H2D / D2H bytes** — packed-problem uploads, catalog tensor
  re-uploads, and fetched result buffers
  (``karpenter_tpu_device_transfer_bytes_total{direction}`` plus the
  per-window ``karpenter_tpu_solve_h2d_bytes`` histogram).
- **Donation misses** — dispatches whose input was a fresh host array
  instead of a donated device-resident buffer
  (``karpenter_tpu_donation_misses_total{site}``): the per-window
  re-upload debt ROADMAP-1 eliminates.

All accounting happens at DISPATCH level on the host — never inside a
jit-traced function, where a metric call would silently become a
trace-time no-op (graftlint GL107 enforces this over solver/, parallel/,
preempt/ and gang/).
"""

from __future__ import annotations

import threading

from karpenter_tpu.utils import metrics

# distinct static-shape signatures are bounded by the bucket ladders;
# this cap is a leak backstop, far above any real combination count
MAX_SIGNATURES = 4096


class DeviceTelemetry:
    """Thread-safe counters for the live solve path's device traffic."""

    def __init__(self):
        self._lock = threading.Lock()
        # insertion-ordered so the cap evicts FIFO — a plain set would
        # stop admitting at the cap and then count every post-cap
        # signature as a fresh recompile on EVERY dispatch, permanently
        # inflating the exact counter ROADMAP-1 gates its before/after on
        self._signatures: dict[tuple, None] = {}
        self.dispatches = 0
        self.recompiles = 0
        self.cache_hits = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.catalog_uploads = 0
        self.catalog_upload_bytes = 0
        self.donation_misses = 0
        self.donation_miss_bytes = 0
        # explain reason-word share of the fetched result buffers: the
        # [G] int32 words karpenter_tpu/explain appends ride the D2H the
        # solve already pays; this counter makes the overhead auditable
        # (bench gates it < 5% of solve D2H)
        self.explain_d2h_bytes = 0
        # telemetry-word share of the fetched result buffers (the fixed
        # 16-word quality block obs/telemetry_words appends) — same
        # attribution contract as explain_d2h_bytes, same <5% bench gate
        self.telemetry_d2h_bytes = 0
        # resident-state accounting (karpenter_tpu/resident/): windows by
        # mode, delta traffic, last rebuild reason — the /statusz and
        # /debug/slo surface for the store's health
        self.resident_windows = 0
        self.resident_hits = 0
        self.resident_deltas = 0
        self.resident_rebuilds = 0
        self.resident_invalidations = 0
        self.resident_delta_bytes = 0
        self.resident_delta_words_last = 0
        self.resident_bytes = 0
        self.resident_last_rebuild_reason = ""
        self.resident_generation = ""
        # optional hook: called OUTSIDE the lock with (kernel, signature)
        # for every signature this process first dispatches — the AOT
        # executable cache (resident/aot.py) records its manifest here
        self.signature_sink = None
        # optional hook: called OUTSIDE the lock with (kernel,) per
        # recompile event — the anomaly watchdog's burst detector
        # (obs/watchdog.py), installed by obs/prof.get_profiler()
        self.recompile_sink = None

    # -- accounting ----------------------------------------------------------

    def note_dispatch(self, kernel: str, signature: tuple, *,
                      h2d_bytes: int = 0, donated: bool = True,
                      backend: str = "jax") -> bool:
        """One kernel dispatch.  Returns True when the signature was new
        (an executable-cache miss => recompile event)."""
        sig = (kernel, signature)
        with self._lock:
            new = sig not in self._signatures
            if new:
                while len(self._signatures) >= MAX_SIGNATURES:
                    self._signatures.pop(next(iter(self._signatures)))
                self._signatures[sig] = None
            self.dispatches += 1
            if new:
                self.recompiles += 1
            else:
                self.cache_hits += 1
            if h2d_bytes:
                self.h2d_bytes += h2d_bytes
            if not donated:
                self.donation_misses += 1
                self.donation_miss_bytes += h2d_bytes
        bucket = self._bucket(signature)
        if new:
            metrics.JIT_RECOMPILES.labels(kernel, bucket).inc()
            sink = self.signature_sink
            if sink is not None:
                try:
                    sink(kernel, signature)
                except Exception:  # noqa: BLE001 — telemetry must never fail a solve
                    pass
            rsink = self.recompile_sink
            if rsink is not None:
                try:
                    rsink(kernel)
                except Exception:  # noqa: BLE001 — telemetry must never fail a solve
                    pass
        metrics.EXEC_CACHE.labels("miss" if new else "hit").inc()
        if h2d_bytes:
            metrics.TRANSFER_BYTES.labels("h2d").inc(h2d_bytes)
            metrics.SOLVE_H2D_BYTES.labels(backend).observe(h2d_bytes)
        if not donated:
            metrics.DONATION_MISSES.labels(kernel).inc()
        return new

    def note_catalog_upload(self, nbytes: int) -> None:
        """Catalog tensors re-uploaded (device-catalog cache miss)."""
        with self._lock:
            self.catalog_uploads += 1
            self.catalog_upload_bytes += nbytes
            self.h2d_bytes += nbytes
        metrics.TRANSFER_BYTES.labels("h2d").inc(nbytes)

    def note_d2h(self, nbytes: int) -> None:
        with self._lock:
            self.d2h_bytes += nbytes
        metrics.TRANSFER_BYTES.labels("d2h").inc(nbytes)

    def note_explain_d2h(self, nbytes: int) -> None:
        """The explain reason-word slice of a fetched result buffer
        (already counted in note_d2h's total — this is the attribution,
        not an extra transfer)."""
        with self._lock:
            self.explain_d2h_bytes += nbytes

    def note_telemetry_d2h(self, nbytes: int) -> None:
        """The telemetry-word slice of a fetched result buffer (already
        counted in note_d2h's total — attribution, not an extra
        transfer)."""
        with self._lock:
            self.telemetry_d2h_bytes += nbytes

    def note_resident_window(self, mode: str, *, h2d_bytes: int = 0,
                             words: int = 0, reason: str = "",
                             resident_bytes: int = 0,
                             generation=None) -> None:
        """One window through the resident store: ``mode`` is hit (no
        change, zero-delta dispatch), delta (compact update tensors), or
        rebuild (full re-upload; ``reason`` says why)."""
        with self._lock:
            self.resident_windows += 1
            if mode == "hit":
                self.resident_hits += 1
            elif mode == "delta":
                self.resident_deltas += 1
            else:
                self.resident_rebuilds += 1
                self.resident_last_rebuild_reason = reason
            self.resident_delta_bytes += h2d_bytes
            self.resident_delta_words_last = words
            self.resident_bytes = resident_bytes
            if generation is not None:
                self.resident_generation = str(generation)
        metrics.RESIDENT_WINDOWS.labels(mode).inc()
        if mode == "rebuild":
            metrics.RESIDENT_REBUILDS.labels(reason or "unknown").inc()
        metrics.RESIDENT_DELTA_BYTES.observe(h2d_bytes)

    def note_resident_invalidation(self, reason: str) -> None:
        """An explicit store invalidation.  Deliberately NOT counted as
        a rebuild: the reason rides to the next window's actual rebuild
        (note_resident_window), so one logical rebuild is counted once —
        under its cause, not a generic "cold"."""
        with self._lock:
            self.resident_invalidations += 1
            self.resident_last_rebuild_reason = reason

    # -- readout -------------------------------------------------------------

    @staticmethod
    def _bucket(signature: tuple) -> str:
        """Constraint-signature bucket label: the padded problem shape
        (the jit cache key's dominant axis), kept low-cardinality."""
        dims = [str(v) for v in signature
                if isinstance(v, int) and not isinstance(v, bool)][:3]
        return "x".join(dims) if dims else "scalar"

    def hit_ratio(self) -> float:
        with self._lock:
            total = self.cache_hits + self.recompiles
            return self.cache_hits / total if total else 1.0

    def snapshot(self) -> dict:
        with self._lock:
            total = self.cache_hits + self.recompiles
            return {
                "dispatches": self.dispatches,
                "recompiles": self.recompiles,
                "executable_cache_hits": self.cache_hits,
                "executable_cache_hit_ratio":
                    round(self.cache_hits / total, 4) if total else 1.0,
                "h2d_bytes": self.h2d_bytes,
                "d2h_bytes": self.d2h_bytes,
                "catalog_uploads": self.catalog_uploads,
                "catalog_upload_bytes": self.catalog_upload_bytes,
                "donation_misses": self.donation_misses,
                "donation_miss_bytes": self.donation_miss_bytes,
                "explain_d2h_bytes": self.explain_d2h_bytes,
                "telemetry_d2h_bytes": self.telemetry_d2h_bytes,
                "resident": {
                    "windows": self.resident_windows,
                    "hits": self.resident_hits,
                    "deltas": self.resident_deltas,
                    "rebuilds": self.resident_rebuilds,
                    "invalidations": self.resident_invalidations,
                    "delta_h2d_bytes": self.resident_delta_bytes,
                    "last_delta_words": self.resident_delta_words_last,
                    "resident_bytes": self.resident_bytes,
                    "last_rebuild_reason": self.resident_last_rebuild_reason,
                    "generation": self.resident_generation,
                },
            }

    def reset(self) -> None:
        """Bench section isolation (signatures survive — the process's
        compiled executables don't evaporate between sections)."""
        with self._lock:
            self.dispatches = self.recompiles = self.cache_hits = 0
            self.h2d_bytes = self.d2h_bytes = 0
            self.catalog_uploads = self.catalog_upload_bytes = 0
            self.donation_misses = self.donation_miss_bytes = 0
            self.explain_d2h_bytes = 0
            self.telemetry_d2h_bytes = 0
            self.resident_windows = self.resident_hits = 0
            self.resident_deltas = self.resident_rebuilds = 0
            self.resident_invalidations = self.resident_delta_bytes = 0
            self.resident_delta_words_last = self.resident_bytes = 0


# process-wide singleton: dispatch sites are module functions/methods
# spread across solver/ and parallel/, and the refactor's before/after
# comparison needs ONE ledger of device traffic
DEVTEL = DeviceTelemetry()


def get_devtel() -> DeviceTelemetry:
    return DEVTEL
