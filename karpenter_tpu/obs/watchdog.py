"""Anomaly watchdog: EWMA baselines over sampled device timings +
recompile-burst detection, emitting rate-limited triage bundles.

The profiler (obs/prof.py) feeds every sampled (kernel, phase) duration
into rolling EWMA baselines (mean + mean absolute deviation).  A sample
that lands far outside its baseline — after a warmup count, above an
absolute floor, and beyond both a ratio and a deviation-multiple bound —
is a **breach**: the tail event the host-side phase histograms
structurally cannot attribute (a slow bucket could be tunnel, chip, or
host; the sampled split says which).  A burst of jit recompiles inside
a rolling window (devtel's ``recompile_sink``) breaches the same way:
recompile storms are the classic silent solve-latency cliff.

A breach triggers a **triage bundle**: one directory under ``.triage/``
holding the flight-recorder span bundle, the placement ledger's worst-K
table, the devtel/resident snapshot, the profiler split, and the breach
detail — everything an operator needs to answer "what just got slow"
without reproducing it.  Bundles are:

- **rate-limited** (one per ``rate_limit_s`` on the obs clock — which
  the chaos VirtualClock patches, so suppression windows are
  deterministic under virtual time);
- **size-bounded** (span dump capped at ``MAX_BUNDLE_SPANS`` lines,
  worst-K at 16 rows) and **FIFO-capped** (oldest bundle directories
  pruned past ``max_bundles``);
- **counted** (``karpenter_tpu_watchdog_breaches_total{kernel,phase}``,
  ``karpenter_tpu_triage_bundles_total{trigger}``,
  ``karpenter_tpu_watchdog_suppressed_total{trigger}``).

Baselines deliberately do NOT absorb breach samples: an anomaly must
not poison the mean it was judged against (a sustained slowdown keeps
breaching; the rate limit keeps the disk quiet).

``chaos/soak.py`` reuses :func:`write_triage_bundle` directly: an SLO
burn in ``make soak`` / ``soak-short`` writes a bundle next to the burn
report, and CI uploads ``.triage/`` as an artifact.  An optional
programmatic ``jax.profiler`` trace rides along when
``KARPENTER_TRIAGE_JAX_TRACE=1`` (best-effort — a profiling failure
must never fail the bundle).  See docs/design/profiling.md.
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import threading
import time
from collections import deque
from pathlib import Path

from karpenter_tpu.obs.trace import now
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.logging import get_logger

log = get_logger("obs.watchdog")

DEFAULT_TRIAGE_DIR = os.environ.get("KARPENTER_TRIAGE_DIR", ".triage")
MAX_BUNDLE_SPANS = 5000
MAX_BUNDLE_WORST = 16

_BUNDLE_SEQ = itertools.count(1)


class Baseline:
    """Rolling EWMA of a (kernel, phase) duration stream: mean + mean
    absolute deviation (cheaper and more outlier-robust than EWM
    variance at these sample rates)."""

    __slots__ = ("mean", "dev", "n")
    ALPHA = 0.2

    def __init__(self):
        self.mean = 0.0
        self.dev = 0.0
        self.n = 0

    def update(self, x: float) -> None:
        if self.n == 0:
            self.mean = x
        else:
            self.dev += self.ALPHA * (abs(x - self.mean) - self.dev)
            self.mean += self.ALPHA * (x - self.mean)
        self.n += 1


class Watchdog:
    """Per-process anomaly detector over the profiler's sample stream."""

    WARMUP = 5              # samples before a baseline can breach
    DEV_MULT = 6.0          # breach: > mean + DEV_MULT * dev ...
    MIN_RATIO = 2.0         # ... AND > MIN_RATIO * mean ...
    MIN_ABS_S = 0.001       # ... AND over an absolute floor (sub-ms
    #                         wobble on a fast kernel is noise, not an
    #                         anomaly worth a bundle)
    RECOMPILE_BURST = 8     # recompiles inside the window that breach
    RECOMPILE_WINDOW_S = 60.0
    # the burst detector arms AFTER a grace period: a fresh process
    # compiling its kernel set is a cold start, not an anomaly — the
    # page-worthy signal is a recompile storm in a WARM process
    # (catalog churn exploding the shape buckets)
    RECOMPILE_GRACE_S = 120.0
    # solver-quality regression (obs/telemetry_words feeds every decoded
    # window's fill here): a window whose fill collapses below
    # QUALITY_COLLAPSE x the plane's EWMA baseline — provided the
    # baseline itself is meaningful (>= QUALITY_MIN_BASELINE_BP; a
    # near-empty fleet "collapsing" to empty is not a regression) —
    # after QUALITY_WARMUP windows.  Escalation re-dispatches burst the
    # way recompiles do: ESCALATION_BURST inside the rolling window.
    QUALITY_WARMUP = 8
    QUALITY_COLLAPSE = 0.5
    QUALITY_MIN_BASELINE_BP = 1000
    ESCALATION_BURST = 8
    ESCALATION_WINDOW_S = 60.0

    def __init__(self, *, triage_dir: str | None = None,
                 rate_limit_s: float = 300.0, max_bundles: int = 8,
                 warmup: int | None = None,
                 recompile_grace_s: float | None = None):
        self.triage_dir = triage_dir or DEFAULT_TRIAGE_DIR
        self.rate_limit_s = rate_limit_s
        self.max_bundles = max_bundles
        self.warmup = self.WARMUP if warmup is None else warmup
        self._armed_at = now() + (
            self.RECOMPILE_GRACE_S if recompile_grace_s is None
            else recompile_grace_s)
        self._lock = threading.Lock()
        self._baselines: dict[tuple[str, str], Baseline] = {}
        self._recompiles: deque[float] = deque()
        # solver-quality state (telemetry words): per-plane fill EWMA +
        # a rolling escalation-event window shaped like the recompile one
        self._quality: dict[str, Baseline] = {}
        self._escalations: deque[float] = deque()
        self._last_bundle_t: float | None = None
        self.breaches = 0
        self.bundles = 0
        self.suppressed = 0
        self.last_breach: dict = {}
        self.last_bundle_path = ""

    # -- detection -----------------------------------------------------------

    def observe(self, kernel: str, phase: str, value: float) -> bool:
        """One sampled duration.  Returns True when it breached (a
        bundle may or may not have been written — the rate limit
        decides)."""
        with self._lock:
            b = self._baselines.setdefault((kernel, phase), Baseline())
            breach = (b.n >= self.warmup
                      and value >= self.MIN_ABS_S
                      and value > b.mean * self.MIN_RATIO
                      and value > b.mean + self.DEV_MULT * b.dev)
            detail = None
            if breach:
                self.breaches += 1
                detail = {
                    "kernel": kernel, "phase": phase,
                    "value_s": round(value, 6),
                    "baseline_mean_s": round(b.mean, 6),
                    "baseline_dev_s": round(b.dev, 6),
                    "baseline_n": b.n,
                }
                self.last_breach = detail
            else:
                # breach samples never update the baseline they were
                # judged against (an anomaly must not raise the bar for
                # the next one)
                b.update(value)
        if detail is not None:
            metrics.WATCHDOG_BREACHES.labels(kernel, phase).inc()
            self.trigger("slow_kernel", detail)
        return detail is not None

    def note_recompile(self, kernel: str) -> bool:
        """One jit recompile event (devtel recompile_sink).  A burst of
        RECOMPILE_BURST inside the rolling window breaches — and resets
        the window so a sustained storm re-arms instead of re-firing
        per event.  Events inside the cold-start grace period are
        recorded but never breach (a fresh process compiling its
        kernels is normal)."""
        t = now()
        with self._lock:
            self._recompiles.append(t)
            cutoff = t - self.RECOMPILE_WINDOW_S
            while self._recompiles and self._recompiles[0] < cutoff:
                self._recompiles.popleft()
            burst = t >= self._armed_at \
                and len(self._recompiles) >= self.RECOMPILE_BURST
            if burst:
                count = len(self._recompiles)
                self._recompiles.clear()
                self.breaches += 1
                detail = {"kernel": kernel, "recompiles_in_window": count,
                          "window_s": self.RECOMPILE_WINDOW_S}
                self.last_breach = detail
        if burst:
            metrics.WATCHDOG_BREACHES.labels(kernel, "recompile").inc()
            self.trigger("recompile_burst", detail)
        return burst

    def note_quality(self, plane: str, fill_bp: int, *,
                     escalations: int = 0) -> bool:
        """One decoded solve window's quality telemetry
        (obs/telemetry_words.record_window).  Two detectors:

        - **fill collapse** — the window's dominant fill fraction (basis
          points) lands below QUALITY_COLLAPSE x the plane's EWMA
          baseline while the baseline is meaningful: the solver suddenly
          packs far worse than it just did (a constraint encoding bug, a
          catalog regression, a quietly degraded lane) even though every
          latency metric looks healthy.
        - **escalation burst** — ESCALATION_BURST host-side re-dispatch
          retries (node escalation / COO growth) inside the rolling
          window: each retry re-pays the full dispatch RTT, so a storm
          is a latency cliff with a solver-shaped cause.

        Returns True when either breached.  Breach windows never update
        the baseline they were judged against (the slow_kernel rule)."""
        t = now()
        qdetail = edetail = None
        with self._lock:
            b = self._quality.setdefault(plane, Baseline())
            collapse = (b.n >= self.QUALITY_WARMUP
                        and b.mean >= self.QUALITY_MIN_BASELINE_BP
                        and fill_bp < b.mean * self.QUALITY_COLLAPSE)
            if collapse:
                self.breaches += 1
                qdetail = {
                    "plane": plane, "fill_bp": int(fill_bp),
                    "baseline_mean_bp": round(b.mean, 1),
                    "baseline_n": b.n,
                    "collapse_ratio": self.QUALITY_COLLAPSE,
                }
                self.last_breach = qdetail
            else:
                b.update(float(fill_bp))
            if escalations:
                self._escalations.extend([t] * min(int(escalations), 64))
                cutoff = t - self.ESCALATION_WINDOW_S
                while self._escalations and self._escalations[0] < cutoff:
                    self._escalations.popleft()
                if len(self._escalations) >= self.ESCALATION_BURST:
                    count = len(self._escalations)
                    self._escalations.clear()
                    self.breaches += 1
                    edetail = {"plane": plane,
                               "escalations_in_window": count,
                               "window_s": self.ESCALATION_WINDOW_S}
                    self.last_breach = edetail
        if qdetail is not None:
            metrics.WATCHDOG_BREACHES.labels(plane, "quality").inc()
            self.trigger("quality_regression", qdetail)
        if edetail is not None:
            metrics.WATCHDOG_BREACHES.labels(plane, "escalation").inc()
            self.trigger("escalation_burst", edetail)
        return qdetail is not None or edetail is not None

    # -- bundle emission -----------------------------------------------------

    def trigger(self, trigger: str, detail: dict) -> str | None:
        """Rate-limited bundle write.  Returns the bundle path, or None
        when suppressed (or the write failed — a watchdog must never
        take down the path it watches)."""
        with self._lock:
            t = now()
            if self._last_bundle_t is not None \
                    and t - self._last_bundle_t < self.rate_limit_s:
                self.suppressed += 1
                metrics.WATCHDOG_SUPPRESSED.labels(trigger).inc()
                return None
            self._last_bundle_t = t
        try:
            path = write_triage_bundle(
                trigger, detail, triage_dir=self.triage_dir,
                max_bundles=self.max_bundles)
        except Exception as e:  # noqa: BLE001 — never fail the solve path
            log.warning("triage bundle write failed", trigger=trigger,
                        error=str(e)[:200])
            metrics.ERRORS.labels("watchdog", "bundle_write").inc()
            return None
        with self._lock:
            self.bundles += 1
            self.last_bundle_path = path
        metrics.TRIAGE_BUNDLES.labels(trigger).inc()
        return path

    # -- readout -------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "breaches": self.breaches,
                "bundles": self.bundles,
                "suppressed": self.suppressed,
                "baselines": len(self._baselines),
                "quality_baselines": len(self._quality),
                "recompile_burst_armed": now() >= self._armed_at,
                "rate_limit_s": self.rate_limit_s,
                "max_bundles": self.max_bundles,
                "triage_dir": self.triage_dir,
                "last_breach": dict(self.last_breach),
                "last_bundle": self.last_bundle_path,
            }

    def reset(self) -> None:
        with self._lock:
            self._baselines.clear()
            self._recompiles.clear()
            self._quality.clear()
            self._escalations.clear()
            self._last_bundle_t = None
            self.breaches = self.bundles = self.suppressed = 0
            self.last_breach = {}
            self.last_bundle_path = ""


def write_triage_bundle(trigger: str, detail: dict, *,
                        triage_dir: str = DEFAULT_TRIAGE_DIR,
                        max_bundles: int = 8,
                        recorder=None, ledger=None,
                        extra: dict | None = None) -> str:
    """One self-contained triage directory:

    - ``bundle.json`` — trigger + detail, the ledger worst-K table
      (trace ids link into the span dump), ledger/devtel/resident/
      profiler/watchdog snapshots, optional caller extras;
    - ``spans.jsonl`` — the flight recorder's retained span bundle
      (size-capped), same wire format as the chaos violation artifact
      and convertible to Perfetto via ``python -m karpenter_tpu.obs
      export --input``.

    FIFO-capped: bundle directories past ``max_bundles`` are pruned
    oldest-first (directory names sort by write order)."""
    from karpenter_tpu import obs
    from karpenter_tpu.obs.devtel import get_devtel
    from karpenter_tpu.obs.export import dump_jsonl, recorder_to_dicts
    from karpenter_tpu.obs.prof import get_profiler

    recorder = recorder or obs.get_recorder()
    ledger = ledger or obs.get_ledger()
    seq = next(_BUNDLE_SEQ)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(time.time()))
    bdir = Path(triage_dir) / f"{stamp}-{seq:04d}-{trigger}"
    bdir.mkdir(parents=True, exist_ok=True)
    spans = recorder_to_dicts(recorder)[:MAX_BUNDLE_SPANS]
    dump_jsonl(spans, bdir / "spans.jsonl")
    manifest = {
        "trigger": trigger,
        "detail": detail,
        "written_at": time.time(),
        "span_count": len(spans),
        "worst_pods": ledger.worst()[:MAX_BUNDLE_WORST],
        "ledger": ledger.stats(),
        "device_telemetry": get_devtel().snapshot(),
        "profiler": get_profiler().snapshot(),
        "watchdog": get_watchdog().snapshot(),
    }
    if extra:
        manifest.update(extra)
    _maybe_jax_trace(bdir)
    (bdir / "bundle.json").write_text(
        json.dumps(manifest, indent=2, default=str, sort_keys=True) + "\n")
    _prune_fifo(Path(triage_dir), max_bundles)
    return str(bdir)


def _maybe_jax_trace(bdir: Path) -> None:
    """Optional programmatic jax.profiler trace into the bundle —
    env-gated (a device trace is heavy and needs live dispatches to be
    useful) and best-effort (a trace session already running, or no
    jax at all, must not fail the bundle)."""
    if os.environ.get("KARPENTER_TRIAGE_JAX_TRACE") != "1":
        return
    try:
        import jax

        jax.profiler.start_trace(str(bdir / "jax-trace"))
        time.sleep(0.25)
        jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001 — best-effort extra evidence
        log.warning("triage jax trace failed", error=str(e)[:200])


def _prune_fifo(root: Path, max_bundles: int) -> None:
    if not root.is_dir():
        return
    dirs = sorted(p for p in root.iterdir() if p.is_dir())
    for stale in dirs[:max(0, len(dirs) - max_bundles)]:
        shutil.rmtree(stale, ignore_errors=True)


# process-wide singleton (same rationale as devtel/prof: one anomaly
# ledger across every dispatch site)
_WATCHDOG: Watchdog | None = None
_SINGLETON_LOCK = threading.Lock()


def get_watchdog() -> Watchdog:
    global _WATCHDOG
    if _WATCHDOG is None:
        with _SINGLETON_LOCK:
            if _WATCHDOG is None:
                _WATCHDOG = Watchdog()
    return _WATCHDOG
