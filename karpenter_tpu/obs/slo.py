"""Declarative SLOs + burn-rate evaluation over the placement ledger.

An :class:`SLOSpec` is pure data (no YAML): an objective key into a
measurement snapshot, a threshold, and a burn window.  The evaluator
compares each spec against measurements assembled from the placement
ledger (obs/ledger.py), the flight recorder, and device telemetry, and
renders a burn-rate report that NAMES the violating pods and the trace
bundles holding their causal chains — a failed SLO gate hands the
operator evidence, not a number.

Consumers:

- ``make soak`` (chaos/soak.py) — the simulated-production-day gate:
  composes chaos profiles on the VirtualClock and fails the run on any
  burned SLO;
- ``/debug/slo`` (operator/server.py) — the live readout, same
  evaluator, default specs;
- ``bench.py`` — emits :func:`slo_summary` into the trajectory JSON so
  the bench files gain p99/staleness columns.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from karpenter_tpu.obs.ledger import PlacementLedger
from karpenter_tpu.obs.trace import FlightRecorder, now


def quantile(xs: list[float], q: float) -> float:
    """Nearest-rank quantile (deterministic, no interpolation jitter)."""
    if not xs:
        return 0.0
    s = sorted(xs)
    idx = min(len(s) - 1, max(0, int(q * len(s) + 0.999999) - 1))
    return s[idx]


@dataclass(frozen=True)
class SLOSpec:
    """One service-level objective.  ``objective`` keys into the
    measurement snapshot; ``comparison`` is "le" (value must stay at or
    under threshold) or "ge"."""

    name: str
    objective: str
    threshold: float
    burn_window_s: float = 600.0
    comparison: str = "le"
    description: str = ""

    def ok(self, value: float) -> bool:
        return value >= self.threshold if self.comparison == "ge" \
            else value <= self.threshold


@dataclass
class Measurement:
    """One objective's evidence: the headline value, optional
    (timestamp, value) samples for burn-rate windows, and the violator
    table (pods + trace ids) shown when the SLO burns."""

    value: float
    samples: list[tuple[float, float]] = field(default_factory=list)
    violators: list[dict] = field(default_factory=list)


@dataclass
class SLOResult:
    spec: SLOSpec
    value: float
    ok: bool
    # windowed violating fraction (or value/threshold for scalar gauges)
    burn_rate: float
    violators: list[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "slo": self.spec.name,
            "objective": self.spec.objective,
            "threshold": self.spec.threshold,
            "comparison": self.spec.comparison,
            "value": round(self.value, 6),
            "ok": self.ok,
            "burn_rate": round(self.burn_rate, 4),
            "burn_window_s": self.spec.burn_window_s,
            "violators": self.violators,
        }


@dataclass
class SLOReport:
    results: list[SLOResult]

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    @property
    def burned(self) -> list[SLOResult]:
        return [r for r in self.results if not r.ok]

    def to_dict(self) -> dict:
        return {"ok": self.ok,
                "burned": [r.spec.name for r in self.burned],
                "results": [r.to_dict() for r in self.results]}

    def render(self) -> str:
        lines = []
        for r in self.results:
            mark = "ok  " if r.ok else "BURN"
            lines.append(
                f"{mark} {r.spec.name:<28} {r.spec.objective}="
                f"{r.value:.4g} (threshold {r.spec.comparison} "
                f"{r.spec.threshold:g}, burn_rate {r.burn_rate:.2f})")
            for v in ([] if r.ok else r.violators[:5]):
                lines.append(
                    f"       pod={v.get('pod', '?')} "
                    f"took={v.get('duration_s', 0):.3f}s "
                    f"trace_id={v.get('trace_id', 0)}"
                    + (f" bundle={v['bundle']}" if v.get("bundle") else ""))
        status = "ALL SLOS MET" if self.ok else \
            f"{len(self.burned)} SLO(S) BURNED"
        lines.append(f"slo report: {status}")
        return "\n".join(lines)


def evaluate_slos(specs: list[SLOSpec],
                  measurements: dict[str, Measurement],
                  at: float | None = None) -> SLOReport:
    """Compare every spec against its measurement.  A missing objective
    evaluates as a burn (value NaN-ish via -inf/inf would hide bugs;
    an SLO nobody measures is a failed SLO, loudly)."""
    at = now() if at is None else at
    results = []
    for spec in specs:
        m = measurements.get(spec.objective)
        if m is None:
            results.append(SLOResult(
                spec=spec, value=float("inf"), ok=False, burn_rate=1.0,
                violators=[{"pod": f"<objective {spec.objective!r} "
                                   f"not measured>", "trace_id": 0}]))
            continue
        ok = spec.ok(m.value)
        if m.samples:
            cutoff = at - spec.burn_window_s
            windowed = [(t, v) for t, v in m.samples if t >= cutoff] \
                or m.samples
            bad = sum(1 for _, v in windowed if not spec.ok(v))
            burn = bad / len(windowed)
        else:
            burn = 0.0 if ok else (
                m.value / spec.threshold if spec.threshold > 0
                and spec.comparison == "le" else 1.0)
        results.append(SLOResult(spec=spec, value=m.value, ok=ok,
                                 burn_rate=round(burn, 4),
                                 violators=list(m.violators)))
    return SLOReport(results=results)


# ---------------------------------------------------------------------------
# Measurement assembly
# ---------------------------------------------------------------------------

# (real perf_counter stamp, cached value) — /debug/slo is scraped by
# dashboards; re-running a 2000-iteration busy loop per request would
# make the observability endpoint the overhead it measures
_OVERHEAD_CACHE: list = [0.0, 0.0]
_OVERHEAD_TTL_S = 60.0


def measure_recorder_overhead_us(samples: int = 2000,
                                 max_age_s: float = _OVERHEAD_TTL_S
                                 ) -> float:
    """Per-stamp cost of the telemetry hot path (one ledger stamp + one
    retroactive span), measured with ``perf_counter`` — which the chaos
    VirtualClock deliberately does NOT patch, so the overhead SLO stays
    a real-microseconds gate even inside a virtual-time soak.  Cached
    for ``max_age_s`` real seconds (pass 0 to force a fresh run)."""
    from karpenter_tpu.obs.trace import Tracer

    measured_at, cached = _OVERHEAD_CACHE
    if cached and time.perf_counter() - measured_at < max_age_s:
        return cached
    ledger = PlacementLedger(capacity=8, error_capacity=8)
    tracer = Tracer(FlightRecorder(capacity=8, error_capacity=8))
    ledger.first_seen("overhead-probe")
    t_now = now()
    t0 = time.perf_counter()
    for _ in range(samples):
        ledger.stamp("overhead-probe", "window_enqueue")
        tracer.record("solve.h2d", t_now, t_now + 0.001)
    per = (time.perf_counter() - t0) / samples
    value = per * 1e6 / 2.0         # two operations per iteration
    _OVERHEAD_CACHE[0] = time.perf_counter()
    _OVERHEAD_CACHE[1] = value
    return value


def ledger_measurements(ledger: PlacementLedger,
                        recorder: FlightRecorder | None = None,
                        extra: dict[str, Measurement] | None = None,
                        threshold_hint: float | None = None,
                        measure_overhead: bool = True
                        ) -> dict[str, Measurement]:
    """The standard measurement snapshot the default SLOs evaluate:

    - ``pod_placement_p99_s``: nearest-rank p99 over retained
      resolutions, violators = the ledger's worst-case table (trace ids
      attached), filtered to entries over ``threshold_hint`` when given;
    - ``pending_staleness_s``: the staleness HIGH-WATER mark (a gauge
      sampled only at quiet moments would lie about the worst case);
    - ``degraded_rate``: degraded/released resolutions over all
      resolutions (gang releases, degraded placements);
    - ``recorder_overhead_us``: measured per-stamp cost (real µs);
    - ``recorder_dropped_fraction``: spans dropped / spans retained+dropped.
    """
    samples = ledger.resolution_samples()
    durations = [d for _, d, _ in samples]
    p99 = quantile(durations, 0.99)
    worst = ledger.worst()
    if threshold_hint is not None:
        over = [w for w in worst if w["duration_s"] > threshold_hint]
        worst = over or worst[:3]
    stats = ledger.stats()
    ledger.pending_staleness()      # refresh the high-water mark
    resolved = max(1, stats["resolved_total"])
    degraded = sum(n for outcome, n in stats["outcomes"].items()
                   if outcome in ("placed_degraded", "released", "failed"))
    out = {
        "pod_placement_p99_s": Measurement(
            value=p99,
            samples=[(t, d) for t, d, _ in samples],
            violators=worst),
        "pending_staleness_s": Measurement(
            value=ledger.staleness_high_water),
        "degraded_rate": Measurement(
            value=degraded / resolved,
            violators=[r.to_dict() for _, _, r in samples
                       if r.outcome in ("placed_degraded", "released",
                                        "failed")][:8]),
    }
    if measure_overhead:
        out["recorder_overhead_us"] = Measurement(
            value=measure_recorder_overhead_us())
    if recorder is not None:
        rstats = recorder.stats()
        kept = max(1, rstats["traces_total"] + rstats["instants_total"])
        out["recorder_dropped_fraction"] = Measurement(
            value=rstats["dropped_spans"] / kept)
    if extra:
        out.update(extra)
    return out


def telemetry_measurements() -> dict[str, Measurement]:
    """Solver-quality measurements from the device telemetry words
    (obs/telemetry_words: the per-window slots every solve plane emits
    inside its fused dispatch), aggregated over the recorder's bounded
    telemetry ring:

    - ``telemetry_escalations_per_window``: node-escalation + COO-growth
      re-dispatches per recorded window across all planes — a healthy
      day re-dispatches rarely; a chronically escalating one is sized
      wrong;
    - ``telemetry_min_fill_fraction``: the lowest per-plane mean fill
      fraction over retained windows, planes with fewer than 8 windows
      skipped (too few samples to call a collapse).  Open nodes exist
      because pods landed on them, so a healthy FFD keeps this well
      above the floor; a collapse is a solver-quality regression, the
      same signal the watchdog's EWMA detector fires on live.
    """
    from karpenter_tpu.obs import telemetry_words

    s = telemetry_words.summary()
    planes = s.get("planes", {})
    windows = sum(p["windows"] for p in planes.values())
    esc = sum(p["escalations"] + p["coo_growths"]
              for p in planes.values())
    meaningful = {name: p for name, p in planes.items()
                  if p["windows"] >= 8}
    fills = [p["mean_fill_fraction"] for p in meaningful.values()]
    return {
        "telemetry_escalations_per_window": Measurement(
            value=esc / windows if windows else 0.0),
        "telemetry_min_fill_fraction": Measurement(
            value=min(fills) if fills else 1.0,
            violators=[{"pod": f"<plane {name}: mean_fill="
                               f"{p['mean_fill_fraction']:.4f} over "
                               f"{p['windows']} windows>",
                        "trace_id": 0}
                       for name, p in sorted(meaningful.items(),
                                             key=lambda kv:
                                             kv[1]["mean_fill_fraction"])
                       ][:5]),
    }


# The production-day gate (chaos/soak.py) — thresholds in VIRTUAL
# seconds for the latency/staleness objectives (soak rounds advance the
# clock 60s per beat; three beats of queueing is the budget), and real
# microseconds for the overhead gate.
DEFAULT_SOAK_SLOS: tuple[SLOSpec, ...] = (
    SLOSpec(name="p99-pod-to-placement", objective="pod_placement_p99_s",
            threshold=3600.0, burn_window_s=7200.0,
            description="99% of pods get a placement decision within 1 "
                        "virtual hour of first-seen — pods stranded "
                        "behind the overload quota legitimately wait "
                        "~1-2 quiesce beats (1200s each); a pod that "
                        "needs MORE than an hour is a stuck plane, not "
                        "a busy one"),
    SLOSpec(name="pending-staleness", objective="pending_staleness_s",
            threshold=7200.0, burn_window_s=7200.0,
            description="no pod waits unresolved past 2 virtual hours "
                        "(high-water, not a quiet-moment sample)"),
    SLOSpec(name="degraded-mode-rate", objective="degraded_rate",
            threshold=0.25, burn_window_s=3600.0,
            description="under 25% of resolutions ride a degraded path "
                        "(gang release / degraded placement)"),
    SLOSpec(name="recorder-overhead", objective="recorder_overhead_us",
            threshold=75.0,
            description="ledger stamp + span record stay at the "
                        "microsecond bound tests pin (real time, "
                        "measured inside the soak)"),
    SLOSpec(name="recorder-drops", objective="recorder_dropped_fraction",
            threshold=0.5,
            description="the flight recorder keeps at least half of "
                        "what it is asked to retain"),
)

# Fixture: provably impossible — the soak evaluates it on EVERY run and
# fails unless it burns, showing a real violation fails the gate (an SLO
# harness that cannot fail is decoration).  Threshold -1 so even an
# all-zero-latency day (every pod resolved within its arrival beat of
# the VirtualClock) still burns it: p99 >= 0 > -1 always.
BROKEN_FIXTURE_SLO = SLOSpec(
    name="broken-fixture", objective="pod_placement_p99_s",
    threshold=-1.0, description="deliberately unmeetable: any measured "
                                "p99 (>= 0) burns it")


def slo_summary(ledger: PlacementLedger,
                specs: tuple[SLOSpec, ...] = DEFAULT_SOAK_SLOS) -> dict:
    """Compact summary block for bench trajectory JSON / statusz: the
    p99/staleness columns plus per-SLO pass state."""
    durations = ledger.durations()
    # the overhead gate is a real-time microbenchmark — skip it for the
    # summary path (bench runs it as its own target elsewhere)
    cheap = [s for s in specs
             if s.objective not in ("recorder_overhead_us",
                                    "recorder_dropped_fraction")]
    report = evaluate_slos(
        cheap, ledger_measurements(ledger, measure_overhead=False))
    return {
        "pod_placement_p50_s": round(quantile(durations, 0.50), 6),
        "pod_placement_p99_s": round(quantile(durations, 0.99), 6),
        "pending_staleness_s": round(ledger.staleness_high_water, 6),
        "snapshot_staleness_s": round(ledger.snapshot_staleness(), 6),
        "resolved": ledger.stats()["resolved_total"],
        "slos": {r.spec.name: r.ok for r in report.results},
    }


def debug_slo_payload(ledger: PlacementLedger,
                      recorder: FlightRecorder | None = None,
                      devtel=None) -> dict:
    """The ``/debug/slo`` endpoint body: live evaluation of the default
    specs, the worst-case pod table (trace ids link into
    ``/debug/traces``), ledger stats, and the device-telemetry
    snapshot."""
    report = evaluate_slos(
        list(DEFAULT_SOAK_SLOS),
        ledger_measurements(ledger, recorder=recorder))
    if devtel is None:
        from karpenter_tpu.obs.devtel import get_devtel

        devtel = get_devtel()
    return {
        "report": report.to_dict(),
        "worst_pods": ledger.worst(),
        "ledger": ledger.stats(),
        "pending_staleness_s": round(ledger.pending_staleness(), 6),
        "device_telemetry": devtel.snapshot(),
    }
