"""Spans + flight recorder: the causal record of a provisioning cycle.

Dependency-free tracing for the pod-event -> batch -> solve -> actuate ->
cloud-RPC path.  Three design constraints shape everything here:

- **Cheap on the hot path.**  A span is one small ``__slots__`` object;
  completed traces land in a *preallocated* ring-buffer slot (the list
  itself never grows), and retroactive phase spans (``Tracer.record``)
  cost one allocation + one slot write — no context-manager machinery on
  the solver's timing path.  tests/test_obs.py asserts the per-span
  bound.
- **Deterministic under the chaos VirtualClock.**  Every timestamp is
  read through ``now()``, which resolves ``time.monotonic`` at CALL time
  — the chaos harness patches the ``time`` module attributes
  (chaos/clock.py), so scenario spans carry virtual durations and the
  span dump of a seeded run is structurally reproducible.  Span/trace
  ids come from a per-tracer counter, never ``uuid``/``random``.
- **Bounded memory, errors never evicted by success.**  The flight
  recorder retains the last N completed traces in one ring and every
  trace that ended in error in a SEPARATE ring — a hot success path
  cannot flush the one failed cycle an operator needs to see.

Context propagation uses a ``contextvars.ContextVar``: spans opened on
the same thread of control nest automatically (the window handler, the
solve, the actuation, and each cloud RPC attempt all run synchronously
on the fired window's executor thread).  Cross-thread hand-off (the
pipelined solve's dispatch vs. fetch) passes the parent span explicitly.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time


def now() -> float:
    """Monotonic clock read at call time — the chaos VirtualClock patches
    ``time.monotonic``, so scenario spans run on virtual time."""
    return time.monotonic()


_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "karpenter_tpu_span", default=None)


def current_span() -> "Span | None":
    return _CURRENT.get()


class Span:
    """One timed operation.  Doubles as its own context manager so the
    common path (``with tracer.span(...)``) allocates exactly one object.

    ``attrs``/``events`` are lazy — a bare span allocates neither."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "end", "status", "error", "attrs", "events",
                 "_tracer", "_token")

    def __init__(self, tracer: "Tracer", trace_id: int, span_id: int,
                 parent_id: int, name: str, start: float,
                 attrs: dict | None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.end = 0.0
        self.status = "ok"
        self.error = ""
        self.attrs = attrs
        self.events = None
        self._tracer = tracer
        self._token = None

    # -- mutation ----------------------------------------------------------

    def set(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def event(self, name: str, **fields) -> None:
        if self.events is None:
            self.events = []
        if len(self.events) < 64:      # bounded: events must not grow a trace
            self.events.append({"name": name, "t": now(), **fields})

    def fail(self, error) -> None:
        """Mark failed without an exception propagating through the span
        (handlers that convert exceptions into per-caller results)."""
        self.status = "error"
        self.error = str(error)[:200]

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end - self.start)

    # -- context manager ----------------------------------------------------

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, et, ev, tb) -> bool:
        if et is not None:
            self.status = "error"
            self.error = f"{et.__name__}: {ev}"[:200]
        self.end = now()
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        self._tracer._finish(self)
        return False


class FlightRecorder:
    """Bounded in-memory retention of completed traces.

    Two preallocated rings (fixed-size lists written by index — the hot
    path never grows a container): ``capacity`` recent traces regardless
    of status, plus ``error_capacity`` traces that ended in error, so
    failures survive an arbitrarily long success streak.  Parentless
    instant spans (pod events, breaker transitions) go to a third small
    ring rather than each becoming a one-span trace."""

    MAX_SPANS_PER_TRACE = 1000
    MAX_OPEN_TRACES = 256

    def __init__(self, capacity: int = 64, error_capacity: int = 32,
                 instant_capacity: int = 256,
                 telemetry_capacity: int = 256):
        self.capacity = capacity
        self.error_capacity = error_capacity
        self.instant_capacity = instant_capacity
        self.telemetry_capacity = telemetry_capacity
        self._lock = threading.Lock()
        # preallocated slots; _n_* monotonically count writes
        self._ring: list = [None] * capacity
        self._n_ring = 0
        self._err_ring: list = [None] * error_capacity
        self._n_err = 0
        self._instants: list = [None] * instant_capacity
        self._n_instants = 0
        # per-window solver-quality telemetry (obs/telemetry_words):
        # decoded slot dicts, one per solve window, same preallocated-
        # ring discipline as spans — /debug/telemetry reads this
        self._telemetry: list = [None] * telemetry_capacity
        self._n_telemetry = 0
        # trace_id -> [spans] completed so far (root still open)
        self._open: dict[int, list] = {}
        # trace_id -> finalized trace tuple, insertion-ordered and
        # bounded: late spans (a pipelined drain finishing after its
        # window's root closed) attach here instead of re-opening a
        # stale _open entry that no root would ever finalize
        self._finalized: dict[int, tuple] = {}
        self.dropped_spans = 0
        # wall/monotonic anchor pair: exports convert monotonic span
        # times to an absolute-ish display timeline
        self.anchor_monotonic = now()
        self.anchor_wall = time.time()

    # -- ingestion ----------------------------------------------------------

    def _drop_locked(self) -> None:
        """Bounded-memory drop accounting: the process counter AND the
        scrapeable karpenter_tpu_recorder_dropped_spans_total series (a
        recorder silently shedding evidence is itself an SLO signal).
        Lazy import: utils.metrics' package __init__ imports the
        batcher, which imports obs — a module-top import here would
        re-enter this module half-built."""
        self.dropped_spans += 1
        from karpenter_tpu.utils import metrics

        metrics.RECORDER_DROPPED.inc()

    def add(self, span: Span) -> None:
        """A span completed.  Root completion finalizes the trace into a
        ring slot; non-root spans accumulate under their open trace."""
        with self._lock:
            spans = self._open.get(span.trace_id)
            if spans is None:
                done = self._finalized.get(span.trace_id)
                if done is not None:
                    # late arrival for a finalized trace: attach to its
                    # span list (still referenced by the ring tuple) so
                    # readouts see it.  The trace's status is already
                    # sealed — a late error span doesn't re-file it into
                    # the error ring.
                    if len(done[3]) < self.MAX_SPANS_PER_TRACE:
                        done[3].append(span)
                    else:
                        self._drop_locked()
                    return
                if len(self._open) >= self.MAX_OPEN_TRACES:
                    # a leaked (never-closed) root must not grow memory
                    self._open.pop(next(iter(self._open)))
                    self._drop_locked()
                spans = self._open[span.trace_id] = []
            if len(spans) >= self.MAX_SPANS_PER_TRACE:
                self._drop_locked()
            else:
                spans.append(span)
            if span.parent_id == 0:
                self._finalize_locked(span.trace_id, spans, span)

    def add_instant(self, span: Span) -> None:
        with self._lock:
            self._instants[self._n_instants % self.instant_capacity] = span
            self._n_instants += 1

    def add_telemetry(self, entry: dict) -> None:
        """One decoded solve window's telemetry slots (a plain dict,
        obs/telemetry_words.record_window) into the bounded ring."""
        with self._lock:
            self._telemetry[self._n_telemetry
                            % self.telemetry_capacity] = entry
            self._n_telemetry += 1

    def _finalize_locked(self, trace_id: int, spans: list,
                         root: Span) -> None:
        self._open.pop(trace_id, None)
        status = "error" if any(s.status == "error" for s in spans) \
            else root.status
        trace = (trace_id, status, root, spans)
        self._ring[self._n_ring % self.capacity] = trace
        self._n_ring += 1
        if status == "error":
            self._err_ring[self._n_err % self.error_capacity] = trace
            self._n_err += 1
        self._finalized[trace_id] = trace
        while len(self._finalized) > self.capacity + self.error_capacity:
            self._finalized.pop(next(iter(self._finalized)))

    # -- readout -------------------------------------------------------------

    def traces(self) -> list:
        """(trace_id, status, root, spans) tuples, newest first; error-ring
        traces included (deduped) so they outlive the recent ring."""
        with self._lock:
            recent = [t for t in self._ring if t is not None]
            errors = [t for t in self._err_ring if t is not None]
        seen = set()
        out = []
        for t in sorted(recent + errors,
                        key=lambda t: t[2].start, reverse=True):
            if t[0] not in seen:
                seen.add(t[0])
                out.append(t)
        return out

    def instants(self) -> list:
        with self._lock:
            return [s for s in self._instants if s is not None]

    def telemetry(self) -> list:
        """Retained telemetry entries in write order (oldest first)."""
        with self._lock:
            n, cap = self._n_telemetry, self.telemetry_capacity
            if n <= cap:
                return [e for e in self._telemetry[:n] if e is not None]
            start = n % cap
            return (self._telemetry[start:] + self._telemetry[:start])

    def stats(self) -> dict:
        with self._lock:
            retained = sum(1 for t in self._ring if t is not None)
            return {
                "traces_retained": retained,
                "traces_total": self._n_ring,
                "error_traces_retained": sum(1 for t in self._err_ring
                                             if t is not None),
                "error_traces_total": self._n_err,
                "instants_total": self._n_instants,
                "telemetry_windows_total": self._n_telemetry,
                "telemetry_retained": sum(1 for e in self._telemetry
                                          if e is not None),
                "open_traces": len(self._open),
                "dropped_spans": self.dropped_spans,
                "capacity": self.capacity,
                "error_capacity": self.error_capacity,
                "ring_occupancy": round(retained / self.capacity, 4)
                if self.capacity else 0.0,
            }


class Tracer:
    """Span factory bound to one recorder.  Ids are a plain counter —
    deterministic for seeded runs, and cheap."""

    def __init__(self, recorder: FlightRecorder | None = None):
        self.recorder = recorder or FlightRecorder()
        self._ids = itertools.count(1)   # .__next__ is atomic under the GIL

    # -- span creation -------------------------------------------------------

    def span(self, name: str, *, start: float | None = None,
             parent: Span | None = None, **attrs) -> Span:
        """Open a span (use as a context manager).  ``parent`` overrides
        the ambient context (cross-thread hand-off); ``start`` backdates
        (the batch window starts when its first item enqueued)."""
        if parent is None:
            parent = _CURRENT.get()
        sid = next(self._ids)
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = sid, 0
        return Span(self, trace_id, sid, parent_id, name,
                    now() if start is None else start, attrs or None)

    def record(self, name: str, start: float, end: float, *,
               parent: Span | None = None, status: str = "ok",
               error: str = "", **attrs) -> Span:
        """Retroactive span from already-measured timestamps — the hot
        solve path's shape: time with two clock reads, then record once
        (one allocation, one preallocated ring-slot write)."""
        if parent is None:
            parent = _CURRENT.get()
        sid = next(self._ids)
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = sid, 0
        sp = Span(self, trace_id, sid, parent_id, name, start, attrs or None)
        sp.end = end
        sp.status = status
        sp.error = error
        if parent_id == 0:
            self.recorder.add_instant(sp) if end == start \
                else self.recorder.add(sp)
        else:
            self.recorder.add(sp)
        return sp

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker.  Attaches as an event to the active span
        when one exists, else lands in the recorder's instant ring (pod
        arrivals, breaker flips — signals with no enclosing operation)."""
        cur = _CURRENT.get()
        if cur is not None:
            cur.event(name, **attrs)
            return
        t = now()
        sid = next(self._ids)
        sp = Span(self, sid, sid, 0, name, t, attrs or None)
        sp.end = t
        self.recorder.add_instant(sp)

    # -- internals -----------------------------------------------------------

    def _finish(self, span: Span) -> None:
        self.recorder.add(span)
