"""Trace export: JSONL span dumps + Chrome trace-event JSON (Perfetto).

Two wire formats:

- **JSONL** — one span per line, times as microsecond offsets from the
  recorder's anchor.  This is the chaos violation artifact and the
  ``/debug/traces`` payload's building block: grep-able, diff-able, and
  structurally deterministic for seeded chaos runs.
- **Chrome trace events** — ``{"traceEvents": [...]}`` with complete
  ("X") events per span and instant ("i") events for span events and
  loose instants.  Loads directly in Perfetto / chrome://tracing; each
  trace gets its own tid row so concurrent provisioning cycles stack
  instead of interleaving.
"""

from __future__ import annotations

import json
from pathlib import Path

from karpenter_tpu.obs.trace import FlightRecorder, Span


def _us(t: float, anchor: float) -> float:
    return round((t - anchor) * 1e6, 1)


def span_to_dict(span: Span, anchor: float) -> dict:
    d = {
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "parent_id": span.parent_id,
        "name": span.name,
        "start_us": _us(span.start, anchor),
        "dur_us": round(span.duration_s * 1e6, 1),
        "status": span.status,
    }
    if span.error:
        d["error"] = span.error
    if span.attrs:
        d["attrs"] = {k: v if isinstance(v, (int, float, bool, str))
                      else str(v) for k, v in span.attrs.items()}
    if span.events:
        d["events"] = [{**e, "t": _us(e["t"], anchor)} for e in span.events]
    return d


def recorder_to_dicts(recorder: FlightRecorder) -> list[dict]:
    """Every retained span (traces newest-first, then loose instants) as
    JSON-safe dicts with anchor-relative times."""
    anchor = recorder.anchor_monotonic
    out: list[dict] = []
    for trace_id, status, _root, spans in recorder.traces():
        for sp in spans:
            d = span_to_dict(sp, anchor)
            d["trace_status"] = status
            out.append(d)
    for sp in recorder.instants():
        d = span_to_dict(sp, anchor)
        d["instant"] = True
        out.append(d)
    return out


def dump_jsonl(span_dicts: list[dict], path) -> Path:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with p.open("w") as f:
        for d in span_dicts:
            f.write(json.dumps(d, sort_keys=True, default=str) + "\n")
    return p


def load_jsonl(path) -> list[dict]:
    out = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if line:
            out.append(json.loads(line))
    return out


def dicts_to_chrome(span_dicts: list[dict]) -> dict:
    """Span dicts -> Chrome trace-event JSON (Perfetto-loadable)."""
    events = []
    tids: dict = {}
    for d in span_dicts:
        tid = tids.setdefault(d["trace_id"], len(tids) + 1)
        args = dict(d.get("attrs") or {})
        if d.get("error"):
            args["error"] = d["error"]
        args["status"] = d.get("status", "ok")
        if d.get("instant") or d["dur_us"] == 0:
            events.append({"name": d["name"], "ph": "i", "s": "t",
                           "ts": d["start_us"], "pid": 1, "tid": tid,
                           "cat": "karpenter_tpu", "args": args})
            continue
        events.append({"name": d["name"], "ph": "X", "ts": d["start_us"],
                       "dur": d["dur_us"], "pid": 1, "tid": tid,
                       "cat": "karpenter_tpu", "args": args})
        for ev in d.get("events") or []:
            events.append({"name": f'{d["name"]}:{ev.get("name", "event")}',
                           "ph": "i", "s": "t", "ts": ev["t"], "pid": 1,
                           "tid": tid, "cat": "karpenter_tpu",
                           "args": {k: v for k, v in ev.items()
                                    if k not in ("name", "t")}})
    events.append({"name": "process_name", "ph": "M", "pid": 1,
                   "args": {"name": "karpenter-tpu"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def to_chrome(recorder: FlightRecorder) -> dict:
    return dicts_to_chrome(recorder_to_dicts(recorder))


def debug_traces(recorder: FlightRecorder, *, status: str | None = None,
                 min_duration_ms: float = 0.0, limit: int = 50,
                 trace_id: int | None = None) -> dict:
    """The ``/debug/traces`` payload: newest-first trace summaries with
    their spans, filterable by status and minimum root duration.
    ``trace_id`` is an exact lookup — the direct fetch for the trace ids
    the ledger's worst-K table and ``/debug/slo`` print (other filters
    are ignored for a pinpoint fetch)."""
    anchor = recorder.anchor_monotonic
    wall0 = recorder.anchor_wall
    traces = []
    for tid, tstatus, root, spans in recorder.traces():
        if trace_id is not None:
            if tid != trace_id:
                continue
        elif status and tstatus != status:
            continue
        dur_ms = root.duration_s * 1000.0
        if trace_id is None and dur_ms < min_duration_ms:
            continue
        traces.append({
            "trace_id": tid,
            "root": root.name,
            "status": tstatus,
            "start_unix": round(wall0 + (root.start - anchor), 6),
            "duration_ms": round(dur_ms, 3),
            "spans": [span_to_dict(s, anchor) for s in spans],
        })
        if len(traces) >= max(1, limit):
            break
    return {"traces": traces, "recorder": recorder.stats()}
