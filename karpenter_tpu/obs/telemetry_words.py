"""Device telemetry words: per-window solver-quality slots that ride
the packed result wire.

The explain suffix (PR 9) proved the pattern: anything the host wants
to know about a solve window can be computed ON DEVICE inside the same
fused dispatch and appended to the result buffer — zero extra
dispatches, zero extra H2D, a few words of D2H the fetch already pays.
This module generalizes that one-off into a REGISTERED plane:

- :data:`TELEMETRY_SLOTS` — the declarative slot registry.  Each slot
  is ``(name, source)`` where source is ``"device"`` (a masked integer
  reduction inside the dispatch — fill fraction per resource, per-node
  slack min/mean, placement counts, chance-constraint binding count)
  or ``"host"`` (control-flow facts only the host knows — escalation /
  COO-growth retries, delta words applied, rebalance skew — which ride
  the wire as zero and are filled at decode/record time).  Slot order
  IS the wire order; graftlint GL112 cross-checks this literal against
  the ``SLOT_*`` index constants in ``solver/result_layout.py`` the
  way GL108 pins the reason enums.  Keep it a pure tuple literal:
  GL112 reads it from the AST.
- :func:`telemetry_words_np` — the numpy host oracle, bit-identical to
  the device reduction ``jax_backend._telemetry_words`` (a registered
  graftlint parity pair; 8-seed differentials in
  tests/test_telemetry.py).  All arithmetic is int32 with explicit
  accumulator dtypes — numpy would otherwise promote reductions to
  int64 and fork from the device's int32 wraparound semantics.
- the host edge: :func:`record_window` feeds decoded slots into the
  ``karpenter_tpu_solve_quality_*`` metric families, the flight
  recorder's bounded telemetry ring (``/debug/telemetry``), and the
  watchdog's solver-quality regression detector (fill-fraction EWMA
  collapse or escalation burst -> triage bundle).

Basis-point fractions are computed by exact base-10 long division
(:func:`frac_bp_np` and its device twin) — ``num * 10000`` would
overflow int32 for any realistic capacity sum, and float division is
banned on the device path (GL202).  Fill and slack are measured in
REQUEST units on every lane, the stochastic one included (the chance
kernel packs by mean usage, so its request-unit fill may legitimately
exceed what a deterministic solve could reach — that headroom is the
plane's whole point and worth seeing on a dashboard).
"""

from __future__ import annotations

import threading

import numpy as np

from karpenter_tpu.solver.result_layout import (
    BP_SCALE,
    HOST_SLOTS,
    SLOT_BINDING_GROUPS,
    SLOT_COO_GROWTHS,
    SLOT_DELTA_WORDS,
    SLOT_ESCALATIONS,
    SLOT_FILL_ACCEL_BP,
    SLOT_FILL_CPU_BP,
    SLOT_FILL_MEM_BP,
    SLOT_FILL_PODS_BP,
    SLOT_GROUPS_PLACED,
    SLOT_GROUPS_UNPLACED,
    SLOT_NODES_OPEN,
    SLOT_PODS_UNPLACED,
    SLOT_REBALANCE_SKEW,
    SLOT_SLACK_MEAN_BP,
    SLOT_SLACK_MIN_BP,
    TELEMETRY_MAGIC,
    TELEMETRY_SLOT_COUNT,
    unpack_telemetry_words,
)

# The slot registry: (name, source) in WIRE ORDER.  Pure tuple literal
# — graftlint GL112 reads it from the AST and cross-checks it against
# result_layout's SLOT_* index constants (bidirectional, so adding a
# slot to one side without the other is a lint failure, not a silent
# mis-decode).  "device" slots are masked reductions inside the solve
# dispatch; "host" slots are zero on the wire, filled at record time.
TELEMETRY_SLOTS = (
    ("fill_cpu_bp", "device"),
    ("fill_mem_bp", "device"),
    ("fill_accel_bp", "device"),
    ("fill_pods_bp", "device"),
    ("slack_min_bp", "device"),
    ("slack_mean_bp", "device"),
    ("nodes_open", "device"),
    ("groups_placed", "device"),
    ("groups_unplaced", "device"),
    ("pods_unplaced", "device"),
    ("binding_groups", "device"),
    ("escalations", "host"),
    ("coo_growths", "host"),
    ("delta_words", "host"),
    ("rebalance_skew", "host"),
)

SLOT_NAMES = tuple(name for name, _ in TELEMETRY_SLOTS)

_FILL_SLOTS = ((SLOT_FILL_CPU_BP, "cpu"), (SLOT_FILL_MEM_BP, "mem"),
               (SLOT_FILL_ACCEL_BP, "accel"), (SLOT_FILL_PODS_BP, "pods"))


# -- numpy oracle (device twin lives in solver/jax_backend.py) ---------------


def _addmod_np(a: np.ndarray, b: np.ndarray,
               den: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """``((a + b) mod den, carry)`` without forming ``a + b`` — both
    operands are ``< den`` which can itself be near int32 max, so the
    naive sum overflows.  ``den - b`` never does."""
    room = (den - b).astype(np.int32)
    wrap = a >= room
    out = np.where(wrap, a - room, a + b).astype(np.int32)
    return out, wrap.astype(np.int32)


def frac_bp_np(num: np.ndarray, den: np.ndarray) -> np.ndarray:
    """``floor(clip(num, 0, den) * BP_SCALE / den)`` in pure int32 by
    base-10 long division — ``num * 10000`` overflows int32 for any
    realistic capacity sum, and the device twin cannot use float
    division (GL202).  Each digit extracts ``floor(10r / den)`` by
    overflow-safe modular doubling (``10r = ((2r)*2 + r)*2``) — the
    remainder can be near int32 max, so even ``r * 10`` is unsafe.
    ``den <= 0`` reads as empty capacity -> 0."""
    num = np.asarray(num, np.int32)
    den = np.asarray(den, np.int32)
    den1 = np.maximum(den, np.int32(1))
    num1 = np.clip(num, np.int32(0), den1)
    bp = (num1 // den1).astype(np.int32)
    r = (num1 - bp * den1).astype(np.int32)
    for _ in range(4):
        r0 = r
        r, c = _addmod_np(r, r, den1)               # 2r
        q = c
        r, c = _addmod_np(r, r, den1)               # 4r
        q = (q * np.int32(2) + c).astype(np.int32)
        r, c = _addmod_np(r, r0, den1)              # 5r
        q = (q + c).astype(np.int32)
        r, c = _addmod_np(r, r, den1)               # 10r
        q = (q * np.int32(2) + c).astype(np.int32)
        bp = (bp * np.int32(10) + q).astype(np.int32)
    return np.clip(bp, np.int32(0), np.int32(BP_SCALE))


def telemetry_words_np(meta: np.ndarray, node_off: np.ndarray,
                       assign: np.ndarray, unplaced: np.ndarray,
                       off_alloc: np.ndarray,
                       binding=None) -> np.ndarray:
    """Host oracle for the device telemetry reduction: the full
    [1 + TELEMETRY_SLOT_COUNT] int32 block (magic word first), bit-
    identical to ``jax_backend._telemetry_words``.  Every reduction
    carries an explicit int32 dtype so numpy cannot promote to int64
    and fork from the device's wraparound semantics."""
    meta = np.asarray(meta, np.int32)
    node_off = np.asarray(node_off, np.int32)
    assign = np.asarray(assign, np.int32)
    unplaced = np.asarray(unplaced, np.int32)
    off_alloc = np.asarray(off_alloc, np.int32)
    req = meta[:, :4]
    count = meta[:, 4]
    open_mask = node_off >= 0                                       # [N]
    safe = np.where(open_mask, node_off, 0)
    caps = (off_alloc[safe]
            * open_mask[:, None].astype(np.int32))                  # [N,4]
    load = np.einsum("gn,gr->nr", assign, req,
                     dtype=np.int32).astype(np.int32)
    load = load * open_mask[:, None].astype(np.int32)
    cap_tot = caps.sum(axis=0, dtype=np.int32)                      # [4]
    load_tot = load.sum(axis=0, dtype=np.int32)
    fill = frac_bp_np(load_tot, cap_tot)
    fill = np.where(cap_tot > 0, fill, np.int32(0))
    # per-open-node slack: min over provisioned resources of the
    # remaining fraction (dimensions a node does not provision are
    # full slack, not zero)
    resid = (caps - load).astype(np.int32)
    node_bp = np.where(caps > 0, frac_bp_np(resid, caps),
                       np.int32(BP_SCALE)).min(axis=1).astype(np.int32)
    nodes_open = open_mask.sum(dtype=np.int32)
    any_open = nodes_open > 0
    slack_min = np.where(open_mask, node_bp,
                         np.int32(BP_SCALE)).min().astype(np.int32)
    slack_sum = np.where(open_mask, node_bp,
                         np.int32(0)).sum(dtype=np.int32)
    slack_mean = slack_sum // np.maximum(nodes_open, np.int32(1))
    live = count > 0
    placed_g = live & ((count - unplaced) > 0)
    unplaced_g = live & (unplaced > 0)
    if binding is None:
        binding_n = np.int32(0)
    else:
        binding_n = (np.asarray(binding, bool)
                     & live).sum(dtype=np.int32)
    words = np.zeros(1 + TELEMETRY_SLOT_COUNT, np.int32)
    words[0] = TELEMETRY_MAGIC
    s = words[1:]
    s[SLOT_FILL_CPU_BP] = fill[0]
    s[SLOT_FILL_MEM_BP] = fill[1]
    s[SLOT_FILL_ACCEL_BP] = fill[2]
    s[SLOT_FILL_PODS_BP] = fill[3]
    s[SLOT_SLACK_MIN_BP] = slack_min if any_open else np.int32(0)
    s[SLOT_SLACK_MEAN_BP] = slack_mean if any_open else np.int32(0)
    s[SLOT_NODES_OPEN] = nodes_open
    s[SLOT_GROUPS_PLACED] = placed_g.sum(dtype=np.int32)
    s[SLOT_GROUPS_UNPLACED] = unplaced_g.sum(dtype=np.int32)
    s[SLOT_PODS_UNPLACED] = np.where(live, unplaced,
                                     0).sum(dtype=np.int32)
    s[SLOT_BINDING_GROUPS] = binding_n
    return words


# -- host edge: decode, fill host slots, record ------------------------------

# last rebalance skew the sharded plane observed — a plane-level fact
# (not per-window device data), stamped into SLOT_REBALANCE_SKEW of
# subsequent sharded windows at record time
_SKEW_LOCK = threading.Lock()
_LAST_REBALANCE_SKEW = 0


def note_rebalance_skew(skew: int) -> None:
    """The sharded rebalance collective's observed pod-count skew —
    stamped into the host-sourced rebalance_skew slot of subsequent
    recorded windows."""
    global _LAST_REBALANCE_SKEW
    with _SKEW_LOCK:
        _LAST_REBALANCE_SKEW = int(skew)


def decode_slots(out_np: np.ndarray, G: int, N: int, K: int,
                 dense16: bool = False,
                 coo16: bool = False) -> np.ndarray:
    """Strict telemetry decode of a packed result buffer (raises
    ``SuffixLayoutError`` on an old-layout buffer — see
    solver/result_layout.py)."""
    return unpack_telemetry_words(np.asarray(out_np), G, N, K,
                                  dense16, coo16)


def record_window(plane: str, slots: np.ndarray, *,
                  escalations: int = 0, coo_growths: int = 0,
                  delta_words: int = 0) -> dict:
    """One decoded window's telemetry: fill the host-sourced slots,
    publish the solve_quality metric families, append to the flight
    recorder's telemetry ring, and feed the watchdog's quality
    regression detector.  Returns the completed slot dict.

    Host-side only (GL107: never call from traced code)."""
    from karpenter_tpu.utils import metrics

    s = np.asarray(slots, np.int32).copy()
    s[SLOT_ESCALATIONS] = escalations
    s[SLOT_COO_GROWTHS] = coo_growths
    s[SLOT_DELTA_WORDS] = delta_words
    with _SKEW_LOCK:
        s[SLOT_REBALANCE_SKEW] = _LAST_REBALANCE_SKEW
    for idx, resource in _FILL_SLOTS:
        metrics.SOLVE_QUALITY_FILL.labels(plane, resource).set(
            int(s[idx]) / BP_SCALE)
    metrics.SOLVE_QUALITY_SLACK.labels(plane, "min").set(
        int(s[SLOT_SLACK_MIN_BP]) / BP_SCALE)
    metrics.SOLVE_QUALITY_SLACK.labels(plane, "mean").set(
        int(s[SLOT_SLACK_MEAN_BP]) / BP_SCALE)
    for idx, kind in ((SLOT_NODES_OPEN, "nodes_open"),
                      (SLOT_GROUPS_PLACED, "groups_placed"),
                      (SLOT_GROUPS_UNPLACED, "groups_unplaced"),
                      (SLOT_PODS_UNPLACED, "pods_unplaced"),
                      (SLOT_BINDING_GROUPS, "binding_groups")):
        metrics.SOLVE_QUALITY_COUNT.labels(plane, kind).set(int(s[idx]))
    metrics.SOLVE_QUALITY_WINDOWS.labels(plane).inc()
    if escalations:
        metrics.SOLVE_QUALITY_ESCALATIONS.labels(plane, "node").inc(
            escalations)
    if coo_growths:
        metrics.SOLVE_QUALITY_ESCALATIONS.labels(plane, "coo").inc(
            coo_growths)
    entry = {"plane": plane}
    entry.update({name: int(s[i]) for i, name in enumerate(SLOT_NAMES)})
    # lazy imports: obs' package __init__ and the watchdog both reach
    # back into obs modules — a module-top import here could re-enter
    # the package half-built
    from karpenter_tpu import obs

    obs.get_recorder().add_telemetry(entry)
    from karpenter_tpu.obs.watchdog import get_watchdog

    fill_bp = max(int(s[idx]) for idx, _ in _FILL_SLOTS)
    get_watchdog().note_quality(plane, fill_bp,
                               escalations=escalations + coo_growths)
    return entry


def decode_and_record(out_np: np.ndarray, G: int, N: int, K: int, *,
                      dense16: bool = False, coo16: bool = False,
                      plane: str = "scan", escalations: int = 0,
                      coo_growths: int = 0,
                      delta_words: int = 0) -> dict | None:
    """Decode + record in one call — the shape every solve plane's
    decode site uses.  Telemetry must never fail a solve: a buffer
    without the expected suffix records nothing and returns None."""
    from karpenter_tpu.solver.result_layout import SuffixLayoutError

    try:
        slots = decode_slots(out_np, G, N, K, dense16, coo16)
    except SuffixLayoutError:
        return None
    return record_window(plane, slots, escalations=escalations,
                         coo_growths=coo_growths,
                         delta_words=delta_words)


def summary() -> dict:
    """Aggregate view of the recorder's telemetry ring for
    ``/debug/telemetry`` and the soak SLO measurements: per plane the
    window count, the latest slots, and mean fill/unplaced over the
    retained ring."""
    from karpenter_tpu import obs

    entries = obs.get_recorder().telemetry()
    planes: dict[str, dict] = {}
    for e in entries:
        p = planes.setdefault(e["plane"], {
            "windows": 0, "fill_bp_sum": 0, "pods_unplaced_sum": 0,
            "escalations": 0, "coo_growths": 0, "last": None})
        p["windows"] += 1
        p["fill_bp_sum"] += max(e["fill_cpu_bp"], e["fill_mem_bp"],
                                e["fill_accel_bp"], e["fill_pods_bp"])
        p["pods_unplaced_sum"] += e["pods_unplaced"]
        p["escalations"] += e["escalations"]
        p["coo_growths"] += e["coo_growths"]
        p["last"] = {k: v for k, v in e.items() if k != "plane"}
    out = {}
    for plane, p in planes.items():
        n = p["windows"]
        out[plane] = {
            "windows": n,
            "mean_fill_fraction": round(p["fill_bp_sum"] / n / BP_SCALE, 4),
            "mean_pods_unplaced": round(p["pods_unplaced_sum"] / n, 2),
            "escalations": p["escalations"],
            "coo_growths": p["coo_growths"],
            "last": p["last"],
        }
    return {
        "slots": [{"index": i, "name": name, "source": source}
                  for i, (name, source) in enumerate(TELEMETRY_SLOTS)],
        "host_slot_indices": list(HOST_SLOTS),
        "windows_recorded": len(entries),
        "planes": out,
    }
